"""`python -m nomad_tpu.ops --selfcheck`: fast oracle/kernel agreement
checks runnable without a test harness (CI smoke; seconds on CPU).

Covers:

- the preemption subsystem: the batched eviction-set kernel
  (ops/preempt.py) must produce exactly the oracle's
  (scheduler/preempt.py) eviction set for every (task-group, node) pair
  of a seeded random 64x64 cluster;
- the degradation plane: a breaker drill injects one corrupted kernel
  batch (fault point ``ops.kernel_result``) and asserts the circuit
  breaker trips, every eval still completes via the CPU oracle, and a
  clean half-open probe restores the device path;
- the device-resident node-state cache: encode → delta-apply →
  differential verify against a fresh full encode (the guard, armed at
  every hit) → staleness-fence fallback for an old snapshot → breaker
  trip on injected resident corruption (fault point
  ``ops.resident_state``);
- the node-mesh production path (ISSUE 8): sharded cold encode →
  sharded delta apply with the per-shard guard → corruption on one
  shard attributed + breaker trip → oracle carries — run on a virtual
  8-device CPU mesh in a subprocess;
- the struct codec (ISSUE 11): seeded-corpus round-trip parity with
  the reflection-msgpack path, encode→corrupt→decode clean rejection,
  and native/python string-column twin agreement.
"""
from __future__ import annotations

import argparse
import sys

from ..utils import knobs
from .preempt import selfcheck


def breaker_drill(seed: int = 0, log=print) -> bool:
    """Inject one corrupted kernel batch; assert trip → oracle fallback →
    recovery.  Uses a private breaker with a fake clock so the drill is
    instant and never touches the process-wide breaker."""
    from .. import fault, mock
    from ..scheduler import Harness
    from ..structs import structs as s
    from .batch_sched import TPUBatchScheduler
    from .breaker import KernelCircuitBreaker

    clock = [0.0]
    brk = KernelCircuitBreaker(threshold=0.9, window=8, min_checks=1,
                               cooldown=5.0, clock=lambda: clock[0])
    h = Harness()
    for _ in range(8):
        node = mock.node()
        node.resources.networks = []
        node.reserved.networks = []
        node.compute_class()
        h.state.upsert_node(h.next_index(), node)

    def run_batch():
        jobs = []
        for _ in range(2):
            job = mock.job()
            for tg in job.task_groups:
                for t in tg.tasks:
                    t.resources.networks = []
            job.task_groups[0].count = 2
            h.state.upsert_job(h.next_index(), job)
            jobs.append(job)
        evals = [s.Evaluation(
            id=s.generate_uuid(), priority=j.priority, type=j.type,
            triggered_by=s.EVAL_TRIGGER_JOB_REGISTER, job_id=j.id,
            status=s.EVAL_STATUS_PENDING) for j in jobs]
        sched = TPUBatchScheduler(h.logger, h.snapshot(), h, breaker=brk)
        stats = sched.schedule_batch(evals)
        placed = all(
            len([a for a in h.state.allocs_by_job(None, j.id, True)
                 if not a.terminal_status()]) == 2 for j in jobs)
        return stats, placed

    def check(cond, msg):
        if not cond:
            log(f"breaker drill: FAIL — {msg}")
        return cond

    with fault.scenario({"seed": seed, "faults": [
            {"point": "ops.kernel_result", "action": "corrupt",
             "times": 1}]}):
        stats, placed = run_batch()
    if not (check(stats.kernel_rejects == 1, "corrupt batch not rejected")
            and check(placed, "oracle fallback did not place the batch")
            and check(brk.state == "open",
                      f"breaker {brk.state!r}, expected open")):
        return False

    stats2, placed2 = run_batch()
    if not (check(stats2.oracle_routed > 0, "open breaker did not route "
                                            "evals through the oracle")
            and check(placed2, "oracle-routed batch did not place")):
        return False

    clock[0] += 10.0  # past cooldown: next batch is the half-open probe
    stats3, placed3 = run_batch()
    if not (check(stats3.oracle_routed == 0, "probe batch did not take "
                                             "the device path")
            and check(placed3, "probe batch did not place")
            and check(brk.state == "closed",
                      f"breaker {brk.state!r} after clean probe")):
        return False
    log(f"breaker drill: OK — trip on corrupt batch (seed {seed}), "
        "oracle fallback placed everything, clean probe re-closed "
        f"(trips={brk.trips})")
    return True


def tracing_drill(seed: int = 0, log=print) -> bool:
    """Run one batch with tracing armed and assert the span tree: the
    batch.schedule root must contain encode/device/finalize phase spans
    with monotonic timestamps and an eval-id index entry per eval; then
    a breaker-tripped (corrupted) batch must produce an
    ``batch.oracle_routed`` span.  Always disarms tracing on exit."""
    from .. import fault, mock
    from ..scheduler import Harness
    from ..structs import structs as s
    from ..utils import tracing
    from .batch_sched import TPUBatchScheduler
    from .breaker import KernelCircuitBreaker

    def check(cond, msg):
        if not cond:
            log(f"tracing drill: FAIL — {msg}")
        return cond

    brk = KernelCircuitBreaker(threshold=0.9, window=8, min_checks=1,
                               cooldown=3600.0)
    h = Harness()
    for _ in range(8):
        node = mock.node()
        node.resources.networks = []
        node.reserved.networks = []
        node.compute_class()
        h.state.upsert_node(h.next_index(), node)

    def run_batch():
        job = mock.job()
        for tg in job.task_groups:
            for t in tg.tasks:
                t.resources.networks = []
        job.task_groups[0].count = 2
        h.state.upsert_job(h.next_index(), job)
        ev = s.Evaluation(
            id=s.generate_uuid(), priority=job.priority, type=job.type,
            triggered_by=s.EVAL_TRIGGER_JOB_REGISTER, job_id=job.id,
            status=s.EVAL_STATUS_PENDING)
        sched = TPUBatchScheduler(h.logger, h.snapshot(), h, breaker=brk)
        sched.schedule_batch([ev])
        return ev

    tracing.enable()
    try:
        ev = run_batch()
        spans = tracing.trace_for_eval(ev.id)
        names = [sp["Name"] for sp in spans]
        roots = [sp for sp in spans if sp["Name"] == "batch.schedule"]
        if not (check(roots, "no batch.schedule root span")
                and check(all(n in names for n in
                              ("batch.encode", "batch.device",
                               "batch.finalize")),
                          f"phase spans missing from {names}")):
            return False
        by_name = {sp["Name"]: sp for sp in spans}
        root_id = roots[0]["SpanID"]
        if not (check(all(by_name[n]["ParentID"] == root_id for n in
                          ("batch.encode", "batch.device",
                           "batch.finalize")),
                      "phase spans not parented under batch.schedule")
                and check(by_name["batch.encode"]["Start"]
                          <= by_name["batch.device"]["Start"]
                          <= by_name["batch.finalize"]["Start"],
                          "phase timestamps not monotonic")):
            return False

        with fault.scenario({"seed": seed, "faults": [
                {"point": "ops.kernel_result", "action": "corrupt",
                 "times": 1}]}):
            ev2 = run_batch()
        spans2 = tracing.trace_for_eval(ev2.id)
        routed = [sp for sp in spans2
                  if sp["Name"] == "batch.oracle_routed"]
        fires = [sp for sp in spans2 if sp["Name"] == "fault.fire"]
        if not (check(routed, "corrupted batch produced no "
                              "batch.oracle_routed span")
                and check(routed[0]["Attrs"].get("reason")
                          == "kernel_reject", f"bad attrs {routed[0]}")
                and check(brk.state == "open",
                          f"breaker {brk.state!r}, expected open")
                and check(fires, "fault.fire span not correlated into "
                                 "the eval trace")):
            return False

        ev3 = run_batch()  # breaker open: routed through the oracle
        routed3 = [sp for sp in tracing.trace_for_eval(ev3.id)
                   if sp["Name"] == "batch.oracle_routed"]
        if not (check(routed3, "open-breaker batch produced no "
                               "batch.oracle_routed span")
                and check(routed3[0]["Attrs"].get("reason")
                          == "breaker_open", f"bad attrs {routed3[0]}")):
            return False
    finally:
        tracing.disable()
    log(f"tracing drill: OK — span tree has encode/device/finalize under "
        f"batch.schedule ({len(spans)} spans for one eval), corrupt batch "
        "traced as oracle_routed(kernel_reject) + fault.fire, open "
        "breaker traced as oracle_routed(breaker_open)")
    return True


def residency_drill(seed: int = 0, log=print) -> bool:
    """Device-resident cache drill: cold encode installs the mirror, a
    second batch takes the delta path with the differential guard armed
    at EVERY hit (so delta-apply is verified against a fresh full
    encode), a stale snapshot falls back over the staleness fence, and
    injected resident corruption trips a private breaker."""
    import os

    from .. import fault, mock
    from ..scheduler import Harness
    from ..structs import structs as s
    from . import resident
    from .batch_sched import TPUBatchScheduler
    from .breaker import KernelCircuitBreaker

    def check(cond, msg):
        if not cond:
            log(f"residency drill: FAIL — {msg}")
        return cond

    saved = {k: os.environ.get(k) for k in
             ("NOMAD_TPU_RESIDENT", "NOMAD_TPU_RESIDENT_GUARD_EVERY")}
    os.environ["NOMAD_TPU_RESIDENT"] = "1"
    os.environ["NOMAD_TPU_RESIDENT_GUARD_EVERY"] = "1"
    resident.reset_counters()
    brk = KernelCircuitBreaker(threshold=0.9, window=8, min_checks=1,
                               cooldown=3600.0)
    try:
        h = Harness()
        for _ in range(8):
            node = mock.node()
            node.resources.networks = []
            node.reserved.networks = []
            node.compute_class()
            h.state.upsert_node(h.next_index(), node)

        def make_batch_job():
            job = mock.job()
            for tg in job.task_groups:
                for t in tg.tasks:
                    t.resources.networks = []
            job.task_groups[0].count = 2
            h.state.upsert_job(h.next_index(), job)
            return job

        def run_batch(state=None, job=None):
            if job is None:
                job = make_batch_job()
            ev = s.Evaluation(
                id=s.generate_uuid(), priority=job.priority, type=job.type,
                triggered_by=s.EVAL_TRIGGER_JOB_REGISTER, job_id=job.id,
                status=s.EVAL_STATUS_PENDING)
            sched = TPUBatchScheduler(
                h.logger, state if state is not None else h.snapshot(),
                h, breaker=brk)
            stats = sched.schedule_batch([ev])
            placed = len([a for a in
                          h.state.allocs_by_job(None, job.id, True)
                          if not a.terminal_status()]) == 2
            return stats, placed

        s1, p1 = run_batch()
        if not (check(s1.full_reencodes == 1 and not s1.resident_hits,
                      f"cold batch should full-encode ({s1!r})")
                and check(p1, "cold batch did not place")):
            return False
        s2, p2 = run_batch()
        if not (check(s2.resident_hits == 1,
                      f"second batch should take the delta path ({s2!r})")
                and check(p2, "delta batch did not place")
                and check(resident.GUARD_RUNS >= 1
                          and resident.GUARD_MISMATCHES == 0,
                          "differential guard did not verify the delta "
                          "apply against a fresh encode")):
            return False

        # Staleness fence: a snapshot two batches old must full-encode
        # without touching the (newer) mirror.  The fence job registers
        # BEFORE the snapshot so the stale world can see it.
        fence_job = make_batch_job()
        stale = h.snapshot()
        run_batch()
        run_batch()
        cached = resident._STATE.alloc_index
        s3, p3 = run_batch(state=stale, job=fence_job)
        if not (check(s3.staleness_fences == 1 and s3.full_reencodes == 1,
                      f"stale snapshot did not take the fence ({s3!r})")
                and check(p3, "fenced batch did not place")
                and check(resident._STATE.alloc_index == cached,
                          "fence regressed the resident mirror")):
            return False

        # Injected resident corruption: guard catches it, breaker trips,
        # the batch still places from the fresh full encode.
        with fault.scenario({"seed": seed, "faults": [
                {"point": "ops.resident_state", "action": "corrupt",
                 "times": 1}]}):
            s4, p4 = run_batch()
        if not (check(resident.GUARD_MISMATCHES == 1,
                      "guard missed the injected corruption")
                and check(brk.state == "open",
                          f"breaker {brk.state!r}, expected open")
                and check(p4, "corrupted-mirror batch did not place")):
            return False
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        resident.reset_counters()
    log("residency drill: OK — cold encode installed the mirror, delta "
        "apply verified bit-identical by the guard, stale snapshot took "
        "the fence, injected corruption tripped the breaker "
        f"(guard runs={resident.GUARD_RUNS or 'reset'})")
    return True


def columnar_drill(seed: int = 0, log=print) -> bool:
    """Columnar state-store drill (ISSUE 9): the first snapshot cold-
    builds the store's numpy mirror and the encode slices it (guard
    armed at EVERY encode, so the column-built buffers are verified
    bit-identical against the object walk), incremental node/alloc
    writes keep parity, an injected column corruption is caught by the
    guard and trips the breaker, and the oracle carries the next
    batch."""
    import os

    from .. import fault, mock
    from ..scheduler import Harness
    from ..state import columnar
    from ..structs import structs as s
    from .batch_sched import TPUBatchScheduler
    from .breaker import KernelCircuitBreaker

    def check(cond, msg):
        if not cond:
            log(f"columnar drill: FAIL — {msg}")
        return cond

    saved = {k: os.environ.get(k) for k in
             ("NOMAD_TPU_COLUMNAR", "NOMAD_TPU_COLUMNAR_GUARD_EVERY")}
    os.environ["NOMAD_TPU_COLUMNAR"] = "1"
    os.environ["NOMAD_TPU_COLUMNAR_GUARD_EVERY"] = "1"
    columnar.reset_counters()
    brk = KernelCircuitBreaker(threshold=0.9, window=8, min_checks=1,
                               cooldown=3600.0)
    try:
        h = Harness()
        for _ in range(8):
            node = mock.node()
            node.resources.networks = []
            node.reserved.networks = []
            node.compute_class()
            h.state.upsert_node(h.next_index(), node)

        def run_batch():
            job = mock.job()
            for tg in job.task_groups:
                for t in tg.tasks:
                    t.resources.networks = []
            job.task_groups[0].count = 2
            h.state.upsert_job(h.next_index(), job)
            ev = s.Evaluation(
                id=s.generate_uuid(), priority=job.priority, type=job.type,
                triggered_by=s.EVAL_TRIGGER_JOB_REGISTER, job_id=job.id,
                status=s.EVAL_STATUS_PENDING)
            sched = TPUBatchScheduler(h.logger, h.snapshot(), h,
                                      breaker=brk)
            stats = sched.schedule_batch([ev])
            placed = len([a for a in
                          h.state.allocs_by_job(None, job.id, True)
                          if not a.terminal_status()]) == 2
            return stats, placed

        # 1. Cold build + first columnar encode, guard-verified.
        _, p1 = run_batch()
        if not (check(columnar.COLUMNAR_ENCODES >= 1,
                      "first batch did not take the columnar encode")
                and check(columnar.GUARD_RUNS >= 1
                          and columnar.GUARD_MISMATCHES == 0,
                          "guard did not verify the cold column build")
                and check(p1, "cold columnar batch did not place")):
            return False

        # 2. Incremental writes (status flip + a fresh node) re-key the
        # static cache; the columnar re-encode must still match the
        # walk bit-for-bit.
        some_node = h.state.nodes(None)[0]
        h.state.update_node_drain(h.next_index(), some_node.id, True)
        h.state.update_node_drain(h.next_index(), some_node.id, False)
        extra = mock.node()
        extra.resources.networks = []
        extra.reserved.networks = []
        extra.compute_class()
        h.state.upsert_node(h.next_index(), extra)
        guard_before = columnar.GUARD_RUNS
        _, p2 = run_batch()
        if not (check(columnar.GUARD_RUNS > guard_before
                      and columnar.GUARD_MISMATCHES == 0,
                      "guard did not verify the incremental re-encode")
                and check(p2, "incremental batch did not place")):
            return False

        # 3. Injected column corruption: the guard catches it, feeds
        # the breaker, and the batch proceeds on the walk's buffers.
        extra2 = mock.node()
        extra2.resources.networks = []
        extra2.reserved.networks = []
        extra2.compute_class()
        h.state.upsert_node(h.next_index(), extra2)  # force re-encode
        with fault.scenario({"seed": seed, "faults": [
                {"point": "state.columns", "action": "corrupt",
                 "times": 1}]}):
            _, p3 = run_batch()
        if not (check(columnar.GUARD_MISMATCHES == 1,
                      "guard missed the injected column corruption")
                and check(brk.state == "open",
                          f"breaker {brk.state!r}, expected open")
                and check(p3, "corrupted-column batch did not place")):
            return False

        # 4. Open breaker: the oracle carries the next batch.
        s4, p4 = run_batch()
        if not (check(s4.oracle_routed > 0,
                      "open breaker did not route through the oracle")
                and check(p4, "oracle-carried batch did not place")):
            return False
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        columnar.reset_counters()
    log("columnar drill: OK — cold column build verified bit-identical "
        "to the object walk, incremental writes kept parity, injected "
        "corruption tripped the breaker, oracle carried the next batch")
    return True


def wal_drill(seed: int = 0, log=print) -> bool:
    """Native group-commit WAL drill (ISSUE 9): append through the
    FileLog, crash mid-frame via the ``wal.fsync`` fault point (a torn
    partial record is left on disk), and recover — the torn tail is
    truncated, committed entries survive, the crashed entry never
    applied, and post-recovery appends land cleanly."""
    import os
    import shutil
    import tempfile

    from .. import fault, mock
    from ..server.fsm import FSM, MessageType
    from ..server.raft import FileLog

    def check(cond, msg):
        if not cond:
            log(f"wal drill: FAIL — {msg}")
        return cond

    d = tempfile.mkdtemp(prefix="nomad-tpu-waldrill-")
    try:
        flog = FileLog(FSM(), d)
        native = flog._nwal is not None
        node = mock.node()
        node.compute_class()
        flog.apply(MessageType.NODE_REGISTER, {"node": node})
        applied = flog.applied_index()

        job = mock.job()
        crashed = False
        with fault.scenario({"seed": seed, "faults": [
                {"point": "wal.fsync", "action": "crash", "times": 1}]}):
            try:
                flog.apply(MessageType.JOB_REGISTER, {"job": job})
            except Exception:
                crashed = True
        flog.close()
        if not check(crashed, "injected mid-frame crash did not fire"):
            return False
        wal_file = os.path.join(d, "wal.crc" if native else "wal.log")
        torn_size = os.path.getsize(wal_file)

        flog2 = FileLog(FSM(), d)
        if not (check(flog2.applied_index() == applied,
                      "recovery lost or invented entries")
                and check(flog2.fsm.state.node_by_id(None, node.id)
                          is not None, "committed entry lost")
                and check(flog2.fsm.state.job_by_id(None, job.id) is None,
                          "torn entry applied")
                and check(os.path.getsize(wal_file) < torn_size,
                          "torn tail was not truncated")):
            flog2.close()
            return False
        flog2.apply(MessageType.JOB_REGISTER, {"job": job})
        applied2 = flog2.applied_index()
        flog2.close()

        flog3 = FileLog(FSM(), d)
        ok = (check(flog3.applied_index() == applied2,
                    "post-recovery append did not survive")
              and check(flog3.fsm.state.job_by_id(None, job.id)
                        is not None, "post-recovery entry lost"))
        flog3.close()
        if not ok:
            return False
    finally:
        shutil.rmtree(d, ignore_errors=True)
    log(f"wal drill: OK — {'native' if native else 'fallback'} WAL "
        "crashed mid-frame, recovery truncated the torn tail, committed "
        "entries survived, post-recovery appends land cleanly")
    return True


def fused_drill(seed: int = 0, log=print) -> bool:
    """Fused score-and-commit drill (PR 6): a cold batch through the
    fused single-dispatch path must place with exactly ONE ``batch.fetch``
    span; the identical problem through the CPU oracle must place the
    same per-job counts with no node overcommitted; quantized resource
    rows must round-trip bit-exactly (and a corrupted codebook must be
    caught); a corrupted fused result buffer must trip the breaker and
    route the batch to the oracle."""
    import os

    import numpy as np

    from .. import fault, mock
    from ..scheduler import Harness
    from ..scheduler.generic import GenericScheduler
    from ..structs import structs as s
    from ..utils import tracing
    from . import encode, resident
    from .batch_sched import TPUBatchScheduler
    from .breaker import KernelCircuitBreaker

    def check(cond, msg):
        if not cond:
            log(f"fused drill: FAIL — {msg}")
        return cond

    saved = {k: os.environ.get(k)
             for k in ("NOMAD_TPU_FUSED", "NOMAD_TPU_QUANT")}
    os.environ["NOMAD_TPU_FUSED"] = "1"
    os.environ["NOMAD_TPU_QUANT"] = "1"
    brk = KernelCircuitBreaker(threshold=0.9, window=8, min_checks=1,
                               cooldown=3600.0)
    try:
        # Twin harnesses over an identical fleet + identical jobs: one
        # scheduled by the fused device path, one by the oracle.
        nodes = []
        for _ in range(8):
            node = mock.node()
            node.resources.networks = []
            node.reserved.networks = []
            node.compute_class()
            nodes.append(node)
        h_dev, h_orc = Harness(), Harness()
        for node in nodes:
            h_dev.state.upsert_node(h_dev.next_index(), node.copy())
            h_orc.state.upsert_node(h_orc.next_index(), node.copy())
        jobs = []
        for _ in range(3):
            job = mock.job()
            for tg in job.task_groups:
                for t in tg.tasks:
                    t.resources.networks = []
            job.task_groups[0].count = 2
            jobs.append(job)
        for h in (h_dev, h_orc):
            for job in jobs:
                h.state.upsert_job(h.next_index(), job)

        def mk_evals():
            return [s.Evaluation(
                id=s.generate_uuid(), priority=j.priority, type=j.type,
                triggered_by=s.EVAL_TRIGGER_JOB_REGISTER, job_id=j.id,
                status=s.EVAL_STATUS_PENDING) for j in jobs]

        # 1. Cold fused batch, tracing armed: one batch.fetch span, the
        # batch placed, and the stats say fused ran.
        evals = mk_evals()
        tracing.enable()
        try:
            sched = TPUBatchScheduler(h_dev.logger, h_dev.snapshot(),
                                      h_dev, breaker=brk)
            stats = sched.schedule_batch(evals)
            fetches = [sp for sp in tracing.trace_for_eval(evals[0].id)
                       if sp["Name"] == "batch.fetch"]
        finally:
            tracing.disable()
        if not (check(stats.fused == 1, f"batch did not run fused ({stats!r})")
                and check(len(fetches) == 1,
                          f"{len(fetches)} batch.fetch spans, expected "
                          "exactly 1 (single-transfer contract)")):
            return False

        # 2. Oracle parity on the twin harness: same per-job placement
        # counts, no node overcommitted on either side.
        for ev in mk_evals():
            GenericScheduler(h_orc.logger, h_orc.snapshot(),
                             h_orc, batch=False).process(ev)
        for job in jobs:
            n_dev = len([a for a in
                         h_dev.state.allocs_by_job(None, job.id, True)
                         if not a.terminal_status()])
            n_orc = len([a for a in
                         h_orc.state.allocs_by_job(None, job.id, True)
                         if not a.terminal_status()])
            if not check(n_dev == n_orc == 2,
                         f"placement parity broke for {job.id}: fused "
                         f"{n_dev} vs oracle {n_orc} (want 2)"):
                return False
        for h in (h_dev, h_orc):
            for node in h.state.nodes(None):
                used = np.zeros(2, dtype=np.int64)
                for a in h.state.allocs_by_node(None, node.id):
                    if a.terminal_status():
                        continue
                    res = a.resources
                    if res is None:
                        # Oracle-path allocs carry per-task resources
                        # only (the combined total is filled at apply).
                        used += (
                            sum(t.cpu for t in a.task_resources.values()),
                            sum(t.memory_mb
                                for t in a.task_resources.values()))
                    else:
                        used += (res.cpu, res.memory_mb)
                if not check(
                        used[0] <= node.resources.cpu
                        and used[1] <= node.resources.memory_mb,
                        f"node {node.id} overcommitted ({used})"):
                    return False

        # 3. Quantization round-trip bound: the bench-shape rows must
        # quantize exactly; a corrupted codebook must be caught and feed
        # the breaker.
        resident.reset_counters()
        cap = np.tile(np.array([4000, 8192, 102400, 150]), (8, 1))
        base_used = np.tile(np.array([100, 128, 0, 0]), (8, 1))
        q = encode.quantize_resource_rows(cap, base_used)
        if not (check(q is not None, "bench-shape rows did not quantize")
                and check(resident.check_quant_roundtrip(
                              cap, q.cap_q, q.scale[0], what="capacity"),
                          "exact quantization failed the round-trip bound")
                and check(np.array_equal(
                              encode.dequantize_rows(q.used_q, q.scale[1]),
                              base_used),
                          "used baseline did not round-trip")):
            return False
        bad_brk = KernelCircuitBreaker(threshold=0.9, window=8,
                                       min_checks=1, cooldown=3600.0)
        corrupt = np.array(q.cap_q)
        corrupt[0, 0] += 1
        if not (check(not resident.check_quant_roundtrip(
                          cap, corrupt, q.scale[0], breaker=bad_brk,
                          what="capacity"),
                      "corrupted codebook passed the round-trip bound")
                and check(resident.QUANT_MISMATCHES == 1,
                          "quant mismatch counter did not move")
                and check(bad_brk.agreement() < 1.0,
                          "quant mismatch did not feed the breaker")):
            return False

        # 4. Corrupted fused result buffer: validation rejects it, the
        # breaker trips, the oracle carries the batch.  Fresh jobs — the
        # step-1 jobs already placed, so their evals would be no-ops.
        jobs2 = []
        for _ in range(2):
            job = mock.job()
            for tg in job.task_groups:
                for t in tg.tasks:
                    t.resources.networks = []
            job.task_groups[0].count = 1
            jobs2.append(job)
            h_dev.state.upsert_job(h_dev.next_index(), job)
        evals2 = [s.Evaluation(
            id=s.generate_uuid(), priority=j.priority, type=j.type,
            triggered_by=s.EVAL_TRIGGER_JOB_REGISTER, job_id=j.id,
            status=s.EVAL_STATUS_PENDING) for j in jobs2]
        with fault.scenario({"seed": seed, "faults": [
                {"point": "ops.kernel_result", "action": "corrupt",
                 "times": 1}]}):
            sched = TPUBatchScheduler(h_dev.logger, h_dev.snapshot(),
                                      h_dev, breaker=brk)
            stats2 = sched.schedule_batch(evals2)
        if not (check(stats2.kernel_rejects == 1,
                      f"corrupt fused batch not rejected ({stats2!r})")
                and check(stats2.oracle_routed == len(jobs2),
                          "rejected fused batch did not route to the "
                          "oracle")
                and check(brk.state == "open",
                          f"breaker {brk.state!r}, expected open")):
            return False
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        resident.reset_counters()
    log("fused drill: OK — single-fetch fused batch placed with oracle "
        "parity and no overcommit, quantized rows round-tripped exactly "
        "(corruption caught), corrupt fused buffer tripped the breaker "
        "and the oracle carried the batch")
    return True


def residue_drill(seed: int = 0, log=print) -> bool:
    """Host-residue drill (ISSUE 13): the donated device-resident usage
    mirror round-trips bit-identical to the host mirror across delta
    batches (and produces the same placements as the sparse-delta upload
    path at a pinned seed), the int8 quantization guard catches an
    out-of-range dimension, and the native packed-result decode agrees
    with its python twins on a seeded corpus."""
    import os
    import random

    import numpy as np

    from .. import mock
    from ..scheduler import Harness
    from ..structs import structs as s
    from . import decode as decode_mod
    from . import encode, resident
    from .batch_sched import TPUBatchScheduler

    def check(cond, msg):
        if not cond:
            log(f"residue drill: FAIL — {msg}")
        return cond

    saved = {k: os.environ.get(k) for k in
             ("NOMAD_TPU_RESIDENT", "NOMAD_TPU_RESIDENT_DEVICE",
              "NOMAD_TPU_RESIDENT_GUARD_EVERY", "NOMAD_TPU_RNG_SEED",
              "NOMAD_TPU_DECODE_GUARD_EVERY")}
    os.environ["NOMAD_TPU_RESIDENT"] = "1"
    os.environ["NOMAD_TPU_RESIDENT_GUARD_EVERY"] = "1"
    os.environ["NOMAD_TPU_RNG_SEED"] = str(1234567 + seed)
    os.environ["NOMAD_TPU_DECODE_GUARD_EVERY"] = "1"
    resident.reset_counters()
    decode_mod.reset_counters()
    try:
        # 1. Donated round-trip parity: the same 4-batch stream through
        # the donated device mirror and the sparse-delta upload path
        # must place identically, and the device mirror must bit-match
        # the host mirror after every donated apply.
        def run_stream(device_mirror: bool):
            os.environ["NOMAD_TPU_RESIDENT_DEVICE"] = (
                "1" if device_mirror else "0")
            resident.invalidate()
            h = Harness()
            for i in range(8):
                node = mock.node()
                # Pinned ids: the two streams build separate harnesses
                # and their placements compare by node identity.
                node.id = f"residue-node-{i:02d}"
                node.name = node.id
                node.resources.networks = []
                node.reserved.networks = []
                node.compute_class()
                h.state.upsert_node(h.next_index(), node)
            placements = []
            for _ in range(4):
                job = mock.job()
                for tg in job.task_groups:
                    for t in tg.tasks:
                        t.resources.networks = []
                job.task_groups[0].count = 2
                h.state.upsert_job(h.next_index(), job)
                ev = s.Evaluation(
                    id=s.generate_uuid(), priority=job.priority,
                    type=job.type,
                    triggered_by=s.EVAL_TRIGGER_JOB_REGISTER,
                    job_id=job.id, status=s.EVAL_STATUS_PENDING)
                TPUBatchScheduler(h.logger, h.snapshot(), h
                                  ).schedule_batch([ev])
                placements.append(sorted(
                    a.node_id for a in
                    h.state.allocs_by_job(None, job.id, True)))
            st = resident._STATE
            dev_ok = True
            if device_mirror:
                dev_ok = (st is not None and st.used_dev is not None
                          and np.array_equal(
                              np.asarray(st.used_dev).astype(np.int64),
                              st.used))
            return placements, dev_ok

        pl_dev, dev_ok = run_stream(True)
        applies = resident.DEV_APPLIES
        installs = resident.DEV_INSTALLS
        pl_delta, _ = run_stream(False)
        if not (check(installs == 1,
                      f"expected ONE device-mirror install, got "
                      f"{installs}")
                and check(applies >= 3,
                          f"donated delta applies did not run ({applies})")
                and check(dev_ok,
                          "device mirror diverged from the host mirror "
                          "after donated applies")
                and check(pl_dev == pl_delta,
                          "donated-mirror placements differ from the "
                          "delta-upload path")
                and check(resident.DEV_GUARD_MISMATCHES == 0
                          and resident.GUARD_MISMATCHES == 0,
                          "mirror guards reported mismatches")):
            return False

        # 2. int8 guard: a scale codebook pushed out of range must fail
        # the round-trip bound (exact-or-absent discipline).
        cap = np.tile(np.array([4000, 8192, 102400, 150]), (8, 1))
        q = encode.quantize_resource_rows(cap, np.zeros_like(cap))
        if not (check(q is not None and q.cap_tag == "i8",
                      f"bench-shape capacity did not quantize int8 "
                      f"({None if q is None else q.cap_tag})")
                and check(resident.check_quant_roundtrip(
                              cap, q.cap_q, q.scale[0], what="capacity"),
                          "exact int8 rows failed the round-trip bound")):
            return False
        bad_scale = np.array(q.scale[0])
        bad_scale[1] <<= 1   # out-of-range dimension: dequant overshoots
        if not check(not resident.check_quant_roundtrip(
                         cap, q.cap_q, bad_scale, what="capacity"),
                     "out-of-range scale dimension passed the guard"):
            return False

        # 3. Native-decode twin agreement on a seeded COO corpus (guard
        # pinned at 1 above, so EVERY native call is twin-verified).
        rng = random.Random(seed)
        n_specs, n_real = 17, 203
        rows_l, cols_l, cnt_l = [], [], []
        for u in range(n_specs):
            for _ in range(rng.randrange(0, 9)):
                rows_l.append(u)
                cols_l.append(rng.randrange(n_real))
                cnt_l.append(rng.randrange(1, 4))
        rows = np.array(rows_l, dtype=np.int32)
        cols = np.array(cols_l, dtype=np.int32)
        cnts = np.array(cnt_l, dtype=np.int32)
        scores = np.array([rng.random() * 18 for _ in rows_l],
                          dtype=np.float32)
        coll = np.array([rng.randrange(0, 3) for _ in rows_l],
                        dtype=np.int32)
        off, exp = decode_mod.expand_coo(rows, cols, cnts, n_specs,
                                         n_real, int(cnts.sum()))
        ref_off, ref_exp = decode_mod._expand_twin(rows, cols, cnts,
                                                   n_specs, n_real)
        ls = decode_mod.last_scores(rows, cols, scores, coll, n_specs,
                                    n_real)
        ref_ls = decode_mod._last_scores_twin(rows, cols, scores, coll,
                                              n_specs, n_real)
        if not (check(np.array_equal(off, ref_off)
                      and np.array_equal(exp, ref_exp),
                      "native expand diverged from the numpy twin")
                and check(all(np.array_equal(a, b)
                              for a, b in zip(ls, ref_ls)),
                          "native last-scores diverged from the twin")
                and check(decode_mod.GUARD_MISMATCHES == 0,
                          "decode guard reported mismatches")):
            return False
        native_note = ("native" if decode_mod.NATIVE_CALLS else
                       "python-twin (toolchain unavailable)")
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        resident.reset_counters()
        decode_mod.reset_counters()
    log("residue drill: OK — donated mirror round-tripped bit-identical "
        "(one install, in-place applies, placements == delta path), "
        "out-of-range int8 scale caught by the round-trip guard, "
        f"packed-result decode twins agree ({native_note})")
    return True


def mesh_drill_child(seed: int = 0, log=print, n_devices: int = 8) -> bool:
    """Node-mesh residue drill body (requires ``n_devices`` jax devices
    — the parent ``mesh_drill`` provisions a virtual CPU mesh): sharded
    cold encode installs the DONATED per-shard usage mirror (ISSUE 14),
    N delta batches catch it up in place via shard-routed donated
    scatter-adds with the differential guard armed at every hit, the
    device mirror bit-compares against the host walk, ONE corrupted
    mirror row is attributed to its owning shard id (guard event) and
    trips the breaker, and the open breaker routes the next batch
    through the CPU oracle which still places everything."""
    import os

    import jax
    import numpy as np

    from .. import fault, mock
    from ..parallel import make_node_mesh
    from ..scheduler import Harness
    from ..server import event_broker
    from ..structs import structs as s
    from . import resident
    from .batch_sched import TPUBatchScheduler
    from .breaker import KernelCircuitBreaker

    def check(cond, msg):
        if not cond:
            log(f"mesh drill: FAIL — {msg}")
        return cond

    devs = jax.devices()
    if not check(len(devs) >= n_devices,
                 f"need {n_devices} devices, have {len(devs)}"):
        return False
    mesh = make_node_mesh(devs[:n_devices])
    saved = {k: os.environ.get(k) for k in
             ("NOMAD_TPU_RESIDENT", "NOMAD_TPU_RESIDENT_GUARD_EVERY",
              "NOMAD_TPU_RESIDENT_DEVICE")}
    os.environ["NOMAD_TPU_RESIDENT"] = "1"
    os.environ["NOMAD_TPU_RESIDENT_GUARD_EVERY"] = "1"
    os.environ["NOMAD_TPU_RESIDENT_DEVICE"] = "1"
    resident.reset_counters()
    brk = KernelCircuitBreaker(threshold=0.9, window=8, min_checks=1,
                               cooldown=3600.0)
    h = Harness()
    broker = event_broker.EventBroker(
        index_source=lambda: h.state.latest_index())
    event_broker.register(broker)
    event_broker.clear_recent()
    try:
        for _ in range(16):
            node = mock.node()
            node.resources.networks = []
            node.reserved.networks = []
            node.compute_class()
            h.state.upsert_node(h.next_index(), node)

        def run_batch():
            job = mock.job()
            for tg in job.task_groups:
                for t in tg.tasks:
                    t.resources.networks = []
            job.task_groups[0].count = 2
            h.state.upsert_job(h.next_index(), job)
            ev = s.Evaluation(
                id=s.generate_uuid(), priority=job.priority, type=job.type,
                triggered_by=s.EVAL_TRIGGER_JOB_REGISTER, job_id=job.id,
                status=s.EVAL_STATUS_PENDING)
            sched = TPUBatchScheduler(h.logger, h.snapshot(), h,
                                      mesh=mesh, breaker=brk)
            stats = sched.schedule_batch([ev])
            placed = len([a for a in
                          h.state.allocs_by_job(None, job.id, True)
                          if not a.terminal_status()]) == 2
            return stats, placed

        s1, p1 = run_batch()
        if not (check(s1.mesh_shards == n_devices and s1.fused == 1,
                      f"cold batch did not run the fused mesh pass "
                      f"({s1!r})")
                and check(s1.full_reencodes == 1,
                          f"cold batch should full-encode ({s1!r})")
                and check(p1, "cold mesh batch did not place")
                and check(resident.DEV_INSTALLS == 1,
                          f"sharded mirror should install exactly once "
                          f"({resident.DEV_INSTALLS})")):
            return False
        s2, p2 = run_batch()
        st = resident._STATE
        if not (check(s2.resident_hits == 1,
                      f"second batch should take the sharded delta path "
                      f"({s2!r})")
                and check(p2, "delta batch did not place")
                and check(resident.DEV_APPLIES >= 1,
                          "no shard-routed donated delta apply ran")
                and check(resident.DEV_INSTALLS == 1,
                          "delta batch reinstalled the mirror instead "
                          "of applying in place")
                and check(st is not None and st.used_dev is not None
                          and np.array_equal(
                              np.asarray(st.used_dev).astype(np.int64),
                              st.used),
                          "sharded device mirror diverged from the "
                          "host walk")
                and check(resident.GUARD_RUNS >= 1
                          and resident.GUARD_MISMATCHES == 0,
                          "per-shard guard did not verify the delta "
                          "apply")):
            return False
        with fault.scenario({"seed": seed, "faults": [
                {"point": "ops.resident_state", "action": "corrupt",
                 "times": 1}]}):
            s3, p3 = run_batch()
        mismatch_events = [
            e for e in event_broker.recent()
            if e.type == "NodeStateDelta"
            and e.payload.get("Reason") == "guard_mismatch"]
        bad_shards = (mismatch_events[-1].payload.get("Shards")
                      if mismatch_events else None)
        if not (check(resident.GUARD_MISMATCHES == 1,
                      "guard missed the injected shard corruption")
                and check(bad_shards is not None and len(bad_shards) == 1
                          and 0 <= bad_shards[0] < n_devices,
                          f"corruption not attributed to its owning "
                          f"shard id (event Shards={bad_shards})")
                and check(brk.state == "open",
                          f"breaker {brk.state!r}, expected open")
                and check(p3, "corrupted-shard batch did not place")):
            return False
        s4, p4 = run_batch()
        if not (check(s4.oracle_routed > 0,
                      "open breaker did not route the mesh batch "
                      "through the oracle")
                and check(p4, "oracle-carried batch did not place")):
            return False
    finally:
        event_broker.unregister(broker)
        event_broker.clear_recent()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
        resident.reset_counters()
    log(f"mesh drill: OK — {n_devices}-shard fused cold encode installed "
        "the donated per-shard mirror and placed, shard-routed donated "
        "applies landed on the owning shards (device mirror bit-matched "
        f"the host walk, guard verified), injected corruption was "
        f"attributed to shard {bad_shards[0]} and tripped the breaker, "
        "and the oracle carried the next batch")
    return True


def mesh_drill(seed: int = 0, log=print, n_devices: int = 8,
               deadline_s: int = 420) -> bool:
    """Parent half of the mesh drill: provision an ``n_devices`` virtual
    CPU mesh in a throwaway subprocess (the same
    xla_force_host_platform_device_count recipe tests/conftest.py and
    the driver dryrun use — the current process may already have a
    single-device backend initialized) and run ``mesh_drill_child``
    there."""
    import subprocess

    from ..utils.platform import virtual_mesh_env

    env = virtual_mesh_env(n_devices)
    try:
        proc = subprocess.run(
            [sys.executable, "-m", "nomad_tpu.ops", "--mesh-drill-child",
             "--seed", str(seed)],
            env=env, timeout=deadline_s, capture_output=True, text=True)
    except subprocess.TimeoutExpired:
        log(f"mesh drill: FAIL — child exceeded {deadline_s}s deadline")
        return False
    for line in (proc.stdout or "").splitlines():
        log(line)
    if proc.returncode != 0:
        tail = (proc.stderr or "").strip().splitlines()[-5:]
        for line in tail:
            log(f"mesh drill child stderr: {line}")
        log(f"mesh drill: FAIL — child rc={proc.returncode}")
        return False
    return True


def codec_drill(seed: int = 0, log=print) -> bool:
    """Struct-codec drill (ISSUE 11): a seeded corpus of hot-type
    payloads must (1) round-trip bit-equal to the reflection-msgpack
    path, (2) REJECT cleanly — CodecError, never a silent misread or a
    crash — under truncation and header/tag corruption, and (3) agree
    byte-for-byte between the native string-column pack and its
    pure-Python twin."""
    import os
    import random

    from .. import codec, mock
    from ..api.codec import to_wire
    from ..codec import CodecError
    from ..codec import native as cnative
    from ..structs import structs as s

    def check(cond, msg):
        if not cond:
            log(f"codec drill: FAIL — {msg}")
        return cond

    rng = random.Random(seed)

    def corpus_item(i):
        job = mock.job()
        alloc = s.Allocation(
            id=s.generate_uuid(), job_id=job.id, job=job,
            name=f"{job.id}.tg[{i}]", node_id=s.generate_uuid(),
            task_resources={"t": s.Resources(cpu=100, memory_mb=128)})
        slab = s.AllocSlab(proto=alloc, ids=s.LazyUuids(8),
                           names=s.LazyNames(8, f"{job.id}.tg"),
                           node_ids=[s.generate_uuid() for _ in range(8)])
        ev = s.Evaluation(id=s.generate_uuid(), job_id=job.id,
                          priority=rng.randrange(1, 100))
        return {"evals": [ev], "allocs": [alloc], "slabs": [slab],
                "job": job, "eval_id": ev.id}

    corpus = [corpus_item(i) for i in range(8)]

    # 1. Round-trip parity with the msgpack path on every item.
    for payload in corpus:
        got = codec.decode(codec.encode(payload))
        if not check(to_wire(got["job"]) == to_wire(payload["job"])
                     and to_wire(got["allocs"]) == to_wire(
                         payload["allocs"])
                     and list(got["slabs"][0].ids)
                     == list(payload["slabs"][0].ids),
                     "round trip diverged from the source payload"):
            return False

    # 2. encode -> corrupt -> decode must reject cleanly.
    rejected = accepted = 0
    for payload in corpus:
        blob = codec.encode(payload)
        cuts = [rng.randrange(1, len(blob)) for _ in range(16)]
        for k in cuts:
            try:
                codec.decode(blob[:k])
                return check(False, f"truncation at {k} was accepted")
            except CodecError:
                rejected += 1
        # Header/tag corruption: magic, version, and a value tag.
        for pos in (0, 1, 2):
            bad = bytearray(blob)
            bad[pos] ^= 0xFF
            try:
                codec.decode(bytes(bad))
                accepted += 1  # content-byte flips may legally decode
            except CodecError:
                rejected += 1
    if not check(rejected > 0, "no corruption was rejected"):
        return False

    # 3. Native/python twin agreement on the seeded column corpus.
    runs_before = cnative.GUARD_RUNS
    saved = knobs.raw("NOMAD_TPU_CODEC_GUARD_EVERY")
    os.environ["NOMAD_TPU_CODEC_GUARD_EVERY"] = "1"
    try:
        for payload in corpus:
            cols = [list(payload["slabs"][0].node_ids),
                    [s.generate_uuid() for _ in range(64)]]
            for col in cols:
                encoded = [x.encode() for x in col]
                py = cnative._py_pack_strs(encoded)
                if not check(cnative.pack_strs(col) == py,
                             "native pack diverged from python twin"):
                    return False
                got, end = cnative.unpack_strs(py, 0, len(col))
                if not check(got == col and end == len(py),
                             "native unpack diverged from python twin"):
                    return False
        if not check(cnative.GUARD_MISMATCHES == 0,
                     "differential guard counted a mismatch"):
            return False
    finally:
        if saved is None:
            os.environ.pop("NOMAD_TPU_CODEC_GUARD_EVERY", None)
        else:
            os.environ["NOMAD_TPU_CODEC_GUARD_EVERY"] = saved
    native_used = cnative._get_lib() is not None and not \
        cnative._native_disabled
    log("codec drill: OK — corpus round-tripped bit-equal, "
        f"{rejected} corruptions rejected cleanly ({accepted} benign "
        "content flips decoded), native/python twins agree "
        f"({'native' if native_used else 'python-twin-only'}, "
        f"{cnative.GUARD_RUNS - runs_before} guarded calls)")
    return True


def follower_drill(seed: int = 0, log=print) -> bool:
    """Follower-read scheduling drill (ISSUE 10): boot a 3-voter
    in-process cluster, pause the leader's LOCAL workers so only
    follower workers can schedule, submit a job, and verify the plan
    was forwarded by a follower, applied by the LEADER's serialized
    plan-apply, and is visible on all three FSMs.  Then the
    lagging-follower streaming-install drill: compact the leader past
    the log horizon with a tiny chunk size and verify a fresh joiner
    catches up via CHUNKED InstallSnapshot."""
    import os
    import time

    from ..server import Server, ServerConfig
    from ..structs import structs as s

    def check(cond, msg):
        if not cond:
            log(f"follower drill: FAIL — {msg}")
        return cond

    def wait_until(pred, timeout=30.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if pred():
                return True
            time.sleep(0.02)
        return pred()

    saved = knobs.raw("NOMAD_TPU_SNAPSHOT_CHUNK")
    servers = []
    fresh = None
    try:
        first = None
        for i in range(3):
            # num_schedulers=0: NO server runs a leader-local worker —
            # follower_schedulers=1 gives each a follower-read worker,
            # so the drill's eval can only complete via the follower
            # path (the leader's own follower worker parks while it
            # leads).
            srv = Server(ServerConfig(
                node_name=f"drill-s{i + 1}", enable_rpc=True,
                bootstrap_expect=3, start_join=[first] if first else [],
                num_schedulers=0, follower_schedulers=1,
                min_heartbeat_ttl=60.0))
            if first is None:
                first = srv.config.rpc_advertise
            servers.append(srv)
        for srv in servers:
            srv.start()
        if not check(wait_until(lambda: any(
                x.is_leader() and x.raft.is_raft_leader()
                for x in servers)), "no leader elected"):
            return False
        leader = next(x for x in servers if x.is_leader())
        followers = [x for x in servers if x is not leader]
        if not check(wait_until(lambda: all(
                len(x.raft.peers) == 3 for x in servers)),
                "voter config did not converge"):
            return False

        node = s.Node(
            id="drill-node", datacenter="dc1", name="drill-node",
            attributes={"kernel.name": "linux", "driver.exec": "1"},
            resources=s.Resources(cpu=4000, memory_mb=8192,
                                  disk_mb=100 * 1024, iops=1000),
            reserved=s.Resources(), status=s.NODE_STATUS_READY)
        leader.node_register(node)
        jid = "drill-job"
        job = s.Job(
            region="global", id=jid, name=jid, type=s.JOB_TYPE_SERVICE,
            priority=50, datacenters=["dc1"],
            task_groups=[s.TaskGroup(
                name="tg", count=2,
                ephemeral_disk=s.EphemeralDisk(size_mb=10),
                tasks=[s.Task(name="t", driver="exec",
                              config={"command": "/bin/date"},
                              resources=s.Resources(cpu=100,
                                                    memory_mb=128),
                              log_config=s.LogConfig())])])
        _, eval_id = leader.job_register(job)
        if not check(wait_until(lambda: (
                (ev := leader.state.eval_by_id(None, eval_id)) is not None
                and ev.status == s.EVAL_STATUS_COMPLETE)),
                "eval did not complete via follower scheduling"):
            return False
        forwarded = sum(f.leader_channel.stats()["ForwardedPlans"]
                        for f in followers)
        if not (check(forwarded >= 1,
                      "no plan was forwarded by a follower")
                and check(wait_until(lambda: all(
                    len(x.state.allocs_by_job(None, jid)) == 2
                    for x in servers)),
                    "placements not visible on every FSM")):
            return False

        # Lagging-follower streaming install: compact the leader past
        # the horizon, then join a FRESH server — with a 1KB chunk
        # ceiling the install must arrive in multiple chunks.
        os.environ["NOMAD_TPU_SNAPSHOT_CHUNK"] = "1024"
        leader.raft.snapshot()
        chunks_before = _counter_total(leader,
                                       "nomad.raft.snapshot.chunks_sent")
        fresh = Server(ServerConfig(
            node_name="drill-fresh", enable_rpc=True, bootstrap_expect=3,
            start_join=[leader.config.rpc_advertise], num_schedulers=0))
        fresh.start()
        if not check(wait_until(lambda: fresh.state.job_by_id(
                None, jid) is not None, timeout=20.0),
                "fresh joiner did not receive the snapshot"):
            return False
        if not check(wait_until(
                lambda: fresh.raft.base_index >= leader.raft.base_index,
                timeout=10.0), "joiner's log base did not advance"):
            return False
        chunks = _counter_total(leader, "nomad.raft.snapshot.chunks_sent")
        if not check(chunks - chunks_before >= 2,
                     f"snapshot was not chunked ({chunks - chunks_before}"
                     " chunks sent)"):
            return False
    finally:
        if saved is None:
            os.environ.pop("NOMAD_TPU_SNAPSHOT_CHUNK", None)
        else:
            os.environ["NOMAD_TPU_SNAPSHOT_CHUNK"] = saved
        if fresh is not None:
            fresh.shutdown()
        for srv in servers:
            srv.shutdown()
    log("follower drill: OK — 3-voter cluster scheduled on a follower "
        f"({forwarded} plan(s) forwarded to the leader's plan-apply, "
        "visible on all FSMs), and a lagging joiner caught up via "
        f"streaming InstallSnapshot ({chunks - chunks_before} chunks)")
    return True


def _counter_total(server, key: str) -> int:
    sink = server.metrics.sink
    if not hasattr(sink, "latest"):
        return 0
    return int((sink.latest().get("CounterTotals") or {}).get(key, 0))


def chaos_drill(seed: int = 0, log=print) -> bool:
    """Cluster chaos drill (ISSUE 12): a 3-voter in-process cluster
    under the safety auditor — partition a follower (both directions
    via the net plane), commit writes it cannot see, verify it lags,
    heal, verify catch-up, and finish with the auditor's converged
    fingerprint cross-check at ZERO violations."""
    import os
    import time

    from .. import fault
    from ..loadgen.auditor import SafetyAuditor
    from ..server import Server, ServerConfig
    from ..server.rpc import ConnPool
    from ..structs import structs as s

    def check(cond, msg):
        if not cond:
            log(f"chaos drill: FAIL — {msg}")
        return cond

    def wait_until(pred, timeout=30.0):
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            if pred():
                return True
            time.sleep(0.02)
        return pred()

    def make_job(jid):
        return s.Job(
            region="global", id=jid, name=jid, type=s.JOB_TYPE_SERVICE,
            priority=50, datacenters=["dc1"],
            task_groups=[s.TaskGroup(
                name="tg", count=1,
                ephemeral_disk=s.EphemeralDisk(size_mb=10),
                tasks=[s.Task(name="t", driver="exec",
                              config={"command": "/bin/date"},
                              resources=s.Resources(cpu=100,
                                                    memory_mb=128),
                              log_config=s.LogConfig())])])

    # Slowed elections: a partitioned VOTER must not campaign during
    # the short split (term inflation would turn the drill into an
    # election-churn test).
    saved = {k: os.environ.get(k) for k in
             ("NOMAD_TPU_RAFT_ELECTION_MIN_S",
              "NOMAD_TPU_RAFT_ELECTION_MAX_S", "NOMAD_TPU_EVENTS")}
    os.environ["NOMAD_TPU_RAFT_ELECTION_MIN_S"] = "8.0"
    os.environ["NOMAD_TPU_RAFT_ELECTION_MAX_S"] = "12.0"
    os.environ["NOMAD_TPU_EVENTS"] = "1"
    servers = []
    auditor = None
    pool = ConnPool()
    pool.chaos_exempt = True
    try:
        first = None
        for i in range(3):
            srv = Server(ServerConfig(
                node_name=f"chaos-s{i + 1}", enable_rpc=True,
                bootstrap_expect=3, start_join=[first] if first else [],
                num_schedulers=0, min_heartbeat_ttl=60.0))
            if first is None:
                first = srv.config.rpc_advertise
            servers.append(srv)
        for srv in servers:
            srv.start()
        if not check(wait_until(lambda: any(
                x.is_leader() and x.raft.is_raft_leader()
                for x in servers)), "no leader elected"):
            return False
        leader = next(x for x in servers if x.is_leader())
        victim = next(x for x in servers if x is not leader)
        if not check(wait_until(lambda: all(
                len(x.raft.peers) == 3 for x in servers)),
                "voter config did not converge"):
            return False

        auditor = SafetyAuditor(
            leader, [x.config.rpc_advertise for x in servers
                     if x is not leader],
            pool=pool, interval=0.25)
        auditor.start()
        leader.job_register(make_job("chaos-pre"))
        if not check(wait_until(lambda: victim.state.job_by_id(
                None, "chaos-pre") is not None),
                "pre-partition write did not replicate"):
            return False

        # Split (both directions: every in-process pool is stamped).
        fault.net_partition("drill", [[leader.config.rpc_advertise],
                                      [victim.config.rpc_advertise]])
        leader.job_register(make_job("chaos-during"))
        time.sleep(0.8)
        if not check(victim.state.job_by_id(None, "chaos-during") is None,
                     "partitioned follower saw a write it cannot have"):
            return False
        fault.net_heal("drill")
        if not check(wait_until(lambda: victim.state.job_by_id(
                None, "chaos-during") is not None, timeout=20.0),
                "healed follower did not catch up"):
            return False
        report = auditor.finalize()
        trace = fault.net().trace()
        if not (check(report["violation_count"] == 0,
                      f"auditor violations: {report['violations']}")
                and check(report["checks"]["fingerprint_matches"] >= 1,
                          "no cross-server fingerprint match recorded")
                and check(("net.partition", "drill", "split") in trace
                          and ("net.partition", "drill", "heal") in trace,
                          f"partition trace incomplete: {trace}")):
            return False
    finally:
        if auditor is not None:
            auditor.stop()
        fault.net_disarm()
        pool.close()
        for srv in servers:
            srv.shutdown()
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    log("chaos drill: OK — partitioned follower blinded then healed and "
        "caught up, auditor recorded 0 violations with "
        f"{report['checks']['fingerprint_matches']} cross-server "
        "fingerprint matches")
    return True


def analysis_drill(seed: int = 0, log=print) -> bool:
    """Invariant-analysis drill (ISSUE 15), three legs:

    1. the static pass is CLEAN on the tree (zero unsuppressed
       violations — the same gate bench --check enforces);
    2. the runtime lock-order sanitizer catches a seeded inversion
       (A→B in one thread, B→A in another ⇒ cycle + witness) and is
       acyclic-silent on the well-ordered control;
    3. the native twin/fuzz corpora run clean under ASan+UBSan
       (graceful skip when the toolchain lacks the sanitizer
       runtimes).
    """
    from ..analysis import run_checks
    from ..native.__main__ import run_sanitized
    from ..utils import lockcheck

    def check(cond, msg):
        if not cond:
            log(f"analysis drill: FAIL — {msg}")
        return bool(cond)

    ok = True
    # 1. lint clean.
    active, suppressed = run_checks()
    ok = check(not active,
               f"static pass found {len(active)} unsuppressed "
               f"violation(s): "
               + "; ".join(v.key for v in active[:4])) and ok

    # 2. seeded lock-order inversion caught, witness printed.
    was_armed = lockcheck.armed()
    if not was_armed:
        lockcheck.arm()
    try:
        lockcheck.reset()
        a = lockcheck.make_tracked("drill:lock_a")
        b = lockcheck.make_tracked("drill:lock_b")
        with a:
            with b:
                pass
        ok = check(lockcheck.find_cycle() is None,
                   "well-ordered acquisitions reported a cycle") and ok
        import threading as _threading

        def invert():
            with b:
                with a:
                    pass

        t = _threading.Thread(target=invert, name="drill-invert")
        t.start()
        t.join(5)
        cycle = lockcheck.find_cycle()
        ok = check(cycle is not None,
                   "seeded A→B / B→A inversion not detected") and ok
        if cycle is not None:
            caught = False
            try:
                lockcheck.assert_acyclic()
            except lockcheck.LockOrderError as exc:
                caught = ("drill:lock_a" in str(exc)
                          and "drill:lock_b" in str(exc))
            ok = check(caught, "witness chain missing the seeded "
                               "locks") and ok
    finally:
        lockcheck.reset()
        if not was_armed:
            lockcheck.disarm()

    # 3. sanitized native corpus.
    verdict = run_sanitized(seed=seed, log=log)
    if verdict == "skip":
        log("analysis drill: ASan corpus leg SKIPPED (no sanitizer "
            "toolchain)")
    else:
        ok = check(verdict == "ok", verdict) and ok

    if ok:
        log("analysis drill: OK — lint clean, seeded inversion caught "
            "with witness, sanitized native corpus "
            + ("skipped" if verdict == "skip" else "clean"))
    return ok


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="python -m nomad_tpu.ops")
    parser.add_argument("--selfcheck", action="store_true",
                        help="run the oracle-vs-kernel agreement checks")
    parser.add_argument("--mesh-drill-child", action="store_true",
                        help=argparse.SUPPRESS)  # subprocess entry
    parser.add_argument("--nodes", type=int, default=64)
    parser.add_argument("--specs", type=int, default=64)
    parser.add_argument("--seed", type=int, default=0)
    args = parser.parse_args(argv)
    if args.mesh_drill_child:
        import jax

        # The environment may pre-import jax pinning the platform; the
        # env var alone is ignored after that (see __graft_entry__).
        jax.config.update("jax_platforms", "cpu")
        return 0 if mesh_drill_child(seed=args.seed) else 1
    if not args.selfcheck:
        parser.print_help()
        return 2
    ok = selfcheck(n_nodes=args.nodes, n_specs=args.specs, seed=args.seed)
    ok = breaker_drill(seed=args.seed) and ok
    ok = tracing_drill(seed=args.seed) and ok
    ok = residency_drill(seed=args.seed) and ok
    ok = columnar_drill(seed=args.seed) and ok
    ok = codec_drill(seed=args.seed) and ok
    ok = wal_drill(seed=args.seed) and ok
    ok = fused_drill(seed=args.seed) and ok
    ok = residue_drill(seed=args.seed) and ok
    ok = follower_drill(seed=args.seed) and ok
    ok = chaos_drill(seed=args.seed) and ok
    ok = mesh_drill(seed=args.seed) and ok
    ok = analysis_drill(seed=args.seed) and ok
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
