"""`python -m nomad_tpu.ops --selfcheck`: fast oracle/kernel agreement
checks runnable without a test harness (CI smoke; seconds on CPU).

Currently covers the preemption subsystem: the batched eviction-set
kernel (ops/preempt.py) must produce exactly the oracle's
(scheduler/preempt.py) eviction set for every (task-group, node) pair
of a seeded random 64x64 cluster.
"""
from __future__ import annotations

import argparse
import sys

from .preempt import selfcheck


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="python -m nomad_tpu.ops")
    parser.add_argument("--selfcheck", action="store_true",
                        help="run the oracle-vs-kernel agreement checks")
    parser.add_argument("--nodes", type=int, default=64)
    parser.add_argument("--specs", type=int, default=64)
    parser.add_argument("--seed", type=int, default=0)
    args = parser.parse_args(argv)
    if not args.selfcheck:
        parser.print_help()
        return 2
    ok = selfcheck(n_nodes=args.nodes, n_specs=args.specs, seed=args.seed)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
