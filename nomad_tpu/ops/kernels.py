"""TPU batch-scheduling kernels: vectorized feasibility + scoring +
round-based placement (SURVEY.md §7 steps 2-3).

Re-derivation of the reference iterator chain (scheduler/stack.go:37) as
masked tensor ops:

- feasibility  F[U,N] = AND_k check(op_k)  — ConstraintChecker/DriverChecker
  (feasible.go:355,92) as integer compares over ordered-interned codes,
  AND'ed with host-precomputed rows for version/regex/set_contains.
- scoring      S[U,N] = score_fit(used+ask) − penalty·collisions
  — BinPackIterator + JobAntiAffinityIterator (rank.go:130,247) as one fused
  elementwise expression over the whole matrix.
- placement    iterative masked rank-and-commit loop with capacity feedback
  — the only sequential part (≤count iterations per spec); anti-affinity
  (20 > max binpack 18) means at most one alloc of a job lands per node per
  round, so each round places min(count, feasible) allocs per spec.

Everything is jittable; no data-dependent Python control flow
(lax.while_loop / lax.scan / lax.fori_loop only), static shapes from the
padded encodings in ops/encode.py.
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .encode import (
    MISSING,
    OP_EQ,
    OP_GE,
    OP_GT,
    OP_LE,
    OP_LT,
    OP_NE,
    OP_PRECOMP,
    OP_TRUE,
    UNKNOWN_RHS,
)

NEG_INF = -1e30

# -- compile-cache audit (ISSUE 13) -----------------------------------------
#
# Recompiles are the silent killer at 10M nodes: one stray shape bucket
# costs tens of seconds of XLA time.  Every distinct static signature the
# placement programs are invoked with is recorded here — a new signature
# is (at most) one fresh XLA compile, an old one is a guaranteed cache
# hit — so `compile_signatures()` is an upper bound on placement-program
# compiles that bench `--check` can assert a ceiling on (config_steady's
# 200-batch stream must stay within a fixed handful of shapes).
_COMPILE_SIGS = set()
COMPILES = 0


def note_signature(kind: str, sig: tuple) -> bool:
    """Record one program invocation signature; True when it is new
    (i.e. this call may trigger an XLA compile)."""
    global COMPILES
    key = (kind, sig)
    if key in _COMPILE_SIGS:
        return False
    _COMPILE_SIGS.add(key)
    COMPILES += 1
    return True


def compile_signatures() -> int:
    return COMPILES


def signature_kinds() -> dict:
    """Distinct recorded signatures per program kind — the debugging
    view behind the `batch.compiles` gauge: when a bench compile
    ceiling trips, this names WHICH program family leaked shapes."""
    out: dict = {}
    for kind, _sig in _COMPILE_SIGS:
        out[kind] = out.get(kind, 0) + 1
    return out


def reset_compile_signatures() -> None:
    """Test/bench helper: zero the audit (does NOT clear jit caches)."""
    global COMPILES
    _COMPILE_SIGS.clear()
    COMPILES = 0


def jitter_seed(rng_key: jnp.ndarray) -> jnp.ndarray:
    """One uint32 tie-break seed from a PRNG key (a single scalar draw;
    the per-(u, n) values come from the counter-based hash below)."""
    return jax.random.bits(rng_key, (), jnp.uint32)


def tie_jitter(seed: jnp.ndarray, u: jnp.ndarray,
               node_idx: jnp.ndarray) -> jnp.ndarray:
    """Deterministic per-(spec, node) tie-break jitter in [0, 1e-3).

    murmur3-style integer mix (fmix32) over (seed, u, node index): ~6
    integer ops per element versus ~48 for threefry — the full-matrix
    ``jax.random.uniform([U, N])`` this replaced cost 2.6s and a 256MB
    HBM buffer at the 1024x65536 mega-batch shape, dominating the whole
    device pass; now each committing spec hashes only its own row.

    Keyed on the GLOBAL node index, so a node shard computing its slice
    (parallel/sharded.py) gets bit-identical values to the single-chip
    kernel.  Decorrelates ties exactly like the reference's node
    shuffling (util.go:325) — magnitude too small to reorder materially
    different scores; avalanche quality is ample for tie-breaking.
    """
    x = (node_idx.astype(jnp.uint32) * jnp.uint32(0x9E3779B9)
         + u.astype(jnp.uint32) * jnp.uint32(0x85EBCA6B) + seed)
    x = x ^ (x >> 16)
    x = x * jnp.uint32(0x85EBCA6B)
    x = x ^ (x >> 13)
    x = x * jnp.uint32(0xC2B2AE35)
    x = x ^ (x >> 16)
    return (x >> 8).astype(jnp.float32) * jnp.float32(1e-3 / (1 << 24))


def _byte_histogram_dense(cand: jnp.ndarray, byte: jnp.ndarray
                          ) -> jnp.ndarray:
    """hist[b] = #cand nodes whose current byte == b, as a [256, N]
    compare-and-reduce with N minor — a dense VPU reduction, the right
    shape for the TPU's lane-parallel units (no scatter, which the TPU
    backend serializes)."""
    bins = jnp.arange(256, dtype=jnp.uint32)
    return jnp.sum(cand[None, :] & (byte[None, :] == bins[:, None]),
                   axis=1, dtype=jnp.int32)


def _byte_histogram_scatter(cand: jnp.ndarray, byte: jnp.ndarray
                            ) -> jnp.ndarray:
    """Same histogram as a 256-bin scatter-add: N index-adds instead of
    256·N compares — 55x faster than the dense form on the CPU backend
    (measured 1.9ms vs 105ms per 4-pass select at N=65536), where
    scatter lowers to efficient serial stores."""
    return jnp.zeros(256, dtype=jnp.int32).at[byte.astype(jnp.int32)].add(
        cand.astype(jnp.int32))


def _byte_histogram(cand: jnp.ndarray, byte: jnp.ndarray) -> jnp.ndarray:
    """Backend-dispatched at trace time (jit caches are per-backend, so
    the choice is consistent for the lifetime of a compiled program).
    Both forms are exact, so placements are bit-identical either way —
    pinned by tests/test_tpu_kernels.py."""
    from ..utils.platform import is_tpu_platform

    if is_tpu_platform(jax.default_backend()):
        return _byte_histogram_dense(cand, byte)
    return _byte_histogram_scatter(cand, byte)


def _select_top_k(scored: jnp.ndarray, ok: jnp.ndarray,
                  k: jnp.ndarray) -> jnp.ndarray:
    """Boolean mask of the k highest-scored ok nodes, without a sort.

    Exact radix-quantile select on the monotone bit-space image of f32:
    IEEE-754 floats map to uint32 such that float order == unsigned
    order (set the sign bit for non-negatives, invert negatives), then
    the k-th largest value T is found byte-by-byte — 4 histogram passes
    (dense compare-and-reduce on TPU, scatter-add on CPU; see
    _byte_histogram), versus the 45 sequential threshold-bisection
    reduce passes this replaced (each a loop-carried [N] pass — latency-
    bound at ~2.7ms/select, the dominant device cost at N ≈ 50k).

    Selection is exact: nodes strictly above T are taken outright and
    the == T band fills in node-index order (cumsum), the same tie order
    a stable argsort over (-score) yields — so placements are
    bit-identical to both the argsort and bisection kernels, which the
    oracle/sharded differential tests pin down.
    """
    bits = lax.bitcast_convert_type(scored, jnp.uint32)
    ordered = jnp.where((bits >> 31) == 0,
                        bits | jnp.uint32(0x80000000), ~bits)
    bins_i = jnp.arange(256, dtype=jnp.int32)

    def radix_pass(cand, byte, above):
        hist = _byte_histogram(cand, byte)
        cnt_ge = above + jnp.cumsum(hist[::-1])[::-1]
        # cnt_ge is non-increasing in b and cnt_ge[0] >= k (the top-k all
        # carry the known prefix or better), so the threshold byte is the
        # last b with cnt_ge[b] >= k.
        t_b = jnp.sum((cnt_ge >= k).astype(jnp.int32)) - 1
        above = above + jnp.sum(jnp.where(bins_i > t_b, hist, 0))
        return t_b.astype(jnp.uint32), above

    above = jnp.int32(0)
    t1, above = radix_pass(ok, ordered >> 24, above)
    cand = ok & ((ordered >> 24) == t1)
    t2, above = radix_pass(cand, (ordered >> 16) & 0xFF, above)
    p16 = (t1 << 8) | t2
    cand = ok & ((ordered >> 16) == p16)
    t3, above = radix_pass(cand, (ordered >> 8) & 0xFF, above)
    p24 = (p16 << 8) | t3
    cand = ok & ((ordered >> 8) == p24)
    t4, above = radix_pass(cand, ordered & 0xFF, above)
    thresh = (p24 << 8) | t4

    # T is exactly the k-th largest ok value; `above` (< k) of the ok
    # nodes are strictly greater.  Fill the remainder from the == T band
    # in node-index order.  (A lax.cond skipping the cumsum when the
    # band exactly fills the need measured SLOWER end-to-end — the cond
    # breaks fusion; keep the straight-line form.)
    sel_gt = ok & (ordered > thresh)
    band = ok & (ordered == thresh)
    need = k - jnp.sum(sel_gt.astype(jnp.int32))
    csum = jnp.cumsum(band.astype(jnp.int32))
    return sel_gt | (band & (csum <= need))


@functools.partial(jax.jit, static_argnames=())
def feasibility_matrix(
    attr_values: jnp.ndarray,   # [N, K] int32 ordered codes, -1 missing
    eligible: jnp.ndarray,      # [N] bool
    dc_code: jnp.ndarray,       # [N] int32
    c_attr: jnp.ndarray,        # [U, Kc] int32 column index
    c_op: jnp.ndarray,          # [U, Kc] int32 op code
    c_rhs: jnp.ndarray,         # [U, Kc] int32 rhs code
    dc_mask: jnp.ndarray,       # [U, D] bool
    precomp: jnp.ndarray,       # [U, N] bool
) -> jnp.ndarray:
    """F[U, N]: static feasibility of spec u on node n.

    Scans over the (small) constraint axis, ANDing one vectorized compare at
    a time — peak memory stays at one [U, N] buffer.
    """
    n = attr_values.shape[0]
    u = c_attr.shape[0]
    kc = c_attr.shape[1]

    # Datacenter membership (readyNodesInDCs, util.go:224): gather each
    # node's dc bit from the spec's allowed-DC mask.
    dc_ok = jnp.take_along_axis(
        dc_mask, jnp.broadcast_to(dc_code[None, :], (u, n)), axis=1
    )  # [U, N]

    init = precomp & dc_ok & eligible[None, :]

    def body(carry, k):
        attr_col = c_attr[:, k]                       # [U]
        vals = attr_values[:, attr_col].T             # [U, N]
        rhs = c_rhs[:, k][:, None]                    # [U, 1]
        op = c_op[:, k][:, None]                      # [U, 1]

        missing = vals == MISSING
        unknown_rhs = rhs == UNKNOWN_RHS

        ok = jnp.where(op == OP_EQ, (vals == rhs) & ~unknown_rhs,
             jnp.where(op == OP_NE, (vals != rhs) | unknown_rhs,
             jnp.where(op == OP_LT, vals < rhs,
             jnp.where(op == OP_LE, vals <= rhs,
             jnp.where(op == OP_GT, vals > rhs,
             jnp.where(op == OP_GE, vals >= rhs,
                       jnp.ones_like(vals, dtype=bool)))))))
        # A missing LHS fails any real constraint (resolveConstraintTarget
        # returns !ok, feasible.go:383-391); OP_TRUE padding passes.
        ok = jnp.where(op == OP_TRUE, True, ok & ~missing)
        return carry & ok, None

    f, _ = lax.scan(body, init, jnp.arange(kc))
    return f


def _pow10(x: jnp.ndarray) -> jnp.ndarray:
    """10^x for the scoring sites.  Measured end-to-end, jnp.power with
    a constant base is NOT the bottleneck XLA's fusion makes it look
    like in isolation — an exp(x·ln10) rewrite benchmarked 8.7x faster
    standalone but REGRESSED the full placement program ~40% (fusion
    changed); keep the direct form and benchmark end-to-end before
    touching this again."""
    return jnp.power(10.0, x)


def _score_fit(
    used: jnp.ndarray,         # [N, 4] int32 — current usage incl. reserved
    ask: jnp.ndarray,          # [4] int32
    denom: jnp.ndarray,        # [N, 2] float32 — cpu/mem capacity minus reserved
) -> jnp.ndarray:
    """Google best-fit-v3 over all nodes at once (funcs.go:123 ScoreFit):
    20 − (10^freeCpuFrac + 10^freeMemFrac), clamped to [0, 18]."""
    after = used[:, :2].astype(jnp.float32) + ask[:2].astype(jnp.float32)
    safe_denom = jnp.where(denom == 0.0, 1.0, denom)
    frac = 1.0 - after / safe_denom
    frac = jnp.where(denom == 0.0, -jnp.inf, frac)
    total = _pow10(frac[:, 0]) + _pow10(frac[:, 1])
    score = 20.0 - total
    score = jnp.nan_to_num(score, nan=0.0, posinf=18.0, neginf=0.0)
    return jnp.clip(score, 0.0, 18.0)


class PlacementResult(NamedTuple):
    placements: jnp.ndarray   # [U, N] int32 — allocs of spec u committed on node n
    unplaced: jnp.ndarray     # [U] int32 — counts that found no feasible node
    used_after: jnp.ndarray   # [N, 4] int32 — final node usage
    rounds: jnp.ndarray       # [] int32
    # AllocMetric side-outputs (structs.go:4074 contract): the PURE
    # binpack score (rank.go:138 score_node "binpack") and the job
    # collision count at commit time — the host derives the separate
    # "job-anti-affinity" score entry from the latter (rank.go:167).
    commit_scores: jnp.ndarray = None      # [U, N] float32
    commit_collisions: jnp.ndarray = None  # [U, N] int32
    # Compact slot record (slot_m > 0): slots[u, j] = node index of spec
    # u's j-th committed alloc, appended in commit order — the COO
    # payload is built from THIS (one pass over U×M cells) instead of a
    # nonzero/compaction pass over the [U, N] matrix (measured 0.5s at
    # the 1024×10048 north-star shape vs ~50ms from slots).  -1 padding
    # beyond each spec's placed count.
    slots: jnp.ndarray = None              # [U, M] int32
    # Commit-aligned score side-outputs (slot_m > 0 AND with_scores):
    # the binpack score / collision count of each slot's commit — the
    # [U, N] commit_scores/commit_collisions carries compile away
    # entirely in this mode.
    slot_scores: jnp.ndarray = None        # [U, M] float32
    slot_coll: jnp.ndarray = None          # [U, M] int32


class NetTensors(NamedTuple):
    """Per-spec network asks + per-node port/bandwidth state
    (SURVEY §7 hard-part iii; reference rank.go:190-238 + network.go)."""

    active: jnp.ndarray      # [U] bool
    mbits: jnp.ndarray       # [U] int32
    dyn_need: jnp.ndarray    # [U] int32 — dynamic ports + reserved-in-dyn-range
    resv_words: jnp.ndarray  # [U, W] uint32 — reserved-port bitmask
    bw_cap: jnp.ndarray      # [N] int32
    bw_used: jnp.ndarray     # [N] int32
    dyn_free: jnp.ndarray    # [N] int32
    port_words: jnp.ndarray  # [N, W] uint32 — node used-port bitmaps


class DPTensors(NamedTuple):
    """distinct_property state (propertyset.go:11): per-spec property
    column + used-value-code bitsets."""

    col: jnp.ndarray         # [U] int32 — attr column, -1 = none
    active: jnp.ndarray      # [U] bool
    used0: jnp.ndarray       # [U, V] bool
    attr_values: jnp.ndarray  # [N, K] int32 — node attribute codes


def _disabled_net(u_pad: int, n_pad: int) -> NetTensors:
    # Size-1 placeholders: with use_net=False the kernel never touches
    # these (python-level `if`, not jnp.where), so they only exist to
    # keep the carry pytree structure stable.
    return NetTensors(
        active=jnp.zeros(1, dtype=bool),
        mbits=jnp.zeros(1, dtype=jnp.int32),
        dyn_need=jnp.zeros(1, dtype=jnp.int32),
        resv_words=jnp.zeros((1, 1), dtype=jnp.uint32),
        bw_cap=jnp.zeros(1, dtype=jnp.int32),
        bw_used=jnp.zeros(1, dtype=jnp.int32),
        dyn_free=jnp.zeros(1, dtype=jnp.int32),
        port_words=jnp.zeros((1, 1), dtype=jnp.uint32),
    )


def _disabled_dp(u_pad: int, n_pad: int) -> DPTensors:
    return DPTensors(
        col=jnp.full(1, -1, dtype=jnp.int32),
        active=jnp.zeros(1, dtype=bool),
        used0=jnp.zeros((1, 1), dtype=bool),
        attr_values=jnp.full((1, 1), MISSING, dtype=jnp.int32),
    )


def placement_rounds(
    feas: jnp.ndarray,         # [U, N] bool — static feasibility
    used0: jnp.ndarray,        # [N, 4] int32 — usage incl. reserved
    capacity: jnp.ndarray,     # [N, 4] int32
    denom: jnp.ndarray,        # [N, 2] float32
    ask: jnp.ndarray,          # [U, 4] int32
    count: jnp.ndarray,        # [U] int32
    penalty: jnp.ndarray,      # [U] float32
    distinct_hosts: jnp.ndarray,  # [U] bool
    job_index: jnp.ndarray,    # [U] int32 → row in job_counts
    job_counts0: jnp.ndarray,  # [J, N] int32 — existing allocs per (job, node)
    rng_key: jnp.ndarray,
    max_rounds: int = 256,
    net: "NetTensors" = None,
    dp: "DPTensors" = None,
    with_scores: bool = True,
    slot_m: int = 0,
) -> PlacementResult:
    """The sequential heart of the batch scheduler (see
    ``_placement_rounds_impl``).  ``net``/``dp`` default to None, which
    statically compiles the network/distinct_property code OUT of the
    program (a disabled-but-present path still costs per-spec gathers
    and scatters inside the scan).  ``with_scores=False`` drops the
    [U, N] commit-score/collision side-outputs (mega-batch shapes: two
    extra carry buffers of that size cost real HBM and compile time;
    counts in the result stay exact)."""
    u_pad, n_pad = feas.shape
    use_net = net is not None
    use_dp = dp is not None
    if net is None:
        net = _disabled_net(u_pad, n_pad)
    if dp is None:
        dp = _disabled_dp(u_pad, n_pad)
    return _placement_rounds_impl(
        feas, used0, capacity, denom, ask, count, penalty, distinct_hosts,
        job_index, job_counts0, rng_key, net, dp, max_rounds=max_rounds,
        with_scores=with_scores, use_net=use_net, use_dp=use_dp,
        slot_m=slot_m)


@functools.partial(jax.jit, static_argnames=("max_rounds", "with_scores",
                                             "use_net", "use_dp", "slot_m"))
def _placement_rounds_impl(
    feas: jnp.ndarray,
    used0: jnp.ndarray,
    capacity: jnp.ndarray,
    denom: jnp.ndarray,
    ask: jnp.ndarray,
    count: jnp.ndarray,
    penalty: jnp.ndarray,
    distinct_hosts: jnp.ndarray,
    job_index: jnp.ndarray,
    job_counts0: jnp.ndarray,
    rng_key: jnp.ndarray,
    net: NetTensors,
    dp: DPTensors,
    max_rounds: int = 256,
    with_scores: bool = True,
    use_net: bool = False,
    use_dp: bool = False,
    slot_m: int = 0,
) -> PlacementResult:
    """The sequential heart of the batch scheduler.

    Each round scans specs in order (host pre-sorts by priority desc — the
    broker's priority heap, eval_broker.go:43); a spec places at most one
    alloc per node per round (justified by the anti-affinity penalty: a
    second same-job alloc on a node scores ≤ −2, below any empty feasible
    node), committing to its top-k scored nodes under remaining capacity.
    Loop exits when a round makes no progress (capacity exhausted or all
    placed).

    Network accounting per (spec, node): bandwidth fit, reserved-port
    bitmap conflict, and dynamic-port-capacity checks, with commit updates
    to all three (rank.go:190-238; concrete dynamic port *values* are
    assigned host-side at finalize, which device-side capacity accounting
    makes safe).  distinct_property: a per-spec used-value bitset masks
    feasibility; a within-round scatter-min keeps only the best-ranked
    node per property value (propertyset.go:150).
    """
    u_pad, n_pad = feas.shape
    v_pad = dp.used0.shape[1]

    jit_seed = jitter_seed(rng_key)
    node_idx = jnp.arange(n_pad, dtype=jnp.int32)
    big_idx = jnp.int32(n_pad + 1)

    def place_one_spec(carry, u):
        def try_place(carry):
            (used, job_counts, remaining_count, placements,
             bw_used, port_words, dyn_free, dp_used, commit_scores,
             commit_coll, slots, slot_scores, slot_coll) = carry

            cap_left = capacity - used                       # [N, 4]
            fits = jnp.all(ask[u][None, :] <= cap_left, axis=1)
            collisions = job_counts[job_index[u]]            # [N] int32
            ok = feas[u] & fits
            ok = ok & jnp.where(distinct_hosts[u], collisions == 0, True)

            # Network feasibility (bandwidth + reserved conflicts +
            # dynamic capacity); statically absent when the batch has no
            # network asks.
            if use_net:
                bw_ok = bw_used + net.mbits[u] <= net.bw_cap
                resv_hit = jnp.any(
                    (port_words & net.resv_words[u][None, :]) != 0, axis=1)
                dyn_ok = dyn_free >= net.dyn_need[u]
                ok = ok & jnp.where(net.active[u],
                                    bw_ok & ~resv_hit & dyn_ok, True)

            # distinct_property feasibility: node must have the property
            # and its value must be unused (propertyset.go:150).
            if use_dp:
                col = jnp.clip(dp.col[u], 0, dp.attr_values.shape[1] - 1)
                codes = dp.attr_values[:, col]                    # [N]
                code_c = jnp.clip(codes, 0, v_pad - 1)
                dp_ok = (codes != MISSING) & ~dp_used[u, code_c]
                ok = ok & jnp.where(dp.active[u], dp_ok, True)
            else:
                code_c = None

            # Commit the top-k scored nodes (k = remaining count, bounded
            # by feasible nodes) — one alloc per node this round.
            k = jnp.minimum(remaining_count[u],
                            jnp.sum(ok).astype(jnp.int32))
            return lax.cond(k > 0, lambda c: commit(c, ok, collisions,
                                                    code_c, k),
                            skip, carry)

        def commit(carry, ok, collisions, code_c, k):
            (used, job_counts, remaining_count, placements,
             bw_used, port_words, dyn_free, dp_used, commit_scores,
             commit_coll, slots, slot_scores, slot_coll) = carry
            base_score = _score_fit(used, ask[u], denom)
            score = base_score - penalty[u] * collisions.astype(jnp.float32)
            score = score + tie_jitter(jit_seed, u, node_idx)
            scored = jnp.where(ok, score, NEG_INF)

            # Threshold bisection instead of a full argsort: same
            # selection, same tie order, ~100x less device work at N≈50k.
            sel = _select_top_k(scored, ok, k)

            # Within-round value dedup for distinct_property: among
            # selected nodes sharing a property value, keep only the
            # best-scored (ties by lowest node index — stable-sort order).
            if use_dp:
                sel_score = jnp.where(sel, scored, jnp.float32(NEG_INF))
                best_per_code = jnp.full(v_pad, NEG_INF, dtype=jnp.float32
                                         ).at[code_c].max(sel_score)
                cand_dp = sel & (sel_score >= best_per_code[code_c])
                best_idx = jnp.full(v_pad, big_idx, dtype=jnp.int32
                                    ).at[code_c].min(
                    jnp.where(cand_dp, node_idx, big_idx))
                keep_dp = cand_dp & (node_idx == best_idx[code_c])
                sel = jnp.where(dp.active[u], keep_dp, sel)

            sel_i = sel.astype(jnp.int32)
            placed = jnp.sum(sel_i)
            used = used + sel_i[:, None] * ask[u][None, :]
            job_counts = job_counts.at[job_index[u]].add(sel_i)
            if not slot_m:
                # The dense [U, N] placement matrix only feeds the
                # matrix-form compaction; in slot mode the slot record
                # IS the placement output, so the carry compiles away.
                placements = placements.at[u].add(sel_i)

            if slot_m:
                # Compact slot record: append this commit's node indices
                # to spec u's slot row in ascending-node order — the COO
                # payload is built from this, no nonzero pass later.
                pos = jnp.cumsum(sel.astype(jnp.int32))
                offset = count[u] - remaining_count[u]  # placed so far
                dest = jnp.where(sel, offset + pos - 1, jnp.int32(slot_m))
                slots = slots.at[u, dest].set(node_idx, mode="drop")
                if with_scores:
                    # Commit-aligned score record: same dest scatter, so
                    # the [U, N] score carries below compile away.
                    slot_scores = slot_scores.at[u, dest].set(
                        base_score, mode="drop")
                    slot_coll = slot_coll.at[u, dest].set(
                        collisions, mode="drop")

            remaining_count = remaining_count.at[u].add(-placed)

            if use_net:
                commit_net = net.active[u]
                bw_used = bw_used + jnp.where(commit_net,
                                              sel_i * net.mbits[u], 0)
                port_words = jnp.where(
                    (commit_net & sel)[:, None],
                    port_words | net.resv_words[u][None, :], port_words)
                dyn_free = dyn_free - jnp.where(commit_net,
                                                sel_i * net.dyn_need[u], 0)
            if use_dp:
                dp_upd = jnp.zeros(v_pad, dtype=bool).at[code_c].max(
                    sel & dp.active[u])
                dp_used = dp_used.at[u].set(dp_used[u] | dp_upd)
            # Commit-time AllocMetric side-outputs: pure binpack score and
            # the collision count behind any anti-affinity penalty.
            if with_scores and not slot_m:
                commit_scores = commit_scores.at[u].set(jnp.where(
                    sel, base_score, commit_scores[u]))
                commit_coll = commit_coll.at[u].set(jnp.where(
                    sel, collisions, commit_coll[u]))
            return (used, job_counts, remaining_count, placements,
                    bw_used, port_words, dyn_free, dp_used,
                    commit_scores, commit_coll, slots, slot_scores,
                    slot_coll), placed

        def skip(carry):
            return carry, jnp.int32(0)

        # Two-level skip, both REAL branches on TPU (the scan over specs
        # is sequential, not vmapped, so lax.cond doesn't get batched
        # into a select):
        #  - outer: remaining_count[u] == 0 (spec fully placed) skips
        #    even the feasibility/fit prefix — a scalar test, so placed
        #    specs cost nothing in later rounds;
        #  - inner (in try_place): k == 0 (no feasible node under
        #    remaining capacity) skips the scoring transcendentals and
        #    the top-k select.
        # Neither branch commits anything, so placements stay
        # bit-identical to the unguarded kernel.
        return lax.cond(carry[2][u] > 0, try_place, skip, carry)

    def round_body(state):
        (used, job_counts, remaining_count, placements,
         bw_used, port_words, dyn_free, dp_used, commit_scores,
         commit_coll, slots, slot_scores, slot_coll, _, rounds) = state
        carry, placed = lax.scan(
            place_one_spec,
            (used, job_counts, remaining_count, placements,
             bw_used, port_words, dyn_free, dp_used, commit_scores,
             commit_coll, slots, slot_scores, slot_coll),
            jnp.arange(u_pad),
        )
        (used, job_counts, remaining_count, placements,
         bw_used, port_words, dyn_free, dp_used, commit_scores,
         commit_coll, slots, slot_scores, slot_coll) = carry
        progress = jnp.sum(placed)
        return (used, job_counts, remaining_count, placements,
                bw_used, port_words, dyn_free, dp_used, commit_scores,
                commit_coll, slots, slot_scores, slot_coll, progress,
                rounds + 1)

    def round_cond(state):
        used = state[0]
        remaining_count = state[2]
        progress = state[13]
        rounds = state[14]
        go = ((progress > 0) & (jnp.sum(remaining_count) > 0)
              & (rounds < max_rounds))
        # Capacity early-exit: if no node can fit even the SMALLEST
        # remaining ask (dimension-wise lower bound), no spec can place
        # anything, so the round would only burn one feasibility prefix
        # per active spec to discover no progress.  This turns the
        # always-paid final no-progress round into one [N, 4] pass.
        # Necessary-condition only (net/dp/constraints are stricter), so
        # placements are unchanged.
        active = remaining_count > 0
        min_ask = jnp.min(jnp.where(active[:, None], ask,
                                    jnp.int32(2**30)), axis=0)
        fits_any = jnp.any(jnp.all(min_ask[None, :] <= capacity - used,
                                   axis=1))
        return go & fits_any

    placements0 = jnp.zeros((u_pad, n_pad) if not slot_m else (1, 1),
                            dtype=jnp.int32)
    # Matrix-form score carries only when scores are wanted AND no slot
    # record exists (slot mode carries commit-aligned [U, M] scores
    # instead — two dense [U, N] buffers cheaper).
    score_shape = ((u_pad, n_pad) if with_scores and not slot_m
                   else (1, 1))
    scores0 = jnp.zeros(score_shape, dtype=jnp.float32)
    coll0 = jnp.zeros(score_shape, dtype=jnp.int32)
    slots0 = jnp.full((u_pad, slot_m) if slot_m else (1, 1), -1,
                      dtype=jnp.int32)
    sscore_shape = (u_pad, slot_m) if with_scores and slot_m else (1, 1)
    sscores0 = jnp.zeros(sscore_shape, dtype=jnp.float32)
    scoll0 = jnp.zeros(sscore_shape, dtype=jnp.int32)
    state = (used0, job_counts0, count, placements0,
             net.bw_used, net.port_words, net.dyn_free, dp.used0, scores0,
             coll0, slots0, sscores0, scoll0,
             jnp.array(1, dtype=jnp.int32), jnp.array(0, dtype=jnp.int32))
    (used, job_counts, remaining, placements,
     _bw, _pw, _df, _dpu, commit_scores, commit_coll, slots, slot_scores,
     slot_coll, _, rounds) = lax.while_loop(round_cond, round_body, state)

    return PlacementResult(
        placements=placements,
        unplaced=remaining,
        used_after=used,
        rounds=rounds,
        commit_scores=commit_scores,
        commit_collisions=commit_coll,
        slots=slots,
        slot_scores=slot_scores,
        slot_coll=slot_coll,
    )


def summary_layout(u_pad: int, n_pad: int):
    """Layout of the packed device→host summary buffer (shared contract
    between device_pass and its caller; see ops/xfer.py layout()).

    used_after is deliberately NOT shipped: [n_pad, 4] int32 is ~1MB at
    50k nodes and the tunneled link runs at single-digit MB/s — the host
    reconstructs it exactly from used0 + the COO placements × asks (see
    batch_sched._place_on_device), so the summary stays a few KB."""
    from . import xfer

    return xfer.layout({
        "unplaced": ("i32", (u_pad,)),
        "feas_count": ("i32", (u_pad,)),
        "scalars": ("i32", (2,)),       # [nnz, rounds]
    })


@functools.partial(jax.jit, static_argnames=(
    "meta_s", "meta_d", "u_pad", "n_pad", "with_networks", "with_dp",
    "with_scores", "max_rounds", "slot_m", "use_used_dev"),
    donate_argnums=(2,))
def _device_schedule(
    static_buf: jnp.ndarray,          # packed uint8, device-cached (xfer)
    dyn_buf: jnp.ndarray,             # packed uint8, per-batch upload
    used_dev: jnp.ndarray,            # [n_pad, 4] int32 DONATED mirror
    *,
    meta_s,
    meta_d,
    u_pad: int,
    n_pad: int,
    with_networks: bool,
    with_dp: bool,
    with_scores: bool,
    max_rounds: int = 256,
    slot_m: int = 0,
    use_used_dev: bool = False,
):
    """Dispatch 1: unpack + feasibility + placement rounds.

    The upload is split so the link carries only what changed: the
    static cluster buffer (attr/elig/dc/cap/denom + network baselines —
    the multi-MB part) is uploaded once per fleet state and cached as a
    device array by the caller; the per-batch dynamic buffer holds the
    U-sized spec tensors plus SPARSE alloc-usage deltas scattered onto
    the static baselines here.

    ``use_used_dev``: the usage matrix arrives as the DONATED
    device-resident mirror (ops/resident.py keeps it caught up in place
    via donated scatter-adds) instead of baseline+deltas — no per-batch
    usage upload, no materialized sum, and the caller gets the aliased
    array back to return to the resident slot.  With it off the donated
    slot is a [1, 4] dummy."""
    from . import xfer

    d = xfer.unpack_device(static_buf, meta_s)
    d.update(xfer.unpack_device(dyn_buf, meta_d))
    # Quantized resource rows (ops/encode.py quantize_resource_rows):
    # the static buffer carries int16/int8 capacity + used-baseline plus
    # a [2, 4] per-matrix, per-dimension power-of-two scale codebook
    # (row 0 capacity, row 1 used); dequantization is one exact integer
    # multiply, so the placement math below is bit-identical to the
    # int32 path.  Keyed on the (static) meta, so the branch specializes
    # at trace time.
    if "res_scale" in d:
        scale = d.pop("res_scale")
        d["cap"] = d.pop("cap_q").astype(jnp.int32) * scale[0][None, :]
        d["used_base"] = (d.pop("used_base_q").astype(jnp.int32)
                          * scale[1][None, :])
    # Materialize the unpacked arrays before they enter the placement
    # while/scan: without the barrier XLA fuses the slice+bitcast decode
    # of the packed buffer into the loop BODY and re-decodes the whole
    # buffer every spec iteration (measured: 0.88s vs 0.04s for the same
    # placement program at U=1024, N=64k).
    d = dict(zip(d.keys(), lax.optimization_barrier(tuple(d.values()))))
    job_counts = scatter_job_counts(
        d["jc_rows"], d["jc_cols"], d["jc_vals"], u_pad=u_pad, n_pad=n_pad)
    feas = feasibility_matrix(
        d["attr"], d["elig"], d["dc"], d["c_attr"], d["c_op"], d["c_rhs"],
        d["dc_mask"], d["precomp"])
    if use_used_dev:
        used0 = used_dev
    else:
        # Alloc usage arrives as sparse (node, 4-dim) deltas over the
        # static reserved-only baseline; -1 rows are padding.  Padding
        # routes to an out-of-bounds index under mode="drop" — clipping
        # it to a real row would put DUPLICATE indices in the scatter,
        # and for the port-word SET below a padding row's identity write
        # could then race with (and clobber) a real touched-node write.
        uvalid = d["u_rows"] >= 0
        uidx = jnp.where(uvalid, d["u_rows"], jnp.int32(n_pad))
        used0 = d["used_base"].at[uidx].add(d["u_vals"], mode="drop")
    net = None
    if with_networks:
        assert not use_used_dev, \
            "device-resident usage mirror is gated to non-network batches"
        bw_used = d["bw_used_base"].at[uidx].add(d["u_bw"], mode="drop")
        dyn_free = d["dyn_free_base"].at[uidx].add(d["u_dyn"], mode="drop")
        # Port bitmaps are REPLACED per touched node (the host re-derives
        # the full set for nodes with allocs), not OR-merged.
        port_words = d["port_words_base"].at[uidx].set(
            d["u_ports"], mode="drop")
        net = NetTensors(
            active=d["net_active"], mbits=d["net_mbits"],
            dyn_need=d["dyn_need"], resv_words=d["resv_words"],
            bw_cap=d["bw_cap"], bw_used=bw_used,
            dyn_free=dyn_free, port_words=port_words)
    dp = None
    if with_dp:
        dp = DPTensors(col=d["dp_col"], active=d["dp_active"],
                       used0=d["dp_used"], attr_values=d["attr"])
    key = jax.random.PRNGKey(d["rng_seed"][0])
    result = placement_rounds(
        feas, used0, d["cap"], d["denom"], d["ask"], d["count"],
        d["penalty"], d["dh"], d["ji"], job_counts, key,
        max_rounds=max_rounds, net=net, dp=dp, with_scores=with_scores,
        slot_m=slot_m)
    # The donated mirror rides back out UNCHANGED so XLA aliases it
    # input→output: the caller re-installs the very same device buffer
    # into the resident slot (zero copies across the batch round-trip).
    return result, feas, used_dev


def _slots_coo_gather(slots: jnp.ndarray, slot_scores: jnp.ndarray,
                      slot_coll: jnp.ndarray, *, out_rows: int,
                      with_scores: bool, compact_u16: bool):
    """COO from the commit-aligned slot record: a GATHER over the output
    rows (searchsorted on the per-spec prefix sums) instead of a nonzero
    over the U×N placement matrix — 0.5s → ~15ms at the 1024×10048
    north-star shape; a scatter formulation of the same thing measured
    0.26s (XLA CPU scatters are serial and bounds-checked).

    Shared contract with the node-mesh program: the sharded fused pass
    (parallel/sharded.sharded_fused_pass) builds the SAME commit-ordered
    slot record (per-shard partials at globally disjoint positions,
    merged by one psum) and runs this very expression on it, so the two
    paths' COO payloads — and therefore placements and AllocMetric
    scores — are byte-identical by construction.

    Entries are per-ALLOC (counts ≡ 1, so a node committed in two
    rounds appears twice), rows ascending by construction (per-spec
    contiguous slot prefixes in spec order), scores aligned with their
    commits.  Rows beyond nnz are -1 padding (the host reads only the
    [:nnz] prefix).  Returns (coo [out_rows, C], nnz)."""
    u_pad, m = slots.shape
    valid_src = slots >= 0                          # [U, M] — contiguous
    placed = jnp.sum(valid_src, axis=1).astype(jnp.int32)
    csum = jnp.cumsum(placed)                       # [U]
    nnz = csum[-1]
    i = jnp.arange(out_rows, dtype=jnp.int32)
    u = jnp.searchsorted(csum, i, side="right").astype(jnp.int32)
    offs = csum - placed                            # per-spec start
    uc = jnp.clip(u, 0, u_pad - 1)
    j = jnp.clip(i - offs[uc], 0, m - 1)
    valid = i < nnz
    rows = jnp.where(valid, uc, -1)
    cols = jnp.where(valid, slots[uc, j], 0)
    counts = valid.astype(jnp.int32)
    dt = jnp.uint16 if compact_u16 else jnp.int32
    coo_cols = [rows.astype(dt), cols.astype(dt), counts.astype(dt)]
    if with_scores:
        sc = jnp.where(valid, slot_scores[uc, j], 0.0)
        co = jnp.where(valid, slot_coll[uc, j], 0)
        coo_cols += [lax.bitcast_convert_type(sc, jnp.int32), co]
    return jnp.stack(coo_cols, axis=1), nnz


@functools.partial(jax.jit, static_argnames=("out_rows", "with_scores",
                                             "compact_u16"))
def slots_to_coo(slots: jnp.ndarray, slot_scores: jnp.ndarray,
                 slot_coll: jnp.ndarray, *, out_rows: int,
                 with_scores: bool, compact_u16: bool):
    """Standalone jitted slot→COO gather for the fused overflow path:
    when nnz exceeds the payload window, the host dispatches this over
    the device-resident slot record and prefix-fetches exactly the rows
    it needs — fetch bytes stay proportional to placements, not to the
    [U, M] record size."""
    return _slots_coo_gather(slots, slot_scores, slot_coll,
                             out_rows=out_rows, with_scores=with_scores,
                             compact_u16=compact_u16)


def _compact_from_slots(result: PlacementResult, *, out_rows: int,
                        with_scores: bool, compact_u16: bool):
    return _slots_coo_gather(result.slots, result.slot_scores,
                             result.slot_coll, out_rows=out_rows,
                             with_scores=with_scores,
                             compact_u16=compact_u16)


def _compact_coo(result: PlacementResult, *, u_pad: int, n_pad: int,
                 with_scores: bool, max_nnz: int, compact_u16: bool):
    """Shared COO compaction expression (the two-phase _device_compact
    and the fused single-buffer program must emit byte-identical
    triplets).  Returns (coo [max_nnz, C], nnz scalar)."""
    rows, cols = jnp.nonzero(result.placements, size=max_nnz, fill_value=-1)
    valid = rows >= 0
    nnz = jnp.sum(valid.astype(jnp.int32))
    r = jnp.clip(rows, 0, u_pad - 1)
    c = jnp.clip(cols, 0, n_pad - 1)
    counts = jnp.where(valid, result.placements[r, c], 0)
    dt = jnp.uint16 if compact_u16 else jnp.int32
    coo_cols = [rows.astype(dt), cols.astype(dt), counts.astype(dt)]
    if with_scores:
        sc = jnp.where(valid, result.commit_scores[r, c], 0.0)
        co = jnp.where(valid, result.commit_collisions[r, c], 0)
        coo_cols += [lax.bitcast_convert_type(sc, jnp.int32), co]
    return jnp.stack(coo_cols, axis=1), nnz


@functools.partial(jax.jit, static_argnames=("with_scores", "max_nnz",
                                             "compact_u16", "slot_m"))
def _device_compact(result: PlacementResult, feas: jnp.ndarray,
                    *, with_scores: bool, max_nnz: int,
                    compact_u16: bool = False, slot_m: int = 0):
    """Dispatch 2: COO compaction + packed summary (device-resident
    inputs, so the extra dispatch costs no link traffic — and keeping it
    out of the scheduling program keeps XLA compile time sane).

    With slot_m the COO comes from the commit-aligned slot record (one
    U×M pass, per-alloc entries); otherwise from a nonzero over the
    [U, N] matrix.  compact_u16 halves the COO bytes on the link
    (row/col/count as uint16) — valid only without scores and when U/N
    fit in 16 bits; safe because the host only ever reads the valid
    [:nnz] prefix (the -1 fill would wrap)."""
    from . import xfer

    u_pad, n_pad = feas.shape
    if slot_m:
        coo, nnz = _compact_from_slots(
            result, out_rows=max_nnz, with_scores=with_scores,
            compact_u16=compact_u16)
    else:
        coo, nnz = _compact_coo(result, u_pad=u_pad, n_pad=n_pad,
                                with_scores=with_scores, max_nnz=max_nnz,
                                compact_u16=compact_u16)
    feas_count = jnp.sum(feas, axis=1).astype(jnp.int32)
    summary, _ = xfer.pack_device({
        "unplaced": result.unplaced,
        "feas_count": feas_count,
        "scalars": jnp.stack([nnz, result.rounds]).astype(jnp.int32),
    })
    return summary, coo


def device_pass(
    static_buf: jnp.ndarray,
    dyn_buf: jnp.ndarray,
    used_dev: jnp.ndarray = None,
    *,
    meta_s,
    meta_d,
    u_pad: int,
    n_pad: int,
    with_networks: bool,
    with_dp: bool,
    with_scores: bool,
    max_nnz: int,
    max_rounds: int = 256,
    slot_m: int = 0,
):
    """The whole batch-scheduling device program over a cached static
    buffer + ONE per-batch dynamic upload, returning ONE packed summary
    + a COO matrix the host fetches as a [nnz, C] prefix — the tunneled
    host↔device link pays ~50-110ms per transfer and single-digit MB/s,
    so transfer bytes (not FLOPs) are the scaling limit (VERDICT r1
    weak #1; bench.py link measurements).

    Two dispatches (schedule, compact) rather than one fused program:
    both stay on device so the split is free at the link, and it keeps
    the XLA optimization time of the big scheduling program from
    compounding with the compaction graph.

    Returns (summary_buf uint8, coo [max_nnz, C], feas, used_out);
    C = 5 with scores (int32: row, col, count, score-bits, collisions),
    else 3 (row, col, count — uint16 when U/N/rounds all fit 16 bits,
    int32 otherwise; read the dtype off the array).  With slot_m > 0 the
    COO is built from the scan's commit-aligned slot record (per-alloc
    entries, counts ≡ 1) instead of a [U, N] nonzero.  feas stays on
    device for the rare lazy failure-forensics row fetch.  ``used_dev``
    (optional): the donated device-resident usage mirror; ``used_out``
    is the aliased buffer to hand back to the resident slot (None when
    no mirror was passed).
    """
    use_used_dev = used_dev is not None
    if used_dev is None:
        used_dev = jnp.zeros((1, 4), dtype=jnp.int32)
    note_signature("device_pass", (
        meta_s, meta_d, u_pad, n_pad, with_networks, with_dp, with_scores,
        max_nnz, max_rounds, slot_m, use_used_dev))
    result, feas, used_out = _device_schedule(
        static_buf, dyn_buf, used_dev, meta_s=meta_s, meta_d=meta_d,
        u_pad=u_pad, n_pad=n_pad,
        with_networks=with_networks, with_dp=with_dp,
        with_scores=with_scores, max_rounds=max_rounds, slot_m=slot_m,
        use_used_dev=use_used_dev)
    # <= 65536: u16 stores values 0..65535 and row/col/count are all
    # strictly below their pad bound (a 65536-node bucket still has max
    # col 65535 — `< 65536` wrongly fell back to int32 exactly at the
    # 50k-node bench shape, tripling the COO bytes on the link).
    compact_u16 = (not with_scores and u_pad <= 65536 and n_pad <= 65536
                   and max_rounds < 65536)
    summary, coo = _device_compact(
        result, feas, with_scores=with_scores, max_nnz=max_nnz,
        compact_u16=compact_u16, slot_m=slot_m)
    return summary, coo, feas, (used_out if use_used_dev else None)


# Fused result-buffer COO window: the single transfer carries at most
# this many payload bytes; batches whose nnz exceeds the window (rare —
# it takes >8MB of placements) pay one extra prefix fetch from the
# device-resident full COO.
FUSED_WINDOW_BYTES = 8 << 20


def fused_window(max_nnz: int, *, with_scores: bool,
                 compact_u16: bool) -> int:
    bytes_per_row = (5 if with_scores else 3) * (2 if compact_u16 else 4)
    window = max_nnz
    while window * bytes_per_row > FUSED_WINDOW_BYTES and window > 8:
        window //= 2
    return window


def fused_layout(u_pad: int, *, window_nnz: int, with_scores: bool,
                 compact_u16: bool):
    """Layout of the fused score-and-commit result buffer: summary
    (unplaced + feas_count + [nnz, rounds]) AND the COO placement
    payload window in ONE packed uint8 buffer, so the whole batch
    result crosses the link in a single transfer (ops/xfer.py layout():
    both sides compute the offsets independently)."""
    from . import xfer

    ncols = 5 if with_scores else 3
    return xfer.layout({
        "unplaced": ("i32", (u_pad,)),
        "feas_count": ("i32", (u_pad,)),
        "scalars": ("i32", (2,)),       # [nnz, rounds]
        "coo": ("u16" if compact_u16 else "i32", (window_nnz, ncols)),
    })


@functools.partial(jax.jit, static_argnames=(
    "meta_s", "meta_d", "u_pad", "n_pad", "with_networks", "with_dp",
    "with_scores", "max_nnz", "max_rounds", "slot_m", "compact_u16",
    "window_nnz", "use_used_dev"),
    donate_argnums=(2,))
def _fused_score_commit(
    static_buf: jnp.ndarray,
    dyn_buf: jnp.ndarray,
    used_dev: jnp.ndarray,
    *,
    meta_s,
    meta_d,
    u_pad: int,
    n_pad: int,
    with_networks: bool,
    with_dp: bool,
    with_scores: bool,
    max_nnz: int,
    max_rounds: int = 256,
    slot_m: int = 0,
    compact_u16: bool = False,
    window_nnz: int = 0,
    use_used_dev: bool = False,
):
    """ONE device dispatch for the whole batch: unpack (+ dequantize) →
    feasibility → lax.scan capacity-feedback placement rounds → COO
    compaction (from the commit-aligned slot record when slot_m) →
    single packed result buffer.  The two-dispatch schedule/compact
    split (device_pass) remains the fallback behind NOMAD_TPU_FUSED=0
    and the diagnostics paths; placements are bit-identical between the
    two by construction (same _device_schedule, same compaction
    expressions).  ``used_dev`` is the DONATED device-resident usage
    mirror (a [1, 4] dummy when use_used_dev is off), returned aliased
    as the last output."""
    result, feas, used_out = _device_schedule(
        static_buf, dyn_buf, used_dev, meta_s=meta_s, meta_d=meta_d,
        u_pad=u_pad, n_pad=n_pad, with_networks=with_networks,
        with_dp=with_dp, with_scores=with_scores, max_rounds=max_rounds,
        slot_m=slot_m, use_used_dev=use_used_dev)
    from . import xfer

    feas_count = jnp.sum(feas, axis=1).astype(jnp.int32)
    if slot_m:
        # The payload window is gathered directly (no full-size COO is
        # ever materialized); the raw slot record rides along as the
        # overflow source — device-resident, fetched only when nnz
        # exceeds the window.
        coo_win, nnz = _compact_from_slots(
            result, out_rows=window_nnz, with_scores=with_scores,
            compact_u16=compact_u16)
        aux = (result.slots, result.slot_scores, result.slot_coll)
    else:
        coo_full, nnz = _compact_coo(
            result, u_pad=u_pad, n_pad=n_pad, with_scores=with_scores,
            max_nnz=max_nnz, compact_u16=compact_u16)
        coo_win = coo_full[:window_nnz]
        aux = coo_full
    buf, _ = xfer.pack_device({
        "unplaced": result.unplaced,
        "feas_count": feas_count,
        "scalars": jnp.stack([nnz, result.rounds]).astype(jnp.int32),
        "coo": coo_win,
    })
    return buf, aux, feas, used_out


def fused_pass(
    static_buf: jnp.ndarray,
    dyn_buf: jnp.ndarray,
    used_dev: jnp.ndarray = None,
    *,
    meta_s,
    meta_d,
    u_pad: int,
    n_pad: int,
    with_networks: bool,
    with_dp: bool,
    with_scores: bool,
    max_nnz: int,
    max_rounds: int = 256,
    slot_m: int = 0,
):
    """Fused score-and-commit entry: returns (packed result buffer,
    full COO on device, feas on device, result layout meta, used_out).
    The caller fetches the packed buffer with ONE jax.device_get and
    decodes host-side with xfer.unpack_host(buf, meta).  ``aux`` is the
    device-resident overflow source — the full COO (matrix mode) or the
    raw slot record triple (slot mode) — touched only when nnz
    overflows the payload window; ``feas`` only for the rare lazy
    failure-forensics rows.  ``used_dev`` (optional) is the donated
    device-resident usage mirror; ``used_out`` is the aliased buffer to
    hand back to ops/resident.py (None when no mirror was passed — the
    sparse-delta upload path)."""
    compact_u16 = (not with_scores and u_pad <= 65536
                   and n_pad <= 65536 and max_rounds < 65536)
    window_nnz = fused_window(max_nnz, with_scores=with_scores,
                              compact_u16=compact_u16)
    use_used_dev = used_dev is not None
    if used_dev is None:
        used_dev = jnp.zeros((1, 4), dtype=jnp.int32)
    note_signature("fused_pass", (
        meta_s, meta_d, u_pad, n_pad, with_networks, with_dp, with_scores,
        max_nnz, max_rounds, slot_m, compact_u16, window_nnz,
        use_used_dev))
    buf, aux, feas, used_out = _fused_score_commit(
        static_buf, dyn_buf, used_dev, meta_s=meta_s, meta_d=meta_d,
        u_pad=u_pad, n_pad=n_pad, with_networks=with_networks,
        with_dp=with_dp, with_scores=with_scores, max_nnz=max_nnz,
        max_rounds=max_rounds, slot_m=slot_m, compact_u16=compact_u16,
        window_nnz=window_nnz, use_used_dev=use_used_dev)
    meta = fused_layout(u_pad, window_nnz=window_nnz,
                        with_scores=with_scores, compact_u16=compact_u16)
    return buf, aux, feas, meta, (used_out if use_used_dev else None)


@functools.partial(jax.jit, static_argnames=("max_nnz",))
def compact_placements(
    feas: jnp.ndarray,          # [U, N] bool
    placements: jnp.ndarray,    # [U, N] int32
    commit_scores: jnp.ndarray,  # [U, N] f32 (or [1,1] when disabled)
    commit_coll: jnp.ndarray,    # [U, N] int32 (or [1,1])
    max_nnz: int,
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """Device-side compaction of the placement matrix to COO — the
    host↔device link (tunneled TPU) is the bottleneck at scale, so the
    dense [U, N] outputs never leave the device:

      rows/cols int32[max_nnz] (-1 padding), counts int32[max_nnz],
      scores f32[max_nnz], feas_count int32[U]

    max_nnz is bounded by the batch's total asks (static per bucket)."""
    rows, cols = jnp.nonzero(placements, size=max_nnz, fill_value=-1)
    valid = rows >= 0
    r = jnp.clip(rows, 0, placements.shape[0] - 1)
    c = jnp.clip(cols, 0, placements.shape[1] - 1)
    counts = jnp.where(valid, placements[r, c], 0)
    sr = jnp.clip(r, 0, commit_scores.shape[0] - 1)
    sc = jnp.clip(c, 0, commit_scores.shape[1] - 1)
    scores = jnp.where(valid, commit_scores[sr, sc], 0.0)
    coll = jnp.where(valid, commit_coll[sr, sc], 0)
    feas_count = jnp.sum(feas, axis=1).astype(jnp.int32)
    return rows, cols, counts, scores, coll, feas_count


@functools.partial(jax.jit, static_argnames=("u_pad", "n_pad"))
def scatter_job_counts(
    rows: jnp.ndarray,   # [K] int32, -1 padding
    cols: jnp.ndarray,   # [K] int32
    vals: jnp.ndarray,   # [K] int32
    u_pad: int,
    n_pad: int,
) -> jnp.ndarray:
    """Build the dense per-(job,node) count matrix on device from a sparse
    host upload — the dense matrix is U×N and mostly zeros."""
    valid = rows >= 0
    r = jnp.clip(rows, 0, u_pad - 1)
    c = jnp.clip(cols, 0, n_pad - 1)
    out = jnp.zeros((u_pad, n_pad), dtype=jnp.int32)
    return out.at[r, c].add(jnp.where(valid, vals, 0))


@jax.jit
def batch_allocs_fit(
    capacity: jnp.ndarray,   # [N, 4] int32
    used: jnp.ndarray,       # [N, 4] int32 — proposed usage incl. reserved
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Vectorized plan-verification re-check (plan_apply.go:327
    evaluateNodePlan / funcs.go:60 AllocsFit): fit[n] plus the first
    exhausted dimension index (-1 if fit)."""
    over = used > capacity                    # [N, 4]
    fit = ~jnp.any(over, axis=1)
    first_dim = jnp.argmax(over, axis=1).astype(jnp.int32)
    return fit, jnp.where(fit, -1, first_dim)


def aggregate_binpack_score(
    placements: jnp.ndarray,  # [U, N] int32
    used0: jnp.ndarray,
    denom: jnp.ndarray,
    ask: jnp.ndarray,
) -> jnp.ndarray:
    """Recompute the sum of marginal ScoreFit values in commit order
    (approximated by recomputing each spec's score against final state minus
    its own ask) — used for differential scoring against the oracle."""
    # For score parity checks we use final utilization per node.
    total_ask = jnp.einsum("un,ud->nd", placements.astype(jnp.int32), ask)
    final_used = used0 + total_ask
    after = final_used[:, :2].astype(jnp.float32)
    safe_denom = jnp.where(denom == 0.0, 1.0, denom)
    frac = 1.0 - after / safe_denom
    total = _pow10(frac[:, 0]) + _pow10(frac[:, 1])
    score = jnp.clip(20.0 - total, 0.0, 18.0)
    n_placed = jnp.sum(placements, axis=0)
    return jnp.sum(score * (n_placed > 0))
