"""TPU batch-scheduling kernels: vectorized feasibility + scoring +
round-based placement (SURVEY.md §7 steps 2-3).

Re-derivation of the reference iterator chain (scheduler/stack.go:37) as
masked tensor ops:

- feasibility  F[U,N] = AND_k check(op_k)  — ConstraintChecker/DriverChecker
  (feasible.go:355,92) as integer compares over ordered-interned codes,
  AND'ed with host-precomputed rows for version/regex/set_contains.
- scoring      S[U,N] = score_fit(used+ask) − penalty·collisions
  — BinPackIterator + JobAntiAffinityIterator (rank.go:130,247) as one fused
  elementwise expression over the whole matrix.
- placement    iterative masked rank-and-commit loop with capacity feedback
  — the only sequential part (≤count iterations per spec); anti-affinity
  (20 > max binpack 18) means at most one alloc of a job lands per node per
  round, so each round places min(count, feasible) allocs per spec.

Everything is jittable; no data-dependent Python control flow
(lax.while_loop / lax.scan / lax.fori_loop only), static shapes from the
padded encodings in ops/encode.py.
"""
from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from .encode import (
    MISSING,
    OP_EQ,
    OP_GE,
    OP_GT,
    OP_LE,
    OP_LT,
    OP_NE,
    OP_PRECOMP,
    OP_TRUE,
    UNKNOWN_RHS,
)

NEG_INF = -1e30


@functools.partial(jax.jit, static_argnames=())
def feasibility_matrix(
    attr_values: jnp.ndarray,   # [N, K] int32 ordered codes, -1 missing
    eligible: jnp.ndarray,      # [N] bool
    dc_code: jnp.ndarray,       # [N] int32
    c_attr: jnp.ndarray,        # [U, Kc] int32 column index
    c_op: jnp.ndarray,          # [U, Kc] int32 op code
    c_rhs: jnp.ndarray,         # [U, Kc] int32 rhs code
    dc_mask: jnp.ndarray,       # [U, D] bool
    precomp: jnp.ndarray,       # [U, N] bool
) -> jnp.ndarray:
    """F[U, N]: static feasibility of spec u on node n.

    Scans over the (small) constraint axis, ANDing one vectorized compare at
    a time — peak memory stays at one [U, N] buffer.
    """
    n = attr_values.shape[0]
    u = c_attr.shape[0]
    kc = c_attr.shape[1]

    # Datacenter membership (readyNodesInDCs, util.go:224): gather each
    # node's dc bit from the spec's allowed-DC mask.
    dc_ok = jnp.take_along_axis(
        dc_mask, jnp.broadcast_to(dc_code[None, :], (u, n)), axis=1
    )  # [U, N]

    init = precomp & dc_ok & eligible[None, :]

    def body(carry, k):
        attr_col = c_attr[:, k]                       # [U]
        vals = attr_values[:, attr_col].T             # [U, N]
        rhs = c_rhs[:, k][:, None]                    # [U, 1]
        op = c_op[:, k][:, None]                      # [U, 1]

        missing = vals == MISSING
        unknown_rhs = rhs == UNKNOWN_RHS

        ok = jnp.where(op == OP_EQ, (vals == rhs) & ~unknown_rhs,
             jnp.where(op == OP_NE, (vals != rhs) | unknown_rhs,
             jnp.where(op == OP_LT, vals < rhs,
             jnp.where(op == OP_LE, vals <= rhs,
             jnp.where(op == OP_GT, vals > rhs,
             jnp.where(op == OP_GE, vals >= rhs,
                       jnp.ones_like(vals, dtype=bool)))))))
        # A missing LHS fails any real constraint (resolveConstraintTarget
        # returns !ok, feasible.go:383-391); OP_TRUE padding passes.
        ok = jnp.where(op == OP_TRUE, True, ok & ~missing)
        return carry & ok, None

    f, _ = lax.scan(body, init, jnp.arange(kc))
    return f


def _score_fit(
    used: jnp.ndarray,         # [N, 4] int32 — current usage incl. reserved
    ask: jnp.ndarray,          # [4] int32
    denom: jnp.ndarray,        # [N, 2] float32 — cpu/mem capacity minus reserved
) -> jnp.ndarray:
    """Google best-fit-v3 over all nodes at once (funcs.go:123 ScoreFit):
    20 − (10^freeCpuFrac + 10^freeMemFrac), clamped to [0, 18]."""
    after = used[:, :2].astype(jnp.float32) + ask[:2].astype(jnp.float32)
    safe_denom = jnp.where(denom == 0.0, 1.0, denom)
    frac = 1.0 - after / safe_denom
    frac = jnp.where(denom == 0.0, -jnp.inf, frac)
    total = jnp.power(10.0, frac[:, 0]) + jnp.power(10.0, frac[:, 1])
    score = 20.0 - total
    score = jnp.nan_to_num(score, nan=0.0, posinf=18.0, neginf=0.0)
    return jnp.clip(score, 0.0, 18.0)


class PlacementResult(NamedTuple):
    placements: jnp.ndarray   # [U, N] int32 — allocs of spec u committed on node n
    unplaced: jnp.ndarray     # [U] int32 — counts that found no feasible node
    used_after: jnp.ndarray   # [N, 4] int32 — final node usage
    rounds: jnp.ndarray       # [] int32


@functools.partial(jax.jit, static_argnames=("max_rounds",))
def placement_rounds(
    feas: jnp.ndarray,         # [U, N] bool — static feasibility
    used0: jnp.ndarray,        # [N, 4] int32 — usage incl. reserved
    capacity: jnp.ndarray,     # [N, 4] int32
    denom: jnp.ndarray,        # [N, 2] float32
    ask: jnp.ndarray,          # [U, 4] int32
    count: jnp.ndarray,        # [U] int32
    penalty: jnp.ndarray,      # [U] float32
    distinct_hosts: jnp.ndarray,  # [U] bool
    job_index: jnp.ndarray,    # [U] int32 → row in job_counts
    job_counts0: jnp.ndarray,  # [J, N] int32 — existing allocs per (job, node)
    rng_key: jnp.ndarray,
    max_rounds: int = 256,
) -> PlacementResult:
    """The sequential heart of the batch scheduler.

    Each round scans specs in order (host pre-sorts by priority desc — the
    broker's priority heap, eval_broker.go:43); a spec places at most one
    alloc per node per round (justified by the anti-affinity penalty: a
    second same-job alloc on a node scores ≤ −2, below any empty feasible
    node), committing to its top-k scored nodes under remaining capacity.
    Loop exits when a round makes no progress (capacity exhausted or all
    placed).
    """
    u_pad, n_pad = feas.shape

    # Deterministic per-(u,n) jitter decorrelates ties exactly like the
    # reference's node shuffling (util.go:325) — magnitude too small to
    # reorder materially different scores.
    jitter = jax.random.uniform(rng_key, (u_pad, n_pad), dtype=jnp.float32) * 1e-3

    def place_one_spec(carry, u):
        used, job_counts, remaining_count, placements = carry

        cap_left = capacity - used                       # [N, 4]
        fits = jnp.all(ask[u][None, :] <= cap_left, axis=1)
        collisions = job_counts[job_index[u]]            # [N] int32
        ok = feas[u] & fits
        ok = ok & jnp.where(distinct_hosts[u], collisions == 0, True)

        score = _score_fit(used, ask[u], denom)
        score = score - penalty[u] * collisions.astype(jnp.float32)
        score = score + jitter[u]
        scored = jnp.where(ok, score, NEG_INF)

        # Rank nodes by score; commit the top-k (k = remaining count,
        # bounded by feasible nodes) — one alloc per node this round.
        order = jnp.argsort(-scored)
        ranks = jnp.zeros(n_pad, dtype=jnp.int32).at[order].set(
            jnp.arange(n_pad, dtype=jnp.int32))
        k = jnp.minimum(remaining_count[u], jnp.sum(ok).astype(jnp.int32))
        sel = ok & (ranks < k)

        sel_i = sel.astype(jnp.int32)
        used = used + sel_i[:, None] * ask[u][None, :]
        job_counts = job_counts.at[job_index[u]].add(sel_i)
        placements = placements.at[u].add(sel_i)
        remaining_count = remaining_count.at[u].add(-k)
        return (used, job_counts, remaining_count, placements), k

    def round_body(state):
        used, job_counts, remaining_count, placements, _, rounds = state
        (used, job_counts, remaining_count, placements), placed = lax.scan(
            place_one_spec,
            (used, job_counts, remaining_count, placements),
            jnp.arange(u_pad),
        )
        progress = jnp.sum(placed)
        return (used, job_counts, remaining_count, placements,
                progress, rounds + 1)

    def round_cond(state):
        _, _, remaining_count, _, progress, rounds = state
        return (progress > 0) & (jnp.sum(remaining_count) > 0) & (rounds < max_rounds)

    placements0 = jnp.zeros((u_pad, n_pad), dtype=jnp.int32)
    state = (used0, job_counts0, count, placements0,
             jnp.array(1, dtype=jnp.int32), jnp.array(0, dtype=jnp.int32))
    used, job_counts, remaining, placements, _, rounds = lax.while_loop(
        round_cond, round_body, state)

    return PlacementResult(
        placements=placements,
        unplaced=remaining,
        used_after=used,
        rounds=rounds,
    )


@jax.jit
def batch_allocs_fit(
    capacity: jnp.ndarray,   # [N, 4] int32
    used: jnp.ndarray,       # [N, 4] int32 — proposed usage incl. reserved
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Vectorized plan-verification re-check (plan_apply.go:327
    evaluateNodePlan / funcs.go:60 AllocsFit): fit[n] plus the first
    exhausted dimension index (-1 if fit)."""
    over = used > capacity                    # [N, 4]
    fit = ~jnp.any(over, axis=1)
    first_dim = jnp.argmax(over, axis=1).astype(jnp.int32)
    return fit, jnp.where(fit, -1, first_dim)


def aggregate_binpack_score(
    placements: jnp.ndarray,  # [U, N] int32
    used0: jnp.ndarray,
    denom: jnp.ndarray,
    ask: jnp.ndarray,
) -> jnp.ndarray:
    """Recompute the sum of marginal ScoreFit values in commit order
    (approximated by recomputing each spec's score against final state minus
    its own ask) — used for differential scoring against the oracle."""
    # For score parity checks we use final utilization per node.
    total_ask = jnp.einsum("un,ud->nd", placements.astype(jnp.int32), ask)
    final_used = used0 + total_ask
    after = final_used[:, :2].astype(jnp.float32)
    safe_denom = jnp.where(denom == 0.0, 1.0, denom)
    frac = 1.0 - after / safe_denom
    total = jnp.power(10.0, frac[:, 0]) + jnp.power(10.0, frac[:, 1])
    score = jnp.clip(20.0 - total, 0.0, 18.0)
    n_placed = jnp.sum(placements, axis=0)
    return jnp.sum(score * (n_placed > 0))
