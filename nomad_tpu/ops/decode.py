"""Packed-result-buffer decode: the host half of the single fused fetch
(ISSUE 13 tentpole item c).

After ``jax.device_get`` returns the packed buffer, two integer passes
turn the COO placement payload into what finalize needs:

- :func:`expand_coo` — per-alloc node-index runs per spec (the
  ``np.repeat``/searchsorted pass the plan materialization feeds on);
- :func:`last_scores` — per-spec last-commit (col, score, collisions)
  entries (slot-mode COO carries one entry per ALLOC, so a node
  committed in several rounds appears several times; the AllocMetric
  keeps the last commit's score — matrix-mode semantics).

At the north-star shape these are ~1M-entry loops — the largest host
residue left after the fused kernel — so both drop to C
(``native/decode.cc``, the wal.cc/codec.cc build pattern) behind pure
numpy/Python twins.  Every ``NOMAD_TPU_DECODE_GUARD_EVERY`` native calls
(default 64; tests pin 1) the twin runs anyway and the outputs are
bit-compared: a mismatch disables the native path for the process,
feeds the PR 2 breaker, and the batch proceeds on the twin's output —
corruption degrades, never mis-places.  ``NOMAD_TPU_NO_NATIVE=1`` forces
the twins outright.
"""
from __future__ import annotations

import ctypes
import logging
import os
from typing import Optional, Tuple

import numpy as np

from ..utils import tracing

logger = logging.getLogger("nomad_tpu.ops.decode")

# Module counters (selfcheck + tests).  NATIVE_CALLS aggregates; the
# per-function counters drive the guard cadence INDEPENDENTLY — a
# shared counter with the production call pattern (expand then
# last_scores once per batch) would park the cadence on one function
# and never twin-verify the other.
NATIVE_CALLS = 0
EXPAND_CALLS = 0
LAST_CALLS = 0
TWIN_CALLS = 0
GUARD_RUNS = 0
GUARD_MISMATCHES = 0

_NATIVE_DISABLED = False
_LIB = None


def guard_every() -> int:
    from ..utils import knobs

    return knobs.get_int("NOMAD_TPU_DECODE_GUARD_EVERY")


def reset_counters() -> None:
    global NATIVE_CALLS, TWIN_CALLS, GUARD_RUNS, GUARD_MISMATCHES
    global EXPAND_CALLS, LAST_CALLS, _NATIVE_DISABLED
    NATIVE_CALLS = TWIN_CALLS = GUARD_RUNS = GUARD_MISMATCHES = 0
    EXPAND_CALLS = LAST_CALLS = 0
    _NATIVE_DISABLED = False


def _lib():
    """The decode .so, or None when unavailable/disabled."""
    global _LIB, _NATIVE_DISABLED
    if _NATIVE_DISABLED:
        return None
    if _LIB is None:
        from .. import native

        try:
            lib = native._load("nomaddecode", "decode.cc")
        except native.NativeUnavailable as exc:
            logger.info("native decode unavailable (%s); python twins "
                        "carry the decode path", exc)
            _NATIVE_DISABLED = True
            return None
        c_i32p = ctypes.POINTER(ctypes.c_int32)
        c_i64p = ctypes.POINTER(ctypes.c_longlong)
        c_f32p = ctypes.POINTER(ctypes.c_float)
        lib.ndec_expand.restype = ctypes.c_longlong
        lib.ndec_expand.argtypes = [
            c_i32p, c_i32p, c_i32p, ctypes.c_longlong, ctypes.c_int32,
            ctypes.c_int32, c_i64p, c_i32p, ctypes.c_longlong]
        lib.ndec_last_scores.restype = ctypes.c_longlong
        lib.ndec_last_scores.argtypes = [
            c_i32p, c_i32p, c_f32p, c_i32p, ctypes.c_longlong,
            ctypes.c_int32, ctypes.c_int32, c_i32p, c_i64p, c_i64p,
            c_i32p, c_f32p, c_i32p]
        _LIB = lib
    return _LIB


def _i32p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def _i64p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong))


def _f32p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_float))


def _note_mismatch(what: str, breaker) -> None:
    global GUARD_MISMATCHES, _NATIVE_DISABLED
    GUARD_MISMATCHES += 1
    _NATIVE_DISABLED = True
    logger.error(
        "native decode %s diverged from the python twin; disabling the "
        "native path and feeding the breaker", what)
    tracing.event("decode.guard_mismatch", what=what)
    if breaker is not None:
        breaker.record(False)


# -- expand -----------------------------------------------------------------


def _expand_twin(rows: np.ndarray, cols: np.ndarray, counts: np.ndarray,
                 n_specs: int, n_real: int
                 ) -> Tuple[np.ndarray, np.ndarray]:
    """Pure-numpy reference: (off [n_specs+1] int64, expanded int32)."""
    valid = (rows >= 0) & (cols < n_real)
    vr, vc = rows[valid], cols[valid]
    vcnt = counts[valid]
    expanded = np.repeat(vc, vcnt).astype(np.int32, copy=False)
    per_spec = np.zeros(n_specs + 1, dtype=np.int64)
    np.add.at(per_spec, vr.astype(np.int64) + 1, vcnt.astype(np.int64))
    return np.cumsum(per_spec), expanded


def expand_coo(rows: np.ndarray, cols: np.ndarray, counts: np.ndarray,
               n_specs: int, n_real: int, total_cap: int, breaker=None
               ) -> Tuple[np.ndarray, np.ndarray]:
    """Per-alloc node-index runs per spec from the fetched COO.

    Returns ``(off, expanded)``: spec u's placements are
    ``expanded[off[u]:off[u+1]]`` (int32 node indexes, entry order).
    ``total_cap`` bounds the expansion (the batch's total asks)."""
    global NATIVE_CALLS, EXPAND_CALLS, TWIN_CALLS, GUARD_RUNS
    rows = np.ascontiguousarray(rows, dtype=np.int32)
    cols = np.ascontiguousarray(cols, dtype=np.int32)
    counts = np.ascontiguousarray(counts, dtype=np.int32)
    lib = _lib()
    if lib is None:
        TWIN_CALLS += 1
        return _expand_twin(rows, cols, counts, n_specs, n_real)
    off = np.zeros(n_specs + 1, dtype=np.int64)
    out = np.empty(max(1, total_cap), dtype=np.int32)
    got = lib.ndec_expand(_i32p(rows), _i32p(cols), _i32p(counts),
                          len(rows), n_specs, n_real, _i64p(off),
                          _i32p(out), out.shape[0])
    if got < 0:
        # Shape the native path refuses (overflow / out-of-range spec):
        # the twin is authoritative.
        TWIN_CALLS += 1
        return _expand_twin(rows, cols, counts, n_specs, n_real)
    NATIVE_CALLS += 1
    EXPAND_CALLS += 1
    out = out[:got]
    every = guard_every()
    if every > 0 and EXPAND_CALLS % every == 0:
        GUARD_RUNS += 1
        ref_off, ref_out = _expand_twin(rows, cols, counts, n_specs,
                                        n_real)
        if not (np.array_equal(ref_off, off)
                and np.array_equal(ref_out, out)):
            _note_mismatch("expand", breaker)
            return ref_off, ref_out
        if breaker is not None:
            breaker.record(True)
    return off, out


# -- last-commit scores -----------------------------------------------------


def _last_scores_twin(rows: np.ndarray, cols: np.ndarray,
                      scores: np.ndarray, coll: np.ndarray,
                      n_specs: int, n_real: int):
    """Pure-python reference: per-spec dicts in first-occurrence order,
    last value wins — exactly the ``last[i] = (sc, co)`` loop this
    module replaces."""
    valid = (rows >= 0) & (cols < n_real)
    vr, vc = rows[valid], cols[valid]
    vsc, vco = scores[valid], coll[valid]
    off = np.zeros(n_specs + 1, dtype=np.int64)
    out_col, out_sc, out_co = [], [], []
    u_lo = np.searchsorted(vr, np.arange(n_specs), side="left")
    u_hi = np.searchsorted(vr, np.arange(n_specs), side="right")
    for u in range(n_specs):
        last = {}
        lo, hi = int(u_lo[u]), int(u_hi[u])
        for i, sc, co in zip(vc[lo:hi].tolist(), vsc[lo:hi].tolist(),
                             vco[lo:hi].tolist()):
            last[i] = (sc, co)
        off[u + 1] = off[u] + len(last)
        for i, (sc, co) in last.items():
            out_col.append(i)
            out_sc.append(sc)
            out_co.append(co)
    return (off, np.array(out_col, dtype=np.int32),
            np.array(out_sc, dtype=np.float32),
            np.array(out_co, dtype=np.int32))


def last_scores(rows: np.ndarray, cols: np.ndarray, scores: np.ndarray,
                coll: np.ndarray, n_specs: int, n_real: int,
                breaker=None):
    """Per-spec last-commit score entries from the fetched COO.

    Returns ``(off, col, score, coll)``: spec u's score entries are the
    ``[off[u]:off[u+1]]`` slices (node col, binpack score, collision
    count), one entry per distinct committed node, last commit wins."""
    global NATIVE_CALLS, LAST_CALLS, TWIN_CALLS, GUARD_RUNS
    rows = np.ascontiguousarray(rows, dtype=np.int32)
    cols = np.ascontiguousarray(cols, dtype=np.int32)
    scores = np.ascontiguousarray(scores, dtype=np.float32)
    coll = np.ascontiguousarray(coll, dtype=np.int32)
    lib = _lib()
    if lib is None:
        TWIN_CALLS += 1
        return _last_scores_twin(rows, cols, scores, coll, n_specs,
                                 n_real)
    n = len(rows)
    stamp = np.full(max(1, n_real), -1, dtype=np.int32)
    pos = np.empty(max(1, n_real), dtype=np.int64)
    off = np.zeros(n_specs + 1, dtype=np.int64)
    out_col = np.empty(max(1, n), dtype=np.int32)
    out_sc = np.empty(max(1, n), dtype=np.float32)
    out_co = np.empty(max(1, n), dtype=np.int32)
    got = lib.ndec_last_scores(
        _i32p(rows), _i32p(cols), _f32p(scores), _i32p(coll), n,
        n_specs, n_real, _i32p(stamp), _i64p(pos), _i64p(off),
        _i32p(out_col), _f32p(out_sc), _i32p(out_co))
    if got < 0:
        TWIN_CALLS += 1
        return _last_scores_twin(rows, cols, scores, coll, n_specs,
                                 n_real)
    NATIVE_CALLS += 1
    LAST_CALLS += 1
    result = (off, out_col[:got], out_sc[:got], out_co[:got])
    every = guard_every()
    if every > 0 and LAST_CALLS % every == 0:
        GUARD_RUNS += 1
        ref = _last_scores_twin(rows, cols, scores, coll, n_specs,
                                n_real)
        if not all(np.array_equal(a, b) for a, b in zip(ref, result)):
            _note_mismatch("last_scores", breaker)
            return ref
        if breaker is not None:
            breaker.record(True)
    return result
