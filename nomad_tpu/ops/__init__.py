"""TPU batch-scheduling kernels and the 'tpu-batch' scheduler.

Importing this package registers the 'tpu-batch' factory with the
scheduler registry.
"""

from .batch_sched import BatchStats, TPUBatchScheduler, new_tpu_batch_scheduler
from .encode import (
    ClusterTensors,
    PlacementSpec,
    SpecTensors,
    build_spec,
    collect_attr_targets,
    encode_cluster,
    encode_specs,
    finalize_codebooks,
)
from .kernels import (
    PlacementResult,
    batch_allocs_fit,
    feasibility_matrix,
    placement_rounds,
)
from .preempt import encode_alloc_tensors, eviction_sets
