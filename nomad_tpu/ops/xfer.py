"""Host↔device transfer packing for tunneled TPU links.

The device link this framework schedules over can be high-latency (a
tunneled chip shows ~50-110ms per transfer regardless of size and ~30MB/s
streaming — measured; see bench.py detail).  jax.device_put of a pytree
issues one transfer per leaf, so a batch upload of ~25 small arrays pays
~25 round trips.  This module packs an arbitrary dict of arrays into ONE
uint8 buffer (one transfer each way) with a deterministic layout both
sides compute independently:

- host→device: pack_host() → device_put → unpack_device() under jit
  (static slices + bitcasts that XLA fuses into the consuming kernel).
- device→host: pack_device() under jit → one device_get → unpack_host()
  (zero-copy numpy views).

layout() is the single source of truth for offsets: given {name: (tag,
shape)} it returns the meta tuple, identical on both sides, so the
device can pack results the host knows how to slice without shipping the
meta across the link.

Reference analogue: the msgpack wire codec (nomad/rpc.go:59) batches
whole request structs into one frame rather than a field at a time; this
is the same idea at the device-link boundary.
"""
from __future__ import annotations

from typing import Dict, List, Tuple

import jax.numpy as jnp
import numpy as np
from jax import lax

# dtype tag → numpy dtype
_DTYPES = {
    "i32": np.int32,
    "u32": np.uint32,
    "f32": np.float32,
    "i16": np.int16,
    "u16": np.uint16,
    "i8": np.int8,
    "u8": np.uint8,
    "b1": np.bool_,
}

# (name, tag, shape, byte offset)
Meta = Tuple[Tuple[str, str, Tuple[int, ...], int], ...]


def _tag(dtype) -> str:
    dtype = np.dtype(dtype)
    for tag, dt in _DTYPES.items():
        if dtype == dt:
            return tag
    raise TypeError(f"unsupported pack dtype {dtype}")


def _nbytes(tag: str, shape: Tuple[int, ...]) -> int:
    nelem = int(np.prod(shape, dtype=np.int64)) if shape else 1
    return nelem * np.dtype(_DTYPES[tag]).itemsize


def layout(items: Dict[str, Tuple[str, Tuple[int, ...]]]) -> Meta:
    """Deterministic buffer layout: sorted by name, 4-byte aligned."""
    metas: List[Tuple[str, str, Tuple[int, ...], int]] = []
    off = 0
    for name in sorted(items):
        tag, shape = items[name]
        metas.append((name, tag, tuple(shape), off))
        nbytes = _nbytes(tag, shape)
        off += nbytes + ((-nbytes) % 4)
    return tuple(metas)


def total_bytes(meta: Meta) -> int:
    if not meta:
        return 0
    name, tag, shape, off = meta[-1]
    nbytes = _nbytes(tag, shape)
    return off + nbytes + ((-nbytes) % 4)


def pack_host(arrays: Dict[str, np.ndarray]) -> Tuple[np.ndarray, Meta]:
    """Concatenate host arrays into one uint8 buffer + layout meta."""
    meta = layout({n: (_tag(a.dtype), tuple(a.shape))
                   for n, a in arrays.items()})
    buf = np.zeros(total_bytes(meta), dtype=np.uint8)
    for name, tag, shape, off in meta:
        a = np.ascontiguousarray(arrays[name])
        raw = a.view(np.uint8).reshape(-1)
        buf[off:off + raw.size] = raw
    return buf, meta


def pack_host_sharded(arrays: Dict[str, np.ndarray], shards: int,
                      replicate: Tuple[str, ...] = ()
                      ) -> Tuple[np.ndarray, Meta]:
    """Per-shard packing for node-mesh uploads: every array is split
    into ``shards`` equal slices along its leading axis — except the
    ``replicate`` names, which are copied whole into every shard (e.g.
    the [4] quantization scale codebook) — and each slice set packs
    into one uint8 row of the returned [shards, B] buffer.  All rows
    share the same layout by construction, so the single returned meta
    describes every shard; placed with ``NamedSharding(mesh,
    P(node_axis))`` each device receives exactly its slice and unpacks
    it with the shared ``unpack_device``.
    """
    for name, arr in arrays.items():
        if name not in replicate and arr.shape[0] % shards:
            # A non-replicated array whose leading axis doesn't divide
            # the mesh would be silently truncated into wrong slices —
            # fail loudly instead (either pad the axis or list the
            # array in ``replicate``).
            raise ValueError(
                f"pack_host_sharded: array {name!r} leading axis "
                f"{arr.shape[0]} not divisible by {shards} shards")
    rows: List[np.ndarray] = []
    meta: Meta = ()
    for s_i in range(shards):
        sl: Dict[str, np.ndarray] = {}
        for name, arr in arrays.items():
            if name in replicate:
                sl[name] = arr
            else:
                n_l = arr.shape[0] // shards
                sl[name] = np.ascontiguousarray(
                    arr[s_i * n_l:(s_i + 1) * n_l])
        buf, meta = pack_host(sl)
        rows.append(buf)
    return np.stack(rows), meta


def unpack_device(buf: jnp.ndarray, meta: Meta) -> Dict[str, jnp.ndarray]:
    """Slice + bitcast each array out of the packed device buffer.

    Runs under jit (meta is static): XLA sees static slices of one input
    and fuses them into the consumers — no materialized copies."""
    out: Dict[str, jnp.ndarray] = {}
    for name, tag, shape, off in meta:
        np_dtype = _DTYPES[tag]
        nbytes = _nbytes(tag, shape)
        itemsize = np.dtype(np_dtype).itemsize
        if np_dtype in (np.uint8, np.bool_):
            arr = lax.slice(buf, (off,), (off + nbytes,))
            if np_dtype == np.bool_:
                arr = arr.astype(jnp.bool_)
            out[name] = arr.reshape(shape)
        elif itemsize == 1:   # int8: same-width bitcast, no regroup
            raw = lax.slice(buf, (off,), (off + nbytes,))
            out[name] = lax.bitcast_convert_type(
                raw, jnp.dtype(np_dtype)).reshape(shape)
        else:
            # Group the bytes into itemsize-wide words and bitcast; the
            # slice stays at nbytes (offsets are 4-aligned by layout(),
            # and nbytes is always a multiple of itemsize).
            raw = lax.slice(buf, (off,), (off + nbytes,))
            words = raw.reshape(-1, itemsize)
            arr = lax.bitcast_convert_type(words, jnp.dtype(np_dtype))
            out[name] = arr.reshape(shape)
    return out


def pack_device(arrays: Dict[str, jnp.ndarray]) -> Tuple[jnp.ndarray, Meta]:
    """Device-side packing under jit: bitcast every array to uint8 and
    concatenate.  The caller fetches the single buffer with one
    device_get and unpacks host-side with unpack_host()."""
    meta = layout({n: (_tag(np.bool_ if a.dtype == jnp.bool_
                            else np.dtype(a.dtype)), tuple(a.shape))
                   for n, a in arrays.items()})
    chunks: List[jnp.ndarray] = []
    pos = 0
    for name, tag, shape, off in meta:
        a = arrays[name]
        if a.dtype == jnp.bool_:
            a = a.astype(jnp.uint8)
        if a.dtype == jnp.uint8:
            raw = a.reshape(-1)
        else:
            raw = lax.bitcast_convert_type(a, jnp.uint8).reshape(-1)
        pad = (-raw.size) % 4
        if pad:
            raw = jnp.concatenate([raw, jnp.zeros(pad, dtype=jnp.uint8)])
        assert pos == off, "layout mismatch"
        chunks.append(raw)
        pos = off + raw.size
    buf = (jnp.concatenate(chunks) if chunks
           else jnp.zeros(0, dtype=jnp.uint8))
    return buf, meta


def unpack_host(buf: np.ndarray, meta: Meta) -> Dict[str, np.ndarray]:
    """numpy-view unpack of a fetched pack_device buffer (zero-copy for
    word-aligned dtypes)."""
    out: Dict[str, np.ndarray] = {}
    for name, tag, shape, off in meta:
        np_dtype = _DTYPES[tag]
        nbytes = _nbytes(tag, shape)
        raw = buf[off:off + nbytes]
        if np_dtype == np.bool_:
            out[name] = raw.view(np.uint8).astype(bool).reshape(shape)
        else:
            out[name] = raw.view(np_dtype).reshape(shape)
    return out
