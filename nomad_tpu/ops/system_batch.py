"""Vectorized system scheduling: the per-node Select loop of the
SystemScheduler (system_sched.go:258 — one full stack evaluation per
node) replaced by one numpy pass over the encoded cluster tensors.

System placement has no inter-node competition — every feasible node
with capacity gets exactly one alloc per task group — so the decision is
a feasibility row AND a capacity compare.  The constraint evaluation is
a numpy mirror of the device feasibility kernel (no host↔device round
trip: on the tunneled link one transfer costs more than this whole
boolean pass), and placements land as one columnar AllocSlab per task
group.

Gate-don't-misplace: the vectorized pass runs only when it places on
EVERY candidate node — any filtered/exhausted node, any inexpressible
spec (networks, distinct_property), or an annotate-plan run falls back
to the inherited per-node oracle loop, which owns the reference's exact
failure accounting (shared-metric quirks included).  The fleet-wide
happy path — the case a system job exists for — is the fast one.

Registered as 'tpu-system'; the worker uses it for system evals when
use_tpu_batch_worker is set.  Differentially tested against the oracle
SystemScheduler in tests/test_system_batch.py.
"""
from __future__ import annotations

from typing import Dict, List

import numpy as np

from ..scheduler.scheduler import register_scheduler
from ..scheduler.system import SystemScheduler
from ..scheduler.util import AllocTuple
from ..structs import structs as s
from . import encode


def feasibility_np(ct, st) -> np.ndarray:
    """numpy mirror of kernels.feasibility_matrix — same op codes, same
    missing/unknown-RHS semantics; returns bool[U, n_pad]."""
    n = ct.n_pad
    u = st.constraint_attr.shape[0]
    dc_ok = np.take_along_axis(
        st.dc_mask, np.broadcast_to(
            np.clip(ct.dc_code[None, :], 0, st.dc_mask.shape[1] - 1), (u, n)),
        axis=1)
    dc_ok = dc_ok & (ct.dc_code[None, :] >= 0)
    precomp = (st.precomp if st.precomp.shape == (u, n)
               else np.broadcast_to(st.precomp, (u, n)))
    out = precomp & dc_ok & ct.eligible[None, :]
    for k in range(st.constraint_attr.shape[1]):
        attr_col = st.constraint_attr[:, k]              # [U]
        vals = ct.attr_values[:, attr_col].T             # [U, N]
        rhs = st.constraint_rhs[:, k][:, None]
        op = st.constraint_op[:, k][:, None]
        missing = vals == encode.MISSING
        unknown = rhs == encode.UNKNOWN_RHS
        ok = np.select(
            [op == encode.OP_EQ, op == encode.OP_NE, op == encode.OP_LT,
             op == encode.OP_LE, op == encode.OP_GT, op == encode.OP_GE],
            [(vals == rhs) & ~unknown, (vals != rhs) | unknown,
             vals < rhs, vals <= rhs, vals > rhs, vals >= rhs],
            default=True,
        )
        ok = np.where(op == encode.OP_TRUE, True, ok & ~missing)
        out = out & ok
    return out


class TPUSystemScheduler(SystemScheduler):
    """SystemScheduler with a vectorized all-or-fallback placement pass."""

    def _compute_placements(self, place: List[AllocTuple]) -> None:
        if self.eval.annotate_plan or not place:
            return super()._compute_placements(place)

        by_tg: Dict[str, List[AllocTuple]] = {}
        order: List[str] = []
        for tup in place:
            if tup.task_group.name not in by_tg:
                by_tg[tup.task_group.name] = []
                order.append(tup.task_group.name)
            by_tg[tup.task_group.name].append(tup)
        specs = {}
        for name in order:
            sp = encode.build_spec(self.job, by_tg[name][0].task_group, False)
            if sp.needs_oracle or sp.net_active or sp.dp_target is not None:
                return super()._compute_placements(place)
            specs[name] = sp

        spec_list = [specs[name] for name in order]
        attr_targets, literals = encode.collect_attr_targets(spec_list)
        allocs_by_node: Dict[str, List[s.Allocation]] = {}
        # Allocs staged for eviction in THIS plan free their capacity
        # (EvalContext.ProposedAllocs subtracts plan.node_update).
        evicted = {a.id for ups in self.plan.node_update.values()
                   for a in ups}
        alloc_rows = getattr(self.state, "alloc_rows", None)
        if alloc_rows is not None:
            rows = alloc_rows(None)
        else:
            rows = [(a.node_id, a) for a in self.state.allocs(None)]
        for node_id, row in rows:
            if not row.terminal_status() and row.id not in evicted:
                allocs_by_node.setdefault(node_id, []).append(row)

        ct = encode.encode_cluster(self.nodes, attr_targets, allocs_by_node)
        encode.finalize_codebooks(ct, literals)
        st = encode.encode_specs(spec_list, ct, self.nodes)
        feas = feasibility_np(ct, st)
        node_index = {nid: i for i, nid in enumerate(ct.node_ids)}
        used = ct.used.copy()                       # [n_pad, 4] int64
        capacity = ct.capacity

        staged: List[tuple] = []
        for u, name in enumerate(order):
            sp = specs[name]
            tups = by_tg[name]
            idx = np.array([node_index[t.alloc.node_id] for t in tups],
                           dtype=np.int64)
            feas_rows = feas[u, idx]
            fits = np.all(sp.ask[None, :] <= (capacity[idx] - used[idx]),
                          axis=1)
            if not bool(np.all(feas_rows & fits)):
                # Any failure → the oracle loop owns the exact filtered/
                # exhausted/queued accounting.  Nothing staged yet, so the
                # fallback starts clean.
                return super()._compute_placements(place)
            # Later task groups of this job see this group's placements
            # (the per-node loop's ProposedAllocs would).
            np.add.at(used, idx, sp.ask)
            staged.append((name, tups))

        for name, tups in staged:
            tg = tups[0].task_group
            # Fresh per-group metric matching the oracle's per-select
            # reset: on the happy path every node's chain sees exactly
            # one evaluated node and no filters, so one shared object
            # per group carries identical content (slab convention).
            m = s.AllocMetric()
            m.nodes_evaluated = 1
            m.nodes_available = self.nodes_by_dc
            combined = s.Resources(disk_mb=tg.ephemeral_disk.size_mb)
            for t in tg.tasks:
                combined.add(t.resources)
            proto = s.Allocation(
                eval_id=self.eval.id,
                job_id=self.job.id,
                task_group=tg.name,
                metrics=m,
                resources=combined,
                task_resources={t.name: t.resources.copy()
                                for t in tg.tasks},
                desired_status=s.ALLOC_DESIRED_STATUS_RUN,
                client_status=s.ALLOC_CLIENT_STATUS_PENDING,
                shared_resources=s.Resources(
                    disk_mb=tg.ephemeral_disk.size_mb),
            )
            prevs = [(t.alloc.id or "") if t.alloc is not None else ""
                     for t in tups]
            slab = s.AllocSlab(
                proto=proto,
                ids=s.generate_uuids(len(tups)),
                names=[t.name for t in tups],
                node_ids=[t.alloc.node_id for t in tups],
                prev_ids=prevs if any(prevs) else [],
            )
            self.plan.append_slab(slab)


def new_tpu_system_scheduler(logger, state, planner) -> TPUSystemScheduler:
    return TPUSystemScheduler(logger, state, planner)


register_scheduler("tpu-system", new_tpu_system_scheduler)
