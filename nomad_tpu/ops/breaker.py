"""TPU-path circuit breaker: graceful degradation from kernel to oracle.

The batch scheduler cross-checks device results against the CPU oracle
wherever both exist (preemption eviction sets) and validates structural
invariants of every kernel output (ops/batch_sched.py
``validate_device_outputs``).  Those checks feed this breaker; when
agreement over a sliding window drops below threshold, the breaker
**trips open** and every eval routes through the CPU ``GenericScheduler``
oracle — scheduling slows down but never stops or mis-places.  After a
cooldown the breaker goes **half-open**: exactly one batch probes the
kernel path; a clean probe closes the breaker, a dirty one re-opens it.

The breaker is process-wide (module singleton): ``BatchWorker``
constructs a fresh ``TPUBatchScheduler`` per batch, and a breaker that
forgot its state between batches would never hold open.

Env knobs (README "Fault model & degradation"):

- ``NOMAD_TPU_BREAKER_THRESHOLD``  — min agreement ratio (default 0.9)
- ``NOMAD_TPU_BREAKER_WINDOW``     — sliding window size in checks (64)
- ``NOMAD_TPU_BREAKER_MIN_CHECKS`` — checks required before tripping (8)
- ``NOMAD_TPU_BREAKER_COOLDOWN``   — seconds open before a probe (10)
- ``NOMAD_TPU_BREAKER_DISABLE``    — 1 ⇒ never trip (kernel always runs)
"""
from __future__ import annotations

import logging
import os
import threading
import time
from collections import deque
from typing import Callable, Optional

from .. import fault
from ..utils import blackbox, tracing

logger = logging.getLogger("nomad_tpu.ops.breaker")

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half-open"


def _stream_transition(frm: str, to: str, **payload) -> None:
    """Mirror a breaker transition into the cluster event stream
    (fault.note_event_stream avoids importing the server package)."""
    fault.note_event_stream("Breaker", "BreakerTransition", to,
                            dict(payload, From=frm, To=to))

# Numeric encoding for the `nomad.breaker.state` gauge (telemetry can
# only carry numbers; 0 = healthy, rising = degraded).
STATE_CODE = {CLOSED: 0, HALF_OPEN: 1, OPEN: 2}


class KernelIntegrityError(Exception):
    """Kernel outputs failed structural validation (corrupt device
    results): the batch must not be materialized into plans."""


def _env_float(name: str, default: float) -> float:
    from ..utils import knobs

    return knobs.get_float(name, default)


class KernelCircuitBreaker:
    def __init__(self, threshold: Optional[float] = None,
                 window: Optional[int] = None,
                 min_checks: Optional[int] = None,
                 cooldown: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic):
        self.threshold = (threshold if threshold is not None else
                          _env_float("NOMAD_TPU_BREAKER_THRESHOLD", 0.9))
        self.window = int(window if window is not None else
                          _env_float("NOMAD_TPU_BREAKER_WINDOW", 64))
        self.min_checks = int(min_checks if min_checks is not None else
                              _env_float("NOMAD_TPU_BREAKER_MIN_CHECKS", 8))
        self.cooldown = (cooldown if cooldown is not None else
                         _env_float("NOMAD_TPU_BREAKER_COOLDOWN", 10.0))
        from ..utils import knobs

        self.disabled = knobs.get_bool("NOMAD_TPU_BREAKER_DISABLE")
        self.clock = clock
        self._l = threading.Lock()
        self._state = CLOSED
        self._checks: deque = deque(maxlen=max(1, self.window))
        self._tripped_at = 0.0
        self._probe_started = 0.0
        self.trips = 0  # lifetime trip count (telemetry / tests)

    # -- observations ------------------------------------------------------

    def record(self, ok: bool, n: int = 1) -> None:
        """Record ``n`` agreement checks with one outcome.  A kernel batch
        contributes its structural-validation verdict plus one check per
        preemption kernel/oracle comparison."""
        if self.disabled or n <= 0:
            return
        with self._l:
            self._checks.extend([bool(ok)] * min(n, self._checks.maxlen))
            if self._state != CLOSED:
                return
            total = len(self._checks)
            if total < self.min_checks:
                return
            ratio = sum(self._checks) / total
            if ratio < self.threshold:
                self._state = OPEN
                self._tripped_at = self.clock()
                self.trips += 1
                tracing.event("breaker.transition", frm=CLOSED, to=OPEN,
                              agreement=round(ratio, 4), trips=self.trips)
                _stream_transition(CLOSED, OPEN,
                                   Agreement=round(ratio, 4),
                                   Trips=self.trips)
                blackbox.note_trigger(
                    "breaker.open", {"Agreement": round(ratio, 4),
                                     "Trips": self.trips})
                logger.warning(
                    "kernel circuit breaker OPEN: agreement %.2f < %.2f "
                    "over %d checks; routing evals through the CPU oracle "
                    "for %.1fs", ratio, self.threshold, total, self.cooldown)

    # -- gating ------------------------------------------------------------

    def allow_kernel(self) -> bool:
        """May the next batch take the device path?  While open, False
        until the cooldown elapses; then exactly one caller gets True as
        the half-open probe and everyone else stays on the oracle until
        ``on_probe`` resolves it."""
        if self.disabled:
            return True
        with self._l:
            if self._state == CLOSED:
                return True
            if self._state == OPEN and (
                    self.clock() - self._tripped_at >= self.cooldown):
                self._state = HALF_OPEN
                self._probe_started = self.clock()
                tracing.event("breaker.transition", frm=OPEN, to=HALF_OPEN)
                _stream_transition(OPEN, HALF_OPEN)
                logger.info("kernel circuit breaker HALF-OPEN: probing the "
                            "device path with one batch")
                return True
            if self._state == HALF_OPEN and (
                    self.clock() - self._probe_started >= self.cooldown):
                # The outstanding probe never resolved (its batch died on
                # an unrelated exception, or the thread was lost): grant a
                # fresh probe rather than wedging on the oracle forever.
                self._probe_started = self.clock()
                logger.warning("kernel circuit breaker: probe expired "
                               "unresolved; granting a new probe batch")
                return True
            return False

    def on_probe(self, ok: bool) -> None:
        """Resolve a half-open probe: clean ⇒ close (fresh window), dirty
        ⇒ re-open and restart the cooldown."""
        with self._l:
            if self._state != HALF_OPEN:
                return
            if ok:
                self._state = CLOSED
                self._checks.clear()
                tracing.event("breaker.transition", frm=HALF_OPEN, to=CLOSED)
                _stream_transition(HALF_OPEN, CLOSED)
                logger.info("kernel circuit breaker CLOSED: probe batch "
                            "agreed; device path restored")
            else:
                self._state = OPEN
                self._tripped_at = self.clock()
                tracing.event("breaker.transition", frm=HALF_OPEN, to=OPEN)
                _stream_transition(HALF_OPEN, OPEN)
                blackbox.note_trigger(
                    "breaker.reopen", {"Trips": self.trips})
                logger.warning("kernel circuit breaker RE-OPEN: probe batch "
                               "disagreed; staying on the CPU oracle")

    # -- introspection -----------------------------------------------------

    @property
    def state(self) -> str:
        with self._l:
            return self._state

    def agreement(self) -> float:
        with self._l:
            return (sum(self._checks) / len(self._checks)
                    if self._checks else 1.0)

    def reset(self) -> None:
        with self._l:
            self._state = CLOSED
            self._checks.clear()
            self._tripped_at = 0.0


# Process-wide breaker shared by every TPUBatchScheduler instance.
BREAKER = KernelCircuitBreaker()


def reset_for_tests() -> None:
    """Fresh process-wide breaker (re-reads env knobs)."""
    global BREAKER
    BREAKER = KernelCircuitBreaker()
