"""Batched eviction-set kernel: the device twin of the preemption oracle
(nomad_tpu/scheduler/preempt.py).

For every (task-group, node) pair at once, compute WHICH
strictly-lower-priority allocations must be evicted for the ask to fit
and the post-eviction bin-pack score — the preemption analogue of the
feasibility/scoring matrices in ops/kernels.py.

The oracle's sequential algorithm vectorizes cleanly because the
candidate order is fixed host-side (sort_candidates: priority asc,
largest-resource-first) and eviction capacity is monotone along it:

- greedy prefix  → an inclusive cumsum over the alloc axis plus one
  monotone-boolean count gives k* (the prefix length) for ALL pairs;
- reverse trim   → one lax.scan over the alloc axis (back to front)
  with a [U, N, 4] freed-capacity carry replays the oracle's
  drop-if-still-fits walk exactly.

Everything is integer arithmetic on the same sorted inputs, so the masks
are bit-identical to the oracle's sets — pinned by the --selfcheck
entry (python -m nomad_tpu.ops) and the test_preempt.py fuzz case.

Memory: the kernel materializes [U, N, A] booleans and an [A, U, N]
scan output (A = max candidate allocs per node, pow2-padded).  At the
bench shape (64 specs x 10k nodes x 16 allocs) that is ~10MB per
buffer; callers with larger spec axes should chunk U.
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..scheduler.preempt import (
    PRIORITY_SENTINEL,
    alloc_size,
    sort_candidates,
)
from ..structs import structs as s
from .encode import RES_DIMS, pow2_bucket


def encode_alloc_tensors(
    node_ids: List[str],
    allocs_by_node: Dict[str, List[s.Allocation]],
    prio_of: Callable[[s.Allocation], int],
    n_pad: Optional[int] = None,
) -> Tuple[np.ndarray, np.ndarray, List[List[s.Allocation]]]:
    """Per-node candidate tensors in the SHARED oracle order
    (sort_candidates), sentinel-padded:

      prio  [n_pad, A] int32 — PRIORITY_SENTINEL padding is never below
                               any real job priority, so padding rows
                               can never enter a candidate prefix;
      sizes [n_pad, A, 4] int32;
      sorted_allocs — per node, the allocs in tensor order (host side,
                      for decoding masks back to allocations).
    """
    n = len(node_ids)
    if n_pad is None:
        n_pad = n
    sorted_allocs: List[List[s.Allocation]] = []
    max_a = 1
    for nid in node_ids:
        cand = sort_candidates(allocs_by_node.get(nid, []), prio_of)
        sorted_allocs.append(cand)
        max_a = max(max_a, len(cand))
    a_pad = pow2_bucket(max_a, minimum=2)

    prio = np.full((n_pad, a_pad), PRIORITY_SENTINEL, dtype=np.int32)
    sizes = np.zeros((n_pad, a_pad, RES_DIMS), dtype=np.int32)
    for i, cand in enumerate(sorted_allocs):
        for a, alloc in enumerate(cand):
            prio[i, a] = prio_of(alloc)
            sizes[i, a] = alloc_size(alloc)
    return prio, sizes, sorted_allocs


@jax.jit
def eviction_sets(
    free: jnp.ndarray,      # [N, 4] int32 — capacity − used (post main pass)
    used: jnp.ndarray,      # [N, 4] int32 — usage incl. reserved
    denom: jnp.ndarray,     # [N, 2] float32 — cpu/mem capacity minus reserved
    prio: jnp.ndarray,      # [N, A] int32 — sorted candidates, sentinel pad
    sizes: jnp.ndarray,     # [N, A, 4] int32
    ask: jnp.ndarray,       # [U, 4] int32
    job_prio: jnp.ndarray,  # [U] int32
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """For every (spec u, node n): the minimal eviction mask over the
    node's sorted candidates, whether preemption makes the ask fit, the
    eviction count, and the post-eviction bin-pack score.

    Returns (mask [U,N,A] bool, feasible [U,N] bool, n_evict [U,N] i32,
    score [U,N] f32).  ``feasible`` is False both when the ask already
    fits with no eviction (the main placement pass owns that case) and
    when even evicting every lower-priority alloc is not enough.
    """
    n, a = prio.shape
    u = ask.shape[0]

    cum = jnp.cumsum(sizes, axis=1)                       # [N, A, 4]
    need = ask[:, None, :] - free[None, :, :]             # [U, N, 4]
    # fits after evicting the k-prefix: k=0 uses freed 0, k>=1 uses
    # cum[k-1].  Monotone in k (sizes are non-negative), so the count of
    # non-fitting prefixes IS k*.
    fits0 = jnp.all(need <= 0, axis=-1)                   # [U, N]
    fits_k = jnp.all(need[:, :, None, :] <= cum[None, :, :, :], axis=-1)
    kstar = (a + 1) - (fits0.astype(jnp.int32)
                       + jnp.sum(fits_k, axis=-1, dtype=jnp.int32))
    ncand = jnp.sum(prio[None, :, :] < job_prio[:, None, None],
                    axis=-1, dtype=jnp.int32)             # [U, N]
    feasible = (kstar >= 1) & (kstar <= ncand)

    arange_a = jnp.arange(a, dtype=jnp.int32)
    m0 = arange_a[None, None, :] < kstar[:, :, None]      # [U, N, A]
    m0 = m0 & feasible[:, :, None]
    freed0 = jnp.einsum("una,nad->und", m0.astype(jnp.int32), sizes)

    def trim(freed, t):
        idx = a - 1 - t
        in_set = m0[:, :, idx]                            # [U, N]
        size_i = sizes[:, idx, :][None, :, :]             # [1, N, 4]
        drop = in_set & jnp.all(need <= freed - size_i, axis=-1)
        freed = freed - drop[:, :, None] * size_i
        return freed, drop

    freed_final, drops = lax.scan(trim, freed0, jnp.arange(a))
    # drops is stacked in scan order (alloc axis reversed) → [U, N, A].
    mask = m0 & ~jnp.flip(jnp.transpose(drops, (1, 2, 0)), axis=-1)
    n_evict = jnp.sum(mask, axis=-1, dtype=jnp.int32)

    # Post-eviction ScoreFit: usage after evicting the set and placing
    # the ask, flattened to rows so kernels._score_fit (the ONE home of
    # the 10^freeFrac expression and its measured-fusion caveats) scores
    # every (spec, node) pair.
    from .kernels import _score_fit

    after = (used[None, :, :] - freed_final
             + ask[:, None, :]).reshape(u * n, 4)
    denom_rows = jnp.broadcast_to(denom[None, :, :], (u, n, 2)
                                  ).reshape(u * n, 2)
    score = _score_fit(after, jnp.zeros(4, dtype=jnp.int32),
                       denom_rows).reshape(u, n)

    return mask, feasible, n_evict, score


def random_cluster(n_nodes: int, n_specs: int, seed: int = 0):
    """Seeded random preemption problem for agreement checks: nodes at
    high utilization with mixed-priority, mixed-size allocs, plus
    high-priority asks that mostly need eviction to fit."""
    rng = np.random.RandomState(seed)
    nodes: List[s.Node] = []
    allocs_by_node: Dict[str, List[s.Allocation]] = {}
    for i in range(n_nodes):
        node = s.Node(
            id=f"n{i:04d}",
            datacenter="dc1",
            resources=s.Resources(cpu=4000, memory_mb=8192,
                                  disk_mb=100 * 1024, iops=150),
            reserved=s.Resources(cpu=100, memory_mb=256),
            status=s.NODE_STATUS_READY,
        )
        nodes.append(node)
        allocs = []
        for a in range(int(rng.randint(0, 9))):
            job = s.Job(id=f"filler-{i}-{a}",
                        priority=int(rng.randint(1, 80)))
            allocs.append(s.Allocation(
                id=f"a{i:04d}-{a}",
                job_id=job.id,
                job=job,
                node_id=node.id,
                resources=s.Resources(
                    cpu=int(rng.randint(100, 900)),
                    memory_mb=int(rng.randint(128, 1800)),
                    disk_mb=int(rng.randint(0, 2000)),
                    iops=int(rng.randint(0, 20))),
            ))
        allocs_by_node[node.id] = allocs
    asks = [s.Resources(cpu=int(rng.randint(500, 3000)),
                        memory_mb=int(rng.randint(512, 6000)),
                        disk_mb=int(rng.randint(0, 4000)),
                        iops=int(rng.randint(0, 40)))
            for _ in range(n_specs)]
    priorities = [int(rng.randint(10, 100)) for _ in range(n_specs)]
    return nodes, allocs_by_node, asks, priorities


def agreement_check(nodes, allocs_by_node, asks, priorities,
                    prio_of=None) -> Tuple[int, int, List[str]]:
    """Run kernel and oracle over every (spec, node) pair; returns
    (pairs_checked, mismatches, first few mismatch descriptions)."""
    from ..scheduler.preempt import alloc_priority, find_eviction_set

    if prio_of is None:
        prio_of = alloc_priority
    node_ids = [n.id for n in nodes]
    prio, sizes, sorted_allocs = encode_alloc_tensors(
        node_ids, allocs_by_node, prio_of)

    free = np.zeros((len(nodes), RES_DIMS), dtype=np.int32)
    used = np.zeros((len(nodes), RES_DIMS), dtype=np.int32)
    denom = np.ones((len(nodes), 2), dtype=np.float32)
    for i, node in enumerate(nodes):
        cap = np.array([node.resources.cpu, node.resources.memory_mb,
                        node.resources.disk_mb, node.resources.iops],
                       dtype=np.int64)
        u = np.zeros(RES_DIMS, dtype=np.int64)
        if node.reserved is not None:
            rv = node.reserved
            u += (rv.cpu, rv.memory_mb, rv.disk_mb, rv.iops)
        for a in allocs_by_node.get(node.id, []):
            u += np.array(alloc_size(a), dtype=np.int64)
        free[i] = cap - u
        used[i] = u
        denom[i] = (cap[0] - (node.reserved.cpu if node.reserved else 0),
                    cap[1] - (node.reserved.memory_mb
                              if node.reserved else 0))

    ask_arr = np.array([[r.cpu, r.memory_mb, r.disk_mb, r.iops]
                        for r in asks], dtype=np.int32)
    jp = np.array(priorities, dtype=np.int32)
    mask, feasible, n_evict, _score = jax.device_get(eviction_sets(
        jnp.asarray(free), jnp.asarray(used), jnp.asarray(denom),
        jnp.asarray(prio), jnp.asarray(sizes),
        jnp.asarray(ask_arr), jnp.asarray(jp)))

    checked = 0
    n_mismatch = 0
    mismatches: List[str] = []
    for u in range(len(asks)):
        for i, node in enumerate(nodes):
            checked += 1
            oracle = find_eviction_set(
                node, allocs_by_node.get(node.id, []), asks[u],
                priorities[u], prio_of)
            kernel_ids = ([sorted_allocs[i][a].id
                           for a in np.nonzero(mask[u, i])[0]]
                          if feasible[u, i] else None)
            oracle_ids = [a.id for a in oracle] if oracle else None
            if kernel_ids != oracle_ids:
                n_mismatch += 1
                if len(mismatches) < 5:
                    mismatches.append(
                        f"spec {u} node {node.id}: kernel={kernel_ids} "
                        f"oracle={oracle_ids}")
    return checked, n_mismatch, mismatches


def selfcheck(n_nodes: int = 64, n_specs: int = 64, seed: int = 0,
              log=print) -> bool:
    """Oracle-vs-kernel eviction-set agreement on a seeded random
    cluster; the CI smoke behind `python -m nomad_tpu.ops --selfcheck`."""
    nodes, allocs_by_node, asks, priorities = random_cluster(
        n_nodes, n_specs, seed)
    checked, n_mismatch, mismatches = agreement_check(
        nodes, allocs_by_node, asks, priorities)
    if n_mismatch:
        log(f"preempt selfcheck: FAIL — {n_mismatch} of {checked} "
            "pairs disagree; first few:")
        for m in mismatches:
            log(f"  {m}")
        return False
    log(f"preempt selfcheck: OK — kernel == oracle on all {checked} "
        f"(spec, node) pairs ({n_specs} specs x {n_nodes} nodes)")
    return True
