"""Central registry of differential-guard coverage.

The repo's standing discipline (ROADMAP item 3): every component with a
fast path and a reference path — native (C++) twins, columnar numpy
mirrors, resident device mirrors, quantized encodings — must be
*paired* with (a) a registered differential guard that bit-compares the
fast path against the reference, (b) a feed into the PR 2 kernel
circuit breaker on mismatch, and (c) an env kill-switch that restores
the reference path.  Until this PR that pairing was enforced only by
convention and review; this registry makes it *structural*: every pair
is declared here, and the static analysis pass
(``nomad_tpu/analysis/guardrules.py``) fails the tree when

- a ``native/*.cc`` source exists with no registry entry,
- an entry names a guard symbol its module does not define,
- an entry's kill-switch / guard-cadence knob is not declared in
  ``utils/knobs.py``,
- an entry claims a breaker feed its module never makes, or
- an entry waives a requirement without a written justification.

Entries are data, not behavior — the guards themselves live where they
always did, next to the paths they protect.
"""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Optional, Tuple

__all__ = ["GuardEntry", "REGISTRY", "native_sources"]


@dataclass(frozen=True)
class GuardEntry:
    name: str
    # "native_twin" | "columnar_mirror" | "device_mirror" | "encoding"
    kind: str
    # Module owning the guard machinery (dotted path).
    module: str
    # The .cc source this entry claims (native twins only).
    native_source: Optional[str] = None
    # Symbol the module must define (the guard cadence accessor or the
    # guard counter); None only with a waiver.
    guard_symbol: Optional[str] = None
    # Cadence knob (None ⇒ the guard runs on every call).
    guard_every_knob: Optional[str] = None
    # Env kill-switches restoring the reference path.
    kill_switches: Tuple[str, ...] = ()
    # The module feeds breaker.record(False) on mismatch.
    breaker_feed: bool = True
    # Waives the guard/breaker requirement — MUST carry a reason.
    waiver: str = ""
    # Where the pairing is exercised (docs pointer, not checked).
    tests: str = ""


REGISTRY: List[GuardEntry] = [
    GuardEntry(
        name="codec.string_columns",
        kind="native_twin",
        module="nomad_tpu.codec.native",
        native_source="codec.cc",
        guard_symbol="guard_every",
        guard_every_knob="NOMAD_TPU_CODEC_GUARD_EVERY",
        kill_switches=("NOMAD_TPU_NO_NATIVE", "NOMAD_TPU_CODEC"),
        breaker_feed=True,
        tests="tests/test_codec.py (twin corpus + truncation)",
    ),
    GuardEntry(
        name="decode.packed_results",
        kind="native_twin",
        module="nomad_tpu.ops.decode",
        native_source="decode.cc",
        guard_symbol="guard_every",
        guard_every_knob="NOMAD_TPU_DECODE_GUARD_EVERY",
        kill_switches=("NOMAD_TPU_NO_NATIVE",),
        breaker_feed=True,
        tests="tests/test_resident.py native-decode twins",
    ),
    GuardEntry(
        name="wal.group_commit",
        kind="native_twin",
        module="nomad_tpu.server.raft",
        native_source="wal.cc",
        guard_symbol=None,
        kill_switches=("NOMAD_TPU_NO_NATIVE",),
        breaker_feed=False,
        waiver=(
            "durability backend: an online differential guard would "
            "double every fsync; the pure-Python synced-seq twin is "
            "pinned equivalent by tests/test_native_wal.py and the "
            "torn-frame chaos drills instead"),
        tests="tests/test_native_wal.py, wal selfcheck drill",
    ),
    GuardEntry(
        name="ids.bulk_uuids",
        kind="native_twin",
        module="nomad_tpu.structs.funcs",
        native_source="ids.cc",
        guard_symbol=None,
        kill_switches=("NOMAD_TPU_NO_NATIVE",),
        breaker_feed=False,
        waiver=(
            "random output has no deterministic twin to bit-compare; "
            "format/uniqueness are asserted by the generate_uuid tests "
            "and every consumer parses the 36-char form"),
        tests="tests/test_structs_funcs.py",
    ),
    GuardEntry(
        name="columnar.node_table",
        kind="columnar_mirror",
        module="nomad_tpu.state.columnar",
        guard_symbol="guard_every",
        guard_every_knob="NOMAD_TPU_COLUMNAR_GUARD_EVERY",
        kill_switches=("NOMAD_TPU_COLUMNAR",),
        breaker_feed=True,
        tests="tests/test_columnar.py (conftest pins cadence 1)",
    ),
    GuardEntry(
        name="columnar.usage_matrix",
        kind="columnar_mirror",
        module="nomad_tpu.state.columnar",
        guard_symbol="USAGE_GUARD_RUNS",
        guard_every_knob="NOMAD_TPU_COLUMNAR_GUARD_EVERY",
        kill_switches=("NOMAD_TPU_COLUMNAR",),
        breaker_feed=True,
        tests="tests/test_columnar.py usage-guard cases",
    ),
    GuardEntry(
        name="resident.device_mirror",
        kind="device_mirror",
        module="nomad_tpu.ops.resident",
        guard_symbol="guard_every",
        guard_every_knob="NOMAD_TPU_RESIDENT_GUARD_EVERY",
        kill_switches=("NOMAD_TPU_RESIDENT",
                       "NOMAD_TPU_RESIDENT_DEVICE"),
        breaker_feed=True,
        tests="tests/test_resident.py, tests/test_mesh_sched.py "
              "(per-shard attribution)",
    ),
    GuardEntry(
        name="encode.quantized_rows",
        kind="encoding",
        module="nomad_tpu.ops.resident",
        guard_symbol="check_quant_roundtrip",
        guard_every_knob=None,  # every static encode
        kill_switches=("NOMAD_TPU_QUANT",),
        breaker_feed=True,
        tests="tests/test_fused.py quant round-trip cases",
    ),
]


def native_sources() -> List[str]:
    """The .cc files the registry claims (guardrules compares this to
    the actual contents of nomad_tpu/native/)."""
    return [e.native_source for e in REGISTRY
            if e.native_source is not None]
