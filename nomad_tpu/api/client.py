"""Python SDK for the HTTP API.

Reference behavior: the api/ Go package (api/api.go Client + per-resource
handles api/jobs.go, api/nodes.go, api/allocations.go, api/evaluations.go,
api/agent.go, api/operator.go, api/system.go).  Shapes: jobs are
structs.Job dataclasses encoded through api/codec.py; list endpoints return
stub dicts exactly as the HTTP layer emits them.

QueryOptions carry the blocking-query contract (wait_index + wait_time ->
``?index&wait``), and every query returns QueryMeta with the last index so
callers can long-poll, like the reference's WaitIndex loop.
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.parse
import urllib.request
from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Tuple

from ..structs import structs as s
from ..utils.backoff import Backoff
from .codec import from_wire, to_wire


class APIError(Exception):
    def __init__(self, code: int, message: str, retry_after: float = 0.0):
        super().__init__(f"Unexpected response code: {code} ({message})")
        self.code = code
        # 429 admission NACKs carry the server's Retry-After hint;
        # callers feed it into their jittered-backoff retry loops.
        self.retry_after = retry_after


@dataclass
class QueryOptions:
    region: str = ""
    prefix: str = ""
    wait_index: int = 0
    wait_time: float = 0.0  # seconds
    params: Optional[Dict[str, str]] = None


@dataclass
class QueryMeta:
    last_index: int = 0
    known_leader: bool = False


class NomadAPI:
    """api.Client (api/api.go:221 NewClient)."""

    def __init__(self, address: str = "http://127.0.0.1:4646",
                 region: str = "", timeout: float = 330.0):
        self.address = address.rstrip("/")
        self.region = region
        self.timeout = timeout
        self.jobs = Jobs(self)
        self.nodes = Nodes(self)
        self.allocations = Allocations(self)
        self.evaluations = Evaluations(self)
        self.agent = AgentAPI(self)
        self.system = System(self)
        self.operator = Operator(self)
        self.status = Status(self)
        self.events = Events(self)
        self.namespaces = Namespaces(self)
        self.regions = Regions(self)

    # -- raw transport -----------------------------------------------------

    def _url(self, path: str, q: Optional[QueryOptions]) -> str:
        params: Dict[str, str] = {}
        if q is not None:
            if q.region or self.region:
                params["region"] = q.region or self.region
            if q.prefix:
                params["prefix"] = q.prefix
            if q.wait_index:
                params["index"] = str(q.wait_index)
            if q.wait_time:
                params["wait"] = f"{q.wait_time}s"
            if q.params:
                params.update(q.params)
        qs = ("?" + urllib.parse.urlencode(params)) if params else ""
        return self.address + path + qs

    def _do(self, method: str, path: str, body: Any = None,
            q: Optional[QueryOptions] = None) -> Tuple[Any, QueryMeta]:
        data = None
        if body is not None:
            data = json.dumps(to_wire(body)).encode()
        req = urllib.request.Request(self._url(path, q), data=data,
                                     method=method)
        req.add_header("Content-Type", "application/json")
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                raw = resp.read()
                meta = QueryMeta(
                    last_index=int(resp.headers.get("X-Nomad-Index") or 0),
                    known_leader=resp.headers.get(
                        "X-Nomad-KnownLeader") == "true")
                obj = json.loads(raw) if raw else None
                return obj, meta
        except urllib.error.HTTPError as e:
            try:
                retry_after = float(e.headers.get("Retry-After") or 0.0)
            except (TypeError, ValueError):
                retry_after = 0.0
            raise APIError(e.code, e.read().decode("utf-8", "replace"),
                           retry_after=retry_after) from e
        except urllib.error.URLError as e:
            # connection-level failure (agent down, bad address)
            raise APIError(0, f"failed to reach agent at "
                              f"{self.address}: {e.reason}") from e

    def get(self, path: str, q: Optional[QueryOptions] = None):
        return self._do("GET", path, None, q)

    def put(self, path: str, body: Any = None, q: Optional[QueryOptions] = None):
        return self._do("PUT", path, body, q)

    def delete(self, path: str, q: Optional[QueryOptions] = None):
        return self._do("DELETE", path, None, q)


class Jobs:
    """api/jobs.go."""

    def __init__(self, c: NomadAPI):
        self.c = c

    def list(self, q: Optional[QueryOptions] = None) -> Tuple[List[dict], QueryMeta]:
        return self.c.get("/v1/jobs", q)

    def register(self, job: s.Job,
                 q: Optional[QueryOptions] = None) -> Tuple[dict, QueryMeta]:
        return self.c.put("/v1/jobs", {"Job": to_wire(job)},
                          q or QueryOptions())

    def register_with_retry(self, job: s.Job, retries: int = 5,
                            q: Optional[QueryOptions] = None,
                            sleep=time.sleep,
                            backoff: Optional[Backoff] = None
                            ) -> Tuple[dict, QueryMeta]:
        """register() with jittered client-side retry on 429 admission
        NACKs.  The delay honors the server's Retry-After hint but
        jitters it (0.5x-1.5x) so a rejected burst doesn't re-arrive as
        the same burst, and never waits less than the utils/backoff
        exponential floor.  Non-429 errors (and the final 429) raise
        unchanged."""
        bo = backoff or Backoff(base=0.05, max_delay=5.0)
        for attempt in range(retries + 1):
            try:
                return self.register(job, q)
            except APIError as e:
                if e.code != 429 or attempt >= retries:
                    raise
                delay = bo.next_delay()
                if e.retry_after > 0:
                    delay = max(delay,
                                e.retry_after * (0.5 + bo.rng.random()))
                sleep(delay)
        raise AssertionError("unreachable")

    def info(self, job_id: str, q: Optional[QueryOptions] = None
             ) -> Tuple[s.Job, QueryMeta]:
        obj, meta = self.c.get(f"/v1/job/{job_id}", q)
        return from_wire(s.Job, obj), meta

    def deregister(self, job_id: str, purge: bool = True,
                   q: Optional[QueryOptions] = None) -> Tuple[dict, QueryMeta]:
        base = q or QueryOptions()
        params = dict(base.params or {})
        params["purge"] = "true" if purge else "false"
        merged = QueryOptions(region=base.region, prefix=base.prefix,
                              wait_index=base.wait_index,
                              wait_time=base.wait_time, params=params)
        return self.c.delete(f"/v1/job/{job_id}", merged)

    def allocations(self, job_id: str, all_allocs: bool = False,
                    q: Optional[QueryOptions] = None):
        q = q or QueryOptions()
        if all_allocs:
            q.params = dict(q.params or {}, all="true")
        return self.c.get(f"/v1/job/{job_id}/allocations", q)

    def evaluations(self, job_id: str, q: Optional[QueryOptions] = None):
        return self.c.get(f"/v1/job/{job_id}/evaluations", q)

    def summary(self, job_id: str, q: Optional[QueryOptions] = None
                ) -> Tuple[s.JobSummary, QueryMeta]:
        obj, meta = self.c.get(f"/v1/job/{job_id}/summary", q)
        return from_wire(s.JobSummary, obj), meta

    def plan(self, job: s.Job, diff: bool = True) -> Tuple[s.JobPlanResponse, QueryMeta]:
        obj, meta = self.c.put(f"/v1/job/{job.id}/plan",
                               {"Job": to_wire(job), "Diff": diff})
        return from_wire(s.JobPlanResponse, obj), meta

    def evaluate(self, job_id: str) -> Tuple[dict, QueryMeta]:
        return self.c.put(f"/v1/job/{job_id}/evaluate")

    def periodic_force(self, job_id: str) -> Tuple[dict, QueryMeta]:
        return self.c.put(f"/v1/job/{job_id}/periodic/force")

    def dispatch(self, job_id: str, payload: bytes = b"",
                 meta: Optional[Dict[str, str]] = None) -> Tuple[dict, QueryMeta]:
        import base64
        body = {"Payload": base64.b64encode(payload).decode("ascii")
                if payload else "", "Meta": meta or {}}
        return self.c.put(f"/v1/job/{job_id}/dispatch", body)

    def validate(self, job: s.Job) -> Tuple[dict, QueryMeta]:
        return self.c.put("/v1/validate/job", {"Job": to_wire(job)})


class Nodes:
    """api/nodes.go."""

    def __init__(self, c: NomadAPI):
        self.c = c

    def list(self, q: Optional[QueryOptions] = None):
        return self.c.get("/v1/nodes", q)

    def info(self, node_id: str, q: Optional[QueryOptions] = None
             ) -> Tuple[s.Node, QueryMeta]:
        obj, meta = self.c.get(f"/v1/node/{node_id}", q)
        return from_wire(s.Node, obj), meta

    def allocations(self, node_id: str, q: Optional[QueryOptions] = None
                    ) -> Tuple[List[s.Allocation], QueryMeta]:
        obj, meta = self.c.get(f"/v1/node/{node_id}/allocations", q)
        return [from_wire(s.Allocation, a) for a in obj or []], meta

    def force_evaluate(self, node_id: str) -> Tuple[dict, QueryMeta]:
        return self.c.put(f"/v1/node/{node_id}/evaluate")

    def toggle_drain(self, node_id: str, drain: bool) -> Tuple[dict, QueryMeta]:
        q = QueryOptions(params={"enable": "true" if drain else "false"})
        return self.c.put(f"/v1/node/{node_id}/drain", None, q)


class Allocations:
    """api/allocations.go."""

    def __init__(self, c: NomadAPI):
        self.c = c

    def list(self, q: Optional[QueryOptions] = None):
        return self.c.get("/v1/allocations", q)

    def info(self, alloc_id: str, q: Optional[QueryOptions] = None
             ) -> Tuple[s.Allocation, QueryMeta]:
        obj, meta = self.c.get(f"/v1/allocation/{alloc_id}", q)
        return from_wire(s.Allocation, obj), meta


class Evaluations:
    """api/evaluations.go."""

    def __init__(self, c: NomadAPI):
        self.c = c

    def list(self, q: Optional[QueryOptions] = None
             ) -> Tuple[List[s.Evaluation], QueryMeta]:
        obj, meta = self.c.get("/v1/evaluations", q)
        return [from_wire(s.Evaluation, e) for e in obj or []], meta

    def info(self, eval_id: str, q: Optional[QueryOptions] = None
             ) -> Tuple[s.Evaluation, QueryMeta]:
        obj, meta = self.c.get(f"/v1/evaluation/{eval_id}", q)
        return from_wire(s.Evaluation, obj), meta

    def allocations(self, eval_id: str, q: Optional[QueryOptions] = None):
        return self.c.get(f"/v1/evaluation/{eval_id}/allocations", q)


class AgentAPI:
    """api/agent.go."""

    def __init__(self, c: NomadAPI):
        self.c = c

    def self_info(self) -> dict:
        obj, _ = self.c.get("/v1/agent/self")
        return obj

    def members(self) -> dict:
        obj, _ = self.c.get("/v1/agent/members")
        return obj

    def servers(self) -> List[str]:
        obj, _ = self.c.get("/v1/agent/servers")
        return obj or []

    def join(self, addresses) -> dict:
        q = QueryOptions(params={"address": ",".join(addresses)})
        obj, _ = self.c.put("/v1/agent/join", None, q)
        return obj or {}

    def force_leave(self, node: str) -> None:
        q = QueryOptions(params={"node": node})
        self.c.put("/v1/agent/force-leave", None, q)

    # Gossip keyring (api/agent.go:175-215 ListKeys/InstallKey/UseKey/
    # RemoveKey → /v1/agent/keyring/<op>).
    def list_keys(self) -> dict:
        obj, _ = self.c.get("/v1/agent/keyring/list")
        return obj

    def install_key(self, key: str) -> dict:
        obj, _ = self.c.put("/v1/agent/keyring/install", {"Key": key})
        return obj

    def use_key(self, key: str) -> dict:
        obj, _ = self.c.put("/v1/agent/keyring/use", {"Key": key})
        return obj

    def remove_key(self, key: str) -> dict:
        obj, _ = self.c.put("/v1/agent/keyring/remove", {"Key": key})
        return obj

    def profile_continuous(self, seconds: float = 60.0) -> dict:
        """Rolling host-attribution window (/v1/profile/continuous):
        CPU shares per subsystem, coverage, GIL pressure, top locks."""
        q = QueryOptions(params={"seconds": str(seconds)})
        obj, _ = self.c.get("/v1/profile/continuous", q)
        return obj

    def debug_bundle(self, reason: str = "operator.request") -> dict:
        """Force a flight-recorder capture (/v1/debug/blackbox) and
        return the bundle (requires enable_debug on the agent)."""
        q = QueryOptions(params={"reason": reason})
        obj, _ = self.c.get("/v1/debug/blackbox", q)
        return obj

    def client_stats(self) -> dict:
        obj, _ = self.c.get("/v1/client/stats")
        return obj

    def alloc_stats(self, alloc_id: str) -> dict:
        obj, _ = self.c.get(f"/v1/client/allocation/{alloc_id}/stats")
        return obj

    def task_logs(self, alloc_id: str, task: str,
                  log_type: str = "stdout") -> str:
        obj, _ = self.c.get(
            f"/v1/client/fs/logs/{alloc_id}",
            QueryOptions(params={"task": task, "type": log_type}))
        return obj or ""

    def _stream(self, path: str, params: Dict[str, str], follow: bool):
        """Consume an NDJSON StreamFrame response (api/fs.go Stream):
        yields dicts with 'Data' decoded back to bytes.  Transport errors
        surface as APIError, like the non-streaming paths."""
        import base64
        import urllib.request

        url = self.c._url(path, QueryOptions(params=params))
        req = urllib.request.Request(url)
        try:
            resp = urllib.request.urlopen(
                req, timeout=None if follow else self.c.timeout)
        except urllib.error.HTTPError as e:
            raise APIError(e.code, e.read().decode("utf-8", "replace")) from e
        except urllib.error.URLError as e:
            raise APIError(0, f"failed to reach agent at "
                              f"{self.c.address}: {e.reason}") from e
        try:
            for line in resp:
                line = line.strip()
                if not line:
                    continue
                frame = json.loads(line)
                if frame.get("Data"):
                    frame["Data"] = base64.b64decode(frame["Data"])
                yield frame
        except OSError as e:
            raise APIError(0, f"stream interrupted: {e}") from e
        finally:
            resp.close()

    def stream_logs(self, alloc_id: str, task: str,
                    log_type: str = "stdout", follow: bool = False,
                    offset: int = 0, origin: str = "start"):
        """Framed log streaming (api/fs.go Logs): generator of StreamFrames."""
        return self._stream(
            f"/v1/client/fs/logs/{alloc_id}",
            {"task": task, "type": log_type, "origin": origin,
             "offset": str(offset),
             "follow": "true" if follow else "false"}, follow)

    def stream_file(self, alloc_id: str, path: str, follow: bool = True,
                    offset: int = 0, origin: str = "start"):
        """Framed single-file streaming (api/fs.go Stream)."""
        return self._stream(
            f"/v1/client/fs/stream/{alloc_id}",
            {"path": path, "origin": origin, "offset": str(offset),
             "follow": "true" if follow else "false"}, follow)

    def fs_list(self, alloc_id: str, path: str = "/") -> List[dict]:
        obj, _ = self.c.get(f"/v1/client/fs/ls/{alloc_id}",
                            QueryOptions(params={"path": path}))
        return obj or []

    def fs_cat(self, alloc_id: str, path: str) -> str:
        obj, _ = self.c.get(f"/v1/client/fs/cat/{alloc_id}",
                            QueryOptions(params={"path": path}))
        return obj or ""

    def fs_stat(self, alloc_id: str, path: str) -> dict:
        obj, _ = self.c.get(f"/v1/client/fs/stat/{alloc_id}",
                            QueryOptions(params={"path": path}))
        return obj or {}


class Events:
    """api/event.go (the 1.0 event stream consumer handle)."""

    def __init__(self, c: NomadAPI):
        self.c = c

    def stream(self, topics: Optional[List[str]] = None, index: int = 0,
               follow: bool = True):
        """Consume /v1/event/stream: a generator of event dicts
        ({Topic, Type, Key, Index, Payload, EvalID, SpanID, Wall}).
        ``topics`` entries are ``Topic`` or ``Topic:key``; ``index``
        resumes from a raft index (events with Index >= index replay
        from the server's ring); ``follow=False`` drains the buffered
        backlog and returns.  Idle-heartbeat frames (``{}``) are
        filtered out.  An out-of-ring resume surfaces as APIError 400
        carrying the oldest buffered index; an in-band server error
        frame (e.g. the slow-subscriber shed) raises APIError too, so
        every yielded value is a real event dict."""
        params: Dict[str, str] = {
            "follow": "true" if follow else "false"}
        if topics:
            params["topic"] = ",".join(topics)
        if index:
            params["index"] = str(index)
        url = self.c._url("/v1/event/stream", QueryOptions(params=params))
        req = urllib.request.Request(url)
        try:
            resp = urllib.request.urlopen(
                req, timeout=None if follow else self.c.timeout)
        except urllib.error.HTTPError as e:
            raise APIError(e.code, e.read().decode("utf-8", "replace")) from e
        except urllib.error.URLError as e:
            raise APIError(0, f"failed to reach agent at "
                              f"{self.c.address}: {e.reason}") from e
        try:
            for line in resp:
                line = line.strip()
                if not line:
                    continue
                event = json.loads(line)
                if not event:
                    continue  # idle heartbeat
                if "Error" in event and "Topic" not in event:
                    raise APIError(0, event["Error"])
                yield event
        except OSError as e:
            raise APIError(0, f"event stream interrupted: {e}") from e
        finally:
            resp.close()


class Namespaces:
    """Tenancy handle: /v1/namespaces + /v1/namespace/<name>."""

    def __init__(self, c: NomadAPI):
        self.c = c

    def list(self, q: Optional[QueryOptions] = None
             ) -> Tuple[List[s.Namespace], QueryMeta]:
        obj, meta = self.c.get("/v1/namespaces", q)
        return [from_wire(s.Namespace, n) for n in obj or []], meta

    def register(self, ns: s.Namespace) -> Tuple[dict, QueryMeta]:
        return self.c.put("/v1/namespaces", {"Namespace": to_wire(ns)})

    def status(self, name: str) -> Tuple[dict, QueryMeta]:
        """Row + live usage + admission counters; the Namespace value
        under "Namespace" stays a wire dict (mixed payload)."""
        return self.c.get(f"/v1/namespace/{name}")

    def deregister(self, name: str) -> Tuple[dict, QueryMeta]:
        return self.c.delete(f"/v1/namespace/{name}")


class Regions:
    """Federation handle: /v1/regions (api/regions.go)."""

    def __init__(self, c: NomadAPI):
        self.c = c

    def names(self) -> List[str]:
        """Plain sorted region-name list (api/regions.go List)."""
        obj, _ = self.c.get("/v1/regions")
        return obj or []

    def list(self) -> List[dict]:
        """Detail rows: [{"Name", "Servers", "Leader"}, ...] — region
        name, alive server count, best-known leader address ("" when
        that region is currently unreachable)."""
        obj, _ = self.c.get("/v1/regions",
                            QueryOptions(params={"detail": "1"}))
        return obj or []


class System:
    """api/system.go."""

    def __init__(self, c: NomadAPI):
        self.c = c

    def garbage_collect(self) -> None:
        self.c.put("/v1/system/gc")

    def reconcile_summaries(self) -> None:
        self.c.put("/v1/system/reconcile/summaries")

    def broker_stats(self) -> dict:
        """Eval-broker saturation surface (/v1/broker/stats)."""
        obj, _ = self.c.get("/v1/broker/stats")
        return obj or {}


class Operator:
    """api/operator.go."""

    def __init__(self, c: NomadAPI):
        self.c = c

    def raft_get_configuration(self) -> dict:
        obj, _ = self.c.get("/v1/operator/raft/configuration")
        return obj

    def raft_remove_peer_by_address(self, address: str) -> None:
        """(api/operator.go:69 RaftRemovePeerByAddress)."""
        self.c.delete("/v1/operator/raft/peer",
                      QueryOptions(params={"address": address}))


class Status:
    """api/status.go."""

    def __init__(self, c: NomadAPI):
        self.c = c

    def leader(self) -> str:
        obj, _ = self.c.get("/v1/status/leader")
        return obj or ""

    def peers(self) -> List[str]:
        obj, _ = self.c.get("/v1/status/peers")
        return obj or []
