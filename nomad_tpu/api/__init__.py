"""Client SDK + wire codec (reference: api/ Go package)."""

from .client import (AgentAPI, Allocations, APIError, Evaluations, Jobs,
                     NomadAPI, Nodes, Operator, QueryMeta, QueryOptions,
                     Status, System)
from .codec import from_wire, to_wire

__all__ = ["AgentAPI", "Allocations", "APIError", "Evaluations", "Jobs",
           "NomadAPI", "Nodes", "Operator", "QueryMeta", "QueryOptions",
           "Status", "System", "from_wire", "to_wire"]
