"""Wire codec: dataclasses <-> Go-style CamelCase JSON objects.

The reference exposes its API as CamelCase JSON of the api/ package structs
(api/jobs.go etc.) encoded by encoding/json.  Here one reflection codec
serves every struct: encode walks dataclass fields emitting
``{GoName: value}``; decode resolves typing hints (Optional/List/Dict/
nested dataclasses) and accepts both CamelCase and snake_case keys.

Durations are plain float seconds on the wire (the reference emits Go
nanosecond ints; seconds are the TPU-build convention, documented in the
SDK).  ``bytes`` round-trip as base64 strings, matching encoding/json.
"""

from __future__ import annotations

import base64
import dataclasses
import typing
from typing import Any, Dict, Optional, Type

from ..utils.names import go_name

_HINTS_CACHE: Dict[type, Dict[str, Any]] = {}
_KEYMAP_CACHE: Dict[type, Dict[str, str]] = {}


def _hints(cls: type) -> Dict[str, Any]:
    h = _HINTS_CACHE.get(cls)
    if h is None:
        h = typing.get_type_hints(cls)
        _HINTS_CACHE[cls] = h
    return h


def _keymap(cls: type) -> Dict[str, str]:
    """wire key (CamelCase or snake) -> dataclass field name."""
    m = _KEYMAP_CACHE.get(cls)
    if m is None:
        m = {}
        for f in dataclasses.fields(cls):
            m[go_name(f.name)] = f.name
            m[f.name] = f.name
        _KEYMAP_CACHE[cls] = m
    return m


def to_wire(v: Any) -> Any:
    """Encode any value (dataclass trees included) to JSON-ready data."""
    if dataclasses.is_dataclass(v) and not isinstance(v, type):
        return {go_name(f.name): to_wire(getattr(v, f.name))
                for f in dataclasses.fields(v)}
    if isinstance(v, dict):
        return {k: to_wire(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [to_wire(x) for x in v]
    if isinstance(v, bytes):
        return base64.b64encode(v).decode("ascii")
    if getattr(v, "__lazy_strs__", False):
        # Lazily-generated slab columns (structs._LazyStrs) materialize
        # to plain string lists on the wire.
        return list(v)
    return v


def from_wire(typ: Any, data: Any) -> Any:
    """Decode JSON data into an instance of ``typ`` (a dataclass or a
    typing hint)."""
    if data is None:
        return None
    origin = typing.get_origin(typ)
    if origin is typing.Union:  # Optional[X] and unions
        for arg in typing.get_args(typ):
            if arg is type(None):
                continue
            return from_wire(arg, data)
        return data
    if origin in (list, tuple):
        (arg,) = typing.get_args(typ) or (Any,)
        return [from_wire(arg, x) for x in data]
    if origin is dict:
        args = typing.get_args(typ)
        val_t = args[1] if len(args) == 2 else Any
        return {k: from_wire(val_t, v) for k, v in data.items()}
    if typ is bytes:
        if isinstance(data, str):
            return base64.b64decode(data)
        return bytes(data)
    if typ is float:
        return float(data)
    if typ is int:
        return int(data)
    if isinstance(typ, type) and dataclasses.is_dataclass(typ):
        if not isinstance(data, dict):
            raise ValueError(f"expected object for {typ.__name__}, got {data!r}")
        hints = _hints(typ)
        keymap = _keymap(typ)
        kwargs = {}
        for k, v in data.items():
            fname = keymap.get(k)
            if fname is None:
                continue  # lenient: unknown wire keys ignored (like json.Unmarshal)
            kwargs[fname] = from_wire(hints.get(fname, Any), v)
        return typ(**kwargs)
    return data


def ensure(typ: Type, data: Any) -> Any:
    """RPC bodies arrive as dataclasses on struct-codec connections and
    as CamelCase wire dicts on msgpack connections (server/rpc.py sniffs
    per frame).  ``ensure`` is the receiver-side adapter: pass through
    what is already typed, reflect-decode what is not."""
    if data is None or isinstance(data, typ):
        return data
    return from_wire(typ, data)


def ensure_list(typ: Type, seq: Any) -> list:
    return [ensure(typ, x) for x in (seq or [])]


def decode_json(typ: Optional[Type], body: bytes) -> Any:
    import json

    data = json.loads(body.decode("utf-8")) if body else None
    if typ is None or data is None:
        return data
    return from_wire(typ, data)
