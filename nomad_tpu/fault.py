"""Deterministic, seeded fault-injection plane.

Production control planes are judged by what happens when things break:
leaders crash mid-plan, nodes go silent, frames get truncated, kernels
misbehave.  The recovery machinery (nack timers, heartbeat TTLs,
lost-alloc rescheduling, the TPU-path circuit breaker) exists — this
module is how tests *exercise* it deterministically.

Model
-----
Code under test is threaded with named **fault points**::

    act = fault.faultpoint("rpc.send", method="Node.Register")
    if act is not None:
        ...interpret act.kind ("drop" / "delay" / "truncate" / ...)

A disarmed plane (the default, and the only production state) costs one
module-global load and a ``None`` check per call — no locks, no dict
lookups, nothing to configure off.

Tests arm a **scenario**: a seed plus a list of rules.  Each rule names a
point (exact or ``fnmatch`` glob), an action, and firing conditions::

    fault.arm({"seed": 7, "faults": [
        {"point": "heartbeat.deliver", "action": "drop", "times": 3},
        {"point": "raft.apply", "action": "crash",
         "match": {"msg_type": "APPLY_PLAN_RESULTS"}, "after": 1},
        {"point": "rpc.send", "action": "truncate", "prob": 0.2},
    ]})

Determinism: every rule owns a private RNG derived from
``(scenario seed, rule index, point)``, and per-rule hit counters are
taken under one lock — the decision sequence *per rule* is a pure
function of the seed and the order of matching calls.  The plane records
every fire in ``trace()`` so a test can assert "same seed → same trace".

Fault-point catalog (kept in sync with README "Fault model"):

=====================  ====================================================
point                  armed at
=====================  ====================================================
``rpc.send``           every wire frame send (server/rpc.py) and the
                       client agent's logical server calls
                       (client/client.py); actions: drop, delay, dup,
                       truncate, error
``raft.apply``         leader log append (server/raft.py RaftLog.apply /
                       MultiRaft.apply); actions: crash, step_down,
                       delay, error
``heartbeat.deliver``  leader-side TTL reset (server/heartbeat.py);
                       actions: drop (silence the heartbeat), delay
``plan.apply``         plan applier commit path (server/plan_apply.py);
                       actions: crash, error, delay
``ops.kernel_result``  device→host kernel outputs (ops/batch_sched.py);
                       actions: corrupt (hands the site a seeded RNG)
=====================  ====================================================
"""
from __future__ import annotations

import fnmatch
import random
import threading
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "FaultAction", "FaultRule", "FaultPlane", "InjectedFault",
    "arm", "disarm", "armed", "faultpoint", "scenario", "trace",
]

ACTIONS = ("drop", "delay", "dup", "truncate", "error", "crash",
           "step_down", "corrupt")


class InjectedFault(Exception):
    """An error deliberately raised by a fault point (``error`` / ``crash``
    actions).  Distinct type so tests can tell injected failures from real
    bugs surfacing mid-scenario."""


class FaultRule:
    """One scenario rule; see module docstring for field semantics."""

    __slots__ = ("point", "action", "prob", "after", "times", "delay",
                 "match", "message", "seen", "fired", "rng", "index")

    def __init__(self, spec: Dict[str, Any], index: int, seed: int):
        self.point: str = spec["point"]
        self.action: str = spec["action"]
        if self.action not in ACTIONS:
            raise ValueError(f"unknown fault action {self.action!r}")
        self.prob: float = float(spec.get("prob", 1.0))
        self.after: int = int(spec.get("after", 0))
        times = spec.get("times")
        self.times: Optional[int] = None if times is None else int(times)
        self.delay: float = float(spec.get("delay", 0.05))
        self.match: Dict[str, Any] = dict(spec.get("match") or {})
        self.message: str = spec.get(
            "error", f"injected {self.action} at {self.point}")
        self.index = index
        # Private, reproducible stream: str seeding hashes via sha512
        # (CPython seeding version 2), immune to PYTHONHASHSEED.
        self.rng = random.Random(f"{seed}/{index}/{self.point}")
        self.seen = 0    # matching calls observed
        self.fired = 0   # times the action actually fired

    def matches(self, name: str, ctx: Dict[str, Any]) -> bool:
        if name != self.point and not fnmatch.fnmatchcase(name, self.point):
            return False
        for key, want in self.match.items():
            if ctx.get(key) != want:
                return False
        return True


class FaultAction:
    """What a fault point should do right now.  ``rng`` is the owning
    rule's private stream — ``corrupt`` sites draw from it so the damage
    is a pure function of the scenario seed."""

    __slots__ = ("kind", "delay", "message", "rng", "rule")

    def __init__(self, rule: FaultRule):
        self.kind = rule.action
        self.delay = rule.delay
        self.message = rule.message
        self.rng = rule.rng
        self.rule = rule

    def raise_injected(self) -> None:
        raise InjectedFault(self.message)

    def __repr__(self) -> str:  # pragma: no cover — debugging aid
        return f"FaultAction({self.kind!r} from rule {self.rule.index})"


class FaultPlane:
    """One armed scenario: rules + counters + the fire trace."""

    def __init__(self, rules: List[Dict[str, Any]], seed: int = 0):
        self.seed = seed
        self.rules = [FaultRule(spec, i, seed)
                      for i, spec in enumerate(rules)]
        self._l = threading.Lock()
        self._trace: List[Tuple[str, int, str]] = []

    def fire(self, name: str, ctx: Dict[str, Any]) -> Optional[FaultAction]:
        """First matching rule that decides to fire wins; counters and the
        probability draw happen under the lock so the per-rule decision
        sequence is deterministic in call order."""
        for rule in self.rules:
            if not rule.matches(name, ctx):
                continue
            with self._l:
                rule.seen += 1
                if rule.seen <= rule.after:
                    continue
                if rule.times is not None and rule.fired >= rule.times:
                    continue
                if rule.prob < 1.0 and rule.rng.random() >= rule.prob:
                    continue
                rule.fired += 1
                self._trace.append((name, rule.index, rule.action))
            # Mirror the fire into the tracing plane (a `fault.fire` span
            # under whatever the current thread is doing) so an eval's
            # timeline names the injection that shaped it.  Import here:
            # fires are rare, and the hot disarmed path must not pay it.
            from .utils import tracing
            tracing.note_fault(name, rule.index, rule.action)
            # ...and into the cluster event stream, so chaos forensics
            # can interleave injections with the state changes they
            # caused.
            note_event_stream("Fault", "FaultFired", name,
                              {"Rule": rule.index, "Action": rule.action})
            return FaultAction(rule)
        return None

    def trace(self) -> List[Tuple[str, int, str]]:
        with self._l:
            return list(self._trace)


# -- process-wide arming -----------------------------------------------------

# The single global the hot path reads.  ``None`` ⇒ disarmed ⇒ every
# faultpoint() call is one load + one comparison.
_PLANE: Optional[FaultPlane] = None


def note_event_stream(topic: str, etype: str, key: str,
                      payload: Optional[Dict[str, Any]] = None,
                      eval_id: str = "") -> None:
    """Mirror a cross-cutting occurrence (fault fire, breaker
    transition) into the cluster event stream without importing the
    server package: sys.modules — if event_broker was never loaded, no
    broker can be armed anyway."""
    import sys

    mod = sys.modules.get("nomad_tpu.server.event_broker")
    if mod is not None:
        mod.note_external(topic, etype, key, payload, eval_id)


def faultpoint(name: str, **ctx: Any) -> Optional[FaultAction]:
    """The hook threaded through production code.  Returns ``None`` when
    disarmed or when no armed rule fires."""
    plane = _PLANE
    if plane is None:
        return None
    return plane.fire(name, ctx)


def arm(scenario_cfg, seed: Optional[int] = None) -> FaultPlane:
    """Arm a scenario.  ``scenario_cfg`` is either a list of rule dicts or
    a dict ``{"seed": int, "faults": [rules...]}``; an explicit ``seed``
    argument overrides the config's."""
    global _PLANE
    if isinstance(scenario_cfg, dict):
        rules = scenario_cfg.get("faults") or []
        cfg_seed = int(scenario_cfg.get("seed", 0))
    else:
        rules = list(scenario_cfg)
        cfg_seed = 0
    plane = FaultPlane(rules, seed=cfg_seed if seed is None else int(seed))
    _PLANE = plane
    return plane


def disarm() -> None:
    global _PLANE
    _PLANE = None


def armed() -> bool:
    return _PLANE is not None


def trace() -> List[Tuple[str, int, str]]:
    plane = _PLANE
    return plane.trace() if plane is not None else []


class scenario:
    """Context manager: ``with fault.scenario(cfg, seed=7) as plane: ...``
    — always disarms on exit, even when the chaos leaks an exception."""

    def __init__(self, scenario_cfg, seed: Optional[int] = None):
        self.cfg = scenario_cfg
        self.seed = seed
        self.plane: Optional[FaultPlane] = None

    def __enter__(self) -> FaultPlane:
        self.plane = arm(self.cfg, seed=self.seed)
        return self.plane

    def __exit__(self, *exc) -> None:
        disarm()
