"""Deterministic, seeded fault-injection plane.

Production control planes are judged by what happens when things break:
leaders crash mid-plan, nodes go silent, frames get truncated, kernels
misbehave.  The recovery machinery (nack timers, heartbeat TTLs,
lost-alloc rescheduling, the TPU-path circuit breaker) exists — this
module is how tests *exercise* it deterministically.

Model
-----
Code under test is threaded with named **fault points**::

    act = fault.faultpoint("rpc.send", method="Node.Register")
    if act is not None:
        ...interpret act.kind ("drop" / "delay" / "truncate" / ...)

A disarmed plane (the default, and the only production state) costs one
module-global load and a ``None`` check per call — no locks, no dict
lookups, nothing to configure off.

Tests arm a **scenario**: a seed plus a list of rules.  Each rule names a
point (exact or ``fnmatch`` glob), an action, and firing conditions::

    fault.arm({"seed": 7, "faults": [
        {"point": "heartbeat.deliver", "action": "drop", "times": 3},
        {"point": "raft.apply", "action": "crash",
         "match": {"msg_type": "APPLY_PLAN_RESULTS"}, "after": 1},
        {"point": "rpc.send", "action": "truncate", "prob": 0.2},
    ]})

Determinism: every rule owns a private RNG derived from
``(scenario seed, rule index, point)``, and per-rule hit counters are
taken under one lock — the decision sequence *per rule* is a pure
function of the seed and the order of matching calls.  The plane records
every fire in ``trace()`` so a test can assert "same seed → same trace".

Fault-point catalog (kept in sync with README "Fault model"):

=====================  ====================================================
point                  armed at
=====================  ====================================================
``rpc.send``           every wire frame send (server/rpc.py) and the
                       client agent's logical server calls
                       (client/client.py); actions: drop, delay, dup,
                       truncate, error
``raft.apply``         leader log append (server/raft.py RaftLog.apply /
                       MultiRaft.apply); actions: crash, step_down,
                       delay, error
``heartbeat.deliver``  leader-side TTL reset (server/heartbeat.py);
                       actions: drop (silence the heartbeat), delay
``plan.apply``         plan applier commit path (server/plan_apply.py);
                       actions: crash, error, delay
``ops.kernel_result``  device→host kernel outputs (ops/batch_sched.py);
                       actions: corrupt (hands the site a seeded RNG)
``net.dial``           connection establishment (server/rpc.py
                       ConnPool._dial); actions: drop, delay
``net.send``           per-call outbound traffic (ConnPool.call, covering
                       the Nomad channel AND the MultiRaft replication
                       transport); actions: drop, delay, reorder
=====================  ====================================================

Network chaos plane (ISSUE 12)
------------------------------
Connection-level faults live on a SEPARATE global — the :class:`NetPlane`
— so cluster chaos (partitions) composes with rule scenarios and can be
driven imperatively mid-run without re-arming::

    fault.net_partition("split-a", [[leader_addr], [follower_addr]])
    ...  # traffic between the two groups is severed, both directions
    fault.net_heal("split-a")

Every ConnPool is stamped with its owner's advertised address
(``pool.local_addr``), so a single process hosting several servers (the
in-process cluster tests) enforces a partition on BOTH sides; subprocess
followers arm their own plane via the ``Chaos.SetNet`` control RPC
(enabled by ``NOMAD_TPU_CHAOS=1``) or the ``NOMAD_TPU_CHAOS_NET`` env
spec.  Asymmetric loss/delay/reorder are expressed as seeded net RULES
(src/dst fnmatch patterns, per-rule RNG — same seed, same decision
sequence), and :func:`flap_windows` derives a deterministic split/heal
schedule from a seed for flapping-link scenarios.
"""
from __future__ import annotations

import fnmatch
import random
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

__all__ = [
    "FaultAction", "FaultRule", "FaultPlane", "InjectedFault",
    "arm", "disarm", "armed", "faultpoint", "scenario", "trace",
    "NetPlane", "NetRule", "net", "net_arm", "net_disarm", "net_armed",
    "netpoint", "net_partition", "net_heal", "flap_windows",
    "net_sever_regions", "net_dcn_delay",
]

ACTIONS = ("drop", "delay", "dup", "truncate", "error", "crash",
           "step_down", "corrupt")


class InjectedFault(Exception):
    """An error deliberately raised by a fault point (``error`` / ``crash``
    actions).  Distinct type so tests can tell injected failures from real
    bugs surfacing mid-scenario."""


class FaultRule:
    """One scenario rule; see module docstring for field semantics."""

    __slots__ = ("point", "action", "prob", "after", "times", "delay",
                 "match", "message", "seen", "fired", "rng", "index")

    def __init__(self, spec: Dict[str, Any], index: int, seed: int):
        self.point: str = spec["point"]
        self.action: str = spec["action"]
        if self.action not in ACTIONS:
            raise ValueError(f"unknown fault action {self.action!r}")
        self.prob: float = float(spec.get("prob", 1.0))
        self.after: int = int(spec.get("after", 0))
        times = spec.get("times")
        self.times: Optional[int] = None if times is None else int(times)
        self.delay: float = float(spec.get("delay", 0.05))
        self.match: Dict[str, Any] = dict(spec.get("match") or {})
        self.message: str = spec.get(
            "error", f"injected {self.action} at {self.point}")
        self.index = index
        # Private, reproducible stream: str seeding hashes via sha512
        # (CPython seeding version 2), immune to PYTHONHASHSEED.
        self.rng = random.Random(f"{seed}/{index}/{self.point}")
        self.seen = 0    # matching calls observed
        self.fired = 0   # times the action actually fired

    def matches(self, name: str, ctx: Dict[str, Any]) -> bool:
        if name != self.point and not fnmatch.fnmatchcase(name, self.point):
            return False
        for key, want in self.match.items():
            if ctx.get(key) != want:
                return False
        return True


class FaultAction:
    """What a fault point should do right now.  ``rng`` is the owning
    rule's private stream — ``corrupt`` sites draw from it so the damage
    is a pure function of the scenario seed."""

    __slots__ = ("kind", "delay", "message", "rng", "rule")

    def __init__(self, rule: FaultRule):
        self.kind = rule.action
        self.delay = rule.delay
        self.message = rule.message
        self.rng = rule.rng
        self.rule = rule

    def raise_injected(self) -> None:
        raise InjectedFault(self.message)

    def __repr__(self) -> str:  # pragma: no cover — debugging aid
        return f"FaultAction({self.kind!r} from rule {self.rule.index})"


class FaultPlane:
    """One armed scenario: rules + counters + the fire trace."""

    def __init__(self, rules: List[Dict[str, Any]], seed: int = 0):
        self.seed = seed
        self.rules = [FaultRule(spec, i, seed)
                      for i, spec in enumerate(rules)]
        self._l = threading.Lock()
        self._trace: List[Tuple[str, int, str]] = []

    def fire(self, name: str, ctx: Dict[str, Any]) -> Optional[FaultAction]:
        """First matching rule that decides to fire wins; counters and the
        probability draw happen under the lock so the per-rule decision
        sequence is deterministic in call order."""
        for rule in self.rules:
            if not rule.matches(name, ctx):
                continue
            with self._l:
                rule.seen += 1
                if rule.seen <= rule.after:
                    continue
                if rule.times is not None and rule.fired >= rule.times:
                    continue
                if rule.prob < 1.0 and rule.rng.random() >= rule.prob:
                    continue
                rule.fired += 1
                self._trace.append((name, rule.index, rule.action))
            # Mirror the fire into the tracing plane (a `fault.fire` span
            # under whatever the current thread is doing) so an eval's
            # timeline names the injection that shaped it.  Import here:
            # fires are rare, and the hot disarmed path must not pay it.
            from .utils import tracing
            tracing.note_fault(name, rule.index, rule.action)
            # ...and into the cluster event stream, so chaos forensics
            # can interleave injections with the state changes they
            # caused.
            note_event_stream("Fault", "FaultFired", name,
                              {"Rule": rule.index, "Action": rule.action})
            return FaultAction(rule)
        return None

    def trace(self) -> List[Tuple[str, int, str]]:
        with self._l:
            return list(self._trace)


# -- process-wide arming -----------------------------------------------------

# The single global the hot path reads.  ``None`` ⇒ disarmed ⇒ every
# faultpoint() call is one load + one comparison.
_PLANE: Optional[FaultPlane] = None


def note_event_stream(topic: str, etype: str, key: str,
                      payload: Optional[Dict[str, Any]] = None,
                      eval_id: str = "") -> None:
    """Mirror a cross-cutting occurrence (fault fire, breaker
    transition) into the cluster event stream without importing the
    server package: sys.modules — if event_broker was never loaded, no
    broker can be armed anyway."""
    import sys

    mod = sys.modules.get("nomad_tpu.server.event_broker")
    if mod is not None:
        mod.note_external(topic, etype, key, payload, eval_id)


def faultpoint(name: str, **ctx: Any) -> Optional[FaultAction]:
    """The hook threaded through production code.  Returns ``None`` when
    disarmed or when no armed rule fires."""
    plane = _PLANE
    if plane is None:
        return None
    return plane.fire(name, ctx)


def arm(scenario_cfg, seed: Optional[int] = None) -> FaultPlane:
    """Arm a scenario.  ``scenario_cfg`` is either a list of rule dicts or
    a dict ``{"seed": int, "faults": [rules...]}``; an explicit ``seed``
    argument overrides the config's."""
    global _PLANE
    if isinstance(scenario_cfg, dict):
        rules = scenario_cfg.get("faults") or []
        cfg_seed = int(scenario_cfg.get("seed", 0))
    else:
        rules = list(scenario_cfg)
        cfg_seed = 0
    plane = FaultPlane(rules, seed=cfg_seed if seed is None else int(seed))
    _PLANE = plane
    return plane


def disarm() -> None:
    global _PLANE
    _PLANE = None


def armed() -> bool:
    return _PLANE is not None


def trace() -> List[Tuple[str, int, str]]:
    plane = _PLANE
    return plane.trace() if plane is not None else []


class scenario:
    """Context manager: ``with fault.scenario(cfg, seed=7) as plane: ...``
    — always disarms on exit, even when the chaos leaks an exception."""

    def __init__(self, scenario_cfg, seed: Optional[int] = None):
        self.cfg = scenario_cfg
        self.seed = seed
        self.plane: Optional[FaultPlane] = None

    def __enter__(self) -> FaultPlane:
        self.plane = arm(self.cfg, seed=self.seed)
        return self.plane

    def __exit__(self, *exc) -> None:
        disarm()


# ---------------------------------------------------------------------------
# network chaos plane (ISSUE 12)
# ---------------------------------------------------------------------------

NET_ACTIONS = ("drop", "delay", "reorder")


class NetRule:
    """One connection-level rule: ``src``/``dst`` fnmatch patterns, a
    ``kind`` (``dial``/``send``/``*``), and an action:

    - ``drop``    — the dial/call fails as an unreachable peer
    - ``delay``   — sleep ``delay`` seconds before proceeding
    - ``reorder`` — seeded bounded extra delay in ``[0, max_delay]``;
      on this strictly-sequential per-connection RPC, reordering
      manifests across *parallel* connections (a delayed call lands
      after its younger siblings), which is the observable that matters

    ``prob``/``times`` gate firing exactly like :class:`FaultRule`, with
    the same private-RNG determinism contract."""

    __slots__ = ("kind", "src", "dst", "action", "prob", "times", "delay",
                 "max_delay", "fired", "rng", "index")

    def __init__(self, spec: Dict[str, Any], index: int, seed: int):
        self.kind: str = spec.get("kind", "*")
        self.src: str = spec.get("src", "*")
        self.dst: str = spec.get("dst", "*")
        self.action: str = spec["action"]
        if self.action not in NET_ACTIONS:
            raise ValueError(f"unknown net action {self.action!r}")
        self.prob: float = float(spec.get("prob", 1.0))
        times = spec.get("times")
        self.times: Optional[int] = None if times is None else int(times)
        self.delay: float = float(spec.get("delay", 0.02))
        self.max_delay: float = float(spec.get("max_delay", 0.1))
        self.index = index
        self.rng = random.Random(f"net/{seed}/{index}")
        self.fired = 0

    def matches(self, kind: str, src: str, dst: str) -> bool:
        return ((self.kind == "*" or self.kind == kind)
                and fnmatch.fnmatchcase(src, self.src)
                and fnmatch.fnmatchcase(dst, self.dst))


class _Partition:
    """One named partition: traffic between addresses matched into
    DIFFERENT groups is severed.  Group entries are fnmatch patterns;
    an address matching no group is unaffected.  Optional ``windows``
    (offsets from the plane's arm anchor, see :func:`flap_windows`)
    make the split flap on a deterministic schedule."""

    __slots__ = ("name", "groups", "windows", "blocked_count")

    def __init__(self, name: str, groups: List[List[str]],
                 windows: Optional[List[Tuple[float, float]]] = None):
        self.name = name
        self.groups = [list(g) for g in groups]
        self.windows = ([(float(a), float(b)) for a, b in windows]
                        if windows else None)
        self.blocked_count = 0

    def active(self, elapsed: float) -> bool:
        if self.windows is None:
            return True
        return any(a <= elapsed < b for a, b in self.windows)

    def separates(self, src: str, dst: str) -> bool:
        def group_of(addr: str) -> int:
            # Most-specific pattern wins, so a ["*"] catch-all group
            # composes with a named group: an address listed literally
            # belongs to ITS group, everything else to the wildcard.
            best, best_spec = -1, -1
            for i, pats in enumerate(self.groups):
                for p in pats:
                    if fnmatch.fnmatchcase(addr, p):
                        spec = sum(c not in "*?[]" for c in p)
                        if spec > best_spec:
                            best, best_spec = i, spec
            return best

        gs, gd = group_of(src), group_of(dst)
        return gs >= 0 and gd >= 0 and gs != gd


class NetPlane:
    """Process-wide network chaos state: named partitions (imperative
    split/heal + deterministic flap windows) and seeded loss/delay
    rules.  The hot disarmed path never reaches this class — see
    :func:`netpoint`."""

    def __init__(self, spec: Optional[Dict[str, Any]] = None,
                 seed: Optional[int] = None):
        spec = dict(spec or {})
        self.seed = int(spec.get("seed", 0) if seed is None else seed)
        self._l = threading.Lock()
        self._anchor = time.monotonic()
        self._partitions: Dict[str, _Partition] = {}
        self.rules = [NetRule(r, i, self.seed)
                      for i, r in enumerate(spec.get("rules") or [])]
        self._trace: List[Tuple[str, str, str]] = []
        for p in spec.get("partitions") or []:
            self.partition(p["name"], p["groups"], windows=p.get("windows"))

    # -- partitions --------------------------------------------------------

    def partition(self, name: str, groups: List[List[str]],
                  windows: Optional[List[Tuple[float, float]]] = None,
                  ) -> None:
        with self._l:
            self._partitions[name] = _Partition(name, groups, windows)
            self._trace.append(("net.partition", name,
                                "flap" if windows else "split"))
        note_event_stream("Chaos", "Partition", name,
                          {"Groups": [list(g) for g in groups],
                           "Flap": bool(windows)})

    def heal(self, name: Optional[str] = None) -> None:
        with self._l:
            names = ([name] if name is not None
                     else list(self._partitions))
            for n in names:
                if self._partitions.pop(n, None) is not None:
                    self._trace.append(("net.partition", n, "heal"))
        for n in names:
            note_event_stream("Chaos", "Heal", n, {})

    def active_partitions(self) -> List[str]:
        elapsed = time.monotonic() - self._anchor
        with self._l:
            return sorted(n for n, p in self._partitions.items()
                          if p.active(elapsed))

    def blocked(self, src: str, dst: str) -> bool:
        elapsed = time.monotonic() - self._anchor
        with self._l:
            for p in self._partitions.values():
                if p.active(elapsed) and p.separates(src, dst):
                    p.blocked_count += 1
                    return True
        return False

    # -- the hook ----------------------------------------------------------

    def check(self, kind: str, src: str, dst: str
              ) -> Optional[Tuple[str, float]]:
        """Partition verdict first (deterministic), then the first
        firing rule.  Returns ``(action, delay_seconds)`` or None."""
        if self.blocked(src, dst):
            return ("drop", 0.0)
        for rule in self.rules:
            if not rule.matches(kind, src, dst):
                continue
            with self._l:
                if rule.times is not None and rule.fired >= rule.times:
                    continue
                if rule.prob < 1.0 and rule.rng.random() >= rule.prob:
                    continue
                rule.fired += 1
                delay = (rule.rng.random() * rule.max_delay
                         if rule.action == "reorder" else rule.delay)
                self._trace.append((f"net.{kind}", f"rule-{rule.index}",
                                    rule.action))
            return (rule.action, delay)
        return None

    def add_rules(self, specs: List[Dict[str, Any]]) -> None:
        """Append loss/delay rules to an armed plane (region-federation
        DCN shaping composes with partitions armed earlier).  Indexes
        continue from the existing rules so every rule keeps a private,
        seed-deterministic RNG stream."""
        with self._l:
            base = len(self.rules)
            self.rules.extend(NetRule(r, base + i, self.seed)
                              for i, r in enumerate(specs))

    def trace(self) -> List[Tuple[str, str, str]]:
        with self._l:
            return list(self._trace)


_NET: Optional[NetPlane] = None


def net_arm(spec: Optional[Dict[str, Any]] = None,
            seed: Optional[int] = None) -> NetPlane:
    global _NET
    _NET = NetPlane(spec, seed=seed)
    return _NET


def net_disarm() -> None:
    global _NET
    _NET = None


def net_armed() -> bool:
    return _NET is not None


def net() -> NetPlane:
    """The process net plane, arming an empty one on first use (the
    imperative partition/heal path needs no scenario)."""
    global _NET
    if _NET is None:
        _NET = NetPlane()
    return _NET


def netpoint(kind: str, src: str, dst: str
             ) -> Optional[Tuple[str, float]]:
    """The hook threaded through ConnPool dial/send.  Disarmed cost:
    one module-global load + a ``None`` check."""
    plane = _NET
    if plane is None:
        return None
    return plane.check(kind, src, dst)


def net_partition(name: str, groups: List[List[str]],
                  windows: Optional[List[Tuple[float, float]]] = None,
                  ) -> NetPlane:
    plane = net()
    plane.partition(name, groups, windows=windows)
    return plane


def net_heal(name: Optional[str] = None) -> None:
    plane = _NET
    if plane is not None:
        plane.heal(name)


def net_sever_regions(region_addrs: Dict[str, List[str]],
                      isolate: Optional[str] = None,
                      name: str = "region-sever",
                      windows: Optional[List[Tuple[float, float]]] = None,
                      ) -> NetPlane:
    """Region-severing partition groups over the DCN (ISSUE 17).

    ``region_addrs`` maps region name → that region's server addresses.
    Default: one partition group per region, severing ALL inter-region
    traffic while leaving intra-region (ICI) traffic — and identity-less
    client pools, which match no literal group — untouched.  With
    ``isolate=<region>``, that one region is blacked out from everything
    else (its addresses in one group, ``"*"`` in the other), modeling a
    full region blackout including its clients.  Pass ``windows`` (e.g.
    :func:`flap_windows`) for a deterministic DCN flap schedule; heal
    with ``net_heal(name)``."""
    if isolate is not None:
        if isolate not in region_addrs:
            raise ValueError(f"unknown region {isolate!r}")
        groups = [list(region_addrs[isolate]), ["*"]]
    else:
        groups = [list(addrs) for _, addrs in sorted(region_addrs.items())]
    return net_partition(name, groups, windows=windows)


def net_dcn_delay(region_addrs: Dict[str, List[str]], delay: float = 0.02,
                  prob: float = 1.0, kind: str = "send") -> NetPlane:
    """Deterministic DCN latency: one ``delay`` rule per cross-region
    (src, dst) server pair, leaving intra-region traffic at ICI speed.
    Composes with :func:`net_sever_regions` on the same plane."""
    specs: List[Dict[str, Any]] = []
    regions = sorted(region_addrs.items())
    for r_src, srcs in regions:
        for r_dst, dsts in regions:
            if r_src == r_dst:
                continue
            specs.extend({"kind": kind, "src": s, "dst": d,
                          "action": "delay", "prob": prob, "delay": delay}
                         for s in srcs for d in dsts)
    plane = net()
    plane.add_rules(specs)
    return plane


def flap_windows(seed: int, count: int = 4, period: float = 2.0,
                 duty: float = 0.5, jitter: float = 0.5,
                 start: float = 0.0) -> List[Tuple[float, float]]:
    """A deterministic split/heal schedule: ``count`` blocked windows,
    each roughly ``duty``·``period`` long, spaced ~``period`` apart with
    seeded jitter.  Same seed → same windows; anchored at the plane's
    arm time, so two processes arming the same spec at the same moment
    flap together."""
    rng = random.Random(f"flap/{seed}")
    out: List[Tuple[float, float]] = []
    t = start
    for _ in range(count):
        gap = period * (1.0 - duty) * (1.0 + jitter * (rng.random() - 0.5))
        dur = period * duty * (1.0 + jitter * (rng.random() - 0.5))
        t += gap
        out.append((round(t, 4), round(t + dur, 4)))
        t += dur
    return out
