"""Computed node class: a stable hash over a node's *non-unique* attributes.

This is the key scheduler-scalability optimization in the reference
(nomad/structs/node_class.go:31-94): nodes with the same computed class are
interchangeable for feasibility checking, so eligibility is cached per class.
In the TPU build, the computed class becomes an int32 per node and the
class-dedup step shrinks the feasibility matrix from [B,N] to [B,C].
"""
from __future__ import annotations

import hashlib
from typing import TYPE_CHECKING, List

if TYPE_CHECKING:
    from .structs import Constraint, Node

# Prefix marking node meta/attribute keys excluded from the computed class.
NODE_UNIQUE_NAMESPACE = "unique."


def unique_namespace(key: str) -> str:
    return f"{NODE_UNIQUE_NAMESPACE}{key}"


def is_unique_namespace(key: str) -> bool:
    return key.startswith(NODE_UNIQUE_NAMESPACE)


def compute_node_class(node: "Node") -> str:
    """Derive the computed class from Datacenter, NodeClass, and the
    non-unique subsets of Attributes and Meta (node_class.go:31)."""
    h = hashlib.sha1()
    h.update(node.datacenter.encode())
    h.update(b"\x00")
    h.update(node.node_class.encode())
    h.update(b"\x00")
    for source in (node.attributes, node.meta):
        for key in sorted(source):
            if is_unique_namespace(key):
                continue
            h.update(key.encode())
            h.update(b"\x01")
            h.update(str(source[key]).encode())
            h.update(b"\x02")
        h.update(b"\x03")
    return f"v1:{int.from_bytes(h.digest()[:8], 'big')}"


def escaped_constraints(constraints: List["Constraint"]) -> List["Constraint"]:
    """Constraints whose targets reference unique per-node identity and thus
    escape computed-class caching (node_class.go:70)."""
    return [
        c
        for c in constraints
        if _target_escapes(c.ltarget) or _target_escapes(c.rtarget)
    ]


def _target_escapes(target: str) -> bool:
    return (
        target.startswith("${node.unique.")
        or target.startswith("${attr.unique.")
        or target.startswith("${meta.unique.")
    )
