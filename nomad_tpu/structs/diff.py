"""Structural job diff for the ``plan`` dry-run path.

Reference behavior: nomad/structs/diff.go (JobDiff / TaskGroupDiff / TaskDiff /
ObjectDiff / FieldDiff, diff.go:14-1205).  The reference hand-writes a diff
function per struct; here a single reflection engine walks the dataclasses and
produces the same shape of output: a tree of typed diffs (None / Added /
Deleted / Edited) with Go-style CamelCase field names so the annotation rules
(scheduler/annotate.go:165-190 matches on "KillTimeout", "LogConfig",
"Service", "Constraint", "Count") carry over unchanged.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

from . import structs as s
from .structs import (DIFF_TYPE_ADDED, DIFF_TYPE_DELETED, DIFF_TYPE_EDITED,
                      DIFF_TYPE_NONE, FieldDiff, JobDiff, ObjectDiff,
                      TaskDiff, TaskGroupDiff)

# Diff types ordered for sorting (diff.go:14-45).
_TYPE_ORDER = {DIFF_TYPE_EDITED: 0, DIFF_TYPE_ADDED: 1,
               DIFF_TYPE_DELETED: 2, DIFF_TYPE_NONE: 3}


# ---------------------------------------------------------------------------
# Name rendering: snake_case dataclass fields -> Go-style CamelCase, matching
# the names the reference emits (and that annotate.go keys on).
# ---------------------------------------------------------------------------

from ..utils.names import go_name  # noqa: E402  (shared with the wire codec)


# Struct-type -> ObjectDiff name, as the reference names them.
_OBJECT_NAMES = {
    s.Constraint: "Constraint",
    s.RestartPolicy: "RestartPolicy",
    s.EphemeralDisk: "EphemeralDisk",
    s.UpdateStrategy: "Update",
    s.PeriodicConfig: "Periodic",
    s.ParameterizedJobConfig: "ParameterizedJob",
    s.LogConfig: "LogConfig",
    s.Service: "Service",
    s.ServiceCheck: "Check",
    s.TaskArtifact: "Artifact",
    s.Template: "Template",
    s.Vault: "Vault",
    s.Resources: "Resources",
    s.NetworkResource: "Network",
    s.DispatchPayloadConfig: "DispatchPayload",
    s.Port: "Port",
}

# Keyed list element types: matched old<->new by this attribute; everything
# else in a list of objects is matched set-wise (equal pairs drop out,
# remainder becomes Added/Deleted) exactly as the reference treats
# constraints/artifacts/templates (diff.go:540-571 uses name keys for
# services; set semantics for the rest).
_LIST_KEYS = {s.Service: "name", s.ServiceCheck: "name", s.Task: "name",
              s.TaskGroup: "name"}

_SCALARS = (str, int, float, bool, bytes)


def _render(v: Any) -> str:
    if v is None:
        return ""
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, float) and v == int(v):
        return str(int(v))
    if isinstance(v, bytes):
        return v.decode("utf-8", "replace")
    return str(v)


def _field_diff(name: str, old: Any, new: Any, contextual: bool) -> Optional[FieldDiff]:
    if old == new:
        if contextual:
            return FieldDiff(DIFF_TYPE_NONE, name, _render(old), _render(new))
        return None
    if old is None:
        return FieldDiff(DIFF_TYPE_ADDED, name, "", _render(new))
    if new is None:
        return FieldDiff(DIFF_TYPE_DELETED, name, _render(old), "")
    return FieldDiff(DIFF_TYPE_EDITED, name, _render(old), _render(new))


def _dict_field_diffs(name: str, old: Optional[Dict], new: Optional[Dict],
                      contextual: bool) -> List[FieldDiff]:
    """Flattened map diffs, rendered as ``Name[key]`` fields (the reference
    flattens maps via flatmap.Flatten, diff.go:870-888)."""
    old = old or {}
    new = new or {}
    out: List[FieldDiff] = []
    for k in sorted(set(old) | set(new)):
        fname = f"{name}[{k}]"
        if k not in old:
            out.append(FieldDiff(DIFF_TYPE_ADDED, fname, "", _render(new[k])))
        elif k not in new:
            out.append(FieldDiff(DIFF_TYPE_DELETED, fname, _render(old[k]), ""))
        elif old[k] != new[k]:
            out.append(FieldDiff(DIFF_TYPE_EDITED, fname, _render(old[k]),
                                 _render(new[k])))
        elif contextual:
            out.append(FieldDiff(DIFF_TYPE_NONE, fname, _render(old[k]),
                                 _render(new[k])))
    return out


def _scalar_list_diffs(name: str, old: Optional[List], new: Optional[List],
                       contextual: bool) -> List[FieldDiff]:
    """Set-semantics diff of scalar lists (e.g. Datacenters, Args)."""
    old_l = list(old or [])
    new_l = list(new or [])
    out: List[FieldDiff] = []
    remaining = list(new_l)
    for v in old_l:
        if v in remaining:
            remaining.remove(v)
            if contextual:
                out.append(FieldDiff(DIFF_TYPE_NONE, name, _render(v), _render(v)))
        else:
            out.append(FieldDiff(DIFF_TYPE_DELETED, name, _render(v), ""))
    for v in remaining:
        out.append(FieldDiff(DIFF_TYPE_ADDED, name, "", _render(v)))
    return out


def _object_list_diffs(old: Optional[List], new: Optional[List],
                       contextual: bool) -> List[ObjectDiff]:
    old_l = list(old or [])
    new_l = list(new or [])
    elem = (old_l + new_l)[0] if (old_l or new_l) else None
    if elem is None:
        return []
    key = _LIST_KEYS.get(type(elem))
    out: List[ObjectDiff] = []
    if key:
        olds = {getattr(o, key): o for o in old_l}
        news = {getattr(n, key): n for n in new_l}
        for k in sorted(set(olds) | set(news)):
            d = object_diff(olds.get(k), news.get(k), contextual)
            if d is not None:
                out.append(d)
    else:
        remaining = list(new_l)
        for o in old_l:
            matched = None
            for n in remaining:
                if o == n:
                    matched = n
                    break
            if matched is not None:
                remaining.remove(matched)
                if contextual:
                    d = object_diff(o, matched, contextual)
                    if d is not None:
                        out.append(d)
            else:
                d = object_diff(o, None, contextual)
                if d is not None:
                    out.append(d)
        for n in remaining:
            d = object_diff(None, n, contextual)
            if d is not None:
                out.append(d)
    return out


def _walk(old: Any, new: Any, contextual: bool, exclude: frozenset = frozenset(),
          ) -> tuple:
    """Diff all dataclass fields of two same-typed objects (either may be
    None). Returns (field_diffs, object_diffs)."""
    proto = old if old is not None else new
    fields: List[FieldDiff] = []
    objects: List[ObjectDiff] = []
    for f in dataclasses.fields(proto):
        if f.name in exclude:
            continue
        name = go_name(f.name)
        ov = getattr(old, f.name, None) if old is not None else None
        nv = getattr(new, f.name, None) if new is not None else None
        sample = ov if ov is not None else nv
        if sample is None or isinstance(sample, _SCALARS):
            d = _field_diff(name, ov, nv, contextual)
            if d is not None:
                fields.append(d)
        elif isinstance(sample, dict):
            vals = list((sample or {}).values())
            if vals and dataclasses.is_dataclass(vals[0]):
                continue  # keyed object maps handled by callers
            fields.extend(_dict_field_diffs(name, ov, nv, contextual))
        elif isinstance(sample, list):
            if sample and dataclasses.is_dataclass(sample[0]):
                objects.extend(_object_list_diffs(ov, nv, contextual))
            else:
                fields.extend(_scalar_list_diffs(name, ov, nv, contextual))
        elif dataclasses.is_dataclass(sample):
            d = object_diff(ov, nv, contextual)
            if d is not None:
                objects.append(d)
    fields.sort(key=lambda d: (d.name, d.old))
    objects.sort(key=lambda d: (d.name, _TYPE_ORDER[d.type]))
    return fields, objects


def _overall(old: Any, new: Any, children_changed: bool) -> str:
    if old is None and new is not None:
        return DIFF_TYPE_ADDED
    if old is not None and new is None:
        return DIFF_TYPE_DELETED
    if children_changed:
        return DIFF_TYPE_EDITED
    return DIFF_TYPE_NONE


def _changed(fields: List[FieldDiff], objects: List[ObjectDiff]) -> bool:
    return (any(f.type != DIFF_TYPE_NONE for f in fields)
            or any(o.type != DIFF_TYPE_NONE for o in objects))


def object_diff(old: Any, new: Any, contextual: bool = False) -> Optional[ObjectDiff]:
    """Diff two nested objects of the same dataclass type (diff.go:507-888)."""
    if old is None and new is None:
        return None
    proto = old if old is not None else new
    name = _OBJECT_NAMES.get(type(proto), type(proto).__name__)
    fields, objects = _walk(old, new, contextual)
    typ = _overall(old, new, _changed(fields, objects))
    if typ == DIFF_TYPE_NONE and not contextual:
        return None
    return ObjectDiff(typ, name, fields, objects)


# Fields that are bookkeeping, not part of the user-visible spec
# (diff.go:69-80 filters these from the job diff).
_JOB_EXCLUDE = frozenset({
    "id", "status", "status_description", "version", "stable", "submit_time",
    "create_index", "modify_index", "job_modify_index", "payload",
    "vault_token", "task_groups",
})
_TG_EXCLUDE = frozenset({"name", "tasks"})
_TASK_EXCLUDE = frozenset({"name"})


def task_diff(old: Optional[s.Task], new: Optional[s.Task],
              contextual: bool = False) -> Optional[TaskDiff]:
    """diff.go:341-440 Task.Diff."""
    if old is None and new is None:
        return None
    proto = old if old is not None else new
    fields, objects = _walk(old, new, contextual, _TASK_EXCLUDE)
    # Driver config is a free-form map -> ObjectDiff named Config
    oc = old.config if old is not None else None
    nc = new.config if new is not None else None
    cfields = _dict_field_diffs("Config", oc, nc, contextual)
    # _walk already flattened config as fields; strip and re-home them.
    fields = [f for f in fields if not f.name.startswith("Config[")]
    if any(f.type != DIFF_TYPE_NONE for f in cfields) or (contextual and cfields):
        ctype = DIFF_TYPE_EDITED if (old is not None and new is not None) else \
            _overall(oc, nc, True)
        objects.append(ObjectDiff(ctype, "Config", cfields, []))
        objects.sort(key=lambda d: (d.name, _TYPE_ORDER[d.type]))
    typ = _overall(old, new, _changed(fields, objects))
    if typ == DIFF_TYPE_NONE and not contextual:
        return None
    return TaskDiff(typ, proto.name, fields, objects)


def task_group_diff(old: Optional[s.TaskGroup], new: Optional[s.TaskGroup],
                    contextual: bool = False) -> Optional[TaskGroupDiff]:
    """diff.go:188-258 TaskGroup.Diff."""
    if old is None and new is None:
        return None
    proto = old if old is not None else new
    fields, objects = _walk(old, new, contextual, _TG_EXCLUDE)
    tasks: List[TaskDiff] = []
    olds = {t.name: t for t in (old.tasks if old else [])}
    news = {t.name: t for t in (new.tasks if new else [])}
    for k in sorted(set(olds) | set(news)):
        d = task_diff(olds.get(k), news.get(k), contextual)
        if d is not None:
            tasks.append(d)
    changed = _changed(fields, objects) or any(
        t.type != DIFF_TYPE_NONE for t in tasks)
    typ = _overall(old, new, changed)
    if typ == DIFF_TYPE_NONE and not contextual:
        return None
    return TaskGroupDiff(typ, proto.name, fields, objects, tasks)


def job_diff(old: Optional[s.Job], new: Optional[s.Job],
             contextual: bool = False) -> JobDiff:
    """diff.go:59-155 Job.Diff.  Raises ValueError when both jobs exist but
    have different IDs (not diffable)."""
    if old is not None and new is not None and old.id != new.id:
        raise ValueError(f"can not diff jobs with different IDs: {old.id!r} vs {new.id!r}")
    proto = old if old is not None else new
    if proto is None:
        return JobDiff(DIFF_TYPE_NONE, "")
    fields, objects = _walk(old, new, contextual, _JOB_EXCLUDE)
    tgs: List[TaskGroupDiff] = []
    olds = {tg.name: tg for tg in (old.task_groups if old else [])}
    news = {tg.name: tg for tg in (new.task_groups if new else [])}
    for k in sorted(set(olds) | set(news)):
        d = task_group_diff(olds.get(k), news.get(k), contextual)
        if d is not None:
            tgs.append(d)
    changed = _changed(fields, objects) or any(
        t.type != DIFF_TYPE_NONE for t in tgs)
    return JobDiff(_overall(old, new, changed), proto.id, fields, objects, tgs)
