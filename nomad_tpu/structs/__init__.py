"""L0 data model & wire types (reference: nomad/structs/)."""

from .bitmap import Bitmap
from .funcs import (
    allocs_fit,
    filter_terminal_allocs,
    remove_allocs,
    score_fit,
)
from .network import (
    MAX_DYNAMIC_PORT,
    MAX_VALID_PORT,
    MIN_DYNAMIC_PORT,
    NetworkIndex,
)
from .node_class import (
    compute_node_class,
    escaped_constraints,
    is_unique_namespace,
    unique_namespace,
)
from . import structs as _s
from .structs import (  # noqa: F401
    AllocListStub,
    AllocMetric,
    Allocation,
    Constraint,
    DesiredUpdates,
    EphemeralDisk,
    Evaluation,
    Job,
    JobChildrenSummary,
    JobSummary,
    LogConfig,
    NetworkResource,
    Node,
    ParameterizedJobConfig,
    PeriodicConfig,
    Plan,
    PlanAnnotations,
    PlanResult,
    Port,
    Resources,
    RestartPolicy,
    Service,
    ServiceCheck,
    Task,
    TaskArtifact,
    TaskEvent,
    TaskGroup,
    TaskGroupSummary,
    TaskState,
    Template,
    UpdateStrategy,
    Vault,
    generate_uuid,
)

# Re-export the string constants (statuses, types, triggers) without leaking
# implementation imports like `time`/`uuid` into the package namespace.
_CONST_PREFIXES = (
    "JOB_", "NODE_", "ALLOC_", "EVAL_", "CONSTRAINT_", "TASK_", "CORE_JOB_",
    "DEFAULT_RESOURCES_", "PERIODIC_", "RESTART_POLICY_",
)
for _name in dir(_s):
    if _name.startswith(_CONST_PREFIXES):
        globals()[_name] = getattr(_s, _name)
del _name, _s
