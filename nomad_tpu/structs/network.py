"""Per-node network/port accounting.

Behavioral parity with reference nomad/structs/network.go:43-326
(NetworkIndex): available bandwidth per device, used-port bitmaps per IP,
dynamic-port assignment that tries a fast stochastic probe before the precise
bitmap scan.  Port bitmaps are numpy-backed (see bitmap.py) so they can be
batch-encoded into device tensors; the TPU path expresses the dynamic-port
pick as a masked argmin over the same bitmaps.
"""
from __future__ import annotations

import ipaddress
import random
from typing import Dict, List, Optional, Tuple

from .bitmap import Bitmap
from .structs import Allocation, NetworkResource, Node, Port

MIN_DYNAMIC_PORT = 20000
MAX_DYNAMIC_PORT = 60000
MAX_RAND_PORT_ATTEMPTS = 20
MAX_VALID_PORT = 65536


class NetworkIndex:
    """Indexes available and used network resources on one machine."""

    def __init__(self) -> None:
        self.avail_networks: List[NetworkResource] = []
        self.avail_bandwidth: Dict[str, int] = {}
        self.used_ports: Dict[str, Bitmap] = {}
        self.used_bandwidth: Dict[str, int] = {}

    def release(self) -> None:
        """Kept for API parity with the pooled reference implementation."""
        self.used_ports.clear()

    def overcommitted(self) -> bool:
        """Any device's used bandwidth above its capacity (network.go:60)."""
        for device, used in self.used_bandwidth.items():
            if used > self.avail_bandwidth.get(device, 0):
                return True
        return False

    def set_node(self, node: Node) -> bool:
        """Load the node's available networks + reserved usage; returns True
        on a reserved-port collision (network.go:71)."""
        collide = False
        for n in node.resources.networks:
            if n.device:
                self.avail_networks.append(n)
                self.avail_bandwidth[n.device] = n.mbits
        if node.reserved is not None:
            for n in node.reserved.networks:
                if self.add_reserved(n):
                    collide = True
        return collide

    def add_allocs(self, allocs: List[Allocation]) -> bool:
        """Add the first network of each task resource (network.go:93)."""
        collide = False
        for alloc in allocs:
            for task_res in alloc.task_resources.values():
                if not task_res.networks:
                    continue
                if self.add_reserved(task_res.networks[0]):
                    collide = True
        return collide

    def add_reserved(self, n: NetworkResource) -> bool:
        """Mark ports + bandwidth used; True on collision (network.go:111)."""
        used = self.used_ports.get(n.ip)
        if used is None:
            used = Bitmap(MAX_VALID_PORT)
            self.used_ports[n.ip] = used

        collide = False
        for port in list(n.reserved_ports) + list(n.dynamic_ports):
            if port.value < 0 or port.value >= MAX_VALID_PORT:
                return True
            if used.check(port.value):
                collide = True
            else:
                used.set(port.value)

        self.used_bandwidth[n.device] = self.used_bandwidth.get(n.device, 0) + n.mbits
        return collide

    def _yield_ips(self):
        for n in self.avail_networks:
            try:
                net = ipaddress.ip_network(n.cidr, strict=False)
            except ValueError:
                continue
            for ip in net:
                yield n, str(ip)

    def assign_network(
        self, ask: NetworkResource, rng: Optional[random.Random] = None
    ) -> Tuple[Optional[NetworkResource], str]:
        """Build an offer satisfying the ask, or (None, reason)
        (network.go:245 AssignNetwork)."""
        rng = rng or random
        err = "no networks available"
        for n, ip_str in self._yield_ips():
            avail_bw = self.avail_bandwidth.get(n.device, 0)
            used_bw = self.used_bandwidth.get(n.device, 0)
            if used_bw + ask.mbits > avail_bw:
                err = "bandwidth exceeded"
                continue

            used = self.used_ports.get(ip_str)

            reserved_collision = False
            for port in ask.reserved_ports:
                if port.value < 0 or port.value >= MAX_VALID_PORT:
                    err = f"invalid port {port.value} (out of range)"
                    reserved_collision = True
                    break
                if used is not None and used.check(port.value):
                    err = "reserved port collision"
                    reserved_collision = True
                    break
            if reserved_collision:
                continue

            offer = NetworkResource(
                device=n.device,
                ip=ip_str,
                mbits=ask.mbits,
                reserved_ports=[Port(p.label, p.value) for p in ask.reserved_ports],
                dynamic_ports=[Port(p.label, p.value) for p in ask.dynamic_ports],
            )

            dyn_ports, dyn_err = _dynamic_ports_stochastic(used, ask, rng)
            if dyn_err:
                dyn_ports, dyn_err = _dynamic_ports_precise(used, ask, rng)
                if dyn_err:
                    err = dyn_err
                    continue

            for i, port_val in enumerate(dyn_ports):
                offer.dynamic_ports[i].value = port_val
            return offer, ""
        return None, err


def _dynamic_ports_precise(
    used: Optional[Bitmap], ask: NetworkResource, rng
) -> Tuple[List[int], str]:
    """Exact scan of the free-port bitmap (network.go:288)."""
    used_set = used.copy() if used is not None else Bitmap(MAX_VALID_PORT)
    for port in ask.reserved_ports:
        used_set.set(port.value)

    available = used_set.indexes_in_range(False, MIN_DYNAMIC_PORT, MAX_DYNAMIC_PORT)
    num_dyn = len(ask.dynamic_ports)
    if len(available) < num_dyn:
        return [], "dynamic port selection failed"
    # Partial Fisher-Yates over the needed amount.
    n_avail = len(available)
    for i in range(num_dyn):
        j = rng.randrange(n_avail)
        available[i], available[j] = available[j], available[i]
    return available[:num_dyn], ""


def _dynamic_ports_stochastic(
    used: Optional[Bitmap], ask: NetworkResource, rng
) -> Tuple[List[int], str]:
    """Bounded random probing — fast path (network.go:318)."""
    reserved = [p.value for p in ask.reserved_ports]
    dynamic: List[int] = []
    for _ in range(len(ask.dynamic_ports)):
        for attempt in range(MAX_RAND_PORT_ATTEMPTS + 1):
            if attempt == MAX_RAND_PORT_ATTEMPTS:
                return [], "stochastic dynamic port selection failed"
            cand = MIN_DYNAMIC_PORT + rng.randrange(MAX_DYNAMIC_PORT - MIN_DYNAMIC_PORT)
            if used is not None and used.check(cand):
                continue
            if cand in reserved or cand in dynamic:
                continue
            dynamic.append(cand)
            break
    return dynamic, ""
