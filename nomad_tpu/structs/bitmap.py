"""Fixed-size bitmap used for port accounting.

Behavioral parity with reference nomad/structs/bitmap.go:9-69, but backed by a
numpy uint8 array so the same buffer lowers directly into the device-side
``uint32`` port-bitmap tensors used by the TPU network kernel
(nomad_tpu/ops/encode.py).
"""
from __future__ import annotations

from typing import List

import numpy as np


class Bitmap:
    """A fixed-size bitmap over ``size`` bits."""

    __slots__ = ("size", "_bits")

    def __init__(self, size: int):
        if size == 0:
            raise ValueError("bitmap must be positive size")
        if size % 8 != 0:
            raise ValueError("bitmap must be byte aligned")
        self.size = size
        self._bits = np.zeros(size >> 3, dtype=np.uint8)

    def copy(self) -> "Bitmap":
        b = Bitmap(self.size)
        b._bits[:] = self._bits
        return b

    def set(self, idx: int) -> None:
        self._bits[idx >> 3] |= np.uint8(1 << (idx & 7))

    def check(self, idx: int) -> bool:
        return bool(self._bits[idx >> 3] & (1 << (idx & 7)))

    def clear(self) -> None:
        self._bits[:] = 0

    def indexes_in_range(self, value: bool, frm: int, to: int) -> List[int]:
        """All indexes in [frm, to] whose bit equals ``value``
        (reference: bitmap.go:52 IndexesInRange)."""
        hi = min(to + 1, self.size)
        if frm >= hi:
            return []
        bits = np.unpackbits(self._bits, bitorder="little")[frm:hi]
        want = 1 if value else 0
        return (np.nonzero(bits == want)[0] + frm).tolist()

    def as_numpy(self) -> np.ndarray:
        """Zero-copy view for tensor encoding."""
        return self._bits
