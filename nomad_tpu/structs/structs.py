"""L0 data model: the host-side dataclasses AND the device-side tensor schema
contract for the TPU batch scheduler.

Behavioral parity with the reference data model (nomad/structs/structs.go:
Node:756, Job:1189, TaskGroup:2130, Task:2616, Allocation:3820,
Evaluation:4244, Plan:4477, PlanResult:4581), re-designed as Python
dataclasses.  Resource quantities are deliberately 4 scalar ints
(cpu, memory_mb, disk_mb, iops) so they lower directly to int32 SoA tensors
``node_res[N,4]`` / ``tg_ask[B,4]`` in nomad_tpu/ops/encode.py.
"""
from __future__ import annotations

import copy as _copylib
import dataclasses
import os as _os
import threading as _threading
import time
import uuid
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

# ---------------------------------------------------------------------------
# Constants (reference: nomad/structs/structs.go)
# ---------------------------------------------------------------------------

# Job types (structs.go:1160-1166)
JOB_TYPE_SERVICE = "service"
JOB_TYPE_BATCH = "batch"
JOB_TYPE_SYSTEM = "system"
JOB_TYPE_CORE = "_core"

# Job statuses (structs.go:1168-1177)
JOB_STATUS_PENDING = "pending"
JOB_STATUS_RUNNING = "running"
JOB_STATUS_DEAD = "dead"

JOB_MIN_PRIORITY = 1
JOB_DEFAULT_PRIORITY = 50
JOB_MAX_PRIORITY = 100

# Core job IDs used by the internal GC scheduler (structs.go / core_sched.go)
CORE_JOB_EVAL_GC = "eval-gc"
CORE_JOB_NODE_GC = "node-gc"
CORE_JOB_JOB_GC = "job-gc"
CORE_JOB_FORCE_GC = "force-gc"

# Node statuses (structs.go:698-707)
NODE_STATUS_INIT = "initializing"
NODE_STATUS_READY = "ready"
NODE_STATUS_DOWN = "down"

# Allocation desired statuses (structs.go:3806-3808)
ALLOC_DESIRED_STATUS_RUN = "run"
ALLOC_DESIRED_STATUS_STOP = "stop"
ALLOC_DESIRED_STATUS_EVICT = "evict"

# Allocation client statuses (structs.go:3812-3816)
ALLOC_CLIENT_STATUS_PENDING = "pending"
ALLOC_CLIENT_STATUS_RUNNING = "running"
ALLOC_CLIENT_STATUS_COMPLETE = "complete"
ALLOC_CLIENT_STATUS_FAILED = "failed"
ALLOC_CLIENT_STATUS_LOST = "lost"

# Evaluation statuses (structs.go:4230-4242)
EVAL_STATUS_BLOCKED = "blocked"
EVAL_STATUS_PENDING = "pending"
EVAL_STATUS_COMPLETE = "complete"
EVAL_STATUS_FAILED = "failed"
EVAL_STATUS_CANCELLED = "canceled"

# Evaluation trigger reasons (structs.go:4218-4228)
EVAL_TRIGGER_JOB_REGISTER = "job-register"
EVAL_TRIGGER_JOB_DEREGISTER = "job-deregister"
EVAL_TRIGGER_PERIODIC_JOB = "periodic-job"
EVAL_TRIGGER_NODE_UPDATE = "node-update"
EVAL_TRIGGER_SCHEDULED = "scheduled"
EVAL_TRIGGER_ROLLING_UPDATE = "rolling-update"
EVAL_TRIGGER_MAX_PLANS = "max-plan-attempts"
EVAL_TRIGGER_PREEMPTION = "preemption"

ALLOC_PREEMPTED = "preempted by a higher-priority allocation"

# Constraint operands (structs.go:3286-3294)
CONSTRAINT_DISTINCT_PROPERTY = "distinct_property"
CONSTRAINT_DISTINCT_HOSTS = "distinct_hosts"
CONSTRAINT_REGEX = "regexp"
CONSTRAINT_VERSION = "version"
CONSTRAINT_SET_CONTAINS = "set_contains"

# Task states (structs.go:2900-2910)
TASK_STATE_PENDING = "pending"
TASK_STATE_RUNNING = "running"
TASK_STATE_DEAD = "dead"

# Default resource values (structs.go:918-935 DefaultResources)
DEFAULT_RESOURCES_CPU = 100
DEFAULT_RESOURCES_MEMORY_MB = 10
DEFAULT_RESOURCES_DISK_MB = 300
DEFAULT_RESOURCES_IOPS = 0

# Periodic spec types (structs.go:1718-1724)
PERIODIC_SPEC_CRON = "cron"
PERIODIC_SPEC_TEST = "_internal_test"

# Restart policy modes (structs.go:1956-1963)
RESTART_POLICY_MODE_DELAY = "delay"
RESTART_POLICY_MODE_FAIL = "fail"


# Buffered entropy for generate_uuid: one urandom syscall per 64 ids.
# The control plane mints several ids per eval (eval id, dequeue token,
# alloc ids, follow-up evals), and at load-harness saturation the
# per-call urandom syscall showed up in the profile.  Cleared in forked
# children so two processes can never slice the same pool.
_uuid_hex_pool = ""
_uuid_pool_lock = _threading.Lock()
if hasattr(_os, "register_at_fork"):
    def _clear_uuid_pool() -> None:
        global _uuid_hex_pool
        _uuid_hex_pool = ""
    _os.register_at_fork(after_in_child=_clear_uuid_pool)


def generate_uuid() -> str:
    """Random UUID for IDs (reference: nomad/structs/funcs.go:158).

    Buffered os.urandom + slicing: ~5x faster than uuid.uuid4() on the
    bulk-alloc hot path, same 8-4-4-4-12 format, OS-quality entropy."""
    global _uuid_hex_pool
    with _uuid_pool_lock:
        pool = _uuid_hex_pool
        if len(pool) < 32:
            pool = _os.urandom(1024).hex()
        h, _uuid_hex_pool = pool[:32], pool[32:]
    return f"{h[:8]}-{h[8:12]}-{h[12:16]}-{h[16:20]}-{h[20:]}"


_native_uuids = None  # resolved in the background; False = unavailable
_native_uuids_resolving = False


def _resolve_native_uuids() -> None:
    global _native_uuids
    try:
        from ..native import generate_uuids as _ng

        _ng(1)  # force build/load; may raise NativeUnavailable
        _native_uuids = _ng
    except Exception:
        _native_uuids = False


def generate_uuids(n: int) -> List[str]:
    """Bulk UUIDs for the bulk-placement hot path: native formatter
    (nomad_tpu/native/ids.cc, ~2.3x end to end) once available, else one
    urandom read + python hex slicing.  The native build/load runs in a
    BACKGROUND thread kicked off by the first bulk call — a cold cache
    means a g++ invocation, which must not stall plan materialization."""
    global _native_uuids_resolving
    if _native_uuids is None and n >= 64 and not _native_uuids_resolving:
        _native_uuids_resolving = True
        import threading as _threading

        _threading.Thread(target=_resolve_native_uuids,
                          name="native-uuids-build", daemon=True).start()
    if _native_uuids and n >= 64:
        return _native_uuids(n)
    hx = _os.urandom(16 * n).hex()
    return [
        f"{h[:8]}-{h[8:12]}-{h[12:16]}-{h[16:20]}-{h[20:]}"
        for h in (hx[32 * i:32 * i + 32] for i in range(n))
    ]


# ---------------------------------------------------------------------------
# Resources
# ---------------------------------------------------------------------------


def _fast_copy(obj):
    """Shallow field copy (== dataclasses.replace with no changes — none of
    these dataclasses define __post_init__) without re-running __init__ or
    copy.copy's __reduce_ex__ dispatch."""
    cls = obj.__class__
    new = cls.__new__(cls)
    new.__dict__.update(obj.__dict__)
    return new


def alloc_usage_vec(alloc) -> "Tuple[int, int, int, int]":
    """The CANONICAL per-alloc usage basis, (cpu, memory_mb, disk_mb,
    iops): combined ``resources`` when present, ``shared_resources`` +
    per-task resources otherwise.  The state store's usage-delta feed
    and the device-resident mirror (ops/resident.py) both use this
    function; ops/encode.apply_alloc_usage is its numpy twin and the
    resident differential guard pins their equality bit-for-bit — any
    change here must land there too."""
    r = alloc.resources
    if r is not None:
        return (r.cpu, r.memory_mb, r.disk_mb, r.iops)
    cpu = mem = disk = iops = 0
    sr = alloc.shared_resources
    if sr is not None:
        cpu, mem, disk, iops = sr.cpu, sr.memory_mb, sr.disk_mb, sr.iops
    for tr in alloc.task_resources.values():
        cpu += tr.cpu
        mem += tr.memory_mb
        disk += tr.disk_mb
        iops += tr.iops
    return (cpu, mem, disk, iops)


@dataclass
class Port:
    label: str = ""
    value: int = 0


@dataclass
class NetworkResource:
    """A network interface / bandwidth+port ask (structs.go:1071-1158)."""

    device: str = ""
    cidr: str = ""
    ip: str = ""
    mbits: int = 0
    reserved_ports: List[Port] = field(default_factory=list)
    dynamic_ports: List[Port] = field(default_factory=list)

    def copy(self) -> "NetworkResource":
        return NetworkResource(
            device=self.device,
            cidr=self.cidr,
            ip=self.ip,
            mbits=self.mbits,
            reserved_ports=[Port(p.label, p.value) for p in self.reserved_ports],
            dynamic_ports=[Port(p.label, p.value) for p in self.dynamic_ports],
        )

    def add(self, delta: "NetworkResource") -> None:
        self.reserved_ports.extend(Port(p.label, p.value) for p in delta.reserved_ports)
        self.mbits += delta.mbits

    def port_labels(self) -> Dict[str, int]:
        labels: Dict[str, int] = {}
        for p in self.reserved_ports:
            labels[p.label] = p.value
        for p in self.dynamic_ports:
            labels[p.label] = p.value
        return labels


@dataclass
class Resources:
    """Resource ask/capacity.  The 4 scalar dims are the tensor schema:
    column order (cpu, memory_mb, disk_mb, iops) is shared with
    ops/encode.py (reference: structs.go:900-1069)."""

    cpu: int = 0
    memory_mb: int = 0
    disk_mb: int = 0
    iops: int = 0
    networks: List[NetworkResource] = field(default_factory=list)

    # Tensor column order contract.
    TENSOR_DIMS = ("cpu", "memory_mb", "disk_mb", "iops")

    def copy(self) -> "Resources":
        return Resources(
            cpu=self.cpu,
            memory_mb=self.memory_mb,
            disk_mb=self.disk_mb,
            iops=self.iops,
            networks=[n.copy() for n in self.networks],
        )

    def net_index(self, n: NetworkResource) -> int:
        """Index of the first network with the same device — including the
        empty device, so device-less asks merge (structs.go:1012)."""
        for idx, existing in enumerate(self.networks):
            if existing.device == n.device:
                return idx
        return -1

    def superset(self, other: "Resources") -> tuple[bool, str]:
        """Whether self >= other on every scalar dimension; returns the
        exhausted dimension name otherwise (structs.go:1024-1040)."""
        if self.cpu < other.cpu:
            return False, "cpu exhausted"
        if self.memory_mb < other.memory_mb:
            return False, "memory exhausted"
        if self.disk_mb < other.disk_mb:
            return False, "disk exhausted"
        if self.iops < other.iops:
            return False, "iops exhausted"
        return True, ""

    def add(self, delta: Optional["Resources"]) -> None:
        """Accumulate delta, merging networks by device (structs.go:1042)."""
        if delta is None:
            return
        self.cpu += delta.cpu
        self.memory_mb += delta.memory_mb
        self.disk_mb += delta.disk_mb
        self.iops += delta.iops
        for n in delta.networks:
            idx = self.net_index(n)
            if idx == -1:
                self.networks.append(n.copy())
            else:
                self.networks[idx].add(n)

    def as_tuple(self) -> tuple[int, int, int, int]:
        return (self.cpu, self.memory_mb, self.disk_mb, self.iops)


# ---------------------------------------------------------------------------
# Node
# ---------------------------------------------------------------------------


@dataclass
class Node:
    """A fingerprinted client machine (structs.go:756-898)."""

    id: str = ""
    datacenter: str = "dc1"
    name: str = ""
    http_addr: str = ""
    attributes: Dict[str, str] = field(default_factory=dict)
    resources: Resources = field(default_factory=Resources)
    reserved: Optional[Resources] = None
    links: Dict[str, str] = field(default_factory=dict)
    meta: Dict[str, str] = field(default_factory=dict)
    node_class: str = ""
    computed_class: str = ""
    drain: bool = False
    status: str = NODE_STATUS_INIT
    status_description: str = ""
    status_updated_at: float = 0.0
    create_index: int = 0
    modify_index: int = 0

    def terminal_status(self) -> bool:
        """Whether the node is down — allocs on it are lost (structs.go:888)."""
        return self.status == NODE_STATUS_DOWN

    def ready(self) -> bool:
        return self.status == NODE_STATUS_READY and not self.drain

    def compute_class(self) -> None:
        from .node_class import compute_node_class

        self.computed_class = compute_node_class(self)

    def copy(self) -> "Node":
        n = _fast_copy(self)
        n.attributes = dict(self.attributes)
        n.meta = dict(self.meta)
        n.links = dict(self.links)
        n.resources = self.resources.copy()
        n.reserved = self.reserved.copy() if self.reserved else None
        return n

    def stat_values(self) -> Dict[str, str]:
        return {"id": self.id, "datacenter": self.datacenter, "name": self.name,
                "class": self.node_class, "drain": str(self.drain), "status": self.status}


# ---------------------------------------------------------------------------
# Job / TaskGroup / Task
# ---------------------------------------------------------------------------


@dataclass
class Constraint:
    """A scheduling constraint (structs.go:3296-3349)."""

    ltarget: str = ""
    rtarget: str = ""
    operand: str = "="

    def copy(self) -> "Constraint":
        return Constraint(self.ltarget, self.rtarget, self.operand)

    def __str__(self) -> str:
        return f"{self.ltarget} {self.operand} {self.rtarget}"


@dataclass
class RestartPolicy:
    """Task restart behavior within a task group (structs.go:1965-2012)."""

    attempts: int = 2
    interval: float = 60.0  # seconds (reference uses ns durations)
    delay: float = 15.0
    mode: str = RESTART_POLICY_MODE_DELAY

    def copy(self) -> "RestartPolicy":
        return _fast_copy(self)


@dataclass
class EphemeralDisk:
    """Shared task-group disk ask (structs.go:3357-3409)."""

    sticky: bool = False
    size_mb: int = 300
    migrate: bool = False

    def copy(self) -> "EphemeralDisk":
        return _fast_copy(self)


@dataclass
class UpdateStrategy:
    """Rolling-update policy (structs.go:1702-1716)."""

    stagger: float = 0.0  # seconds between rolling batches
    max_parallel: int = 0

    def rolling(self) -> bool:
        return self.stagger > 0 and self.max_parallel > 0

    def copy(self) -> "UpdateStrategy":
        return _fast_copy(self)


@dataclass
class PeriodicConfig:
    """Cron-style periodic launch config (structs.go:1726-1810)."""

    enabled: bool = False
    spec: str = ""
    spec_type: str = PERIODIC_SPEC_CRON
    prohibit_overlap: bool = False

    def copy(self) -> "PeriodicConfig":
        return _fast_copy(self)

    def next(self, from_time: float) -> float:
        """Next launch time strictly after from_time, or 0 if none."""
        if self.spec_type == PERIODIC_SPEC_CRON:
            from ..utils.cron import cron_next

            return cron_next(self.spec, from_time)
        if self.spec_type == PERIODIC_SPEC_TEST:
            # test spec: comma-separated unix timestamps; return the first
            # one after from_time (structs.go PeriodicConfig.Next test path)
            for part in self.spec.split(","):
                part = part.strip()
                if not part:
                    continue
                t = float(part)
                if t > from_time:
                    return t
            return 0.0
        return 0.0


@dataclass
class ParameterizedJobConfig:
    """Dispatchable-job config (structs.go:1860+ in later refs; minimal here)."""

    payload: str = ""
    meta_required: List[str] = field(default_factory=list)
    meta_optional: List[str] = field(default_factory=list)

    def copy(self) -> "ParameterizedJobConfig":
        return ParameterizedJobConfig(self.payload, list(self.meta_required), list(self.meta_optional))


@dataclass
class LogConfig:
    """Task log rotation config (structs.go:2540-2576)."""

    max_files: int = 10
    max_file_size_mb: int = 10

    def copy(self) -> "LogConfig":
        return _fast_copy(self)


@dataclass
class ServiceCheck:
    """Health check for a registered service (structs.go:2250-2360)."""

    name: str = ""
    type: str = ""  # http | tcp | script
    command: str = ""
    args: List[str] = field(default_factory=list)
    path: str = ""
    protocol: str = ""
    port_label: str = ""
    interval: float = 10.0
    timeout: float = 3.0
    initial_status: str = ""

    def copy(self) -> "ServiceCheck":
        c = _fast_copy(self)
        c.args = list(self.args)
        return c


@dataclass
class Service:
    """A service advertised by a task (structs.go:2362-2470)."""

    name: str = ""
    port_label: str = ""
    tags: List[str] = field(default_factory=list)
    checks: List[ServiceCheck] = field(default_factory=list)

    def copy(self) -> "Service":
        return Service(self.name, self.port_label, list(self.tags),
                       [c.copy() for c in self.checks])


@dataclass
class TaskArtifact:
    """Remote artifact to fetch before task start (structs.go:3196-3280)."""

    getter_source: str = ""
    getter_options: Dict[str, str] = field(default_factory=dict)
    relative_dest: str = ""

    def copy(self) -> "TaskArtifact":
        return TaskArtifact(self.getter_source, dict(self.getter_options), self.relative_dest)


TEMPLATE_CHANGE_MODE_NOOP = "noop"
TEMPLATE_CHANGE_MODE_SIGNAL = "signal"
TEMPLATE_CHANGE_MODE_RESTART = "restart"


@dataclass
class Template:
    """Rendered template block (structs.go:2914-3020)."""

    source_path: str = ""
    dest_path: str = ""
    embedded_tmpl: str = ""
    change_mode: str = "restart"  # noop | signal | restart
    change_signal: str = ""
    splay: float = 5.0
    perms: str = "0644"

    def copy(self) -> "Template":
        return _fast_copy(self)


@dataclass
class Vault:
    """Vault policy ask for a task (structs.go:4120-4180 region)."""

    policies: List[str] = field(default_factory=list)
    env: bool = True
    change_mode: str = "restart"
    change_signal: str = ""

    def copy(self) -> "Vault":
        v = _fast_copy(self)
        v.policies = list(self.policies)
        return v


@dataclass
class DispatchPayloadConfig:
    file: str = ""

    def copy(self) -> "DispatchPayloadConfig":
        return _fast_copy(self)


@dataclass
class Task:
    """A unit of work executed by a driver (structs.go:2616-2790)."""

    name: str = ""
    driver: str = ""
    user: str = ""
    config: Dict[str, Any] = field(default_factory=dict)
    env: Dict[str, str] = field(default_factory=dict)
    services: List[Service] = field(default_factory=list)
    vault: Optional[Vault] = None
    templates: List[Template] = field(default_factory=list)
    constraints: List[Constraint] = field(default_factory=list)
    resources: Resources = field(default_factory=Resources)
    dispatch_payload: Optional[DispatchPayloadConfig] = None
    meta: Dict[str, str] = field(default_factory=dict)
    kill_timeout: float = 5.0
    log_config: LogConfig = field(default_factory=LogConfig)
    artifacts: List[TaskArtifact] = field(default_factory=list)
    leader: bool = False

    def copy(self) -> "Task":
        return Task(
            name=self.name,
            driver=self.driver,
            user=self.user,
            config=dict(self.config),
            env=dict(self.env),
            services=[s.copy() for s in self.services],
            vault=self.vault.copy() if self.vault else None,
            templates=[t.copy() for t in self.templates],
            constraints=[c.copy() for c in self.constraints],
            resources=self.resources.copy(),
            dispatch_payload=self.dispatch_payload.copy() if self.dispatch_payload else None,
            meta=dict(self.meta),
            kill_timeout=self.kill_timeout,
            log_config=self.log_config.copy(),
            artifacts=[a.copy() for a in self.artifacts],
            leader=self.leader,
        )


@dataclass
class TaskGroup:
    """A colocated set of tasks; the scheduler's placement unit
    (structs.go:2130-2248)."""

    name: str = ""
    count: int = 1
    constraints: List[Constraint] = field(default_factory=list)
    restart_policy: RestartPolicy = field(default_factory=RestartPolicy)
    tasks: List[Task] = field(default_factory=list)
    ephemeral_disk: EphemeralDisk = field(default_factory=EphemeralDisk)
    meta: Dict[str, str] = field(default_factory=dict)

    def copy(self) -> "TaskGroup":
        return TaskGroup(
            name=self.name,
            count=self.count,
            constraints=[c.copy() for c in self.constraints],
            restart_policy=self.restart_policy.copy(),
            tasks=[t.copy() for t in self.tasks],
            ephemeral_disk=self.ephemeral_disk.copy(),
            meta=dict(self.meta),
        )

    def lookup_task(self, name: str) -> Optional[Task]:
        for t in self.tasks:
            if t.name == name:
                return t
        return None


@dataclass
class Job:
    """A declarative workload specification (structs.go:1189-1560)."""

    region: str = "global"
    namespace: str = "default"
    id: str = ""
    parent_id: str = ""
    name: str = ""
    type: str = JOB_TYPE_SERVICE
    priority: int = JOB_DEFAULT_PRIORITY
    all_at_once: bool = False
    datacenters: List[str] = field(default_factory=list)
    constraints: List[Constraint] = field(default_factory=list)
    task_groups: List[TaskGroup] = field(default_factory=list)
    update: UpdateStrategy = field(default_factory=UpdateStrategy)
    periodic: Optional[PeriodicConfig] = None
    parameterized_job: Optional[ParameterizedJobConfig] = None
    payload: bytes = b""
    meta: Dict[str, str] = field(default_factory=dict)
    vault_token: str = ""
    status: str = JOB_STATUS_PENDING
    status_description: str = ""
    stop: bool = False
    stable: bool = False
    version: int = 0
    submit_time: float = 0.0
    create_index: int = 0
    modify_index: int = 0
    job_modify_index: int = 0

    def copy(self) -> "Job":
        j = _fast_copy(self)
        j.datacenters = list(self.datacenters)
        j.constraints = [c.copy() for c in self.constraints]
        j.task_groups = [tg.copy() for tg in self.task_groups]
        j.update = self.update.copy()
        j.periodic = self.periodic.copy() if self.periodic else None
        j.parameterized_job = self.parameterized_job.copy() if self.parameterized_job else None
        j.meta = dict(self.meta)
        return j

    def stopped(self) -> bool:
        return self.stop

    def is_periodic(self) -> bool:
        return self.periodic is not None and self.periodic.enabled

    def is_parameterized(self) -> bool:
        return self.parameterized_job is not None

    def lookup_task_group(self, name: str) -> Optional[TaskGroup]:
        for tg in self.task_groups:
            if tg.name == name:
                return tg
        return None

    def required_signals(self) -> Dict[str, Dict[str, List[str]]]:
        signals: Dict[str, Dict[str, List[str]]] = {}
        for tg in self.task_groups:
            for task in tg.tasks:
                sigs: List[str] = []
                if task.vault and task.vault.change_mode == "signal":
                    sigs.append(task.vault.change_signal)
                for tmpl in task.templates:
                    if tmpl.change_mode == "signal":
                        sigs.append(tmpl.change_signal)
                if sigs:
                    signals.setdefault(tg.name, {})[task.name] = sigs
        return signals

    def validate(self) -> List[str]:
        """Structural validation; returns a list of problems
        (reference behavior: structs.go:1334 Job.Validate)."""
        problems: List[str] = []
        if not self.region:
            problems.append("job region is empty")
        if not self.id:
            problems.append("job ID is empty")
        if not self.name:
            problems.append("job name is empty")
        if self.type not in (JOB_TYPE_SERVICE, JOB_TYPE_BATCH, JOB_TYPE_SYSTEM):
            problems.append(f"job type '{self.type}' is invalid")
        if not (JOB_MIN_PRIORITY <= self.priority <= JOB_MAX_PRIORITY):
            problems.append(
                f"job priority must be between [{JOB_MIN_PRIORITY}, {JOB_MAX_PRIORITY}]")
        if not self.datacenters:
            problems.append("job must specify at least one datacenter")
        if not self.task_groups:
            problems.append("job must have at least one task group")
        seen: Dict[str, int] = {}
        for tg in self.task_groups:
            if not tg.name:
                problems.append("task group name is empty")
            if tg.name in seen:
                problems.append(f"task group '{tg.name}' defined more than once")
            seen[tg.name] = 1
            if tg.count < 0:
                problems.append(f"task group '{tg.name}' has negative count")
            if self.type == JOB_TYPE_SYSTEM and tg.count not in (0, 1):
                problems.append(
                    f"system job task group '{tg.name}' should have count 1, not {tg.count}")
            if not tg.tasks:
                problems.append(f"task group '{tg.name}' has no tasks")
            tseen: Dict[str, int] = {}
            for task in tg.tasks:
                if not task.name:
                    problems.append(f"task name empty in group '{tg.name}'")
                if task.name in tseen:
                    problems.append(f"task '{task.name}' defined more than once")
                tseen[task.name] = 1
                if not task.driver:
                    problems.append(f"task '{task.name}' must specify a driver")
        if self.type == JOB_TYPE_SYSTEM and self.periodic and self.periodic.enabled:
            problems.append("periodic is not allowed on system jobs")
        for c in self.constraints:
            if c.operand in (CONSTRAINT_DISTINCT_HOSTS, CONSTRAINT_DISTINCT_PROPERTY):
                pass
            elif not c.operand:
                problems.append(f"constraint missing operand: {c}")
        return problems

    def canonicalize(self) -> None:
        """Fill defaults (reference behavior: structs.go:1286 Job.Canonicalize)."""
        if not self.name:
            self.name = self.id
        if not self.region:
            self.region = "global"
        if not self.namespace:
            self.namespace = DEFAULT_NAMESPACE
        if not self.datacenters:
            self.datacenters = ["dc1"]
        for tg in self.task_groups:
            if tg.count == 0 and self.type != JOB_TYPE_SYSTEM:
                tg.count = 1


# ---------------------------------------------------------------------------
# Task events / states
# ---------------------------------------------------------------------------

TASK_SETUP_FAILURE = "Setup Failure"
TASK_DRIVER_FAILURE = "Driver Failure"
TASK_RECEIVED = "Received"
TASK_FAILED_VALIDATION = "Failed Validation"
TASK_STARTED = "Started"
TASK_TERMINATED = "Terminated"
TASK_KILLING = "Killing"
TASK_KILLED = "Killed"
TASK_RESTARTING = "Restarting"
TASK_NOT_RESTARTING = "Not Restarting"
TASK_DOWNLOADING_ARTIFACTS = "Downloading Artifacts"
TASK_ARTIFACT_DOWNLOAD_FAILED = "Failed Artifact Download"
TASK_SIGNALING = "Signaling"
TASK_RESTART_SIGNAL = "Restart Signaled"
TASK_SIBLING_FAILED = "Sibling task failed"


@dataclass
class TaskEvent:
    """An event in a task's lifecycle (structs.go:3030-3190)."""

    type: str = ""
    time: float = 0.0
    message: str = ""
    driver_error: str = ""
    exit_code: int = 0
    signal: int = 0
    kill_timeout: float = 0.0
    restart_reason: str = ""
    failed_sibling: str = ""
    # Marks the event as failing the task (structs.go TaskEvent.FailsTask);
    # alloc_runner folds it into TaskState.failed.
    failed: bool = False
    # Delay before a restart is attempted (structs.go TaskEvent.StartDelay).
    start_delay: float = 0.0

    def copy(self) -> "TaskEvent":
        return _fast_copy(self)

    def display_message(self) -> str:
        """Human-readable one-liner for CLI/alloc-status (the reference CLI
        formats events per type in command/alloc_status.go)."""
        if self.message:
            return self.message
        if self.type == TASK_TERMINATED:
            return f"Exit Code: {self.exit_code}"
        if self.type == TASK_DRIVER_FAILURE and self.driver_error:
            return self.driver_error
        if self.type == TASK_KILLING and self.kill_timeout:
            return f"Kill Timeout: {self.kill_timeout}s"
        if self.type == TASK_RESTARTING:
            parts = []
            if self.restart_reason:
                parts.append(self.restart_reason)
            parts.append(f"Task restarting in {self.start_delay:.1f}s")
            return " - ".join(parts)
        if self.type == TASK_SIBLING_FAILED and self.failed_sibling:
            return f"Sibling task {self.failed_sibling!r} failed"
        return ""


@dataclass
class TaskState:
    """Client-side task state (structs.go:2928-3010)."""

    state: str = TASK_STATE_PENDING
    failed: bool = False
    started_at: float = 0.0
    finished_at: float = 0.0
    events: List[TaskEvent] = field(default_factory=list)

    def copy(self) -> "TaskState":
        t = _fast_copy(self)
        t.events = [e.copy() for e in self.events]
        return t

    def successful(self) -> bool:
        """Task is dead and its terminating event did not fail
        (structs.go:2980 TaskState.Successful)."""
        if self.state != TASK_STATE_DEAD:
            return False
        if not self.events:
            return False
        last = self.events[-1]
        return last.type == TASK_TERMINATED and last.exit_code == 0


# ---------------------------------------------------------------------------
# AllocMetric — user-visible placement forensics
# ---------------------------------------------------------------------------


@dataclass
class AllocMetric:
    """Placement forensics surfaced in alloc-status; the batched TPU kernel
    must preserve this contract via side-output counters
    (structs.go:4074-4172)."""

    nodes_evaluated: int = 0
    nodes_filtered: int = 0
    nodes_available: Dict[str, int] = field(default_factory=dict)
    class_filtered: Dict[str, int] = field(default_factory=dict)
    constraint_filtered: Dict[str, int] = field(default_factory=dict)
    nodes_exhausted: int = 0
    class_exhausted: Dict[str, int] = field(default_factory=dict)
    dimension_exhausted: Dict[str, int] = field(default_factory=dict)
    scores: Dict[str, float] = field(default_factory=dict)
    allocation_time: float = 0.0
    coalesced_failures: int = 0

    def copy(self) -> "AllocMetric":
        m = _fast_copy(self)
        m.nodes_available = dict(self.nodes_available)
        m.class_filtered = dict(self.class_filtered)
        m.constraint_filtered = dict(self.constraint_filtered)
        m.class_exhausted = dict(self.class_exhausted)
        m.dimension_exhausted = dict(self.dimension_exhausted)
        m.scores = dict(self.scores)
        return m

    def evaluate_node(self) -> None:
        self.nodes_evaluated += 1

    def filter_node(self, node: Optional[Node], constraint: str) -> None:
        self.nodes_filtered += 1
        if node is not None and node.node_class:
            self.class_filtered[node.node_class] = self.class_filtered.get(node.node_class, 0) + 1
        if constraint:
            self.constraint_filtered[constraint] = self.constraint_filtered.get(constraint, 0) + 1

    def exhausted_node(self, node: Optional[Node], dimension: str) -> None:
        self.nodes_exhausted += 1
        if node is not None and node.node_class:
            self.class_exhausted[node.node_class] = self.class_exhausted.get(node.node_class, 0) + 1
        if dimension:
            self.dimension_exhausted[dimension] = self.dimension_exhausted.get(dimension, 0) + 1

    def score_node(self, node: Node, name: str, score: float) -> None:
        key = f"{node.id}.{name}"
        self.scores[key] = self.scores.get(key, 0.0) + score


# ---------------------------------------------------------------------------
# Allocation
# ---------------------------------------------------------------------------


@dataclass
class Allocation:
    """A placed task group on a node (structs.go:3820-4070)."""

    id: str = ""
    namespace: str = "default"
    eval_id: str = ""
    name: str = ""
    node_id: str = ""
    job_id: str = ""
    job: Optional[Job] = None
    task_group: str = ""
    resources: Optional[Resources] = None
    shared_resources: Optional[Resources] = None
    task_resources: Dict[str, Resources] = field(default_factory=dict)
    metrics: Optional[AllocMetric] = None
    desired_status: str = ALLOC_DESIRED_STATUS_RUN
    desired_description: str = ""
    client_status: str = ALLOC_CLIENT_STATUS_PENDING
    client_description: str = ""
    task_states: Dict[str, TaskState] = field(default_factory=dict)
    previous_allocation: str = ""
    create_index: int = 0
    modify_index: int = 0
    alloc_modify_index: int = 0
    create_time: float = 0.0

    def copy(self) -> "Allocation":
        a = _fast_copy(self)
        a.job = self.job.copy() if self.job else None
        a.resources = self.resources.copy() if self.resources else None
        a.shared_resources = self.shared_resources.copy() if self.shared_resources else None
        a.task_resources = {k: v.copy() for k, v in self.task_resources.items()}
        a.metrics = self.metrics.copy() if self.metrics else None
        a.task_states = {k: v.copy() for k, v in self.task_states.items()}
        return a

    def terminal_status(self) -> bool:
        """Desired stop/evict, else terminal client status (structs.go:3945)."""
        if self.desired_status in (ALLOC_DESIRED_STATUS_STOP, ALLOC_DESIRED_STATUS_EVICT):
            return True
        return self.client_status in (
            ALLOC_CLIENT_STATUS_COMPLETE,
            ALLOC_CLIENT_STATUS_FAILED,
            ALLOC_CLIENT_STATUS_LOST,
        )

    def client_terminal_status(self) -> bool:
        return self.client_status in (
            ALLOC_CLIENT_STATUS_COMPLETE,
            ALLOC_CLIENT_STATUS_FAILED,
            ALLOC_CLIENT_STATUS_LOST,
        )

    def ran_successfully(self) -> bool:
        """All task states finished successfully (structs.go:3974)."""
        if not self.task_states:
            return False
        return all(ts.successful() for ts in self.task_states.values())

    def stub(self) -> "AllocListStub":
        return AllocListStub(
            id=self.id,
            eval_id=self.eval_id,
            name=self.name,
            node_id=self.node_id,
            job_id=self.job_id,
            task_group=self.task_group,
            desired_status=self.desired_status,
            desired_description=self.desired_description,
            client_status=self.client_status,
            client_description=self.client_description,
            task_states={k: v.copy() for k, v in self.task_states.items()},
            create_index=self.create_index,
            modify_index=self.modify_index,
            create_time=self.create_time,
        )


@dataclass
class AllocListStub:
    """Lightweight allocation view for list endpoints (structs.go:4044)."""

    id: str = ""
    eval_id: str = ""
    name: str = ""
    node_id: str = ""
    job_id: str = ""
    task_group: str = ""
    desired_status: str = ""
    desired_description: str = ""
    client_status: str = ""
    client_description: str = ""
    task_states: Dict[str, TaskState] = field(default_factory=dict)
    create_index: int = 0
    modify_index: int = 0
    create_time: float = 0.0


# ---------------------------------------------------------------------------
# Evaluation
# ---------------------------------------------------------------------------


@dataclass
class Evaluation:
    """A scheduling work item: 'job X needs reconciling' (structs.go:4244-4475)."""

    id: str = ""
    namespace: str = "default"
    priority: int = JOB_DEFAULT_PRIORITY
    type: str = JOB_TYPE_SERVICE
    triggered_by: str = ""
    job_id: str = ""
    job_modify_index: int = 0
    node_id: str = ""
    node_modify_index: int = 0
    status: str = EVAL_STATUS_PENDING
    status_description: str = ""
    wait: float = 0.0  # seconds to delay before processing
    next_eval: str = ""
    previous_eval: str = ""
    blocked_eval: str = ""
    failed_tg_allocs: Dict[str, AllocMetric] = field(default_factory=dict)
    class_eligibility: Dict[str, bool] = field(default_factory=dict)
    escaped_computed_class: bool = False
    annotate_plan: bool = False
    queued_allocations: Dict[str, int] = field(default_factory=dict)
    snapshot_index: int = 0
    create_index: int = 0
    modify_index: int = 0

    def copy(self) -> "Evaluation":
        e = _fast_copy(self)
        e.failed_tg_allocs = {k: v.copy() for k, v in self.failed_tg_allocs.items()}
        e.class_eligibility = dict(self.class_eligibility)
        e.queued_allocations = dict(self.queued_allocations)
        return e

    def terminal_status(self) -> bool:
        return self.status in (EVAL_STATUS_COMPLETE, EVAL_STATUS_FAILED, EVAL_STATUS_CANCELLED)

    def trigger_index(self) -> int:
        """The lowest applied index a state snapshot must cover for a
        scheduler to SEE what this eval was created about: the job
        write, the node transition, or the capacity change / previous
        attempt recorded in snapshot_index (BlockedEvals raises it to
        the unblock index on re-admission).  Shared by the
        stale-snapshot worker fence (worker.py _required_index) and the
        broker's coalescing guard — an eval may only absorb another if
        its own trigger index covers the other's."""
        return max(self.job_modify_index, self.node_modify_index,
                   self.snapshot_index)

    def should_enqueue(self) -> bool:
        """Whether the eval belongs in the broker's ready queue (structs.go:4404)."""
        return self.status == EVAL_STATUS_PENDING

    def should_block(self) -> bool:
        return self.status == EVAL_STATUS_BLOCKED

    def make_plan(self, job: Optional[Job]) -> "Plan":
        """Create an empty plan for this eval (structs.go:4418 MakePlan)."""
        plan = Plan(
            eval_id=self.id,
            priority=self.priority,
            job=job,
            node_update={},
            node_allocation={},
        )
        if job is not None:
            plan.all_at_once = job.all_at_once
        return plan

    def next_rolling_eval(self, wait: float) -> "Evaluation":
        """Follow-up eval for a rolling update (structs.go:4440)."""
        return Evaluation(
            id=generate_uuid(),
            namespace=self.namespace,
            priority=self.priority,
            type=self.type,
            triggered_by=EVAL_TRIGGER_ROLLING_UPDATE,
            job_id=self.job_id,
            job_modify_index=self.job_modify_index,
            status=EVAL_STATUS_PENDING,
            wait=wait,
            previous_eval=self.id,
        )

    def create_blocked_eval(self, class_eligibility: Dict[str, bool],
                            escaped: bool) -> "Evaluation":
        """Blocked eval to retry placement when capacity appears
        (structs.go:4494 CreateBlockedEval)."""
        return Evaluation(
            id=generate_uuid(),
            namespace=self.namespace,
            priority=self.priority,
            type=self.type,
            triggered_by=self.triggered_by,
            job_id=self.job_id,
            job_modify_index=self.job_modify_index,
            status=EVAL_STATUS_BLOCKED,
            previous_eval=self.id,
            class_eligibility=class_eligibility,
            escaped_computed_class=escaped,
        )

    def create_failed_follow_up_eval(self, wait: float) -> "Evaluation":
        """Follow-up after hitting the delivery limit (structs.go:4460)."""
        return Evaluation(
            id=generate_uuid(),
            namespace=self.namespace,
            priority=self.priority,
            type=self.type,
            triggered_by="failed-follow-up",
            job_id=self.job_id,
            job_modify_index=self.job_modify_index,
            status=EVAL_STATUS_PENDING,
            wait=wait,
            previous_eval=self.id,
        )


def preemption_follow_up_evals(
    preempted: List["Allocation"], snapshot_index: int,
    job_lookup=None,
) -> List["Evaluation"]:
    """One BLOCKED follow-up eval per distinct evicted job, so preempted
    work re-enters the scheduler when capacity appears (the plan-apply /
    Harness halves share this so their eval shapes agree).  job_lookup
    (job_id -> Job) recovers priority/type; plan copies strip the job."""
    seen: Dict[str, Evaluation] = {}
    for alloc in preempted:
        if alloc.job_id in seen:
            continue
        job = alloc.job
        if job is None and job_lookup is not None:
            job = job_lookup(alloc.job_id)
        seen[alloc.job_id] = Evaluation(
            id=generate_uuid(),
            priority=job.priority if job is not None else JOB_DEFAULT_PRIORITY,
            type=job.type if job is not None else JOB_TYPE_SERVICE,
            triggered_by=EVAL_TRIGGER_PREEMPTION,
            job_id=alloc.job_id,
            status=EVAL_STATUS_BLOCKED,
            status_description=ALLOC_PREEMPTED,
            snapshot_index=snapshot_index,
        )
    return list(seen.values())


# ---------------------------------------------------------------------------
# Plan
# ---------------------------------------------------------------------------


# Deployment statuses (structs.go:3688-3694).
DEPLOYMENT_STATUS_RUNNING = "running"
DEPLOYMENT_STATUS_FAILED = "failed"
DEPLOYMENT_STATUS_SUCCESSFUL = "successful"
DEPLOYMENT_STATUS_CANCELLED = "cancelled"
DEPLOYMENT_STATUS_PAUSED = "paused"


@dataclass
class DeploymentState:
    """Per-task-group deployment progress (structs.go:3757-3790)."""

    promoted: bool = False
    requires_promotion: bool = False
    desired_canaries: int = 0
    desired_total: int = 0
    placed_allocs: int = 0
    healthy_allocs: int = 0
    unhealthy_allocs: int = 0

    def copy(self) -> "DeploymentState":
        return _fast_copy(self)


@dataclass
class Deployment:
    """Tracks a job version's rollout (structs.go:3698-3755).

    At this reference version the scheduler never CREATES deployments
    (`grep CreatedDeployment scheduler/` is empty — SURVEY.md §2.1);
    the struct + state-store surface exist for the API contract."""

    id: str = ""
    job_id: str = ""
    job_version: int = 0
    job_modify_index: int = 0
    job_create_index: int = 0
    task_groups: Dict[str, DeploymentState] = field(default_factory=dict)
    status: str = DEPLOYMENT_STATUS_RUNNING
    status_description: str = ""
    create_index: int = 0
    modify_index: int = 0

    def active(self) -> bool:
        """(structs.go:3747-3752)."""
        return self.status in (DEPLOYMENT_STATUS_RUNNING,
                               DEPLOYMENT_STATUS_PAUSED)

    def copy(self) -> "Deployment":
        c = _fast_copy(self)
        c.task_groups = {k: v.copy() for k, v in self.task_groups.items()}
        return c


@dataclass
class DeploymentStatusUpdate:
    """A status transition carried in a plan (structs.go:379,3795)."""

    deployment_id: str = ""
    status: str = ""
    status_description: str = ""


# ---------------------------------------------------------------------------
# Namespace (multi-tenant serving plane)
# ---------------------------------------------------------------------------

#: The implicit tenant every pre-tenancy job/eval/alloc belongs to.
#: Wire frames and snapshots written before the field existed decode to
#: this via the dataclass default, so mixed-version clusters agree.
DEFAULT_NAMESPACE = "default"

#: Per-namespace fairness objectives for the broker's tenant dequeue
#: (Gavel-style pluggable policy; "" on a Namespace inherits the
#: cluster-wide NOMAD_TPU_TENANCY_OBJECTIVE knob).
TENANCY_OBJECTIVE_DRF = "drf"
TENANCY_OBJECTIVE_WRR = "weighted-rr"
TENANCY_OBJECTIVE_FIFO = "fifo"
TENANCY_OBJECTIVES = (TENANCY_OBJECTIVE_DRF, TENANCY_OBJECTIVE_WRR,
                      TENANCY_OBJECTIVE_FIFO)


@dataclass
class Namespace:
    """A tenant: quota + fairness configuration, registered through raft
    like jobs and persisted in both snapshot formats.  All quota fields
    use 0 = unlimited so the implicit "default" namespace (and any
    namespace created with bare defaults) never throttles anything —
    pre-tenancy behavior is the zero value."""

    name: str = ""
    description: str = ""
    #: Max nodes-worth of dominant-resource usage (fractional ok):
    #: a tenant whose dominant share exceeds quota_node_units/cluster
    #: nodes is over quota for admission purposes.
    quota_node_units: float = 0.0
    #: Max live (non-terminal) allocations in committed state.
    max_live_allocs: int = 0
    #: Max evals pending in the broker (admission front door).
    max_pending_evals: int = 0
    #: Token-bucket API submit rate (requests/second) in agent/http.
    api_rate: float = 0.0
    #: Bucket depth; 0 derives a burst of max(1, 2*api_rate).
    api_burst: int = 0
    #: Fair-dequeue weight: a weight-2 tenant is charged half as much
    #: virtual time / dominant share as a weight-1 tenant.
    dequeue_weight: float = 1.0
    #: Per-tenant fairness objective override ("" inherits the global
    #: knob): drf | weighted-rr | fifo.
    objective: str = ""
    create_index: int = 0
    modify_index: int = 0

    def copy(self) -> "Namespace":
        return _fast_copy(self)

    def validate(self) -> List[str]:
        problems: List[str] = []
        if not self.name:
            problems.append("namespace name is empty")
        if self.dequeue_weight <= 0:
            problems.append("namespace dequeue_weight must be positive")
        if self.objective and self.objective not in TENANCY_OBJECTIVES:
            problems.append(
                f"namespace objective '{self.objective}' is invalid "
                f"(want one of {', '.join(TENANCY_OBJECTIVES)})")
        if (self.quota_node_units < 0 or self.max_live_allocs < 0
                or self.max_pending_evals < 0 or self.api_rate < 0
                or self.api_burst < 0):
            problems.append("namespace quota fields must be >= 0")
        return problems


class _LazyStrs:
    """A lazily-generated string column for AllocSlab: values are
    formulaic (prefix + ordinal) and materialized only when read.  The
    batch scheduler commits hundreds of thousands of slab allocs per
    pass; generating every id/name string eagerly was a measurable slice
    of the plan-materialization hot path, and most are never read
    individually.  ``__lazy_strs__`` marks instances for the wire codec
    (api/codec.to_wire), which materializes them to plain lists."""

    __lazy_strs__ = True
    __slots__ = ("n",)

    def __init__(self, n: int) -> None:
        self.n = n

    def _make(self, i: int) -> str:
        raise NotImplementedError

    def __len__(self) -> int:
        return self.n

    def __bool__(self) -> bool:
        return self.n > 0

    def __getitem__(self, i):
        if isinstance(i, slice):
            return [self._make(j) for j in range(*i.indices(self.n))]
        if i < 0:
            i += self.n
        if not 0 <= i < self.n:
            raise IndexError(i)
        return self._make(i)

    def __iter__(self):
        make = self._make
        return (make(i) for i in range(self.n))


class LazyUuids(_LazyStrs):
    """Formulaic uuid column: one random uuid prefix (first 24 chars,
    8-4-4-4- groups) + the ordinal as the final 12 hex digits — still
    canonical 36-char uuid form, unique across slabs by the ~76 random
    prefix bits."""

    __slots__ = ("prefix",)

    def __init__(self, n: int, prefix: Optional[str] = None) -> None:
        super().__init__(n)
        self.prefix = prefix if prefix is not None else generate_uuid()[:24]

    def _make(self, i: int) -> str:
        return f"{self.prefix}{i:012x}"


class LazyNames(_LazyStrs):
    """Formulaic alloc names '<job>.<tg>[i]' (reference
    structs.go AllocName / scheduler/util.go:22)."""

    __slots__ = ("prefix",)

    def __init__(self, n: int, prefix: str) -> None:
        super().__init__(n)
        self.prefix = prefix

    def _make(self, i: int) -> str:
        return f"{self.prefix}[{i}]"


@dataclass
class AllocSlab:
    """Columnar batch of placements sharing one prototype allocation.

    The TPU batch scheduler places tens of thousands of near-identical
    task-group instances per device dispatch; materializing a full
    Allocation object per placement is the dominant host-side cost at
    that scale.  A slab stores the shared prototype ONCE plus per-alloc
    columns (id, name, node, previous-alloc) and materializes Allocation
    objects lazily on read — the same pointer-sharing go-memdb relies on
    (the reference inserts the FSM's pointers outright,
    state_store.go:1435), taken to its SoA conclusion.

    ``prev_ids`` uses "" for "no previous allocation" so the slab stays
    a plain data-only msgpack tree on the replicated log (log_codec)."""

    proto: Optional[Allocation] = None
    ids: List[str] = field(default_factory=list)
    names: List[str] = field(default_factory=list)
    node_ids: List[str] = field(default_factory=list)
    prev_ids: List[str] = field(default_factory=list)
    create_index: int = 0
    modify_index: int = 0

    def __len__(self) -> int:
        return len(self.ids)

    def materialize(self, i: int) -> Allocation:
        a = _fast_copy(self.proto)
        a.id = self.ids[i]
        a.name = self.names[i]
        a.node_id = self.node_ids[i]
        if self.prev_ids and self.prev_ids[i]:
            a.previous_allocation = self.prev_ids[i]
        a.create_index = self.create_index
        a.modify_index = self.modify_index
        a.alloc_modify_index = self.modify_index
        return a

    def id_index(self, alloc_id: str) -> int:
        """Column index of an alloc id; the reverse map is built lazily on
        first by-id access (bulk inserts never need it — undeclared attr,
        so it stays off the wire codec)."""
        idx = getattr(self, "_id_idx", None)
        if idx is None:
            idx = {aid: i for i, aid in enumerate(self.ids)}
            self._id_idx = idx
        return idx[alloc_id]

    def allocs(self) -> List[Allocation]:
        return [self.materialize(i) for i in range(len(self.ids))]

    def node_counts(self) -> Dict[str, int]:
        counts: Dict[str, int] = {}
        for nid in self.node_ids:
            counts[nid] = counts.get(nid, 0) + 1
        return counts

    def filter_nodes(self, keep: set) -> "AllocSlab":
        """Slab restricted to placements on ``keep`` nodes (partial plan
        commit, plan_apply.go:242)."""
        idx = [i for i, nid in enumerate(self.node_ids) if nid in keep]
        return AllocSlab(
            proto=self.proto,
            ids=[self.ids[i] for i in idx],
            names=[self.names[i] for i in idx],
            node_ids=[self.node_ids[i] for i in idx],
            prev_ids=[self.prev_ids[i] for i in idx] if self.prev_ids else [],
            create_index=self.create_index,
            modify_index=self.modify_index,
        )


@dataclass
class Plan:
    """The scheduler's proposed state mutation, submitted for optimistic
    apply (structs.go:4477-4570)."""

    eval_id: str = ""
    eval_token: str = ""
    # Applied index of the snapshot the scheduler planned against
    # (optimistic concurrency, PAPER.md L3): the plan applier samples
    # apply_index − snapshot_index as plan staleness, the telemetry for
    # how far behind stale-snapshot workers run.
    snapshot_index: int = 0
    priority: int = 0
    all_at_once: bool = False
    job: Optional[Job] = None
    node_update: Dict[str, List[Allocation]] = field(default_factory=dict)
    node_allocation: Dict[str, List[Allocation]] = field(default_factory=dict)
    alloc_slabs: List[AllocSlab] = field(default_factory=list)
    # Evictions of strictly-lower-priority allocs this plan makes room
    # with (scheduler/preempt.py): committed atomically with the
    # placements, rejected if a preempted alloc changed underneath
    # (plan_apply.py optimistic-concurrency re-check).
    node_preemptions: Dict[str, List[Allocation]] = field(default_factory=dict)
    annotations: Optional["PlanAnnotations"] = None

    def append_update(
        self,
        alloc: Allocation,
        desired_status: str,
        desired_description: str,
        client_status: str = "",
    ) -> None:
        """Mark an existing alloc for stop/evict (structs.go:4520 AppendUpdate).

        If the plan has no job (job deregistration) the alloc's job is adopted
        so the applier can identify what is being stopped; the staged update
        itself is normalized (job + combined resources stripped)."""
        new_alloc = alloc.copy()
        if self.job is None and new_alloc.job is not None:
            self.job = new_alloc.job
        new_alloc.job = None
        new_alloc.resources = None
        new_alloc.desired_status = desired_status
        new_alloc.desired_description = desired_description
        if client_status:
            new_alloc.client_status = client_status
        self.node_update.setdefault(alloc.node_id, []).append(new_alloc)

    def pop_update(self, alloc: Allocation) -> None:
        """Remove a staged eviction (used by in-place update speculation,
        structs.go:4546 PopUpdate)."""
        updates = self.node_update.get(alloc.node_id, [])
        if updates and updates[-1].id == alloc.id:
            updates.pop()
            if not updates:
                self.node_update.pop(alloc.node_id, None)

    def append_alloc(self, alloc: Allocation) -> None:
        self.node_allocation.setdefault(alloc.node_id, []).append(alloc)

    def append_preempted_alloc(self, alloc: Allocation) -> None:
        """Stage an eviction that makes room for a higher-priority
        placement.  The copy keeps the victim's modify_index — the plan
        applier's staleness fence (reject if it moved underneath)."""
        new_alloc = alloc.copy()
        new_alloc.job = None
        new_alloc.resources = None
        new_alloc.desired_status = ALLOC_DESIRED_STATUS_EVICT
        new_alloc.desired_description = ALLOC_PREEMPTED
        self.node_preemptions.setdefault(alloc.node_id, []).append(new_alloc)

    def append_slab(self, slab: AllocSlab) -> None:
        self.alloc_slabs.append(slab)

    def is_no_op(self) -> bool:
        return (not self.node_update and not self.node_allocation
                and not self.alloc_slabs and not self.node_preemptions)

    def total_allocs(self) -> int:
        return (sum(len(v) for v in self.node_allocation.values())
                + sum(len(v) for v in self.node_update.values())
                + sum(len(v) for v in self.node_preemptions.values())
                + sum(len(sl) for sl in self.alloc_slabs))


@dataclass
class PlanResult:
    """The subset of a plan the leader committed (structs.go:4581-4620)."""

    node_update: Dict[str, List[Allocation]] = field(default_factory=dict)
    node_allocation: Dict[str, List[Allocation]] = field(default_factory=dict)
    alloc_slabs: List[AllocSlab] = field(default_factory=list)
    node_preemptions: Dict[str, List[Allocation]] = field(default_factory=dict)
    refresh_index: int = 0
    alloc_index: int = 0

    def full_commit(self, plan: Plan) -> tuple[bool, int, int]:
        """Whether every proposed alloc was committed (structs.go:4604)."""
        expected = 0
        actual = 0
        for node, allocs in plan.node_update.items():
            expected += len(allocs)
            actual += len(self.node_update.get(node, []))
        for node, allocs in plan.node_allocation.items():
            expected += len(allocs)
            actual += len(self.node_allocation.get(node, []))
        for node, allocs in plan.node_preemptions.items():
            expected += len(allocs)
            actual += len(self.node_preemptions.get(node, []))
        expected += sum(len(sl) for sl in plan.alloc_slabs)
        actual += sum(len(sl) for sl in self.alloc_slabs)
        return actual == expected, expected, actual


@dataclass
class PlanAnnotations:
    """Dry-run plan diff annotations for the plan CLI (structs.go:4625)."""

    desired_tg_updates: Dict[str, "DesiredUpdates"] = field(default_factory=dict)


@dataclass
class DesiredUpdates:
    ignore: int = 0
    place: int = 0
    migrate: int = 0
    stop: int = 0
    in_place_update: int = 0
    destructive_update: int = 0


# ---------------------------------------------------------------------------
# Job diff wire types (diff.go:14-200; the diff engine lives in diff.py)
# ---------------------------------------------------------------------------

DIFF_TYPE_NONE = "None"
DIFF_TYPE_ADDED = "Added"
DIFF_TYPE_DELETED = "Deleted"
DIFF_TYPE_EDITED = "Edited"


@dataclass
class FieldDiff:
    type: str = DIFF_TYPE_NONE
    name: str = ""
    old: str = ""
    new: str = ""
    annotations: List[str] = field(default_factory=list)


@dataclass
class ObjectDiff:
    type: str = DIFF_TYPE_NONE
    name: str = ""
    fields: List[FieldDiff] = field(default_factory=list)
    objects: List["ObjectDiff"] = field(default_factory=list)


@dataclass
class TaskDiff:
    type: str = DIFF_TYPE_NONE
    name: str = ""
    fields: List[FieldDiff] = field(default_factory=list)
    objects: List[ObjectDiff] = field(default_factory=list)
    annotations: List[str] = field(default_factory=list)


@dataclass
class TaskGroupDiff:
    type: str = DIFF_TYPE_NONE
    name: str = ""
    fields: List[FieldDiff] = field(default_factory=list)
    objects: List[ObjectDiff] = field(default_factory=list)
    tasks: List[TaskDiff] = field(default_factory=list)
    updates: Dict[str, int] = field(default_factory=dict)


@dataclass
class JobDiff:
    type: str = DIFF_TYPE_NONE
    id: str = ""
    fields: List[FieldDiff] = field(default_factory=list)
    objects: List[ObjectDiff] = field(default_factory=list)
    task_groups: List[TaskGroupDiff] = field(default_factory=list)


@dataclass
class JobPlanResponse:
    """Dry-run result returned by Job.Plan (structs.go JobPlanResponse):
    the annotated diff plus placement forensics, no state mutated."""

    annotations: Optional[PlanAnnotations] = None
    failed_tg_allocs: Dict[str, AllocMetric] = field(default_factory=dict)
    job_modify_index: int = 0
    created_evals: List["Evaluation"] = field(default_factory=list)
    diff: Optional[JobDiff] = None
    next_periodic_launch: float = 0.0


# ---------------------------------------------------------------------------
# Job summary
# ---------------------------------------------------------------------------


@dataclass
class TaskGroupSummary:
    """Per-TG alloc status counts (structs.go:1680-1700)."""

    queued: int = 0
    complete: int = 0
    failed: int = 0
    running: int = 0
    starting: int = 0
    lost: int = 0


@dataclass
class JobSummary:
    """Materialized per-job alloc summary (structs.go:1640-1678)."""

    job_id: str = ""
    summary: Dict[str, TaskGroupSummary] = field(default_factory=dict)
    children: Optional["JobChildrenSummary"] = None
    create_index: int = 0
    modify_index: int = 0

    def copy(self) -> "JobSummary":
        s = _fast_copy(self)
        s.summary = {k: dataclasses.replace(v) for k, v in self.summary.items()}
        s.children = dataclasses.replace(self.children) if self.children else None
        return s


@dataclass
class JobChildrenSummary:
    pending: int = 0
    running: int = 0
    dead: int = 0


# -- cluster event stream (reference: nomad/stream, the 1.0 event broker) ----

TOPIC_NODE = "Node"
TOPIC_JOB = "Job"
TOPIC_EVAL = "Eval"
TOPIC_ALLOC = "Alloc"
TOPIC_DEPLOYMENT = "Deployment"
TOPIC_PLAN = "Plan"
TOPIC_BREAKER = "Breaker"
TOPIC_FAULT = "Fault"
TOPIC_NAMESPACE = "Namespace"

EVENT_TOPICS = (TOPIC_NODE, TOPIC_JOB, TOPIC_EVAL, TOPIC_ALLOC,
                TOPIC_DEPLOYMENT, TOPIC_PLAN, TOPIC_BREAKER, TOPIC_FAULT,
                TOPIC_NAMESPACE)


@dataclass
class Event:
    """One structured state-change event (structs/event.go Event): a
    (topic, type, key) triple stamped with the raft index of the write
    that produced it, a payload stub, and — when the write happened
    under a traced span — the correlating eval/span ids from the
    tracing plane, so an event timeline joins against
    ``/v1/trace/eval/<id>``."""

    topic: str = ""
    type: str = ""
    key: str = ""
    index: int = 0
    payload: Dict[str, object] = field(default_factory=dict)
    eval_id: str = ""
    span_id: int = 0
    wall: float = 0.0

    def to_wire_dict(self) -> Dict[str, object]:
        return {"Topic": self.topic, "Type": self.type, "Key": self.key,
                "Index": self.index, "Payload": self.payload,
                "EvalID": self.eval_id, "SpanID": self.span_id,
                "Wall": self.wall}


def now() -> float:
    return time.time()
