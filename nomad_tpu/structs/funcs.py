"""Pure fit & scoring functions — the #1 vectorization targets.

Behavioral parity with reference nomad/structs/funcs.go:
``allocs_fit`` (:60) and ``score_fit`` (:123, Google best-fit-v3).  The scalar
versions here are the CPU oracle; nomad_tpu/ops/scoring.py computes the same
quantities as one batched XLA op over the [B, N] (task-group × node) matrix.
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional, Tuple

from .network import NetworkIndex
from .structs import Allocation, Node, Resources


def remove_allocs(allocs: List[Allocation], remove: List[Allocation]) -> List[Allocation]:
    """Filter out allocs whose IDs appear in remove (funcs.go:11)."""
    remove_ids = {a.id for a in remove}
    return [a for a in allocs if a.id not in remove_ids]


def filter_terminal_allocs(
    allocs: List[Allocation],
) -> Tuple[List[Allocation], Dict[str, Allocation]]:
    """Split out terminal allocs, keeping the latest terminal alloc per name
    (funcs.go:33)."""
    terminal: Dict[str, Allocation] = {}
    live: List[Allocation] = []
    for alloc in allocs:
        if alloc.terminal_status():
            prev = terminal.get(alloc.name)
            if prev is None or prev.create_index < alloc.create_index:
                terminal[alloc.name] = alloc
        else:
            live.append(alloc)
    return live, terminal


def allocs_fit(
    node: Node,
    allocs: List[Allocation],
    net_idx: Optional[NetworkIndex] = None,
) -> Tuple[bool, str, Resources]:
    """Whether the given allocs all fit on the node; returns
    (fit, exhausted_dimension, used_resources) (funcs.go:60).

    If ``net_idx`` is provided the caller has already verified there are no
    port collisions; otherwise one is built here and checked.
    """
    used = Resources()
    if node.reserved is not None:
        used.add(node.reserved)

    for alloc in allocs:
        if alloc.resources is not None:
            used.add(alloc.resources)
        elif alloc.task_resources:
            # Plan-internal allocs carry per-task resources with the combined
            # ask stripped; sum shared + per-task.
            used.add(alloc.shared_resources)
            for task_res in alloc.task_resources.values():
                used.add(task_res)
        else:
            raise ValueError(f"allocation {alloc.id!r} has no resources set")

    ok, dimension = node.resources.superset(used)
    if not ok:
        return False, dimension, used

    if net_idx is None:
        net_idx = NetworkIndex()
        if net_idx.set_node(node) or net_idx.add_allocs(allocs):
            return False, "reserved port collision", used

    if net_idx.overcommitted():
        return False, "bandwidth exceeded", used

    return True, "", used


def score_fit(node: Node, util: Resources) -> float:
    """Google best-fit-v3 bin-packing score in [0, 18] (funcs.go:123).

    ``20 − (10^freeCpuFrac + 10^freeMemFrac)``: 18 at a perfect fit, 0 at
    fully free.  Two exponentials + clamp per (tg, node) pair — on TPU this
    is a single fused elementwise op over the whole score matrix.
    """
    node_cpu = float(node.resources.cpu)
    node_mem = float(node.resources.memory_mb)
    if node.reserved is not None:
        node_cpu -= float(node.reserved.cpu)
        node_mem -= float(node.reserved.memory_mb)

    # Go float division by zero yields ±Inf and the clamp absorbs it; Python
    # raises, so reproduce the IEEE behavior explicitly.
    free_pct_cpu = 1.0 - _safe_div(float(util.cpu), node_cpu)
    free_pct_mem = 1.0 - _safe_div(float(util.memory_mb), node_mem)

    try:
        total = math.pow(10.0, free_pct_cpu) + math.pow(10.0, free_pct_mem)
    except OverflowError:
        total = math.inf
    score = 20.0 - total
    if math.isnan(score):
        return 0.0
    return max(0.0, min(18.0, score))


def _safe_div(num: float, den: float) -> float:
    if den == 0.0:
        return math.nan if num == 0.0 else math.copysign(math.inf, num)
    return num / den
