"""Human-readable plan-diff annotations for the ``plan`` command.

Reference behavior: scheduler/annotate.go:37-214 — decorate a JobDiff with the
scheduler's DesiredUpdates counts, flag count changes as forces-create/destroy,
and classify each task change as in-place vs destructive using the same rules
as tasksUpdated (scheduler/util.go:336).
"""

from __future__ import annotations

from typing import Optional

from ..structs import structs as s
from ..structs.diff import (DIFF_TYPE_ADDED, DIFF_TYPE_DELETED, DIFF_TYPE_NONE,
                            JobDiff, TaskDiff, TaskGroupDiff)

ANNOTATION_FORCES_CREATE = "forces create"
ANNOTATION_FORCES_DESTROY = "forces destroy"
ANNOTATION_FORCES_INPLACE_UPDATE = "forces in-place update"
ANNOTATION_FORCES_DESTRUCTIVE_UPDATE = "forces create/destroy update"

UPDATE_TYPE_IGNORE = "ignore"
UPDATE_TYPE_CREATE = "create"
UPDATE_TYPE_DESTROY = "destroy"
UPDATE_TYPE_MIGRATE = "migrate"
UPDATE_TYPE_INPLACE_UPDATE = "in-place update"
UPDATE_TYPE_DESTRUCTIVE_UPDATE = "create/destroy update"

# Object changes that can be applied without restarting the task
# (annotate.go:180-190).
_INPLACE_OBJECTS = {"LogConfig", "Service", "Constraint"}


def annotate(diff: JobDiff, annotations: Optional[s.PlanAnnotations]) -> None:
    """annotate.go:37 Annotate."""
    for tg_diff in diff.task_groups:
        _annotate_task_group(tg_diff, annotations)


def _annotate_task_group(diff: TaskGroupDiff,
                         annotations: Optional[s.PlanAnnotations]) -> None:
    if annotations is not None:
        tg = annotations.desired_tg_updates.get(diff.name)
        if tg is not None:
            for label, count in (
                    (UPDATE_TYPE_IGNORE, tg.ignore),
                    (UPDATE_TYPE_CREATE, tg.place),
                    (UPDATE_TYPE_MIGRATE, tg.migrate),
                    (UPDATE_TYPE_DESTROY, tg.stop),
                    (UPDATE_TYPE_INPLACE_UPDATE, tg.in_place_update),
                    (UPDATE_TYPE_DESTRUCTIVE_UPDATE, tg.destructive_update)):
                if count:
                    diff.updates[label] = count

    _annotate_count_change(diff)
    for task_diff in diff.tasks:
        _annotate_task(task_diff, diff)


def _annotate_count_change(diff: TaskGroupDiff) -> None:
    """annotate.go:122 — flag Count field edits as scale up/down."""
    count_diff = next((f for f in diff.fields if f.name == "Count"), None)
    if count_diff is None:
        return
    old = int(count_diff.old) if count_diff.old else 0
    new = int(count_diff.new) if count_diff.new else 0
    if old < new:
        count_diff.annotations.append(ANNOTATION_FORCES_CREATE)
    elif new < old:
        count_diff.annotations.append(ANNOTATION_FORCES_DESTROY)


def _annotate_task(diff: TaskDiff, parent: TaskGroupDiff) -> None:
    """annotate.go:146 — classify each task change."""
    if diff.type == DIFF_TYPE_NONE:
        return

    # The whole task group is coming or going.
    if parent.type in (DIFF_TYPE_ADDED, DIFF_TYPE_DELETED):
        if diff.type == DIFF_TYPE_ADDED:
            diff.annotations.append(ANNOTATION_FORCES_CREATE)
            return
        if diff.type == DIFF_TYPE_DELETED:
            diff.annotations.append(ANNOTATION_FORCES_DESTROY)
            return

    # Any primitive field change except KillTimeout forces a destructive
    # update; only a small set of object changes are in-place.
    destructive = any(f.name != "KillTimeout" and f.type != DIFF_TYPE_NONE
                      for f in diff.fields)
    if not destructive:
        destructive = any(o.type != DIFF_TYPE_NONE
                          and o.name not in _INPLACE_OBJECTS
                          for o in diff.objects)

    diff.annotations.append(
        ANNOTATION_FORCES_DESTRUCTIVE_UPDATE if destructive
        else ANNOTATION_FORCES_INPLACE_UPDATE)
