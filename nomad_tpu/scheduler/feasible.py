"""Feasibility checking: source iterators, constraint checkers, and the
computed-class caching wrapper (reference: scheduler/feasible.go).

This module is the CPU oracle for the TPU feasibility kernel
(nomad_tpu/ops/feasibility.py): each checker here is a per-(tg, node)
predicate that the kernel evaluates as one vectorized compare over
attribute-codebook tensors.
"""
from __future__ import annotations

import re
from typing import Dict, List, Optional, Set

from ..structs import structs as s
from ..utils import version as goversion
from .context import ComputedClassFeasibility, EvalContext


class StaticIterator:
    """Yields nodes in fixed order; base of the iterator chain
    (feasible.go:34-78).

    With ``lazy_shuffle`` armed it yields an incremental Fisher-Yates
    order instead: position i is finalized (one rng draw + swap) only
    when first consumed.  The LimitIterator at the top of the stack
    consumes O(log N) candidates of an N-node shuffle, and the eager
    O(N) shuffle was the single largest scheduler cost in the
    control-plane load-harness profile.  The finalized prefix is stable
    across reset()/wrap-around, so within one arming the order is
    exactly one fixed shuffle, same as the eager version."""

    def __init__(self, ctx: EvalContext, nodes: Optional[List[s.Node]],
                 rng=None):
        self.ctx = ctx
        self.nodes: List[s.Node] = nodes or []
        self.offset = 0
        self.seen = 0
        self.rng = rng
        self._finalized = 0

    def lazy_shuffle(self, rng) -> None:
        """Arm (or re-arm) incremental shuffling of the current node
        list; already-finalized positions are forgotten."""
        self.rng = rng
        self._finalized = 0

    def next_option(self) -> Optional[s.Node]:
        n = len(self.nodes)
        if self.offset == n or self.seen == n:
            if self.seen != n:
                self.offset = 0
            else:
                return None
        if self.rng is not None and self.offset >= self._finalized:
            j = self.offset + self.rng.randrange(n - self.offset)
            nodes = self.nodes
            nodes[self.offset], nodes[j] = nodes[j], nodes[self.offset]
            self._finalized = self.offset + 1
        option = self.nodes[self.offset]
        self.offset += 1
        self.seen += 1
        self.ctx.metrics.evaluate_node()
        return option

    def reset(self) -> None:
        self.seen = 0

    def set_nodes(self, nodes: List[s.Node]) -> None:
        self.nodes = nodes
        self.offset = 0
        self.seen = 0
        self._finalized = 0


def new_random_iterator(ctx: EvalContext, nodes: Optional[List[s.Node]]) -> StaticIterator:
    """Fisher-Yates order, finalized lazily as consumed (feasible.go:82)."""
    return StaticIterator(ctx, nodes or [], rng=ctx.rng)


def shuffle_nodes(nodes: List[s.Node], rng) -> None:
    """In-place Fisher-Yates (util.go:325 shuffleNodes)."""
    for i in range(len(nodes) - 1, 0, -1):
        j = rng.randrange(i + 1)
        nodes[i], nodes[j] = nodes[j], nodes[i]


class DriverChecker:
    """Node must advertise every required driver as a truthy
    ``driver.<name>`` attribute (feasible.go:92-143)."""

    def __init__(self, ctx: EvalContext, drivers: Optional[Set[str]] = None):
        self.ctx = ctx
        self.drivers = drivers or set()

    def set_drivers(self, drivers: Set[str]) -> None:
        self.drivers = drivers

    def feasible(self, option: s.Node) -> bool:
        if self._has_drivers(option):
            return True
        self.ctx.metrics.filter_node(option, "missing drivers")
        return False

    def _has_drivers(self, option: s.Node) -> bool:
        for driver in self.drivers:
            value = option.attributes.get(f"driver.{driver}")
            if value is None:
                return False
            enabled = _parse_bool(value)
            if enabled is None:
                self.ctx.logger.warning(
                    "node %s has invalid driver setting driver.%s=%s",
                    option.id, driver, value)
                return False
            if not enabled:
                return False
        return True


def _parse_bool(value: str) -> Optional[bool]:
    # Go strconv.ParseBool semantics.
    if value in ("1", "t", "T", "true", "TRUE", "True"):
        return True
    if value in ("0", "f", "F", "false", "FALSE", "False"):
        return False
    return None


class ConstraintChecker:
    """Evaluates a set of constraints against one node
    (feasible.go:355-396)."""

    def __init__(self, ctx: EvalContext, constraints: Optional[List[s.Constraint]] = None):
        self.ctx = ctx
        self.constraints = constraints or []

    def set_constraints(self, constraints: List[s.Constraint]) -> None:
        self.constraints = constraints

    def feasible(self, option: s.Node) -> bool:
        for constraint in self.constraints:
            if not self._meets_constraint(constraint, option):
                self.ctx.metrics.filter_node(option, str(constraint))
                return False
        return True

    def _meets_constraint(self, constraint: s.Constraint, option: s.Node) -> bool:
        lval, lok = resolve_constraint_target(constraint.ltarget, option)
        if not lok:
            return False
        rval, rok = resolve_constraint_target(constraint.rtarget, option)
        if not rok:
            return False
        return check_constraint(self.ctx, constraint.operand, lval, rval)


def resolve_constraint_target(target: str, node: s.Node):
    """Interpolate ``${node.*}/${attr.*}/${meta.*}`` targets
    (feasible.go:397-430); non-interpolated targets are literals."""
    if not target.startswith("${"):
        return target, True
    if target == "${node.unique.id}":
        return node.id, True
    if target == "${node.datacenter}":
        return node.datacenter, True
    if target == "${node.unique.name}":
        return node.name, True
    if target == "${node.class}":
        return node.node_class, True
    if target.startswith("${attr."):
        attr = target[len("${attr."):].rstrip("}")
        if attr in node.attributes:
            return node.attributes[attr], True
        return None, False
    if target.startswith("${meta."):
        key = target[len("${meta."):].rstrip("}")
        if key in node.meta:
            return node.meta[key], True
        return None, False
    return None, False


def check_constraint(ctx: EvalContext, operand: str, lval, rval) -> bool:
    """Dispatch one constraint operand (feasible.go:433-458)."""
    if operand in (s.CONSTRAINT_DISTINCT_HOSTS, s.CONSTRAINT_DISTINCT_PROPERTY):
        # Handled by dedicated iterators, pass here.
        return True
    if operand in ("=", "==", "is"):
        return lval == rval
    if operand in ("!=", "not"):
        return lval != rval
    if operand in ("<", "<=", ">", ">="):
        return _check_lexical_order(operand, lval, rval)
    if operand == s.CONSTRAINT_VERSION:
        return _check_version_constraint(ctx, lval, rval)
    if operand == s.CONSTRAINT_REGEX:
        return _check_regexp_constraint(ctx, lval, rval)
    if operand == s.CONSTRAINT_SET_CONTAINS:
        return _check_set_contains(lval, rval)
    return False


def _check_lexical_order(op: str, lval, rval) -> bool:
    if not isinstance(lval, str) or not isinstance(rval, str):
        return False
    if op == "<":
        return lval < rval
    if op == "<=":
        return lval <= rval
    if op == ">":
        return lval > rval
    if op == ">=":
        return lval >= rval
    return False


def _check_version_constraint(ctx: EvalContext, lval, rval) -> bool:
    """(feasible.go:487) with the per-eval constraint cache."""
    if isinstance(lval, int):
        lval = str(lval)
    if not isinstance(lval, str) or not isinstance(rval, str):
        return False
    vers = goversion.parse_version(lval)
    if vers is None:
        return False
    cache = ctx.cache.constraint_cache
    if rval in cache:
        constraints = cache[rval]
    else:
        constraints = goversion.parse_constraints(rval)
        cache[rval] = constraints
    if constraints is None:
        return False
    return constraints.check(vers)


def _check_regexp_constraint(ctx: EvalContext, lval, rval) -> bool:
    """(feasible.go:530) with the per-eval regex cache."""
    if not isinstance(lval, str) or not isinstance(rval, str):
        return False
    cache = ctx.cache.re_cache
    if rval in cache:
        pattern = cache[rval]
    else:
        try:
            pattern = re.compile(rval)
        except re.error:
            pattern = None
        cache[rval] = pattern
    if pattern is None:
        return False
    return pattern.search(lval) is not None


def _check_set_contains(lval, rval) -> bool:
    """Left comma-set must contain every right comma-element
    (feasible.go:563)."""
    if not isinstance(lval, str) or not isinstance(rval, str):
        return False
    have = {part.strip() for part in lval.split(",")}
    return all(part.strip() in have for part in rval.split(","))


class DistinctHostsIterator:
    """Filters nodes that already host an alloc of this job/TG when a
    distinct_hosts constraint is present (feasible.go:148-243)."""

    def __init__(self, ctx: EvalContext, source):
        self.ctx = ctx
        self.source = source
        self.tg: Optional[s.TaskGroup] = None
        self.job: Optional[s.Job] = None
        self.tg_distinct = False
        self.job_distinct = False

    def set_task_group(self, tg: s.TaskGroup) -> None:
        self.tg = tg
        self.tg_distinct = self._has_distinct_hosts(tg.constraints)

    def set_job(self, job: s.Job) -> None:
        self.job = job
        self.job_distinct = self._has_distinct_hosts(job.constraints)

    @staticmethod
    def _has_distinct_hosts(constraints: List[s.Constraint]) -> bool:
        return any(c.operand == s.CONSTRAINT_DISTINCT_HOSTS for c in constraints)

    def next_option(self) -> Optional[s.Node]:
        while True:
            option = self.source.next_option()
            if option is None or not (self.job_distinct or self.tg_distinct):
                return option
            if not self._satisfies(option):
                self.ctx.metrics.filter_node(option, s.CONSTRAINT_DISTINCT_HOSTS)
                continue
            return option

    def _satisfies(self, option: s.Node) -> bool:
        proposed = self.ctx.proposed_allocs(option.id)
        for alloc in proposed:
            job_collision = alloc.job_id == self.job.id
            task_collision = alloc.task_group == self.tg.name
            if (self.job_distinct and job_collision) or (job_collision and task_collision):
                return False
        return True

    def reset(self) -> None:
        self.source.reset()


class DistinctPropertyIterator:
    """Filters nodes whose property value is already used by the job's
    allocs when a distinct_property constraint exists
    (feasible.go:248-352)."""

    def __init__(self, ctx: EvalContext, source):
        self.ctx = ctx
        self.source = source
        self.tg: Optional[s.TaskGroup] = None
        self.job: Optional[s.Job] = None
        self.has_distinct_property = False
        self.job_property_sets: List = []
        self.group_property_sets: Dict[str, List] = {}

    def set_task_group(self, tg: s.TaskGroup) -> None:
        from .propertyset import PropertySet

        self.tg = tg
        if tg.name not in self.group_property_sets:
            sets = []
            for c in tg.constraints:
                if c.operand != s.CONSTRAINT_DISTINCT_PROPERTY:
                    continue
                pset = PropertySet(self.ctx, self.job)
                pset.set_tg_constraint(c, tg.name)
                sets.append(pset)
            self.group_property_sets[tg.name] = sets
        self.has_distinct_property = bool(
            self.job_property_sets or self.group_property_sets[tg.name]
        )

    def set_job(self, job: s.Job) -> None:
        from .propertyset import PropertySet

        self.job = job
        for c in job.constraints:
            if c.operand != s.CONSTRAINT_DISTINCT_PROPERTY:
                continue
            pset = PropertySet(self.ctx, job)
            pset.set_job_constraint(c)
            self.job_property_sets.append(pset)

    def next_option(self) -> Optional[s.Node]:
        while True:
            option = self.source.next_option()
            if option is None or not self.has_distinct_property:
                return option
            if not self._satisfies(option, self.job_property_sets):
                continue
            if not self._satisfies(option, self.group_property_sets.get(self.tg.name, [])):
                continue
            return option

    def _satisfies(self, option: s.Node, psets) -> bool:
        for pset in psets:
            ok, reason = pset.satisfies_distinct_properties(option, self.tg.name)
            if not ok:
                self.ctx.metrics.filter_node(option, reason)
                return False
        return True

    def reset(self) -> None:
        self.source.reset()
        for pset in self.job_property_sets:
            pset.populate_proposed()
        for sets in self.group_property_sets.values():
            for pset in sets:
                pset.populate_proposed()


class FeasibilityWrapper:
    """Runs job/TG feasibility checks with per-computed-class caching and
    escape semantics (feasible.go:597-708)."""

    def __init__(self, ctx: EvalContext, source, job_checkers, tg_checkers):
        self.ctx = ctx
        self.source = source
        self.job_checkers = job_checkers
        self.tg_checkers = tg_checkers
        self.tg = ""

    def set_task_group(self, tg: str) -> None:
        self.tg = tg

    def reset(self) -> None:
        self.source.reset()

    def next_option(self) -> Optional[s.Node]:
        elig = self.ctx.eligibility()
        metrics = self.ctx.metrics
        while True:
            option = self.source.next_option()
            if option is None:
                return None

            job_escaped = job_unknown = False
            status = elig.job_status(option.computed_class)
            if status == ComputedClassFeasibility.INELIGIBLE:
                metrics.filter_node(option, "computed class ineligible")
                continue
            elif status == ComputedClassFeasibility.ESCAPED:
                job_escaped = True
            elif status == ComputedClassFeasibility.UNKNOWN:
                job_unknown = True

            if not self._run_checks(self.job_checkers, option, job_escaped,
                                    lambda ok: elig.set_job_eligibility(ok, option.computed_class)):
                continue
            if not job_escaped and job_unknown:
                elig.set_job_eligibility(True, option.computed_class)

            tg_escaped = tg_unknown = False
            status = elig.task_group_status(self.tg, option.computed_class)
            if status == ComputedClassFeasibility.INELIGIBLE:
                metrics.filter_node(option, "computed class ineligible")
                continue
            elif status == ComputedClassFeasibility.ELIGIBLE:
                return option
            elif status == ComputedClassFeasibility.ESCAPED:
                tg_escaped = True
            elif status == ComputedClassFeasibility.UNKNOWN:
                tg_unknown = True

            if not self._run_checks(
                self.tg_checkers, option, tg_escaped,
                lambda ok: elig.set_task_group_eligibility(ok, self.tg, option.computed_class),
            ):
                continue
            if not tg_escaped and tg_unknown:
                elig.set_task_group_eligibility(True, self.tg, option.computed_class)
            return option

    @staticmethod
    def _run_checks(checkers, option, escaped, mark) -> bool:
        for checker in checkers:
            if not checker.feasible(option):
                if not escaped:
                    mark(False)
                return False
        return True
