"""Scheduler test harness: a real StateStore plus a fake Planner that
captures plans/evals and applies plans at synthetic raft indexes
(reference: scheduler/testing.go:41-218).

This is exactly the oracle interface the TPU batch kernel is
differential-tested against (SURVEY.md §4)."""
from __future__ import annotations

import logging
import threading
from typing import Callable, List, Optional, Tuple

from ..state import StateStore
from ..structs import structs as s


class RejectPlan:
    """A planner that rejects every plan and forces a state refresh,
    exercising the refresh/retry path (testing.go:16)."""

    def __init__(self, harness: "Harness"):
        self.harness = harness

    def submit_plan(self, plan: s.Plan):
        result = s.PlanResult()
        result.refresh_index = self.harness.next_index()
        return result, self.harness.state

    def update_eval(self, ev: s.Evaluation) -> None:
        pass

    def create_eval(self, ev: s.Evaluation) -> None:
        pass

    def reblock_eval(self, ev: s.Evaluation) -> None:
        pass


class Harness:
    """Lightweight harness implementing the Planner interface."""

    def __init__(self, state: Optional[StateStore] = None):
        self.state = state or StateStore()
        self.planner = None  # optional custom planner
        self._plan_lock = threading.Lock()
        self.plans: List[s.Plan] = []
        self.evals: List[s.Evaluation] = []
        self.create_evals: List[s.Evaluation] = []
        self.reblock_evals: List[s.Evaluation] = []
        self._next_index = 1
        self._index_lock = threading.Lock()
        self.logger = logging.getLogger("nomad_tpu.scheduler.harness")

    # -- Planner interface -------------------------------------------------

    def submit_plan(self, plan: s.Plan) -> Tuple[s.PlanResult, Optional[StateStore]]:
        with self._plan_lock:
            self.plans.append(plan)
            if self.planner is not None:
                return self.planner.submit_plan(plan)

            index = self.next_index()
            result = s.PlanResult(
                node_update=plan.node_update,
                node_allocation=plan.node_allocation,
                alloc_slabs=plan.alloc_slabs,
                node_preemptions=plan.node_preemptions,
                alloc_index=index,
            )

            allocs: List[s.Allocation] = []
            for update_list in plan.node_update.values():
                allocs.extend(update_list)
            for alloc_list in plan.node_allocation.values():
                allocs.extend(alloc_list)
            preempted: List[s.Allocation] = []
            for evicted_list in plan.node_preemptions.values():
                allocs.extend(evicted_list)
                preempted.extend(evicted_list)

            if plan.job is not None:
                # Same guard as upsert_plan_results: never stamp the
                # plan's job onto terminal allocs — an evicted victim
                # belongs to its OWN (lower-priority) job.
                for alloc in allocs:
                    if alloc.job is None and not alloc.terminal_status():
                        alloc.job = plan.job
                for slab in plan.alloc_slabs:
                    if slab.proto.job is None:
                        slab.proto.job = plan.job

            self.state.upsert_allocs(index, allocs, owned=True)
            if plan.alloc_slabs:
                self.state.upsert_slabs(index, plan.alloc_slabs)
            if preempted:
                # Mirror the real plan applier: every evicted alloc's job
                # gets ONE blocked follow-up eval so the displaced work
                # reschedules (plan_apply.py / blocked_evals.py).
                for ev in s.preemption_follow_up_evals(
                        preempted, index,
                        job_lookup=lambda jid: self.state.job_by_id(None, jid)):
                    self.state.upsert_evals(self.next_index(), [ev])
                    self.create_evals.append(ev)
            return result, None

    def update_eval(self, ev: s.Evaluation) -> None:
        with self._plan_lock:
            self.evals.append(ev)
            if self.planner is not None:
                self.planner.update_eval(ev)

    def create_eval(self, ev: s.Evaluation) -> None:
        with self._plan_lock:
            self.create_evals.append(ev)
            if self.planner is not None:
                self.planner.create_eval(ev)

    def reblock_eval(self, ev: s.Evaluation) -> None:
        with self._plan_lock:
            old = self.state.eval_by_id(None, ev.id)
            if old is None:
                raise ValueError("evaluation does not exist to be reblocked")
            if old.status != s.EVAL_STATUS_BLOCKED:
                raise ValueError(
                    f"evaluation {old.id!r} is not already in a blocked state")
            self.reblock_evals.append(ev)

    # -- helpers -----------------------------------------------------------

    def next_index(self) -> int:
        with self._index_lock:
            idx = self._next_index
            self._next_index += 1
            return idx

    def snapshot(self):
        return self.state.snapshot()

    def scheduler(self, factory: Callable):
        return factory(self.logger, self.snapshot(), self)

    def process(self, factory: Callable, ev: s.Evaluation) -> None:
        sched = self.scheduler(factory)
        sched.process(ev)

    def assert_eval_status(self, status: str) -> None:
        assert len(self.evals) == 1, f"expected exactly one eval update: {self.evals}"
        assert self.evals[0].status == status, (
            f"expected status {status}, got {self.evals[0].status}")
