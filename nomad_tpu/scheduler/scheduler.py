"""L4 scheduler interfaces and factory.

Behavioral parity with reference scheduler/scheduler.go:16-104: a factory
registry keyed by eval type, plus the State and Planner interfaces that keep
the scheduler plumbing-free (it sees only an immutable state snapshot and a
planner to submit plans through).

This package is the **CPU oracle**: an exact re-implementation of the
reference's placement semantics used (a) standalone for small clusters and
(b) as the differential-test oracle for the TPU batch scheduler in
nomad_tpu/ops/.
"""
from __future__ import annotations

import logging
from typing import Callable, Dict, List, Optional, Protocol, Tuple

from ..structs import structs as s

# Identifies the version of the scheduling algorithm; plans from a different
# major version are rejected at apply time (scheduler.go:16).
SCHEDULER_VERSION = 1


class State(Protocol):
    """The immutable world view the scheduler works from
    (scheduler.go:63-82)."""

    def nodes(self, ws) -> List[s.Node]: ...

    def node_by_id(self, ws, node_id: str) -> Optional[s.Node]: ...

    def allocs_by_job(self, ws, job_id: str, all_allocs: bool = False) -> List[s.Allocation]: ...

    def allocs_by_node(self, ws, node_id: str) -> List[s.Allocation]: ...

    def allocs_by_node_terminal(self, ws, node_id: str, terminal: bool) -> List[s.Allocation]: ...

    def job_by_id(self, ws, job_id: str) -> Optional[s.Job]: ...


class Planner(Protocol):
    """How the scheduler submits its decisions (scheduler.go:84-104)."""

    def submit_plan(self, plan: s.Plan) -> Tuple[Optional[s.PlanResult], Optional[State]]:
        """Returns (result, refreshed_state_or_None)."""
        ...

    def update_eval(self, ev: s.Evaluation) -> None: ...

    def create_eval(self, ev: s.Evaluation) -> None: ...

    def reblock_eval(self, ev: s.Evaluation) -> None: ...


class Scheduler(Protocol):
    def process(self, ev: s.Evaluation) -> None: ...


SchedulerFactory = Callable[[logging.Logger, State, Planner], Scheduler]

_BUILTIN: Dict[str, SchedulerFactory] = {}


def register_scheduler(name: str, factory: SchedulerFactory) -> None:
    _BUILTIN[name] = factory


def new_scheduler(name: str, logger: logging.Logger, state: State, planner: Planner) -> Scheduler:
    """Instantiate a scheduler by eval type (scheduler.go:42 NewScheduler)."""
    factory = _BUILTIN.get(name)
    if factory is None:
        raise ValueError(f"unknown scheduler {name!r}")
    return factory(logger, state, planner)


def builtin_schedulers() -> List[str]:
    return list(_BUILTIN)
