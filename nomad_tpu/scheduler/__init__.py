"""L4 scheduler: the CPU oracle implementation of placement logic
(reference: scheduler/).

The factory registry mirrors BuiltinSchedulers (scheduler.go:21-25):
service, batch, system — plus ``tpu-batch`` (registered by
nomad_tpu.ops.batch_sched when imported) which drains evals into batched
tensor kernels.
"""

from ..structs import structs as _s
from .context import ComputedClassFeasibility, EvalContext, EvalEligibility
from .generic import (
    GenericScheduler,
    new_batch_scheduler,
    new_service_scheduler,
)
from .scheduler import (
    SCHEDULER_VERSION,
    Planner,
    Scheduler,
    State,
    builtin_schedulers,
    new_scheduler,
    register_scheduler,
)
from .stack import GenericStack, SystemStack
from .system import SystemScheduler, new_system_scheduler
from .testing import Harness, RejectPlan

register_scheduler(_s.JOB_TYPE_SERVICE, new_service_scheduler)
register_scheduler(_s.JOB_TYPE_BATCH, new_batch_scheduler)
register_scheduler(_s.JOB_TYPE_SYSTEM, new_system_scheduler)
