"""Placement stacks: the composed iterator chains
(reference: scheduler/stack.go).

GenericStack:  Random → FeasibilityWrapper(job; tg-drivers, tg-constraints)
               → DistinctHosts → DistinctProperty → FeasibleRank → BinPack
               → JobAntiAffinity → Limit(max(2, ⌈log₂N⌉) service / 2 batch)
               → MaxScore
SystemStack:   Static → FeasibilityWrapper → DistinctProperty
               → FeasibleRank → BinPack

The TPU batch scheduler re-derives this whole chain as masked tensor ops
(nomad_tpu/ops/batch_sched.py); this is the per-placement oracle.
"""
from __future__ import annotations

import math
from typing import List, Optional, Tuple

from ..structs import structs as s
from .context import EvalContext
from .feasible import (
    ConstraintChecker,
    DistinctHostsIterator,
    DistinctPropertyIterator,
    DriverChecker,
    FeasibilityWrapper,
    StaticIterator,
)
from .rank import BinPackIterator, FeasibleRankIterator, JobAntiAffinityIterator, RankedNode
from .select import LimitIterator, MaxScoreIterator
from .util import task_group_constraints

# Anti-affinity penalty for co-placing allocs of one job (stack.go:10-19).
SERVICE_JOB_ANTI_AFFINITY_PENALTY = 20.0
BATCH_JOB_ANTI_AFFINITY_PENALTY = 10.0


class GenericStack:
    """Service/batch placement stack (stack.go:37-115)."""

    def __init__(self, batch: bool, ctx: EvalContext,
                 preemption_enabled: bool = False):
        self.batch = batch
        self.ctx = ctx

        self.source = StaticIterator(ctx, [])

        self.job_constraint = ConstraintChecker(ctx)
        self.task_group_drivers = DriverChecker(ctx)
        self.task_group_constraint = ConstraintChecker(ctx)
        self.wrapped_checks = FeasibilityWrapper(
            ctx, self.source, [self.job_constraint],
            [self.task_group_drivers, self.task_group_constraint],
        )
        self.distinct_hosts_constraint = DistinctHostsIterator(ctx, self.wrapped_checks)
        self.distinct_property_constraint = DistinctPropertyIterator(
            ctx, self.distinct_hosts_constraint)
        rank_source = FeasibleRankIterator(ctx, self.distinct_property_constraint)
        # Eviction is only offered to service jobs; it only actually
        # preempts when the operator enables preemption (rank.py).
        self.bin_pack = BinPackIterator(ctx, rank_source, evict=not batch,
                                        priority=0,
                                        preemption_enabled=preemption_enabled)
        penalty = BATCH_JOB_ANTI_AFFINITY_PENALTY if batch else SERVICE_JOB_ANTI_AFFINITY_PENALTY
        self.job_anti_aff = JobAntiAffinityIterator(ctx, self.bin_pack, penalty, "")
        self.limit = LimitIterator(ctx, self.job_anti_aff, 2)
        self.max_score = MaxScoreIterator(ctx, self.limit)

    def set_nodes(self, base_nodes: List[s.Node]) -> None:
        """Random order (finalized lazily as consumed — the limit below
        bounds the scan, so an eager O(N) shuffle pays for positions no
        iterator ever reads), then bound candidate scans: 2 for batch
        (power-of-two-choices), max(2, ⌈log₂ N⌉) for service
        (stack.go:118-137)."""
        self.source.set_nodes(base_nodes)
        self.source.lazy_shuffle(self.ctx.rng)

        limit = 2
        n = len(base_nodes)
        if not self.batch and n > 0:
            log_limit = int(math.ceil(math.log2(n))) if n > 1 else 1
            limit = max(limit, log_limit)
        self.limit.set_limit(limit)

    def set_job(self, job: s.Job) -> None:
        self.job_constraint.set_constraints(job.constraints)
        self.distinct_hosts_constraint.set_job(job)
        self.distinct_property_constraint.set_job(job)
        self.bin_pack.set_priority(job.priority)
        self.job_anti_aff.set_job(job.id)
        self.ctx.eligibility().set_job(job)

    def select(self, tg: s.TaskGroup) -> Tuple[Optional[RankedNode], s.Resources]:
        """Pick the best node for one task group (stack.go:148-178)."""
        self.max_score.reset()
        self.ctx.reset()

        tg_constr = task_group_constraints(tg)
        self.task_group_drivers.set_drivers(tg_constr.drivers)
        self.task_group_constraint.set_constraints(tg_constr.constraints)
        self.distinct_hosts_constraint.set_task_group(tg)
        self.distinct_property_constraint.set_task_group(tg)
        self.wrapped_checks.set_task_group(tg.name)
        self.bin_pack.set_task_group(tg)

        option = self.max_score.next_option()
        if (option is None and self.bin_pack.preemption_enabled
                and self.bin_pack.evict and self.bin_pack.priority > 0):
            # Preemption is strictly a last resort: only when NO node
            # fits without eviction does a second pass rank preempting
            # options (rank.py allow_preempt) — so a preemptible-but-
            # full node can never beat free capacity inside the
            # LimitIterator's small candidate sample.
            self.max_score.reset()
            self.bin_pack.allow_preempt = True
            try:
                option = self.max_score.next_option()
            finally:
                self.bin_pack.allow_preempt = False

        if option is not None and len(option.task_resources) != len(tg.tasks):
            for task in tg.tasks:
                option.set_task_resources(task, task.resources)
        return option, tg_constr.size

    def select_preferring_nodes(
        self, tg: s.TaskGroup, nodes: List[s.Node]
    ) -> Tuple[Optional[RankedNode], s.Resources]:
        """Try the preferred nodes first (sticky disk), then fall back
        (stack.go:182)."""
        original = self.source.nodes
        self.source.set_nodes(nodes)
        option, resources = self.select(tg)
        self.source.set_nodes(original)
        if option is not None:
            return option, resources
        return self.select(tg)


class SystemStack:
    """System placement stack: evaluates every node (stack.go:195-286)."""

    def __init__(self, ctx: EvalContext, preemption_enabled: bool = False):
        self.ctx = ctx
        self.source = StaticIterator(ctx, [])
        self.job_constraint = ConstraintChecker(ctx)
        self.task_group_drivers = DriverChecker(ctx)
        self.task_group_constraint = ConstraintChecker(ctx)
        self.wrapped_checks = FeasibilityWrapper(
            ctx, self.source, [self.job_constraint],
            [self.task_group_drivers, self.task_group_constraint],
        )
        self.distinct_property_constraint = DistinctPropertyIterator(ctx, self.wrapped_checks)
        rank_source = FeasibleRankIterator(ctx, self.distinct_property_constraint)
        self.bin_pack = BinPackIterator(ctx, rank_source, evict=True,
                                        priority=0,
                                        preemption_enabled=preemption_enabled)

    def set_nodes(self, base_nodes: List[s.Node]) -> None:
        self.source.set_nodes(base_nodes)

    def set_job(self, job: s.Job) -> None:
        self.job_constraint.set_constraints(job.constraints)
        self.distinct_property_constraint.set_job(job)
        self.bin_pack.set_priority(job.priority)
        self.ctx.eligibility().set_job(job)

    def select(self, tg: s.TaskGroup) -> Tuple[Optional[RankedNode], s.Resources]:
        self.bin_pack.reset()
        self.ctx.reset()

        tg_constr = task_group_constraints(tg)
        self.task_group_drivers.set_drivers(tg_constr.drivers)
        self.task_group_constraint.set_constraints(tg_constr.constraints)
        self.wrapped_checks.set_task_group(tg.name)
        self.distinct_property_constraint.set_task_group(tg)
        self.bin_pack.set_task_group(tg)

        option = self.bin_pack.next_option()
        if (option is None and self.bin_pack.preemption_enabled
                and self.bin_pack.evict and self.bin_pack.priority > 0):
            # Same last-resort second pass as GenericStack.select.
            self.bin_pack.reset()
            self.bin_pack.allow_preempt = True
            try:
                option = self.bin_pack.next_option()
            finally:
                self.bin_pack.allow_preempt = False

        if option is not None and len(option.task_resources) != len(tg.tasks):
            for task in tg.tasks:
                option.set_task_resources(task, task.resources)
        return option, tg_constr.size
