"""SystemScheduler: one alloc per feasible node
(reference: scheduler/system_sched.go)."""
from __future__ import annotations

import logging
import random
from typing import Dict, List, Optional

from ..structs import structs as s
from ..structs.funcs import filter_terminal_allocs
from .context import EvalContext
from .stack import SystemStack
from .util import (
    ALLOC_LOST,
    ALLOC_NOT_NEEDED,
    ALLOC_UPDATING,
    AllocTuple,
    SetStatusError,
    adjust_queued_allocations,
    desired_updates,
    diff_system_allocs,
    evict_and_place,
    inplace_update,
    progress_made,
    ready_nodes_in_dcs,
    retry_max,
    set_status,
    tainted_nodes,
    update_non_terminal_allocs_to_lost,
)

MAX_SYSTEM_SCHEDULE_ATTEMPTS = 5  # system_sched.go:12-15


class SystemScheduler:
    def __init__(self, logger: logging.Logger, state, planner,
                 rng: Optional[random.Random] = None):
        self.logger = logger
        self.state = state
        self.planner = planner
        self.rng = rng

        self.eval: Optional[s.Evaluation] = None
        self.job: Optional[s.Job] = None
        self.plan: Optional[s.Plan] = None
        self.plan_result: Optional[s.PlanResult] = None
        self.ctx: Optional[EvalContext] = None
        self.stack: Optional[SystemStack] = None
        self.nodes: List[s.Node] = []
        self.nodes_by_dc: Dict[str, int] = {}

        self.limit_reached = False
        self.next_eval: Optional[s.Evaluation] = None
        self.failed_tg_allocs: Optional[Dict[str, s.AllocMetric]] = None
        self.queued_allocs: Dict[str, int] = {}

    def process(self, ev: s.Evaluation) -> None:
        """(system_sched.go:56)."""
        self.eval = ev
        if ev.triggered_by not in (
            s.EVAL_TRIGGER_JOB_REGISTER,
            s.EVAL_TRIGGER_NODE_UPDATE,
            s.EVAL_TRIGGER_JOB_DEREGISTER,
            s.EVAL_TRIGGER_ROLLING_UPDATE,
        ):
            desc = f"scheduler cannot handle '{ev.triggered_by}' evaluation reason"
            set_status(self.logger, self.planner, ev, self.next_eval, None,
                       self.failed_tg_allocs, s.EVAL_STATUS_FAILED, desc, self.queued_allocs)
            return

        try:
            retry_max(MAX_SYSTEM_SCHEDULE_ATTEMPTS, self._process,
                      lambda: progress_made(self.plan_result))
        except SetStatusError as err:
            set_status(self.logger, self.planner, ev, self.next_eval, None,
                       self.failed_tg_allocs, err.eval_status, str(err), self.queued_allocs)
            return

        set_status(self.logger, self.planner, ev, self.next_eval, None,
                   self.failed_tg_allocs, s.EVAL_STATUS_COMPLETE, "", self.queued_allocs)

    def _process(self) -> bool:
        """(system_sched.go:88)."""
        self.job = self.state.job_by_id(None, self.eval.job_id)
        self.queued_allocs = {}

        if self.job is not None and not self.job.stopped():
            self.nodes, self.nodes_by_dc = ready_nodes_in_dcs(
                self.state, self.job.datacenters)

        self.plan = self.eval.make_plan(self.job)
        self.failed_tg_allocs = None
        self.ctx = EvalContext(self.state, self.plan, self.logger, rng=self.rng)
        self.stack = SystemStack(self.ctx)
        if self.job is not None and not self.job.stopped():
            self.stack.set_job(self.job)

        self._compute_job_allocs()

        if self.plan.is_no_op() and not self.eval.annotate_plan:
            return True

        if self.limit_reached and self.next_eval is None:
            self.next_eval = self.eval.next_rolling_eval(self.job.update.stagger)
            self.planner.create_eval(self.next_eval)

        result, new_state = self.planner.submit_plan(self.plan)
        self.plan_result = result

        adjust_queued_allocations(self.logger, result, self.queued_allocs)

        if new_state is not None:
            self.state = new_state
            return False

        full_commit, expected, actual = result.full_commit(self.plan)
        if not full_commit:
            self.logger.debug("attempted %d placements, %d placed", expected, actual)
            return False
        return True

    def _compute_job_allocs(self) -> None:
        """(system_sched.go:181)."""
        allocs = self.state.allocs_by_job(None, self.eval.job_id, True)
        tainted = tainted_nodes(self.state, allocs)
        update_non_terminal_allocs_to_lost(self.plan, tainted, allocs)
        allocs, terminal_allocs = filter_terminal_allocs(allocs)

        diff = diff_system_allocs(self.job, self.nodes, tainted, allocs, terminal_allocs)
        self.logger.debug("eval %s job %s: %s", self.eval.id, self.eval.job_id, diff)

        for e in diff.stop:
            self.plan.append_update(e.alloc, s.ALLOC_DESIRED_STATUS_STOP, ALLOC_NOT_NEEDED)
        for e in diff.lost:
            self.plan.append_update(e.alloc, s.ALLOC_DESIRED_STATUS_STOP, ALLOC_LOST,
                                    s.ALLOC_CLIENT_STATUS_LOST)

        destructive, inplace = inplace_update(self.ctx, self.eval, self.job,
                                              self.stack, diff.update)
        diff.update = destructive

        if self.eval.annotate_plan:
            self.plan.annotations = s.PlanAnnotations(
                desired_tg_updates=desired_updates(diff, inplace, destructive))

        limit_box = [len(diff.update)]
        if self.job is not None and not self.job.stopped() and self.job.update.rolling():
            limit_box[0] = self.job.update.max_parallel

        self.limit_reached = evict_and_place(
            self.ctx, diff, diff.update, ALLOC_UPDATING, limit_box)

        if not diff.place:
            if self.job is not None and not self.job.stopped():
                for tg in self.job.task_groups:
                    self.queued_allocs[tg.name] = 0
            return

        for tup in diff.place:
            self.queued_allocs[tup.task_group.name] = (
                self.queued_allocs.get(tup.task_group.name, 0) + 1)

        self._compute_placements(diff.place)

    def _compute_placements(self, place: List[AllocTuple]) -> None:
        """Per-node Select loop (system_sched.go:258)."""
        node_by_id = {n.id: n for n in self.nodes}
        for missing in place:
            node = node_by_id.get(missing.alloc.node_id)
            if node is None:
                raise KeyError(f"could not find node {missing.alloc.node_id!r}")

            self.stack.set_nodes([node])
            option, _ = self.stack.select(missing.task_group)

            if option is None:
                # Constraint-filtered nodes are not 'queued' failures for
                # system jobs (system_sched.go:276-292).
                if self.ctx.metrics.nodes_filtered > 0:
                    self.queued_allocs[missing.task_group.name] -= 1
                    if (self.eval.annotate_plan and self.plan.annotations is not None
                            and self.plan.annotations.desired_tg_updates):
                        desired = self.plan.annotations.desired_tg_updates.get(
                            missing.task_group.name)
                        if desired is not None:
                            desired.place -= 1
                existing_metric = (self.failed_tg_allocs or {}).get(missing.task_group.name)
                if existing_metric is not None:
                    existing_metric.coalesced_failures += 1
                    continue

            self.ctx.metrics.nodes_available = self.nodes_by_dc

            if option is not None:
                alloc = s.Allocation(
                    id=s.generate_uuid(),
                    eval_id=self.eval.id,
                    namespace=self.job.namespace,
                    name=missing.name,
                    job_id=self.job.id,
                    task_group=missing.task_group.name,
                    metrics=self.ctx.metrics,
                    node_id=option.node.id,
                    task_resources=option.task_resources,
                    desired_status=s.ALLOC_DESIRED_STATUS_RUN,
                    client_status=s.ALLOC_CLIENT_STATUS_PENDING,
                    shared_resources=s.Resources(
                        disk_mb=missing.task_group.ephemeral_disk.size_mb),
                )
                if missing.alloc is not None and missing.alloc.id:
                    alloc.previous_allocation = missing.alloc.id
                self.plan.append_alloc(alloc)
            else:
                if self.failed_tg_allocs is None:
                    self.failed_tg_allocs = {}
                self.failed_tg_allocs[missing.task_group.name] = self.ctx.metrics


def new_system_scheduler(logger, state, planner) -> SystemScheduler:
    return SystemScheduler(logger, state, planner)
