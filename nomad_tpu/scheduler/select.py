"""Selection iterators: candidate limiting and max-score pick
(reference: scheduler/select.go).

The limit is the reference's power-of-two-choices bound; on TPU the same
role is played by top-k sampling over the score matrix.
"""
from __future__ import annotations

from typing import Optional

from .rank import RankedNode


class LimitIterator:
    """Stops after yielding N options (select.go:5-44)."""

    def __init__(self, ctx, source, limit: int):
        self.ctx = ctx
        self.source = source
        self.limit = limit
        self.seen = 0

    def set_limit(self, limit: int) -> None:
        self.limit = limit

    def next_option(self) -> Optional[RankedNode]:
        if self.seen == self.limit:
            return None
        option = self.source.next_option()
        if option is None:
            return None
        self.seen += 1
        return option

    def reset(self) -> None:
        self.source.reset()
        self.seen = 0


class MaxScoreIterator:
    """Consumes the source and returns only the top-scoring option
    (select.go:46-85)."""

    def __init__(self, ctx, source):
        self.ctx = ctx
        self.source = source
        self.max: Optional[RankedNode] = None

    def next_option(self) -> Optional[RankedNode]:
        if self.max is not None:
            return None
        while True:
            option = self.source.next_option()
            if option is None:
                return self.max
            if self.max is None or option.score > self.max.score:
                self.max = option

    def reset(self) -> None:
        self.source.reset()
        self.max = None
