"""Preemption oracle: minimal eviction-set selection for priority-tier
preemption.

When a high-priority task group finds no node with spare capacity, the
scheduler may make room by evicting strictly-lower-priority allocations
(the reference reserves this via BinPackIterator's evict/priority flags,
rank.go:130; the selection semantics mirror Nomad's later
SpaceToMakeRoom: candidates ordered priority-ascending, then
largest-resource-first, so the cheapest work is displaced and the fewest
allocations move).

This module is the per-node CPU oracle.  The batched device twin
(nomad_tpu/ops/preempt.py) runs the SAME algorithm over every
(task-group, node) pair at once; both consume candidates produced by
``sort_candidates`` so their eviction sets agree exactly — the
oracle/kernel differential contract the repo already uses for scoring.

Algorithm per (node, ask, priority):

1. candidates = non-terminal allocs with job priority < the placing
   priority, sorted by (priority asc, resources desc, id asc);
2. greedy prefix: take candidates in order until the ask fits the freed
   capacity (scalar dims: cpu, memory, disk, iops);
3. reverse trim: walk the chosen prefix backwards (highest-priority
   victim first) dropping any alloc whose eviction is not needed for the
   fit.  Dropping only shrinks the freed capacity, so a kept alloc can
   never become droppable later — one pass yields an inclusion-minimal
   set (no member can be removed; asserted by tests/test_preempt.py).
"""
from __future__ import annotations

import os
from typing import Callable, List, Optional, Tuple

from ..structs import structs as s

# Score discount applied to a preempting placement so any node that fits
# WITHOUT eviction outranks it (binpack scores live in [0, 18]); the
# per-alloc term prefers smaller eviction sets among preempting nodes.
PREEMPTION_SCORE_PENALTY = 20.0
PREEMPTION_PER_ALLOC_PENALTY = 1.0

# Sentinel priority for padding rows in the device encoding: never a
# candidate (real job priorities are 0-100, structs.go JobMaxPriority).
PRIORITY_SENTINEL = 1 << 30


def preemption_score_penalty(n_evicted: int) -> float:
    return (PREEMPTION_SCORE_PENALTY
            + PREEMPTION_PER_ALLOC_PENALTY * n_evicted)


def preemption_enabled_default() -> bool:
    """Operator default for schedulers constructed without an explicit
    flag: NOMAD_TPU_PREEMPTION=1 (any value except 0/false/no/empty)."""
    from ..utils import knobs

    return knobs.get_bool("NOMAD_TPU_PREEMPTION")


def alloc_priority(alloc: s.Allocation, state=None) -> int:
    """The priority tier an allocation runs at: its job's priority,
    falling back to a state lookup for normalized plan copies (the job
    pointer is stripped by Plan.append_update) and to the default tier
    when neither is available."""
    if alloc.job is not None:
        return alloc.job.priority
    if state is not None:
        job = state.job_by_id(None, alloc.job_id)
        if job is not None:
            return job.priority
    return s.JOB_DEFAULT_PRIORITY


def alloc_size(alloc: s.Allocation) -> Tuple[int, int, int, int]:
    """(cpu, memory_mb, disk_mb, iops) an allocation occupies — combined
    resources when present, else shared + per-task (the same split
    funcs.allocs_fit consumes)."""
    r = alloc.resources
    if r is not None:
        return (r.cpu, r.memory_mb, r.disk_mb, r.iops)
    cpu = mem = disk = iops = 0
    if alloc.shared_resources is not None:
        sr = alloc.shared_resources
        cpu, mem, disk, iops = sr.cpu, sr.memory_mb, sr.disk_mb, sr.iops
    for tr in alloc.task_resources.values():
        cpu += tr.cpu
        mem += tr.memory_mb
        disk += tr.disk_mb
        iops += tr.iops
    return (cpu, mem, disk, iops)


def sort_candidates(
    allocs: List[s.Allocation],
    prio_of: Callable[[s.Allocation], int],
) -> List[s.Allocation]:
    """Eviction-candidate order shared by the oracle and the device
    encoding: priority ascending (cheapest tier first), then
    largest-resource-first within a tier (fewest evictions make room),
    id ascending as the deterministic tie-break."""
    return sorted(allocs, key=lambda a: (
        prio_of(a), tuple(-d for d in alloc_size(a)), a.id))


def select_eviction_prefix(
    free: Tuple[int, int, int, int],
    ask: Tuple[int, int, int, int],
    sizes: List[Tuple[int, int, int, int]],
) -> Optional[List[int]]:
    """Indices (into the pre-sorted candidate list) to evict so that
    ``ask`` fits into ``free`` plus the freed capacity, or None when even
    evicting every candidate is not enough.  Pure integer arithmetic —
    the exact sequence the device kernel replays as cumsum + scan."""
    freed = [0, 0, 0, 0]

    def fits(extra=(0, 0, 0, 0), minus=(0, 0, 0, 0)) -> bool:
        return all(ask[d] <= free[d] + freed[d] + extra[d] - minus[d]
                   for d in range(4))

    k = 0
    while not fits():
        if k == len(sizes):
            return None
        for d in range(4):
            freed[d] += sizes[k][d]
        k += 1
    chosen = list(range(k))
    # Reverse trim: un-evict from the back (highest-priority victim
    # first) whenever the fit survives without that alloc.
    for i in reversed(range(k)):
        size = sizes[i]
        if fits(minus=size):
            for d in range(4):
                freed[d] -= size[d]
            chosen.remove(i)
    return chosen


def find_eviction_set(
    node: s.Node,
    allocs: List[s.Allocation],
    ask: s.Resources,
    priority: int,
    prio_of: Optional[Callable[[s.Allocation], int]] = None,
) -> Optional[List[s.Allocation]]:
    """Minimal set of strictly-lower-priority allocs on ``node`` whose
    eviction lets ``ask`` fit, or None when no such set exists.

    ``allocs`` is the node's proposed (non-terminal) allocation list;
    capacity accounting covers the four scalar dimensions — network
    feasibility after eviction is the caller's re-check (rank.py rebuilds
    the NetworkIndex over the survivors)."""
    if prio_of is None:
        prio_of = alloc_priority
    cand = sort_candidates([a for a in allocs if prio_of(a) < priority],
                           prio_of)
    if not cand:
        return None

    cap = node.resources
    used = [0, 0, 0, 0]
    if node.reserved is not None:
        rv = node.reserved
        used = [rv.cpu, rv.memory_mb, rv.disk_mb, rv.iops]
    for a in allocs:
        sz = alloc_size(a)
        for d in range(4):
            used[d] += sz[d]
    free = (cap.cpu - used[0], cap.memory_mb - used[1],
            cap.disk_mb - used[2], cap.iops - used[3])
    ask_vec = (ask.cpu, ask.memory_mb, ask.disk_mb, ask.iops)
    if all(ask_vec[d] <= free[d] for d in range(4)):
        return []  # fits without eviction; nothing to preempt

    chosen = select_eviction_prefix(
        free, ask_vec, [alloc_size(a) for a in cand])
    if chosen is None or not chosen:
        return None
    return [cand[i] for i in chosen]
