"""Distinct-property bookkeeping: tracks existing / proposed / cleared values
of a node property across a job's allocations
(reference: scheduler/propertyset.go:11-265)."""
from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from ..structs import structs as s
from .feasible import resolve_constraint_target


class PropertySet:
    def __init__(self, ctx, job: Optional[s.Job]):
        self.ctx = ctx
        self.job_id = job.id if job is not None else ""
        self.task_group = ""
        self.constraint: Optional[s.Constraint] = None
        self.error_building: Optional[str] = None
        self.existing_values: Set[str] = set()
        self.proposed_values: Set[str] = set()
        self.cleared_values: Set[str] = set()

    def set_job_constraint(self, constraint: s.Constraint) -> None:
        self.constraint = constraint
        self._populate_existing()

    def set_tg_constraint(self, constraint: s.Constraint, task_group: str) -> None:
        self.task_group = task_group
        self.constraint = constraint
        self._populate_existing()

    def _populate_existing(self) -> None:
        allocs = self.ctx.state.allocs_by_job(None, self.job_id, False)
        allocs = self._filter_allocs(allocs, filter_terminal=True)
        nodes = self._build_node_map(allocs)
        self._populate_properties(allocs, nodes, self.existing_values)

    def populate_proposed(self) -> None:
        """Recompute proposed/cleared from the current plan; called whenever
        the plan changes (propertyset.go:103)."""
        self.proposed_values = set()
        self.cleared_values = set()

        stopping: List[s.Allocation] = []
        for updates in self.ctx.plan.node_update.values():
            stopping.extend(updates)
        stopping = self._filter_allocs(stopping, filter_terminal=False)

        proposed: List[s.Allocation] = []
        for pallocs in self.ctx.plan.node_allocation.values():
            proposed.extend(pallocs)
        proposed = self._filter_allocs(proposed, filter_terminal=True)

        nodes = self._build_node_map(stopping + proposed)
        self._populate_properties(stopping, nodes, self.cleared_values)
        self._populate_properties(proposed, nodes, self.proposed_values)
        self.cleared_values -= self.proposed_values

    def satisfies_distinct_properties(self, option: s.Node, tg: str) -> Tuple[bool, str]:
        """(propertyset.go:150)."""
        if self.error_building:
            return False, self.error_building
        value, ok = _get_property(option, self.constraint.ltarget)
        if not ok:
            return False, f"missing property {self.constraint.ltarget!r}"
        for used in (self.existing_values, self.proposed_values):
            if value in used and value not in self.cleared_values:
                return False, (
                    f"distinct_property: {self.constraint.ltarget}={value} already used"
                )
        return True, ""

    def _filter_allocs(self, allocs: List[s.Allocation], filter_terminal: bool) -> List[s.Allocation]:
        out = []
        for alloc in allocs:
            if filter_terminal and alloc.terminal_status():
                continue
            if self.task_group and alloc.task_group != self.task_group:
                continue
            out.append(alloc)
        return out

    def _build_node_map(self, allocs: List[s.Allocation]) -> Dict[str, Optional[s.Node]]:
        nodes: Dict[str, Optional[s.Node]] = {}
        for alloc in allocs:
            if alloc.node_id not in nodes:
                nodes[alloc.node_id] = self.ctx.state.node_by_id(None, alloc.node_id)
        return nodes

    def _populate_properties(self, allocs, nodes, properties: Set[str]) -> None:
        for alloc in allocs:
            value, ok = _get_property(nodes.get(alloc.node_id), self.constraint.ltarget)
            if ok:
                properties.add(value)


def _get_property(node: Optional[s.Node], prop: str) -> Tuple[str, bool]:
    if node is None or not prop:
        return "", False
    value, ok = resolve_constraint_target(prop, node)
    if not ok or not isinstance(value, str):
        return "", False
    return value, True
