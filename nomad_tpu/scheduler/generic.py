"""GenericScheduler: service + batch jobs
(reference: scheduler/generic_sched.go)."""
from __future__ import annotations

import logging
import random
from typing import Dict, List, Optional, Tuple

from ..structs import structs as s
from .context import EvalContext
from .stack import GenericStack
from .util import (
    ALLOC_LOST,
    ALLOC_MIGRATING,
    ALLOC_NOT_NEEDED,
    ALLOC_UPDATING,
    AllocTuple,
    SetStatusError,
    adjust_queued_allocations,
    desired_updates,
    diff_allocs,
    evict_and_place,
    inplace_update,
    mark_lost_and_place,
    materialize_task_groups,
    progress_made,
    ready_nodes_in_dcs,
    retry_max,
    set_status,
    tainted_nodes,
    update_non_terminal_allocs_to_lost,
)

# Retry budgets (generic_sched.go:14-19).
MAX_SERVICE_SCHEDULE_ATTEMPTS = 5
MAX_BATCH_SCHEDULE_ATTEMPTS = 2

BLOCKED_EVAL_MAX_PLAN_DESC = "created due to placement conflicts"
BLOCKED_EVAL_FAILED_PLACEMENTS = "created to place remaining allocations"


class GenericScheduler:
    """Optimizes placement quality for services; fast mode for batch
    (generic_sched.go:57)."""

    def __init__(self, logger: logging.Logger, state, planner, batch: bool,
                 rng: Optional[random.Random] = None,
                 preemption_enabled: Optional[bool] = None):
        self.logger = logger
        self.state = state
        self.planner = planner
        self.batch = batch
        self.rng = rng
        if preemption_enabled is None:
            from .preempt import preemption_enabled_default

            preemption_enabled = preemption_enabled_default()
        self.preemption_enabled = preemption_enabled

        self.eval: Optional[s.Evaluation] = None
        self.job: Optional[s.Job] = None
        self.plan: Optional[s.Plan] = None
        self.plan_result: Optional[s.PlanResult] = None
        self.ctx: Optional[EvalContext] = None
        self.stack: Optional[GenericStack] = None

        self.limit_reached = False
        self.next_eval: Optional[s.Evaluation] = None
        self.blocked: Optional[s.Evaluation] = None
        self.failed_tg_allocs: Optional[Dict[str, s.AllocMetric]] = None
        self.queued_allocs: Dict[str, int] = {}

    # -- entry -------------------------------------------------------------

    def process(self, ev: s.Evaluation) -> None:
        """Handle one evaluation end-to-end (generic_sched.go:104)."""
        self.eval = ev

        if ev.triggered_by not in (
            s.EVAL_TRIGGER_JOB_REGISTER,
            s.EVAL_TRIGGER_NODE_UPDATE,
            s.EVAL_TRIGGER_JOB_DEREGISTER,
            s.EVAL_TRIGGER_ROLLING_UPDATE,
            s.EVAL_TRIGGER_PERIODIC_JOB,
            s.EVAL_TRIGGER_MAX_PLANS,
        ):
            desc = f"scheduler cannot handle '{ev.triggered_by}' evaluation reason"
            set_status(self.logger, self.planner, ev, self.next_eval, self.blocked,
                       self.failed_tg_allocs, s.EVAL_STATUS_FAILED, desc, self.queued_allocs)
            return

        limit = MAX_BATCH_SCHEDULE_ATTEMPTS if self.batch else MAX_SERVICE_SCHEDULE_ATTEMPTS
        try:
            retry_max(limit, self._process, lambda: progress_made(self.plan_result))
        except SetStatusError as err:
            # No forward progress: leave a blocked eval to retry when
            # resources free up (generic_sched.go:130-147).
            self._create_blocked_eval(plan_failure=True)
            set_status(self.logger, self.planner, ev, self.next_eval, self.blocked,
                       self.failed_tg_allocs, err.eval_status, str(err), self.queued_allocs)
            return

        # A blocked eval that still couldn't place everything reblocks
        # itself with refreshed eligibility (generic_sched.go:150-159).
        if self.eval.status == s.EVAL_STATUS_BLOCKED and self.failed_tg_allocs:
            e = self.ctx.eligibility()
            new_eval = self.eval.copy()
            new_eval.escaped_computed_class = e.has_escaped()
            new_eval.class_eligibility = e.get_classes()
            self.planner.reblock_eval(new_eval)
            return

        set_status(self.logger, self.planner, ev, self.next_eval, self.blocked,
                   self.failed_tg_allocs, s.EVAL_STATUS_COMPLETE, "", self.queued_allocs)

    def _create_blocked_eval(self, plan_failure: bool) -> None:
        """(generic_sched.go:163)."""
        e = self.ctx.eligibility()
        escaped = e.has_escaped()
        class_eligibility = {} if escaped else e.get_classes()
        self.blocked = self.eval.create_blocked_eval(class_eligibility, escaped)
        if plan_failure:
            self.blocked.triggered_by = s.EVAL_TRIGGER_MAX_PLANS
            self.blocked.status_description = BLOCKED_EVAL_MAX_PLAN_DESC
        else:
            self.blocked.status_description = BLOCKED_EVAL_FAILED_PLACEMENTS
        self.planner.create_eval(self.blocked)

    # -- one attempt -------------------------------------------------------

    def _process(self) -> bool:
        """(generic_sched.go:184)."""
        self.job = self.state.job_by_id(None, self.eval.job_id)
        self.queued_allocs = {}

        self.plan = self.eval.make_plan(self.job)
        self.failed_tg_allocs = None
        self.ctx = EvalContext(self.state, self.plan, self.logger, rng=self.rng)
        self.stack = GenericStack(self.batch, self.ctx,
                                  preemption_enabled=self.preemption_enabled)
        if self.job is not None and not self.job.stopped():
            self.stack.set_job(self.job)

        self._compute_job_allocs()

        if (self.eval.status != s.EVAL_STATUS_BLOCKED and self.failed_tg_allocs
                and self.blocked is None):
            self._create_blocked_eval(plan_failure=False)

        if self.plan.is_no_op() and not self.eval.annotate_plan:
            return True

        if self.limit_reached and self.next_eval is None:
            self.next_eval = self.eval.next_rolling_eval(self.job.update.stagger)
            self.planner.create_eval(self.next_eval)

        result, new_state = self.planner.submit_plan(self.plan)
        self.plan_result = result

        adjust_queued_allocations(self.logger, result, self.queued_allocs)

        if new_state is not None:
            self.state = new_state
            return False

        full_commit, expected, actual = result.full_commit(self.plan)
        if not full_commit:
            self.logger.debug("attempted %d placements, %d placed", expected, actual)
            raise RuntimeError("missing state refresh after partial commit")
        return True

    # -- reconciliation ----------------------------------------------------

    def _filter_complete_allocs(
        self, allocs: List[s.Allocation]
    ) -> Tuple[List[s.Allocation], Dict[str, s.Allocation]]:
        """(generic_sched.go:283): batch keeps successfully-finished allocs
        and dedupes re-placed names to the newest incarnation."""

        def should_filter(a: s.Allocation) -> bool:
            if self.batch:
                if a.desired_status in (s.ALLOC_DESIRED_STATUS_STOP,
                                        s.ALLOC_DESIRED_STATUS_EVICT):
                    return not a.ran_successfully()
                return a.client_status == s.ALLOC_CLIENT_STATUS_FAILED
            return a.terminal_status()

        terminal: Dict[str, s.Allocation] = {}
        live: List[s.Allocation] = []
        for a in allocs:
            if should_filter(a):
                prev = terminal.get(a.name)
                if prev is None or prev.create_index < a.create_index:
                    terminal[a.name] = a
            else:
                live.append(a)

        if self.batch:
            by_name: Dict[str, s.Allocation] = {}
            for a in live:
                prev = by_name.get(a.name)
                if prev is None or prev.create_index < a.create_index:
                    by_name[a.name] = a
            live = list(by_name.values())
        return live, terminal

    def _compute_job_allocs(self) -> None:
        """(generic_sched.go:350)."""
        groups: Dict[str, s.TaskGroup] = {}
        if self.job is not None and not self.job.stopped():
            groups = materialize_task_groups(self.job)

        allocs = self.state.allocs_by_job(None, self.eval.job_id, True)
        tainted = tainted_nodes(self.state, allocs)
        update_non_terminal_allocs_to_lost(self.plan, tainted, allocs)
        allocs, terminal_allocs = self._filter_complete_allocs(allocs)

        diff = diff_allocs(self.job, tainted, groups, allocs, terminal_allocs)
        self.logger.debug("eval %s job %s: %s", self.eval.id, self.eval.job_id, diff)

        for e in diff.stop:
            self.plan.append_update(e.alloc, s.ALLOC_DESIRED_STATUS_STOP, ALLOC_NOT_NEEDED)

        destructive, inplace = inplace_update(self.ctx, self.eval, self.job,
                                              self.stack, diff.update)
        diff.update = destructive

        if self.eval.annotate_plan:
            self.plan.annotations = s.PlanAnnotations(
                desired_tg_updates=desired_updates(diff, inplace, destructive))

        limit_box = [len(diff.update) + len(diff.migrate) + len(diff.lost)]
        if self.job is not None and not self.job.stopped() and self.job.update.rolling():
            limit_box[0] = self.job.update.max_parallel

        self.limit_reached = evict_and_place(
            self.ctx, diff, diff.migrate, ALLOC_MIGRATING, limit_box)
        self.limit_reached = self.limit_reached or evict_and_place(
            self.ctx, diff, diff.update, ALLOC_UPDATING, limit_box)
        self.limit_reached = self.limit_reached or mark_lost_and_place(
            self.ctx, diff, diff.lost, ALLOC_LOST, limit_box)

        if not diff.place:
            if self.job is not None and not self.job.stopped():
                for tg in self.job.task_groups:
                    self.queued_allocs[tg.name] = 0
            return

        for tup in diff.place:
            self.queued_allocs[tup.task_group.name] = (
                self.queued_allocs.get(tup.task_group.name, 0) + 1)

        self._compute_placements(diff.place)

    def _compute_placements(self, place: List[AllocTuple]) -> None:
        """The inner hot loop (generic_sched.go:434) — on TPU this whole
        loop is one batched kernel invocation."""
        nodes, by_dc = ready_nodes_in_dcs(self.state, self.job.datacenters)
        self.stack.set_nodes(nodes)

        for missing in place:
            existing_metric = (self.failed_tg_allocs or {}).get(missing.task_group.name)
            if existing_metric is not None:
                existing_metric.coalesced_failures += 1
                continue

            preferred = self._find_preferred_node(missing)
            if preferred is not None:
                option, _ = self.stack.select_preferring_nodes(
                    missing.task_group, [preferred])
            else:
                option, _ = self.stack.select(missing.task_group)

            self.ctx.metrics.nodes_available = by_dc

            if option is not None:
                alloc = s.Allocation(
                    id=s.generate_uuid(),
                    eval_id=self.eval.id,
                    namespace=self.job.namespace,
                    name=missing.name,
                    job_id=self.job.id,
                    task_group=missing.task_group.name,
                    metrics=self.ctx.metrics,
                    node_id=option.node.id,
                    task_resources=option.task_resources,
                    desired_status=s.ALLOC_DESIRED_STATUS_RUN,
                    client_status=s.ALLOC_CLIENT_STATUS_PENDING,
                    shared_resources=s.Resources(
                        disk_mb=missing.task_group.ephemeral_disk.size_mb),
                )
                if missing.alloc is not None:
                    alloc.previous_allocation = missing.alloc.id
                if option.preempted_allocs:
                    # Evictions the fit depends on commit with (and
                    # gate) the placement; clear the marker so a reused
                    # RankedNode cannot leak victims into later picks.
                    for victim in option.preempted_allocs:
                        self.plan.append_preempted_alloc(victim)
                    option.preempted_allocs = None
                self.plan.append_alloc(alloc)
            else:
                if self.failed_tg_allocs is None:
                    self.failed_tg_allocs = {}
                self.failed_tg_allocs[missing.task_group.name] = self.ctx.metrics

    def _find_preferred_node(self, missing: AllocTuple) -> Optional[s.Node]:
        """Sticky-disk allocs prefer their previous node
        (generic_sched.go:510)."""
        if missing.alloc is None or missing.alloc.job is None:
            return None
        tg = missing.alloc.job.lookup_task_group(missing.alloc.task_group)
        if tg is None or not tg.ephemeral_disk.sticky:
            return None
        node = self.state.node_by_id(None, missing.alloc.node_id)
        if node is not None and node.ready():
            return node
        return None


def new_service_scheduler(logger, state, planner) -> GenericScheduler:
    return GenericScheduler(logger, state, planner, batch=False)


def new_batch_scheduler(logger, state, planner) -> GenericScheduler:
    return GenericScheduler(logger, state, planner, batch=True)
