"""Reconciliation utilities: alloc diffing, tainted-node classification,
in-place updates, rolling-limit eviction (reference: scheduler/util.go)."""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Set, Tuple

from ..structs import structs as s

# Desired-status descriptions (generic_sched.go:20-36).
ALLOC_NOT_NEEDED = "alloc not needed due to job update"
ALLOC_MIGRATING = "alloc is being migrated"
ALLOC_UPDATING = "alloc is being updated due to job update"
ALLOC_LOST = "alloc is lost since its node is down"
ALLOC_IN_PLACE = "alloc updating in-place"


@dataclass
class AllocTuple:
    """(name, task group, existing alloc) placement work item
    (util.go:14)."""

    name: str
    task_group: Optional[s.TaskGroup]
    alloc: Optional[s.Allocation]


@dataclass
class DiffResult:
    """The six reconciliation sets (util.go:38)."""

    place: List[AllocTuple] = field(default_factory=list)
    update: List[AllocTuple] = field(default_factory=list)
    migrate: List[AllocTuple] = field(default_factory=list)
    stop: List[AllocTuple] = field(default_factory=list)
    ignore: List[AllocTuple] = field(default_factory=list)
    lost: List[AllocTuple] = field(default_factory=list)

    def append(self, other: "DiffResult") -> None:
        self.place.extend(other.place)
        self.update.extend(other.update)
        self.migrate.extend(other.migrate)
        self.stop.extend(other.stop)
        self.ignore.extend(other.ignore)
        self.lost.extend(other.lost)

    def __str__(self) -> str:
        return (f"allocs: (place {len(self.place)}) (update {len(self.update)}) "
                f"(migrate {len(self.migrate)}) (stop {len(self.stop)}) "
                f"(ignore {len(self.ignore)}) (lost {len(self.lost)})")


def materialize_task_groups(job: Optional[s.Job]) -> Dict[str, s.TaskGroup]:
    """Count expansion → '<job>.<tg>[i]' names (util.go:22)."""
    out: Dict[str, s.TaskGroup] = {}
    if job is None or job.stopped():
        return out
    for tg in job.task_groups:
        for i in range(tg.count):
            out[f"{job.name}.{tg.name}[{i}]"] = tg
    return out


def diff_allocs(
    job: Optional[s.Job],
    tainted_nodes: Dict[str, Optional[s.Node]],
    required: Dict[str, s.TaskGroup],
    allocs: List[s.Allocation],
    terminal_allocs: Dict[str, s.Allocation],
) -> DiffResult:
    """Set-difference between required and existing allocations
    (util.go:70-160)."""
    result = DiffResult()
    existing: Set[str] = set()
    for exist in allocs:
        name = exist.name
        existing.add(name)
        tg = required.get(name)
        if tg is None:
            result.stop.append(AllocTuple(name, tg, exist))
            continue

        if exist.node_id in tainted_nodes:
            # Successfully finished batch work needn't move off a tainted
            # node — ignored outright (util.go:97-105 goto IGNORE).
            if (exist.job is not None and exist.job.type == s.JOB_TYPE_BATCH
                    and exist.ran_successfully()):
                result.ignore.append(AllocTuple(name, tg, exist))
                continue
            node = tainted_nodes[exist.node_id]
            if node is None or node.terminal_status():
                result.lost.append(AllocTuple(name, tg, exist))
            else:
                result.migrate.append(AllocTuple(name, tg, exist))
            continue
        if (exist.job is not None and job is not None
                and job.job_modify_index != exist.job.job_modify_index):
            result.update.append(AllocTuple(name, tg, exist))
            continue
        result.ignore.append(AllocTuple(name, tg, exist))

    for name, tg in required.items():
        if name not in existing:
            result.place.append(AllocTuple(name, tg, terminal_allocs.get(name)))
    return result


def diff_system_allocs(
    job: s.Job,
    nodes: List[s.Node],
    tainted_nodes: Dict[str, Optional[s.Node]],
    allocs: List[s.Allocation],
    terminal_allocs: Dict[str, s.Allocation],
) -> DiffResult:
    """Per-node diff for system jobs; placements are node-annotated
    (util.go:171-220)."""
    node_allocs: Dict[str, List[s.Allocation]] = {}
    for alloc in allocs:
        node_allocs.setdefault(alloc.node_id, []).append(alloc)
    for node in nodes:
        node_allocs.setdefault(node.id, [])

    required = materialize_task_groups(job)
    result = DiffResult()
    for node_id, nallocs in node_allocs.items():
        diff = diff_allocs(job, tainted_nodes, required, nallocs, terminal_allocs)
        if node_id in tainted_nodes:
            diff.place = []
        else:
            for tup in diff.place:
                if tup.alloc is None or tup.alloc.node_id != node_id:
                    tup.alloc = s.Allocation(node_id=node_id)
        # A tainted node invalidates system allocs outright: stop, not
        # migrate (util.go:211-214).
        diff.stop.extend(diff.migrate)
        diff.migrate = []
        result.append(diff)
    return result


def ready_nodes_in_dcs(state, dcs: List[str]) -> Tuple[List[s.Node], Dict[str, int]]:
    """Ready, undrained nodes in the job's datacenters + per-DC counts
    (util.go:224).

    Memoized per store/snapshot (invalidated by node writes via
    StateStore._bump): the stale-snapshot worker pool schedules many
    evals off one snapshot, and this walk was the second-largest
    per-eval cost in the load-harness profile.  Callers receive a fresh
    list (stacks shuffle it in place)."""
    cache = getattr(state, "_ready_nodes_cache", None)
    key = tuple(dcs)
    if cache is not None:
        hit = cache.get(key)
        if hit is not None:
            return list(hit[0]), dict(hit[1])
    dc_map = {dc: 0 for dc in dcs}
    out: List[s.Node] = []
    for node in state.nodes(None):
        if node.status != s.NODE_STATUS_READY or node.drain:
            continue
        if node.datacenter not in dc_map:
            continue
        out.append(node)
        dc_map[node.datacenter] += 1
    try:
        if cache is None:
            cache = state._ready_nodes_cache = {}
        cache[key] = (out, dc_map)
    except AttributeError:
        return out, dc_map  # slot-restricted store: serve uncached
    return list(out), dict(dc_map)


class SetStatusError(Exception):
    """Carries the eval status to set when retries are exhausted
    (generic_sched.go:47)."""

    def __init__(self, message: str, eval_status: str):
        super().__init__(message)
        self.eval_status = eval_status


def retry_max(max_attempts: int, cb, reset=None,
              max_total: Optional[int] = None) -> None:
    """Retry cb until done, resetting the budget when progress is made
    (util.go:262).

    ``max_total`` caps TOTAL attempts regardless of progress resets: a
    plan that keeps getting partially committed (e.g. staleness fences
    rejecting a few nodes every round under churn) makes "progress" each
    time and would otherwise resubmit forever — a plan-resubmission
    storm against the single-threaded applier.  Defaults to
    ``8 × max_attempts``; the eval fails (→ blocked, retried later)
    rather than hammering the plan queue."""
    if max_total is None:
        max_total = max_attempts * 8
    attempts = 0
    total = 0
    while attempts < max_attempts and total < max_total:
        done = cb()
        total += 1
        if done:
            return
        if reset is not None and reset():
            attempts = 0
        else:
            attempts += 1
    raise SetStatusError(
        f"maximum attempts reached ({max_attempts}/{total} total)",
        s.EVAL_STATUS_FAILED)


def progress_made(result: Optional[s.PlanResult]) -> bool:
    """(util.go:291)."""
    return result is not None and (bool(result.node_update)
                                   or bool(result.node_allocation)
                                   or bool(result.alloc_slabs))


def tainted_nodes(state, allocs: List[s.Allocation]) -> Dict[str, Optional[s.Node]]:
    """Nodes (of the given allocs) that are down, draining, or gone
    (util.go:299)."""
    out: Dict[str, Optional[s.Node]] = {}
    for alloc in allocs:
        if alloc.node_id in out:
            continue
        node = state.node_by_id(None, alloc.node_id)
        if node is None:
            out[alloc.node_id] = None
            continue
        if node.status == s.NODE_STATUS_DOWN or node.drain:
            out[alloc.node_id] = node
    return out


def tasks_updated(job_a: s.Job, job_b: s.Job, task_group: str) -> bool:
    """Whether the TG change is destructive (driver/config/env/artifacts/
    vault/templates/meta/network/resources) vs in-place (util.go:336)."""
    a = job_a.lookup_task_group(task_group)
    b = job_b.lookup_task_group(task_group)
    if a is None or b is None:
        return True
    if len(a.tasks) != len(b.tasks):
        return True
    if a.ephemeral_disk != b.ephemeral_disk:
        return True
    for at in a.tasks:
        bt = b.lookup_task(at.name)
        if bt is None:
            return True
        if at.driver != bt.driver or at.user != bt.user:
            return True
        if at.config != bt.config or at.env != bt.env:
            return True
        if at.artifacts != bt.artifacts or at.vault != bt.vault:
            return True
        if at.templates != bt.templates:
            return True
        if _combined_meta(job_a, task_group, at.name) != _combined_meta(job_b, task_group, bt.name):
            return True
        if len(at.resources.networks) != len(bt.resources.networks):
            return True
        for an, bn in zip(at.resources.networks, bt.resources.networks):
            if an.mbits != bn.mbits:
                return True
            if _network_port_map(an) != _network_port_map(bn):
                return True
        ar, br = at.resources, bt.resources
        if ar.cpu != br.cpu or ar.memory_mb != br.memory_mb or ar.iops != br.iops:
            return True
    return False


def _combined_meta(job: s.Job, tg_name: str, task_name: str) -> Dict[str, str]:
    """Job < TG < task meta layering (structs.go CombinedTaskMeta)."""
    meta = dict(job.meta)
    tg = job.lookup_task_group(tg_name)
    if tg is not None:
        meta.update(tg.meta)
        task = tg.lookup_task(task_name)
        if task is not None:
            meta.update(task.meta)
    return meta


def _network_port_map(n: s.NetworkResource) -> Dict[str, int]:
    """Port labels → values, dynamic values disregarded (util.go:417)."""
    out = {p.label: p.value for p in n.reserved_ports}
    for p in n.dynamic_ports:
        out[p.label] = -1
    return out


def set_status(
    logger,
    planner,
    ev: s.Evaluation,
    next_eval: Optional[s.Evaluation],
    spawned_blocked: Optional[s.Evaluation],
    tg_metrics: Optional[Dict[str, s.AllocMetric]],
    status: str,
    description: str,
    queued_allocs: Optional[Dict[str, int]],
) -> None:
    """Update the eval's status via the planner (util.go:430)."""
    new_eval = ev.copy()
    new_eval.status = status
    new_eval.status_description = description
    new_eval.failed_tg_allocs = tg_metrics or {}
    if next_eval is not None:
        new_eval.next_eval = next_eval.id
    if spawned_blocked is not None:
        new_eval.blocked_eval = spawned_blocked.id
    if queued_allocs is not None:
        new_eval.queued_allocations = queued_allocs
    planner.update_eval(new_eval)


def inplace_update(
    ctx, ev: s.Evaluation, job: s.Job, stack, updates: List[AllocTuple]
) -> Tuple[List[AllocTuple], List[AllocTuple]]:
    """Attempt in-place updates; returns (destructive, inplace)
    (util.go:455-551).  Works by staging an eviction of the current alloc,
    running Select against only its node, then popping the staged evict."""
    destructive: List[AllocTuple] = []
    inplace: List[AllocTuple] = []
    for update in updates:
        existing_job = update.alloc.job
        if existing_job is None or tasks_updated(job, existing_job, update.task_group.name):
            destructive.append(update)
            continue

        # Successfully-finished terminal batch allocs: in-place with no plan
        # entry at all (util.go:481-488).
        if update.alloc.terminal_status():
            inplace.append(update)
            continue

        node = ctx.state.node_by_id(None, update.alloc.node_id)
        if node is None:
            destructive.append(update)
            continue

        stack.set_nodes([node])
        ctx.plan.append_update(update.alloc, s.ALLOC_DESIRED_STATUS_STOP, ALLOC_IN_PLACE)
        option, _ = stack.select(update.task_group)
        ctx.plan.pop_update(update.alloc)

        if option is None:
            destructive.append(update)
            continue

        # Network resources are never updated in place; restore the existing
        # offers (util.go:520-528).
        for task_name, resources in option.task_resources.items():
            existing_res = update.alloc.task_resources.get(task_name)
            if existing_res is not None:
                resources.networks = existing_res.networks

        new_alloc = update.alloc.copy()
        new_alloc.eval_id = ev.id
        new_alloc.job = None  # plan carries the job
        new_alloc.resources = None  # recomputed at plan apply
        new_alloc.task_resources = option.task_resources
        new_alloc.metrics = ctx.metrics
        ctx.plan.append_alloc(new_alloc)
        inplace.append(update)
    return destructive, inplace


def evict_and_place(
    ctx, diff: DiffResult, allocs: List[AllocTuple], desc: str, limit_box: List[int]
) -> bool:
    """Evict up to the rolling limit, queueing replacements; True if the
    limit was hit (util.go:556)."""
    n = len(allocs)
    limit = limit_box[0]
    for i in range(min(n, limit)):
        a = allocs[i]
        ctx.plan.append_update(a.alloc, s.ALLOC_DESIRED_STATUS_STOP, desc)
        diff.place.append(a)
    if n <= limit:
        limit_box[0] = limit - n
        return False
    limit_box[0] = 0
    return True


def mark_lost_and_place(
    ctx, diff: DiffResult, allocs: List[AllocTuple], desc: str, limit_box: List[int]
) -> bool:
    """Like evict_and_place but also forces client status lost
    (util.go:574)."""
    n = len(allocs)
    limit = limit_box[0]
    for i in range(min(n, limit)):
        a = allocs[i]
        ctx.plan.append_update(
            a.alloc, s.ALLOC_DESIRED_STATUS_STOP, desc, s.ALLOC_CLIENT_STATUS_LOST)
        diff.place.append(a)
    if n <= limit:
        limit_box[0] = limit - n
        return False
    limit_box[0] = 0
    return True


@dataclass
class TGConstraintTuple:
    """Aggregated constraints/drivers/resources of a TG (util.go:590)."""

    constraints: List[s.Constraint]
    drivers: Set[str]
    size: s.Resources


def task_group_constraints(tg: s.TaskGroup) -> TGConstraintTuple:
    """(util.go:606)."""
    size = s.Resources(disk_mb=tg.ephemeral_disk.size_mb)
    constraints = list(tg.constraints)
    drivers: Set[str] = set()
    for task in tg.tasks:
        drivers.add(task.driver)
        constraints.extend(task.constraints)
        size.add(task.resources)
    return TGConstraintTuple(constraints, drivers, size)


def desired_updates(
    diff: DiffResult,
    inplace_updates: List[AllocTuple],
    destructive_updates: List[AllocTuple],
) -> Dict[str, s.DesiredUpdates]:
    """Plan annotations per TG (util.go:625)."""
    out: Dict[str, s.DesiredUpdates] = {}

    def get(name: str) -> s.DesiredUpdates:
        return out.setdefault(name, s.DesiredUpdates())

    for tup in diff.place:
        get(tup.task_group.name).place += 1
    for tup in diff.stop:
        get(tup.alloc.task_group).stop += 1
    for tup in diff.ignore:
        get(tup.task_group.name).ignore += 1
    for tup in diff.migrate:
        get(tup.task_group.name).migrate += 1
    for tup in inplace_updates:
        get(tup.task_group.name).in_place_update += 1
    for tup in destructive_updates:
        get(tup.task_group.name).destructive_update += 1
    return out


def adjust_queued_allocations(
    logger, result: Optional[s.PlanResult], queued_allocs: Dict[str, int]
) -> None:
    """Decrement queued counts for freshly created allocs (util.go:698)."""
    if result is None:
        return
    for allocations in result.node_allocation.values():
        for allocation in allocations:
            if allocation.create_index != allocation.modify_index:
                continue
            if allocation.task_group in queued_allocs:
                queued_allocs[allocation.task_group] -= 1
            else:
                logger.error(
                    "allocation %r placed but not in list of unplaced allocations",
                    allocation.task_group)
    for slab in result.alloc_slabs:
        if slab.create_index != slab.modify_index:
            continue
        tg = slab.proto.task_group
        if tg in queued_allocs:
            queued_allocs[tg] -= len(slab)
        else:
            logger.error(
                "allocation %r placed but not in list of unplaced allocations",
                tg)


def update_non_terminal_allocs_to_lost(
    plan: s.Plan, tainted: Dict[str, Optional[s.Node]], allocs: List[s.Allocation]
) -> None:
    """Stopped-but-still-running allocs on tainted nodes become lost
    (util.go:725)."""
    for alloc in allocs:
        if (alloc.node_id in tainted
                and alloc.desired_status == s.ALLOC_DESIRED_STATUS_STOP
                and alloc.client_status in (s.ALLOC_CLIENT_STATUS_RUNNING,
                                            s.ALLOC_CLIENT_STATUS_PENDING)):
            plan.append_update(alloc, s.ALLOC_DESIRED_STATUS_STOP, ALLOC_LOST,
                               s.ALLOC_CLIENT_STATUS_LOST)
