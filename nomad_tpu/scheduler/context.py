"""Evaluation context: plan-aware state view, caches, and the computed-class
eligibility lattice (reference: scheduler/context.go)."""
from __future__ import annotations

import logging
import random
import re
from enum import IntEnum
from typing import Dict, List, Optional

from ..structs import structs as s
from ..structs.funcs import remove_allocs
from ..structs.node_class import escaped_constraints
from ..utils import version as goversion

# Shared seed source for per-eval PRNGs (EvalContext.rng): seeded once
# from the OS, then each eval draws 64 bits instead of paying its own
# urandom read.
_SEED_SOURCE = random.Random()


class EvalCache:
    """Regex + version-constraint caches, matching the per-eval caches in
    context.go:46-62."""

    def __init__(self) -> None:
        self.re_cache: Dict[str, Optional[re.Pattern]] = {}
        self.constraint_cache: Dict[str, Optional[goversion.Constraints]] = {}


class EvalContext:
    """Tracks contextual info for one evaluation (context.go:66-149)."""

    def __init__(
        self,
        state,
        plan: s.Plan,
        logger: Optional[logging.Logger] = None,
        rng: Optional[random.Random] = None,
    ) -> None:
        self.state = state
        self.plan = plan
        self.logger = logger or logging.getLogger("nomad_tpu.scheduler")
        self.metrics = s.AllocMetric()
        self.cache = EvalCache()
        self._eligibility: Optional[EvalEligibility] = None
        # Per-eval PRNG ≙ the reference's global math/rand; seedable for
        # deterministic differential tests.  Constructed lazily: seeding
        # from os.urandom costs ~130µs and the TPU batch path never
        # touches it (measured at 0.13s per 1k-eval batch).
        self._rng = rng

    @property
    def rng(self) -> random.Random:
        if self._rng is None:
            # Seed from the module PRNG, not the OS: an unseeded
            # Random() reads urandom (~50µs), once per eval on the
            # oracle hot path.  getrandbits on the shared source is one
            # C call (GIL-atomic), and determinism is unchanged — the
            # unseeded path was never reproducible.
            self._rng = random.Random(_SEED_SOURCE.getrandbits(64))
        return self._rng

    @rng.setter
    def rng(self, value) -> None:
        self._rng = value

    def reset(self) -> None:
        """Invoked after each placement (context.go:107)."""
        self.metrics = s.AllocMetric()

    def proposed_allocs(self, node_id: str) -> List[s.Allocation]:
        """Existing non-terminal allocs − planned evictions + planned
        placements, deduped by alloc ID (context.go:109)."""
        existing = self.state.allocs_by_node_terminal(None, node_id, False)
        proposed = existing
        update = (self.plan.node_update.get(node_id, [])
                  + self.plan.node_preemptions.get(node_id, []))
        if update:
            proposed = remove_allocs(existing, update)
        by_id = {a.id: a for a in proposed}
        for alloc in self.plan.node_allocation.get(node_id, []):
            by_id[alloc.id] = alloc
        return list(by_id.values())

    def eligibility(self) -> "EvalEligibility":
        if self._eligibility is None:
            self._eligibility = EvalEligibility()
        return self._eligibility


class ComputedClassFeasibility(IntEnum):
    """4-state eligibility lattice (context.go:151-170)."""

    UNKNOWN = 0
    INELIGIBLE = 1
    ELIGIBLE = 2
    ESCAPED = 3


class EvalEligibility:
    """Per-eval cache of node-class eligibility at job and task-group level
    (context.go:174-331).  This is the reference's key scalability
    optimization and the contract the TPU class-dedup kernel must honor."""

    def __init__(self) -> None:
        self.job: Dict[str, ComputedClassFeasibility] = {}
        self.job_escaped = False
        self.task_groups: Dict[str, Dict[str, ComputedClassFeasibility]] = {}
        self.tg_escaped: Dict[str, bool] = {}

    def set_job(self, job: s.Job) -> None:
        self.job_escaped = bool(escaped_constraints(job.constraints))
        for tg in job.task_groups:
            constraints = list(tg.constraints)
            for task in tg.tasks:
                constraints.extend(task.constraints)
            self.tg_escaped[tg.name] = bool(escaped_constraints(constraints))

    def has_escaped(self) -> bool:
        return self.job_escaped or any(self.tg_escaped.values())

    def get_classes(self) -> Dict[str, bool]:
        """Class → eligible map fed into blocked evals (context.go:231)."""
        elig: Dict[str, bool] = {}
        for klass, feas in self.job.items():
            if feas == ComputedClassFeasibility.ELIGIBLE:
                elig[klass] = True
            elif feas == ComputedClassFeasibility.INELIGIBLE:
                elig[klass] = False
        for classes in self.task_groups.values():
            for klass, feas in classes.items():
                if feas == ComputedClassFeasibility.ELIGIBLE:
                    elig[klass] = True
                elif feas == ComputedClassFeasibility.INELIGIBLE:
                    # Don't overwrite an eligibility granted by another TG.
                    elig.setdefault(klass, False)
        return elig

    def job_status(self, klass: str) -> ComputedClassFeasibility:
        if self.job_escaped or not klass:
            return ComputedClassFeasibility.ESCAPED
        return self.job.get(klass, ComputedClassFeasibility.UNKNOWN)

    def set_job_eligibility(self, eligible: bool, klass: str) -> None:
        self.job[klass] = (
            ComputedClassFeasibility.ELIGIBLE if eligible else ComputedClassFeasibility.INELIGIBLE
        )

    def task_group_status(self, tg: str, klass: str) -> ComputedClassFeasibility:
        if not klass:
            return ComputedClassFeasibility.ESCAPED
        if self.tg_escaped.get(tg, False):
            return ComputedClassFeasibility.ESCAPED
        return self.task_groups.get(tg, {}).get(klass, ComputedClassFeasibility.UNKNOWN)

    def set_task_group_eligibility(self, eligible: bool, tg: str, klass: str) -> None:
        value = (
            ComputedClassFeasibility.ELIGIBLE if eligible else ComputedClassFeasibility.INELIGIBLE
        )
        self.task_groups.setdefault(tg, {})[klass] = value
