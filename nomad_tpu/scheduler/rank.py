"""Ranking iterators: bin-pack scoring and job anti-affinity
(reference: scheduler/rank.go).

The TPU analogue computes ``S[tg, node] = score_fit(free_after) −
penalty·collisions`` for the full matrix at once (nomad_tpu/ops/scoring.py);
this module is the per-node oracle.
"""
from __future__ import annotations

from typing import Dict, List, Optional

from ..structs import structs as s
from ..structs.funcs import allocs_fit, remove_allocs, score_fit
from ..structs.network import NetworkIndex
from . import preempt
from .context import EvalContext


class RankedNode:
    """A node plus its accumulated score and per-task resources
    (rank.go:12-45)."""

    __slots__ = ("node", "score", "task_resources", "proposed",
                 "preempted_allocs")

    def __init__(self, node: s.Node):
        self.node = node
        self.score = 0.0
        self.task_resources: Dict[str, s.Resources] = {}
        self.proposed: Optional[List[s.Allocation]] = None
        # Lower-priority allocs whose eviction this option depends on
        # (preempt.py); staged into Plan.node_preemptions on selection.
        self.preempted_allocs: Optional[List[s.Allocation]] = None

    def __repr__(self) -> str:
        return f"<Node: {self.node.id} Score: {self.score:.3f}>"

    def proposed_allocs(self, ctx: EvalContext) -> List[s.Allocation]:
        if self.proposed is None:
            self.proposed = ctx.proposed_allocs(self.node.id)
        return self.proposed

    def set_task_resources(self, task: s.Task, resources: s.Resources) -> None:
        self.task_resources[task.name] = resources


class FeasibleRankIterator:
    """Upgrades a feasible iterator into the ranking chain (rank.go:60)."""

    def __init__(self, ctx: EvalContext, source):
        self.ctx = ctx
        self.source = source

    def next_option(self) -> Optional[RankedNode]:
        option = self.source.next_option()
        if option is None:
            return None
        return RankedNode(option)

    def reset(self) -> None:
        self.source.reset()


class StaticRankIterator:
    """Yields a fixed list of ranked nodes; used in tests (rank.go:91)."""

    def __init__(self, ctx: EvalContext, nodes: List[RankedNode]):
        self.ctx = ctx
        self.nodes = nodes
        self.offset = 0
        self.seen = 0

    def next_option(self) -> Optional[RankedNode]:
        n = len(self.nodes)
        if self.offset == n or self.seen == n:
            if self.seen != n:
                self.offset = 0
            else:
                return None
        option = self.nodes[self.offset]
        self.offset += 1
        self.seen += 1
        return option

    def reset(self) -> None:
        self.seen = 0


class BinPackIterator:
    """Scores nodes by best-fit bin packing after assigning task networks
    (rank.go:130-240)."""

    def __init__(self, ctx: EvalContext, source, evict: bool, priority: int,
                 preemption_enabled: bool = False):
        self.ctx = ctx
        self.source = source
        # evict + priority gate the preemption path: a node that cannot
        # fit the task group may still rank if evicting strictly-lower-
        # priority allocs makes room (preempt.py).  preemption_enabled
        # is the operator switch; with it off, evict is recorded but
        # inert — the reference ships the same dormant flag.
        self.evict = evict
        self.priority = priority
        self.preemption_enabled = preemption_enabled
        # Set by the stack for its SECOND select pass only (no node fits
        # without eviction).  Preempting options must never compete with
        # normally-fitting nodes inside the LimitIterator's small sample
        # — a full-but-preemptible node would consume a candidate slot
        # and could win on score while free capacity exists elsewhere.
        self.allow_preempt = False
        self.task_group: Optional[s.TaskGroup] = None

    def set_priority(self, priority: int) -> None:
        self.priority = priority

    def set_task_group(self, tg: s.TaskGroup) -> None:
        self.task_group = tg

    def next_option(self) -> Optional[RankedNode]:
        while True:
            option = self.source.next_option()
            if option is None:
                return None

            proposed = option.proposed_allocs(self.ctx)

            net_idx = NetworkIndex()
            net_idx.set_node(option.node)
            net_idx.add_allocs(proposed)

            total = s.Resources(disk_mb=self.task_group.ephemeral_disk.size_mb)
            network_ok = True
            for task in self.task_group.tasks:
                task_resources = task.resources.copy()
                if task_resources.networks:
                    ask = task_resources.networks[0]
                    offer, err = net_idx.assign_network(ask, self.ctx.rng)
                    if offer is None:
                        self.ctx.metrics.exhausted_node(option.node, f"network: {err}")
                        network_ok = False
                        break
                    net_idx.add_reserved(offer)
                    task_resources.networks = [offer]
                option.set_task_resources(task, task_resources)
                total.add(task_resources)
            if not network_ok:
                continue

            probe = s.Allocation(id="_binpack_probe", resources=total)
            candidate = proposed + [probe]
            fit, dim, util = allocs_fit(option.node, candidate, net_idx)
            if not fit:
                if (self.allow_preempt and self.evict
                        and self.preemption_enabled and self.priority > 0
                        and self._try_preempt(option, proposed, probe,
                                              total)):
                    return option
                if not self.allow_preempt:
                    # The preempt pass re-walks nodes the first pass
                    # already attributed; don't double-count exhaustion.
                    self.ctx.metrics.exhausted_node(option.node, dim)
                continue

            fitness = score_fit(option.node, util)
            option.score += fitness
            self.ctx.metrics.score_node(option.node, "binpack", fitness)
            return option

    def _try_preempt(self, option: RankedNode,
                     proposed: List[s.Allocation], probe: s.Allocation,
                     total: s.Resources) -> bool:
        """Rank the node anyway if evicting strictly-lower-priority
        allocs makes the task group fit (preempt.py oracle).  The score
        carries a discount so any node that fits WITHOUT eviction
        outranks a preempting one; ties among preempting nodes prefer
        the smaller eviction set."""
        state = self.ctx.state

        def prio_of(a: s.Allocation) -> int:
            return preempt.alloc_priority(a, state)

        victims = preempt.find_eviction_set(
            option.node, proposed, total, self.priority, prio_of)
        if not victims:
            return False
        survivors = remove_allocs(proposed, victims)
        # Full re-check over the survivors with a rebuilt NetworkIndex:
        # the scalar-dimension oracle freed enough cpu/mem/disk/iops,
        # but ports/bandwidth held by non-evicted allocs still bind.
        net_idx = NetworkIndex()
        net_idx.set_node(option.node)
        net_idx.add_allocs(survivors)
        for res in option.task_resources.values():
            for offer in res.networks or []:
                net_idx.add_reserved(offer)
        fit, _, util = allocs_fit(option.node, survivors + [probe], net_idx)
        if not fit:
            return False
        fitness = score_fit(option.node, util)
        penalty = preempt.preemption_score_penalty(len(victims))
        option.score += fitness - penalty
        option.preempted_allocs = victims
        self.ctx.metrics.score_node(option.node, "binpack", fitness)
        self.ctx.metrics.score_node(option.node, "preemption", -penalty)
        return True

    def reset(self) -> None:
        self.source.reset()


class JobAntiAffinityIterator:
    """Penalizes nodes already running allocs of this job (rank.go:247-306)."""

    def __init__(self, ctx: EvalContext, source, penalty: float, job_id: str):
        self.ctx = ctx
        self.source = source
        self.penalty = penalty
        self.job_id = job_id

    def set_job(self, job_id: str) -> None:
        self.job_id = job_id

    def next_option(self) -> Optional[RankedNode]:
        option = self.source.next_option()
        if option is None:
            return None
        proposed = option.proposed_allocs(self.ctx)
        collisions = sum(1 for alloc in proposed if alloc.job_id == self.job_id)
        if collisions > 0:
            penalty = -1.0 * collisions * self.penalty
            option.score += penalty
            self.ctx.metrics.score_node(option.node, "job-anti-affinity", penalty)
        return option

    def reset(self) -> None:
        self.source.reset()
