"""Node fingerprinting: detect host facts and publish them as node
attributes/resources before registration
(reference: client/fingerprint/fingerprint.go:28-100 + per-fact files).

Registry order matters like the reference's ``BuiltinFingerprints``
ordered list: later fingerprints may read attributes set by earlier ones.
Each fingerprint returns whether it applied; periodic ones re-run on an
interval (fingerprint.go:67-100).

TPU note: a ``tpu`` fingerprint publishes accelerator facts
(``attr.tpu.*``) from jax.devices() when a TPU is attached — the node
attributes a TPU-aware job would constrain on.  It degrades to absent
on CPU-only hosts and never imports jax unless enabled.
"""
from __future__ import annotations

import multiprocessing
import os
import platform
import shutil
import socket
import time
from typing import Callable, Dict, List, Optional, Tuple

from ..structs import structs as s

FingerprintFn = Callable[["object", s.Node], bool]


class Fingerprint:
    name = ""

    def fingerprint(self, config, node: s.Node) -> bool:
        raise NotImplementedError

    def periodic(self) -> Tuple[bool, float]:
        return (False, 0.0)


class ArchFingerprint(Fingerprint):
    """(fingerprint/arch.go)."""

    name = "arch"

    def fingerprint(self, config, node: s.Node) -> bool:
        node.attributes["cpu.arch"] = platform.machine()
        return True


class CPUFingerprint(Fingerprint):
    """(fingerprint/cpu.go) — core count + total MHz → node resources."""

    name = "cpu"

    def fingerprint(self, config, node: s.Node) -> bool:
        cores = multiprocessing.cpu_count()
        mhz = self._clock_mhz()
        node.attributes["cpu.numcores"] = str(cores)
        node.attributes["cpu.frequency"] = f"{mhz:.0f}"
        total = int(cores * mhz)
        node.attributes["cpu.totalcompute"] = str(total)
        if node.resources is None:
            node.resources = s.Resources()
        if node.resources.cpu == 0:
            node.resources.cpu = total
        return True

    @staticmethod
    def _clock_mhz() -> float:
        try:
            with open("/proc/cpuinfo") as f:
                for line in f:
                    if line.lower().startswith("cpu mhz"):
                        return float(line.split(":")[1])
        except (OSError, ValueError, IndexError):
            pass
        return 1000.0


class MemoryFingerprint(Fingerprint):
    """(fingerprint/memory.go)."""

    name = "memory"

    def fingerprint(self, config, node: s.Node) -> bool:
        total_mb = self._total_mb()
        if total_mb <= 0:
            return False
        node.attributes["memory.totalbytes"] = str(total_mb * 1024 * 1024)
        if node.resources is None:
            node.resources = s.Resources()
        if node.resources.memory_mb == 0:
            node.resources.memory_mb = total_mb
        return True

    @staticmethod
    def _total_mb() -> int:
        try:
            with open("/proc/meminfo") as f:
                for line in f:
                    if line.startswith("MemTotal:"):
                        return int(line.split()[1]) // 1024
        except (OSError, ValueError, IndexError):
            pass
        try:
            return (os.sysconf("SC_PHYS_PAGES") * os.sysconf("SC_PAGE_SIZE")) // (1 << 20)
        except (ValueError, OSError):
            return 0


class HostFingerprint(Fingerprint):
    """(fingerprint/host.go) — os/kernel/hostname."""

    name = "host"

    def fingerprint(self, config, node: s.Node) -> bool:
        node.attributes["kernel.name"] = platform.system().lower()
        node.attributes["kernel.version"] = platform.release()
        node.attributes["os.name"] = platform.system().lower()
        node.attributes["os.version"] = platform.version()
        node.attributes["unique.hostname"] = socket.gethostname()
        return True


class NetworkFingerprint(Fingerprint):
    """(fingerprint/network.go) — primary IP + link speed → network
    resource."""

    name = "network"

    def fingerprint(self, config, node: s.Node) -> bool:
        ip = self._default_ip(getattr(config, "network_interface", "") or "")
        if not ip:
            return False
        node.attributes["unique.network.ip-address"] = ip
        if node.resources is None:
            node.resources = s.Resources()
        speed = getattr(config, "network_speed", 0) or 1000
        if not node.resources.networks:
            node.resources.networks = [
                s.NetworkResource(device="eth0", cidr=f"{ip}/32", ip=ip,
                                  mbits=speed)]
        return True

    @staticmethod
    def _default_ip(interface: str) -> str:
        if interface:
            # read the address of a named interface from /sys + a UDP probe
            pass
        try:
            with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as sk:
                sk.connect(("8.8.8.8", 80))
                return sk.getsockname()[0]
        except OSError:
            return "127.0.0.1"


class StorageFingerprint(Fingerprint):
    """(fingerprint/storage.go) — free disk on the alloc volume."""

    name = "storage"

    def fingerprint(self, config, node: s.Node) -> bool:
        path = getattr(config, "alloc_dir", "") or "/"
        try:
            usage = shutil.disk_usage(path if os.path.exists(path) else "/")
        except OSError:
            return False
        mb = usage.free // (1 << 20)
        node.attributes["unique.storage.volume"] = path
        node.attributes["unique.storage.bytesfree"] = str(usage.free)
        node.attributes["unique.storage.bytestotal"] = str(usage.total)
        if node.resources is None:
            node.resources = s.Resources()
        if node.resources.disk_mb == 0:
            node.resources.disk_mb = int(mb)
        return True


class NomadFingerprint(Fingerprint):
    """(fingerprint/nomad.go) — agent version attrs."""

    name = "nomad"

    def fingerprint(self, config, node: s.Node) -> bool:
        from ..utils.version import VERSION
        node.attributes["nomad.version"] = VERSION
        node.attributes["nomad.revision"] = "tpu"
        return True


class SignalFingerprint(Fingerprint):
    """(fingerprint/signal.go) — signals the drivers can deliver."""

    name = "signal"

    def fingerprint(self, config, node: s.Node) -> bool:
        node.attributes["os.signals"] = (
            "SIGABRT,SIGALRM,SIGBUS,SIGCHLD,SIGCONT,SIGFPE,SIGHUP,SIGILL,"
            "SIGINT,SIGIO,SIGKILL,SIGPIPE,SIGPROF,SIGQUIT,SIGSEGV,SIGSTOP,"
            "SIGSYS,SIGTERM,SIGTRAP,SIGTSTP,SIGTTIN,SIGTTOU,SIGURG,SIGUSR1,"
            "SIGUSR2,SIGWINCH,SIGXCPU,SIGXFSZ")
        return True


class TPUFingerprint(Fingerprint):
    """TPU-native addition: publish accelerator topology as node attrs so
    jobs can constrain on ``${attr.tpu.type}`` etc.  Gated behind the
    client option ``fingerprint.tpu.enable`` because importing jax is
    heavyweight."""

    name = "tpu"

    def fingerprint(self, config, node: s.Node) -> bool:
        options = getattr(config, "options", {}) or {}
        if str(options.get("fingerprint.tpu.enable", "")).lower() not in ("1", "true"):
            return False
        try:
            import jax

            from ..utils.platform import is_tpu_platform
            devs = [d for d in jax.devices() if is_tpu_platform(d.platform)]
        except Exception:
            return False
        if not devs:
            return False
        node.attributes["tpu.count"] = str(len(devs))
        node.attributes["tpu.type"] = getattr(devs[0], "device_kind", "tpu")
        node.attributes["driver.tpu"] = "1"
        return True


class EnvAWSFingerprint(Fingerprint):
    """(fingerprint/env_aws.go) — instance metadata; zero-egress here, so
    it applies only when the metadata answers instantly (it won't off
    EC2), exactly like the reference's 2s-timeout probe."""

    name = "env_aws"

    def fingerprint(self, config, node: s.Node) -> bool:
        try:
            sk = socket.create_connection(("169.254.169.254", 80), timeout=0.2)
            sk.close()
        except OSError:
            return False
        # GCE answers the same address: its replies carry
        # Metadata-Flavor: Google — that is NOT an EC2 metadata service.
        import urllib.request
        try:
            with urllib.request.urlopen("http://169.254.169.254/",
                                        timeout=0.2) as resp:
                if resp.headers.get("Metadata-Flavor") == "Google":
                    return False
        except OSError:
            pass  # EC2 IMDSv2 may refuse the bare request; still AWS-ish
        node.attributes["platform.aws.probed"] = "1"
        return True


class EnvGCEFingerprint(Fingerprint):
    """(fingerprint/env_gce.go) — GCE metadata; same zero-egress fast
    probe as env_aws (metadata.google.internal answers instantly on GCE,
    refuses instantly elsewhere)."""

    name = "env_gce"

    def fingerprint(self, config, node: s.Node) -> bool:
        try:
            sk = socket.create_connection(("169.254.169.254", 80),
                                          timeout=0.2)
            sk.close()
        except OSError:
            return False
        # Distinguish from AWS by the Metadata-Flavor header probe.
        import urllib.request
        try:
            req = urllib.request.Request(
                "http://169.254.169.254/computeMetadata/v1/",
                headers={"Metadata-Flavor": "Google"})
            with urllib.request.urlopen(req, timeout=0.2) as resp:
                if resp.headers.get("Metadata-Flavor") != "Google":
                    return False
        except OSError:
            return False
        node.attributes["platform.gce.probed"] = "1"
        return True


BUILTIN_FINGERPRINTS: List[Callable[[], Fingerprint]] = [
    ArchFingerprint,
    CPUFingerprint,
    MemoryFingerprint,
    HostFingerprint,
    NetworkFingerprint,
    NomadFingerprint,
    SignalFingerprint,
    StorageFingerprint,
    TPUFingerprint,
    EnvAWSFingerprint,
    EnvGCEFingerprint,
]


def fingerprint_node(config, node: s.Node) -> List[str]:
    """Run every builtin fingerprint; returns names that applied
    (reference: client.go:902 fingerprint())."""
    applied = []
    for factory in BUILTIN_FINGERPRINTS:
        fp = factory()
        try:
            if fp.fingerprint(config, node):
                applied.append(fp.name)
        except Exception:
            continue
    return applied
