"""TaskRunner: drives one task through its lifecycle — artifacts,
driver start, wait, restart policy, kill/signal/update — and reports
TaskState transitions up to the AllocRunner
(reference: client/task_runner.go:69-1737).

The run loop mirrors task_runner.go:517 Run: prestart (artifacts) →
driver start → wait for exit or control events → consult RestartTracker
→ delay → loop.  Event names and the dead/failed accounting match the
reference so `alloc-status` output is comparable.
"""
from __future__ import annotations

import logging
import os
import threading
import time
from typing import Callable, Dict, Optional

from ..structs import structs as s
from .allocdir import TaskDir
from .driver import env as envmod
from .driver.driver import (
    Driver,
    DriverContext,
    DriverError,
    DriverHandle,
    ExecContext,
    StartResponse,
    WaitResult,
    new_driver,
)
from .getter import ArtifactError, get_artifact
from .restarts import RestartTracker

# Update callback: (task_name, new_state, event) → None.  state may be ""
# (append event only, no transition) and event may be None (transition
# only), matching task_runner.go setState semantics.
StateUpdater = Callable[[str, str, Optional[s.TaskEvent]], None]


class TaskRunner:
    def __init__(self,
                 config,                    # client config
                 alloc: s.Allocation,
                 task: s.Task,
                 task_dir: TaskDir,
                 updater: StateUpdater,
                 node: Optional[s.Node] = None,
                 vault_token: str = "",
                 vault_client=None,
                 consul=None,
                 logger: Optional[logging.Logger] = None):
        self.config = config
        self.alloc = alloc
        self.task = task.copy()
        self.task_dir = task_dir
        self.updater = updater
        self.node = node
        self.vault_token = vault_token
        self.vault_client = vault_client
        self.consul = consul
        self._template_mgr = None
        self.logger = logger or logging.getLogger("nomad_tpu.client.task_runner")

        tg = alloc.job.lookup_task_group(alloc.task_group) if alloc.job else None
        policy = tg.restart_policy if tg and tg.restart_policy else s.RestartPolicy()
        job_type = alloc.job.type if alloc.job else s.JOB_TYPE_SERVICE
        self.restart_tracker = RestartTracker(policy, job_type)

        self.handle: Optional[DriverHandle] = None
        self._handle_lock = threading.Lock()
        self._destroy = threading.Event()
        self._destroy_event: Optional[s.TaskEvent] = None
        self._restart_ch = threading.Event()
        self._signal_queue: list[int] = []
        self._update_queue: list[s.Allocation] = []
        self._control = threading.Condition()
        self._wait_thread: Optional[threading.Thread] = None
        self._dead_emitted = False
        self.done = threading.Event()

    # -- env / driver ------------------------------------------------------
    def _build_env(self) -> envmod.TaskEnv:
        b = envmod.Builder()
        b.set_task(self.task).set_alloc(self.alloc)
        if self.node is not None:
            b.set_node(self.node)
        b.set_region(getattr(self.config, "region", "global"))
        b.set_dirs(self.task_dir.shared_alloc_dir, self.task_dir.local_dir,
                   self.task_dir.secrets_dir)
        if self.vault_token:
            b.set_vault_token(self.vault_token)
        return b.build()

    def _create_driver(self, task_env: envmod.TaskEnv) -> Driver:
        ctx = DriverContext(
            driver_name=self.task.driver,
            alloc_id=self.alloc.id,
            config=self.config,
            node=self.node,
            task_env=task_env,
            logger=self.logger,
        )
        return new_driver(self.task.driver, ctx)

    # -- state reporting ---------------------------------------------------
    def _emit(self, state: str, event: Optional[s.TaskEvent]) -> None:
        if state == s.TASK_STATE_DEAD:
            self._dead_emitted = True
        self.updater(self.task.name, state, event)

    # -- control surface (called by AllocRunner / client API) --------------
    def restart(self, source: str = "", reason: str = "") -> None:
        """(task_runner.go Restart) — user/template triggered restart."""
        self.restart_tracker.set_restart_triggered()
        with self._handle_lock:
            h = self.handle
        if h is not None:
            self._emit(s.TASK_STATE_RUNNING,
                       s.TaskEvent(type=s.TASK_RESTART_SIGNAL,
                                   restart_reason=reason or source))
            h.kill()

    def signal(self, sig: int) -> None:
        with self._handle_lock:
            h = self.handle
        if h is not None:
            self._emit(s.TASK_STATE_RUNNING,
                       s.TaskEvent(type=s.TASK_SIGNALING, signal=sig))
            h.signal(sig)

    def update(self, alloc: s.Allocation) -> None:
        """Adopt in-place updates (kill_timeout, env) without a restart
        (task_runner.go Update)."""
        self.alloc = alloc
        if alloc.job:
            tg = alloc.job.lookup_task_group(alloc.task_group)
            if tg:
                if tg.restart_policy:
                    self.restart_tracker.set_policy(tg.restart_policy)
                updated = tg.lookup_task(self.task.name)
                if updated is not None:
                    self.task = updated.copy()
                    with self._handle_lock:
                        if self.handle is not None:
                            self.handle.update(self.task)

    def destroy(self, event: Optional[s.TaskEvent] = None) -> None:
        """Kill the task and stop the runner (task_runner.go Destroy)."""
        self._destroy_event = event
        self._destroy.set()
        with self._handle_lock:
            h = self.handle
        if h is not None:
            h.kill()

    # -- main loop ---------------------------------------------------------
    def run(self) -> None:
        threading.Thread(target=self._run, daemon=True,
                         name=f"task-runner-{self.alloc.id[:8]}-{self.task.name}").start()

    def _run(self) -> None:
        try:
            self._run_loop()
        except Exception as e:  # defensive: never strand the alloc runner
            self.logger.exception("task runner crashed")
            self._emit(s.TASK_STATE_DEAD,
                       s.TaskEvent(type=s.TASK_SETUP_FAILURE, failed=True,
                                   message=str(e)))
        finally:
            if self.vault_token and self.vault_client is not None:
                self.vault_client.stop_renew_token(self.vault_token)
            if self._template_mgr is not None:
                self._template_mgr.stop()
            self._deregister_services()
            self.done.set()

    def _prestart(self, task_env: envmod.TaskEnv) -> bool:
        """Artifacts (+ dispatch payload); templates render here in the
        reference (task_runner.go prestart)."""
        if self.task.artifacts:
            self._emit(s.TASK_STATE_PENDING,
                       s.TaskEvent(type=s.TASK_DOWNLOADING_ARTIFACTS))
            for art in self.task.artifacts:
                try:
                    get_artifact(task_env, art, self.task_dir.dir)
                except ArtifactError as e:
                    self._emit(
                        s.TASK_STATE_DEAD,
                        s.TaskEvent(type=s.TASK_ARTIFACT_DOWNLOAD_FAILED,
                                    failed=True, message=str(e)))
                    return False
        return True

    def _run_loop(self) -> None:
        self._emit(s.TASK_STATE_PENDING, s.TaskEvent(type=s.TASK_RECEIVED))

        self._loop_body()
        # Destroyed before (or between) iterations: still record the death
        # so the alloc status converges.
        if self._destroy.is_set() and not self._dead_emitted:
            ev = self._destroy_event or s.TaskEvent(type=s.TASK_KILLED)
            self._emit(s.TASK_STATE_DEAD, ev)

    def _derive_vault_token(self) -> bool:
        """Fetch this task's Vault token through the client's manager and
        write it to the secrets dir (task_runner.go:675 vault token
        lifecycle + :785 writeToken); starts renewal tracking."""
        if self.task.vault is None or self.vault_client is None \
                or self.vault_token:
            return True
        try:
            info = self.vault_client.derive_token(
                self.alloc.id, [self.task.name])[self.task.name]
        except Exception as e:
            self._emit(s.TASK_STATE_DEAD,
                       s.TaskEvent(type=s.TASK_SETUP_FAILURE, failed=True,
                                   message=f"vault token derivation "
                                           f"failed: {e}"))
            return False
        self.vault_token = info["token"]
        try:
            token_path = os.path.join(self.task_dir.secrets_dir,
                                      "vault_token")
            with open(token_path, "w", encoding="utf-8") as fh:
                fh.write(self.vault_token)
            os.chmod(token_path, 0o600)
        except OSError as e:
            self.logger.warning("vault token write failed: %s", e)
        self.vault_client.renew_token(self.vault_token,
                                      float(info.get("ttl") or 3600.0))
        return True

    def _register_services(self, handle) -> None:
        """Advertise the task's services + checks with the task lifecycle
        (consul/client.go RegisterTask; script checks exec through the
        driver handle, consul/script.go)."""
        if self.consul is None or not self.task.services:
            return
        # Driver handles expose exec_cmd(cmd, args) -> (output, exit_code)
        # (driver.py DriverHandle); script checks run through it
        # (consul/script.go execs via the driver).
        exec_fn = getattr(handle, "exec_cmd", None)
        try:
            self.consul.register_task(self.alloc, self.task, exec_fn=exec_fn)
        except Exception as e:
            self.logger.warning("consul: service registration failed: %s", e)

    def _deregister_services(self) -> None:
        if self.consul is None or not self.task.services:
            return
        try:
            self.consul.deregister_task(self.alloc.id, self.task.name)
        except Exception as e:
            self.logger.warning("consul: deregistration failed: %s", e)

    def _render_templates(self, task_env) -> bool:
        """Render-block before start (consul_template.go:52: tasks wait
        for the initial render) and start the change watcher."""
        if not self.task.templates:
            return True
        if self._template_mgr is None:
            from .template import TaskTemplateManager

            catalog = getattr(self.consul, "catalog", None) \
                if self.consul is not None else None
            self._template_mgr = TaskTemplateManager(
                templates=self.task.templates,
                task_dir=self.task_dir.dir,
                env=task_env.env(),
                catalog=catalog,
                on_signal=self.signal,
                on_restart=lambda: self.restart(source="template",
                                                reason="template changed"),
                logger=self.logger)
            self._emit(s.TASK_STATE_PENDING,
                       s.TaskEvent(type=s.TASK_RECEIVED,
                                   message="rendering templates"))
            ok = self._template_mgr.render_all_blocking(
                should_abort=self._destroy.is_set)
            if not ok:
                return False
            self._template_mgr.start_watching()
        return True

    def _loop_body(self) -> None:
        while not self._destroy.is_set():
            if not self._derive_vault_token():
                return
            task_env = self._build_env()

            if not self._render_templates(task_env):
                return

            if not self._prestart(task_env):
                return

            # -- start ----------------------------------------------------
            # Config validation is TERMINAL: an invalid config can never
            # succeed, so it must not burn restart attempts
            # (the reference fails Validate once, before the run loop).
            try:
                driver = self._create_driver(task_env)
                driver.validate(self.task.config or {})
            except ValueError as e:
                self._emit(s.TASK_STATE_DEAD,
                           s.TaskEvent(type=s.TASK_DRIVER_FAILURE,
                                       failed=True,
                                       message=f"driver config "
                                               f"validation failed: {e}"))
                return

            try:
                exec_ctx = ExecContext(task_dir=self.task_dir, task_env=task_env)
                driver.prestart(exec_ctx, self.task)
                resp: StartResponse = driver.start(exec_ctx, self.task)
            except Exception as e:
                self.logger.warning("driver start failed: %s", e)
                self._emit(s.TASK_STATE_PENDING,
                           s.TaskEvent(type=s.TASK_DRIVER_FAILURE,
                                       message=str(e)))
                self.restart_tracker.set_start_error(e)
                if not self._should_restart():
                    return
                continue

            with self._handle_lock:
                self.handle = resp.handle
            self._emit(s.TASK_STATE_RUNNING, s.TaskEvent(type=s.TASK_STARTED))
            self._register_services(resp.handle)

            # -- wait -----------------------------------------------------
            wait_ev = resp.handle.wait_ch()
            while not wait_ev.wait(timeout=0.1):
                if self._destroy.is_set():
                    self._emit(s.TASK_STATE_RUNNING,
                               s.TaskEvent(type=s.TASK_KILLING,
                                           kill_timeout=self.task.kill_timeout))
                    resp.handle.kill()
                    wait_ev.wait()
                    break
            res: WaitResult = resp.handle.wait_result()
            self._deregister_services()
            with self._handle_lock:
                self.handle = None

            if self._destroy.is_set():
                # the _run_loop trailer emits the dead state
                return

            # Event-only append: the restart decision below sets the state
            # (task_runner.go: setState("", waitEvent) then shouldRestart).
            self._emit(
                "",
                s.TaskEvent(type=s.TASK_TERMINATED, exit_code=res.exit_code,
                            signal=res.signal, message=res.err or ""))
            self.restart_tracker.set_wait_result(res)
            if not self._should_restart():
                return

    def _should_restart(self) -> bool:
        """Consult the tracker; sleep the restart delay; emit the verdict
        events (task_runner.go:1400 shouldRestart)."""
        state, delay = self.restart_tracker.get_state()
        reason = self.restart_tracker.get_reason()

        if state in ("", s.TASK_TERMINATED):
            # The Terminated event is already appended; just transition.
            self._emit(s.TASK_STATE_DEAD, None)
            return False
        if state == s.TASK_NOT_RESTARTING:
            self._emit(s.TASK_STATE_DEAD,
                       s.TaskEvent(type=s.TASK_NOT_RESTARTING, failed=True,
                                   restart_reason=reason))
            return False
        # TASK_RESTARTING
        self._emit(s.TASK_STATE_PENDING,
                   s.TaskEvent(type=s.TASK_RESTARTING, restart_reason=reason,
                               start_delay=delay))
        if self._destroy.wait(timeout=delay):
            # destroyed during the restart delay; trailer emits dead
            return False
        return True
