"""Restart policy state machine
(reference: client/restarts.go:1-222).

Given the latest start error / wait result / restart signal, decides
whether the task should restart and after what delay, honoring the
task group's RestartPolicy (attempts within interval, delay vs fail
mode, 25% jitter).
"""
from __future__ import annotations

import random
import threading
import time
from typing import Optional

from ..structs import structs as s
from .driver.driver import WaitResult, is_recoverable

JITTER = 0.25

REASON_NO_RESTARTS_ALLOWED = "Policy allows no restarts"
REASON_UNRECOVERABLE = "Error was unrecoverable"
REASON_WITHIN_POLICY = "Restart within policy"
REASON_DELAY = "Exceeded allowed attempts, applying a delay"


class RestartTracker:
    def __init__(self, policy: s.RestartPolicy, job_type: str):
        # Batch jobs that exit 0 are done; service jobs restart on success
        # (restarts.go:23-27).
        self.on_success = job_type != s.JOB_TYPE_BATCH
        self.policy = policy
        self.count = 0
        self.start_time = time.time()
        self.reason = ""
        self._wait_res: Optional[WaitResult] = None
        self._start_err: Optional[BaseException] = None
        self._restart_triggered = False
        self._lock = threading.Lock()
        self._rand = random.Random()

    def set_policy(self, policy: s.RestartPolicy) -> None:
        with self._lock:
            self.policy = policy

    def set_start_error(self, err: Optional[BaseException]) -> "RestartTracker":
        with self._lock:
            self._start_err = err
        return self

    def set_wait_result(self, res: WaitResult) -> "RestartTracker":
        with self._lock:
            self._wait_res = res
        return self

    def set_restart_triggered(self) -> "RestartTracker":
        with self._lock:
            self._restart_triggered = True
        return self

    def get_reason(self) -> str:
        with self._lock:
            return self.reason

    def get_state(self) -> tuple[str, float]:
        """→ (TASK_RESTARTING|TASK_NOT_RESTARTING|TASK_TERMINATED|'', delay)
        (restarts.go:91 GetState)."""
        with self._lock:
            try:
                return self._get_state()
            finally:
                self._start_err = None
                self._wait_res = None
                self._restart_triggered = False

    def _get_state(self) -> tuple[str, float]:
        if self._restart_triggered:
            self.reason = ""
            return s.TASK_RESTARTING, 0.0

        if self.policy.attempts == 0:
            self.reason = REASON_NO_RESTARTS_ALLOWED
            if self._wait_res is not None and self._wait_res.successful():
                return s.TASK_TERMINATED, 0.0
            return s.TASK_NOT_RESTARTING, 0.0

        self.count += 1

        # New interval resets the attempt budget (restarts.go:129-135).
        now = time.time()
        if now > self.start_time + self.policy.interval:
            self.count = 0
            self.start_time = now

        if self._start_err is not None:
            return self._handle_start_error()
        if self._wait_res is not None:
            return self._handle_wait_result()
        return "", 0.0

    def _over_budget(self) -> Optional[tuple[str, float]]:
        if self.count > self.policy.attempts:
            if self.policy.mode == s.RESTART_POLICY_MODE_FAIL:
                self.reason = (
                    f'Exceeded allowed attempts {self.policy.attempts} in interval '
                    f'{self.policy.interval}s and mode is "fail"')
                return s.TASK_NOT_RESTARTING, 0.0
            self.reason = REASON_DELAY
            return s.TASK_RESTARTING, self._interval_delay()
        return None

    def _handle_start_error(self) -> tuple[str, float]:
        if not is_recoverable(self._start_err):
            self.reason = REASON_UNRECOVERABLE
            return s.TASK_NOT_RESTARTING, 0.0
        over = self._over_budget()
        if over is not None:
            return over
        self.reason = REASON_WITHIN_POLICY
        return s.TASK_RESTARTING, self._jitter()

    def _handle_wait_result(self) -> tuple[str, float]:
        if self._wait_res.successful() and not self.on_success:
            self.reason = "Restart unnecessary as task terminated successfully"
            return s.TASK_TERMINATED, 0.0
        over = self._over_budget()
        if over is not None:
            return over
        self.reason = REASON_WITHIN_POLICY
        return s.TASK_RESTARTING, self._jitter()

    def _interval_delay(self) -> float:
        """Wait out the remainder of the current interval (restarts.go:199)."""
        return max(0.0, self.start_time + self.policy.interval - time.time())

    def _jitter(self) -> float:
        d = self.policy.delay or 1e-9
        return d + self._rand.uniform(0, d) * JITTER


def no_restarts_tracker() -> RestartTracker:
    return RestartTracker(
        s.RestartPolicy(attempts=0, mode=s.RESTART_POLICY_MODE_FAIL),
        s.JOB_TYPE_BATCH)
