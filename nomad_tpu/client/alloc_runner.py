"""AllocRunner: runs one allocation — builds the alloc dir, spawns a
TaskRunner per task, aggregates task states into the allocation's
client status, and pushes dirty status upstream
(reference: client/alloc_runner.go:47-921).
"""
from __future__ import annotations

import logging
import os
import threading
import time
from typing import Callable, Dict, List, Optional

from ..structs import structs as s
from .allocdir import AllocDir
from .task_runner import TaskRunner

AllocUpdater = Callable[[s.Allocation], None]


def get_client_status(task_states: Dict[str, s.TaskState]) -> str:
    """Fold task states into an alloc client status
    (alloc_runner.go:491 getClientStatus)."""
    pending = running = dead = failed = False
    for st in task_states.values():
        if st.state == s.TASK_STATE_RUNNING:
            running = True
        elif st.state == s.TASK_STATE_PENDING:
            pending = True
        elif st.state == s.TASK_STATE_DEAD:
            if st.failed:
                failed = True
            else:
                dead = True
    if failed:
        return s.ALLOC_CLIENT_STATUS_FAILED
    if running:
        return s.ALLOC_CLIENT_STATUS_RUNNING
    if pending:
        return s.ALLOC_CLIENT_STATUS_PENDING
    if dead:
        return s.ALLOC_CLIENT_STATUS_COMPLETE
    return ""


class AllocRunner:
    def __init__(self,
                 config,
                 alloc: s.Allocation,
                 updater: AllocUpdater,
                 node: Optional[s.Node] = None,
                 state_db=None,
                 prev_alloc_dir: Optional[AllocDir] = None,
                 vault_client=None,
                 consul=None,
                 logger: Optional[logging.Logger] = None):
        self.config = config
        self.alloc = alloc.copy()
        self.updater = updater
        self.node = node
        self.state_db = state_db
        self.vault_client = vault_client
        self.consul = consul
        self.logger = logger or logging.getLogger("nomad_tpu.client.alloc_runner")

        base = getattr(config, "alloc_dir", None) or "/tmp/nomad-tpu-allocs"
        self.alloc_dir = AllocDir(os.path.join(base, alloc.id))
        self.prev_alloc_dir = prev_alloc_dir

        self.task_states: Dict[str, s.TaskState] = {}
        self.task_runners: Dict[str, TaskRunner] = {}
        self._state_lock = threading.Lock()
        self._alloc_client_status = ""
        self._alloc_client_description = ""
        self._failed_task = ""
        self._dirty = threading.Event()
        self._destroy = threading.Event()
        self.done = threading.Event()
        self.waiting_on_previous = threading.Event()
        self.waiting_on_previous.set()
        # Path to a sticky-disk tar pulled from a previous alloc on
        # another node (client.go:1743); applied after the alloc dir is
        # built, then deleted.
        self.remote_snapshot_path = None

    # -- views -------------------------------------------------------------
    def current_alloc(self) -> s.Allocation:
        """Copy with live client status folded in (alloc_runner.go Alloc)."""
        alloc = self.alloc.copy()
        with self._state_lock:
            alloc.task_states = {k: v.copy() for k, v in self.task_states.items()}
            if self._alloc_client_status:
                alloc.client_status = self._alloc_client_status
                alloc.client_description = self._alloc_client_description
            else:
                alloc.client_status = (
                    get_client_status(self.task_states)
                    or s.ALLOC_CLIENT_STATUS_PENDING)
        return alloc

    # -- task state intake -------------------------------------------------
    def _set_task_state(self, task_name: str, state: str,
                        event: Optional[s.TaskEvent]) -> None:
        """(alloc_runner.go:558 setTaskState) + failed-sibling kill."""
        kill_siblings = False
        with self._state_lock:
            ts = self.task_states.setdefault(task_name, s.TaskState())
            if event is not None:
                if event.time == 0.0:
                    event.time = time.time()
                if event.failed:
                    ts.failed = True
                ts.events.append(event)
                # Keep the event window bounded like the 10-event ring
                # (structs.go maxTaskEventBuffer).
                if len(ts.events) > 10:
                    ts.events = ts.events[-10:]
            if state:
                if state == s.TASK_STATE_RUNNING and ts.state != state:
                    ts.started_at = time.time()
                if state == s.TASK_STATE_DEAD and ts.state != state:
                    ts.finished_at = time.time()
                ts.state = state
            if ts.state == s.TASK_STATE_DEAD and ts.failed:
                kill_siblings = True
                self._failed_task = task_name
            # Snapshot under the lock: _run_inner may still be inserting
            # runners concurrently.
            siblings = [(n, tr) for n, tr in self.task_runners.items()
                        if n != task_name] if kill_siblings else []

        for name, tr in siblings:
            tr.destroy(s.TaskEvent(
                type=s.TASK_SIBLING_FAILED, failed_sibling=task_name,
                failed=True))
        self._dirty.set()

    # -- persistence -------------------------------------------------------
    def save_state(self) -> None:
        if self.state_db is None:
            return
        with self._state_lock:
            handles = {
                name: tr.handle.id()
                for name, tr in self.task_runners.items()
                if tr.handle is not None
            }
            self.state_db.put_alloc_runner(self.alloc.id, {
                "alloc": self.alloc,
                "task_states": {k: v.copy() for k, v in self.task_states.items()},
                "handles": handles,
                "alloc_dir": self.alloc_dir.alloc_dir,
            })

    # -- lifecycle ---------------------------------------------------------
    def run(self) -> None:
        threading.Thread(target=self._run, daemon=True,
                         name=f"alloc-runner-{self.alloc.id[:8]}").start()
        threading.Thread(target=self._sync_loop, daemon=True).start()

    def _run(self) -> None:
        try:
            self._run_inner()
        except Exception as e:
            self.logger.exception("alloc runner failed")
            with self._state_lock:
                self._alloc_client_status = s.ALLOC_CLIENT_STATUS_FAILED
                self._alloc_client_description = str(e)
            self._dirty.set()
        finally:
            self.done.set()
            self._dirty.set()

    def _run_inner(self) -> None:
        tg = (self.alloc.job.lookup_task_group(self.alloc.task_group)
              if self.alloc.job else None)
        if tg is None:
            with self._state_lock:
                self._alloc_client_status = s.ALLOC_CLIENT_STATUS_FAILED
                self._alloc_client_description = (
                    f"task group {self.alloc.task_group!r} not in job")
            return

        if self.alloc.terminal_status():
            return

        # Block on a previous allocation's shutdown for sticky disks
        # (client.go:1654 blocking + migration).
        self.waiting_on_previous.wait()

        self.alloc_dir.build()
        for task in tg.tasks:
            self.alloc_dir.new_task_dir(task.name).build()
        if (self.prev_alloc_dir is not None and tg.ephemeral_disk is not None
                and tg.ephemeral_disk.sticky):
            try:
                self.alloc_dir.move(self.prev_alloc_dir,
                                    [t.name for t in tg.tasks])
            except OSError as e:
                self.logger.warning("sticky disk move failed: %s", e)
        elif self.remote_snapshot_path:
            import tarfile
            try:
                self.alloc_dir.restore_snapshot_file(self.remote_snapshot_path)
            except (OSError, tarfile.TarError) as e:
                self.logger.warning("remote sticky restore failed: %s", e)
            try:
                os.unlink(self.remote_snapshot_path)
            except OSError:
                pass
            self.remote_snapshot_path = None

        for task in tg.tasks:
            tr = TaskRunner(
                config=self.config,
                alloc=self.alloc,
                task=task,
                task_dir=self.alloc_dir.task_dirs[task.name],
                updater=self._set_task_state,
                node=self.node,
                vault_client=self.vault_client,
                consul=self.consul,
                logger=self.logger,
            )
            with self._state_lock:
                self.task_runners[task.name] = tr
                failed_sibling = self._failed_task
            if failed_sibling:
                # A sibling already failed while we were still spawning —
                # this late runner must die too, not slip past the kill.
                tr.destroy(s.TaskEvent(type=s.TASK_SIBLING_FAILED,
                                       failed_sibling=failed_sibling,
                                       failed=True))
            tr.run()

        for tr in self.task_runners.values():
            while not tr.done.wait(timeout=0.25):
                if self._destroy.is_set():
                    break
        self.save_state()

    def _sync_loop(self) -> None:
        """Debounced status push (alloc_runner.go dirtySyncState)."""
        while True:
            self._dirty.wait()
            self._dirty.clear()
            self.updater(self.current_alloc())
            self.save_state()
            if self.done.is_set() and not self._dirty.is_set():
                return
            time.sleep(0.05)

    # -- control -----------------------------------------------------------
    def update(self, alloc: s.Allocation) -> None:
        """Server pushed a new version of this alloc
        (alloc_runner.go Update)."""
        self.alloc = alloc.copy()
        if alloc.desired_status in (s.ALLOC_DESIRED_STATUS_STOP,
                                    s.ALLOC_DESIRED_STATUS_EVICT):
            self.destroy()
            return
        for tr in self.task_runners.values():
            tr.update(alloc)
        self._dirty.set()

    def destroy(self, event: Optional[s.TaskEvent] = None) -> None:
        self._destroy.set()
        self.waiting_on_previous.set()
        for tr in self.task_runners.values():
            tr.destroy(event or s.TaskEvent(type=s.TASK_KILLED))

    def destroy_alloc_dir(self) -> None:
        self.alloc_dir.destroy()
        if self.state_db is not None:
            self.state_db.delete_alloc_runner(self.alloc.id)

    def wait(self, timeout: Optional[float] = None) -> bool:
        return self.done.wait(timeout)

    def is_destroyed(self) -> bool:
        return self._destroy.is_set()

    def stats_report(self) -> Dict:
        """Per-task resource-usage snapshot for the client HTTP stats
        endpoint (reference: AllocRunner.StatsReporter / alloc stats)."""
        tasks: Dict[str, Dict] = {}
        for name, tr in list(self.task_runners.items()):
            h = tr.handle
            if h is None:
                continue
            try:
                tasks[name] = h.stats()
            except Exception:
                tasks[name] = {}
        return {"ResourceUsage": {"Tasks": tasks}, "Timestamp": time.time()}
