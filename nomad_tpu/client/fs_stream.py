"""Framed file/log streaming with follow (reference:
command/agent/fs_endpoint.go — StreamFrame {File, Offset, Data, FileEvent}
over a chunked response; client/allocdir ReadAt/BlockUntilExists/
ChangeEvents).

Generators yield frame dicts; the HTTP layer serializes each as one
NDJSON line (Data bytes → base64 via the wire codec).  Log streaming
follows the executor's rotated files (`<task>.<stream>.<n>`,
client/driver/executor.py LogRotator) across rotation boundaries,
emitting a FileEvent frame on each switch.
"""

from __future__ import annotations

import os
import time
from typing import Callable, Dict, Iterator, List, Optional

# Frame payload cap; the reference streams 64KiB frames.
MAX_FRAME = 64 * 1024
POLL_INTERVAL = 0.15
# Follow mode emits an empty heartbeat frame when idle so consumers can
# detect liveness (fs_endpoint.go heartbeat ticker).
HEARTBEAT_INTERVAL = 10.0


def _frame(file: str, offset: int, data: bytes = b"",
           event: str = "") -> Dict:
    out: Dict = {"File": file, "Offset": offset}
    if data:
        out["Data"] = data
    if event:
        out["FileEvent"] = event
    return out


def stream_file_frames(
    path: str,
    rel_name: str,
    offset: int = 0,
    origin: str = "start",
    follow: bool = False,
    alive: Optional[Callable[[], bool]] = None,
    poll: float = POLL_INTERVAL,
) -> Iterator[Dict]:
    """Stream one file from ``origin``±``offset``; with ``follow``, keep
    tailing until the consumer stops or ``alive()`` turns false with no
    more data (truncation resets to the new end)."""
    pos = _start_pos(path, offset, origin)
    last_beat = time.monotonic()
    while True:
        size = os.path.getsize(path) if os.path.exists(path) else 0
        if size < pos:
            pos = 0  # truncated/rewritten — restart from the top
        if size > pos:
            with open(path, "rb") as fh:
                fh.seek(pos)
                data = fh.read(MAX_FRAME)
            pos += len(data)
            yield _frame(rel_name, pos, data)
            last_beat = time.monotonic()
            continue
        if not follow:
            return
        if alive is not None and not alive():
            return
        if time.monotonic() - last_beat >= HEARTBEAT_INTERVAL:
            yield _frame(rel_name, pos)
            last_beat = time.monotonic()
        time.sleep(poll)


def _start_pos(path: str, offset: int, origin: str) -> int:
    size = os.path.getsize(path) if os.path.exists(path) else 0
    if origin == "end":
        return max(0, size - offset)
    return min(offset, size) if size else 0


def _log_files(log_dir: str, prefix: str) -> List[str]:
    if not os.path.isdir(log_dir):
        return []
    out = [f for f in os.listdir(log_dir)
           if f.startswith(prefix) and f[len(prefix):].isdigit()]
    return sorted(out, key=lambda f: int(f[len(prefix):]))


def stream_log_frames(
    log_dir: str,
    task: str,
    log_type: str = "stdout",
    offset: int = 0,
    origin: str = "start",
    follow: bool = False,
    alive: Optional[Callable[[], bool]] = None,
    poll: float = POLL_INTERVAL,
) -> Iterator[Dict]:
    """Stream a task's rotated logs as frames, following across rotation
    boundaries (fs_endpoint.go logs handler + logging/rotator.go)."""
    prefix = f"{task}.{log_type}."

    # Wait for the first log file in follow mode (BlockUntilExists).
    files = _log_files(log_dir, prefix)
    while not files:
        if not follow or (alive is not None and not alive()):
            return
        time.sleep(poll)
        files = _log_files(log_dir, prefix)

    if origin == "end":
        fname = files[-1]
        pos = _start_pos(os.path.join(log_dir, fname), offset, "end")
    else:
        fname = files[0]
        pos = offset

    rel = f"alloc/logs/{fname}"
    last_beat = time.monotonic()
    idle_after_dead = False
    while True:
        path = os.path.join(log_dir, fname)
        size = os.path.getsize(path) if os.path.exists(path) else 0
        if size < pos:
            pos = 0
        if size > pos:
            with open(path, "rb") as fh:
                fh.seek(pos)
                data = fh.read(MAX_FRAME)
            pos += len(data)
            yield _frame(rel, pos, data)
            last_beat = time.monotonic()
            idle_after_dead = False
            continue

        # Current file exhausted: advance across a rotation boundary.
        files = _log_files(log_dir, prefix)
        try:
            cur = files.index(fname)
        except ValueError:
            cur = -1
        if cur != -1 and cur + 1 < len(files):
            fname = files[cur + 1]
            rel = f"alloc/logs/{fname}"
            pos = 0
            yield _frame(rel, 0, event="next log file")
            continue

        if not follow:
            return
        if alive is not None and not alive():
            if idle_after_dead:
                return  # drained once after death — done
            idle_after_dead = True
        if time.monotonic() - last_beat >= HEARTBEAT_INTERVAL:
            yield _frame(rel, pos)
            last_beat = time.monotonic()
        time.sleep(poll)
