"""Allocation directory tree
(reference: client/allocdir/alloc_dir.go:56-393, task_dir.go).

Layout per allocation:
    <alloc_id>/
      alloc/            shared between tasks
        data/  logs/  tmp/
      <task>/
        local/  secrets/  tmp/

Shared-dir contents migrate between allocations for sticky ephemeral
disks (`move`), and snapshot to a tar stream for cross-node migration
(`snapshot`).  The reference bind-mounts the shared dir into chroots;
here tasks get the paths via NOMAD_* env instead, which is the same
user-facing contract for non-chroot drivers.
"""
from __future__ import annotations

import io
import os
import shutil
import tarfile
import time
from typing import Dict, List, Optional

SHARED_ALLOC_NAME = "alloc"
SHARED_DATA_DIR = "data"
SHARED_LOGS = "logs"
TMP_DIR = "tmp"
TASK_LOCAL = "local"
TASK_SECRETS = "secrets"


class TaskDir:
    """Paths for one task within an allocation (task_dir.go)."""

    def __init__(self, alloc_dir: str, task_name: str):
        self.task_name = task_name
        self.dir = os.path.join(alloc_dir, task_name)
        self.local_dir = os.path.join(self.dir, TASK_LOCAL)
        self.secrets_dir = os.path.join(self.dir, TASK_SECRETS)
        self.tmp_dir = os.path.join(self.dir, TMP_DIR)
        self.shared_alloc_dir = os.path.join(alloc_dir, SHARED_ALLOC_NAME)
        self.log_dir = os.path.join(self.shared_alloc_dir, SHARED_LOGS)

    def build(self) -> None:
        for d in (self.dir, self.local_dir, self.tmp_dir):
            os.makedirs(d, exist_ok=True)
        os.makedirs(self.secrets_dir, exist_ok=True)
        try:
            os.chmod(self.secrets_dir, 0o700)
        except OSError:
            pass


class AllocDir:
    """(alloc_dir.go:56 AllocDir)."""

    def __init__(self, alloc_dir: str):
        self.alloc_dir = alloc_dir
        self.shared_dir = os.path.join(alloc_dir, SHARED_ALLOC_NAME)
        self.task_dirs: Dict[str, TaskDir] = {}
        self.built = False

    def new_task_dir(self, task_name: str) -> TaskDir:
        td = TaskDir(self.alloc_dir, task_name)
        self.task_dirs[task_name] = td
        return td

    def build(self) -> None:
        os.makedirs(self.alloc_dir, exist_ok=True)
        for sub in (SHARED_DATA_DIR, SHARED_LOGS, TMP_DIR):
            os.makedirs(os.path.join(self.shared_dir, sub), exist_ok=True)
        self.built = True

    def destroy(self) -> None:
        shutil.rmtree(self.alloc_dir, ignore_errors=True)

    # -- sticky disk -------------------------------------------------------
    def move(self, other: "AllocDir", tasks: List[str]) -> None:
        """Adopt the shared data dir + task local dirs from a previous
        allocation on the same node (alloc_dir.go:172 Move)."""
        other_data = os.path.join(other.shared_dir, SHARED_DATA_DIR)
        self_data = os.path.join(self.shared_dir, SHARED_DATA_DIR)
        if os.path.isdir(other_data):
            shutil.rmtree(self_data, ignore_errors=True)
            shutil.move(other_data, self_data)
        for name in tasks:
            src = TaskDir(other.alloc_dir, name).local_dir
            dst = self.task_dirs.get(name)
            if dst is None or not os.path.isdir(src):
                continue
            shutil.rmtree(dst.local_dir, ignore_errors=True)
            shutil.move(src, dst.local_dir)

    # -- migration ---------------------------------------------------------
    def snapshot(self) -> bytes:
        """Tar of shared data + task local dirs for cross-node sticky-disk
        migration (alloc_dir.go:110 Snapshot)."""
        buf = io.BytesIO()
        with tarfile.open(fileobj=buf, mode="w") as tar:
            targets = [os.path.join(self.shared_dir, SHARED_DATA_DIR)]
            targets += [td.local_dir for td in self.task_dirs.values()]
            for root in targets:
                if not os.path.isdir(root):
                    continue
                arc_root = os.path.relpath(root, self.alloc_dir)
                tar.add(root, arcname=arc_root)
        return buf.getvalue()

    def snapshot_to_file(self, path: str) -> None:
        """Tar the sticky data straight to ``path`` — migration transfers
        must not hold whole disks in memory (alloc_dir.go streams its
        Snapshot too)."""
        with tarfile.open(path, mode="w") as tar:
            targets = [os.path.join(self.shared_dir, SHARED_DATA_DIR)]
            targets += [td.local_dir for td in self.task_dirs.values()]
            for root in targets:
                if not os.path.isdir(root):
                    continue
                arc_root = os.path.relpath(root, self.alloc_dir)
                tar.add(root, arcname=arc_root)

    def restore_snapshot_file(self, path: str) -> None:
        with tarfile.open(path, mode="r") as tar:
            self._extract(tar)

    def restore_snapshot(self, data: bytes) -> None:
        with tarfile.open(fileobj=io.BytesIO(data), mode="r") as tar:
            self._extract(tar)

    def _extract(self, tar) -> None:
        for member in tar.getmembers():
            # refuse path escapes
            target = os.path.join(self.alloc_dir, member.name)
            if not os.path.realpath(target).startswith(
                    os.path.realpath(self.alloc_dir) + os.sep):
                continue
            tar.extract(member, self.alloc_dir, filter="data")

    # -- log access (fs API) ----------------------------------------------
    def list_dir(self, rel: str) -> List[Dict]:
        base = self._safe_path(rel)
        out = []
        for name in sorted(os.listdir(base)):
            st = os.stat(os.path.join(base, name))
            out.append({
                "Name": name,
                "IsDir": os.path.isdir(os.path.join(base, name)),
                "Size": st.st_size,
                "ModTime": st.st_mtime,
            })
        return out

    def stat(self, rel: str) -> Dict:
        p = self._safe_path(rel)
        st = os.stat(p)
        return {"Name": os.path.basename(p), "IsDir": os.path.isdir(p),
                "Size": st.st_size, "ModTime": st.st_mtime}

    def read_all(self, rel: str, max_bytes: int = 1 << 20) -> bytes:
        """Read a file, capped at max_bytes (the HTTP cat endpoint must not
        buffer arbitrarily large task output)."""
        with open(self._safe_path(rel), "rb") as f:
            return f.read(max_bytes)

    def read_at(self, rel: str, offset: int, limit: int) -> bytes:
        """(alloc_dir.go:334 ReadAt)."""
        p = self._safe_path(rel)
        with open(p, "rb") as f:
            f.seek(offset)
            return f.read(limit if limit > 0 else -1)

    def block_until_exists(self, rel: str, timeout: float = 10.0) -> bool:
        """(alloc_dir.go:358 BlockUntilExists) — poll-based tail support."""
        deadline = time.time() + timeout
        p = os.path.join(self.alloc_dir, rel)
        while time.time() < deadline:
            if os.path.exists(p):
                return True
            time.sleep(0.05)
        return False

    def _safe_path(self, rel: str) -> str:
        p = os.path.realpath(os.path.join(self.alloc_dir, rel.lstrip("/")))
        root = os.path.realpath(self.alloc_dir)
        if not (p == root or p.startswith(root + os.sep)):
            raise PermissionError(f"path escapes alloc dir: {rel}")
        return p


def disk_usage(path: str) -> int:
    """Bytes used under path (client/gc uses this for threshold checks)."""
    total = 0
    for root, _dirs, files in os.walk(path, onerror=lambda e: None):
        for f in files:
            try:
                total += os.lstat(os.path.join(root, f)).st_size
            except OSError:
                pass
    return total
