"""Client-side allocation garbage collector
(reference: client/gc.go:20-435).

Terminal alloc runners enter an eviction priority queue (oldest
terminal first); collection triggers on an interval when disk usage or
the alloc-count cap is exceeded, and ``make_room_for`` evicts ahead of
new allocations.
"""
from __future__ import annotations

import heapq
import logging
import shutil
import threading
import time
from typing import Dict, List, Optional

from .alloc_runner import AllocRunner


class _IndexedGCAlloc:
    __slots__ = ("mod_time", "alloc_id", "runner")

    def __init__(self, mod_time: float, alloc_id: str, runner: AllocRunner):
        self.mod_time = mod_time
        self.alloc_id = alloc_id
        self.runner = runner

    def __lt__(self, other: "_IndexedGCAlloc") -> bool:
        return self.mod_time < other.mod_time


class AllocGarbageCollector:
    def __init__(self, config, stats_path: str = "/",
                 logger: Optional[logging.Logger] = None):
        self.config = config
        self.stats_path = stats_path
        self.logger = logger or logging.getLogger("nomad_tpu.client.gc")
        self._heap: List[_IndexedGCAlloc] = []
        self._index: Dict[str, _IndexedGCAlloc] = {}
        self._lock = threading.Lock()
        self._shutdown = threading.Event()

    # -- queue -------------------------------------------------------------
    def mark_for_collection(self, runner: AllocRunner) -> None:
        with self._lock:
            if runner.alloc.id in self._index:
                return
            item = _IndexedGCAlloc(time.time(), runner.alloc.id, runner)
            self._index[runner.alloc.id] = item
            heapq.heappush(self._heap, item)

    def remove(self, alloc_id: str) -> None:
        with self._lock:
            item = self._index.pop(alloc_id, None)
            if item is not None:
                self._heap.remove(item)
                heapq.heapify(self._heap)

    def _pop(self) -> Optional[AllocRunner]:
        with self._lock:
            while self._heap:
                item = heapq.heappop(self._heap)
                if self._index.pop(item.alloc_id, None) is not None:
                    return item.runner
        return None

    def count(self) -> int:
        with self._lock:
            return len(self._heap)

    # -- collection --------------------------------------------------------
    def _destroy(self, runner: AllocRunner) -> None:
        runner.destroy()
        runner.wait(timeout=30.0)
        runner.destroy_alloc_dir()

    def collect(self, alloc_id: str) -> bool:
        """Explicit GC of one alloc (client GC HTTP endpoint)."""
        with self._lock:
            item = self._index.pop(alloc_id, None)
            if item is None:
                return False
            self._heap.remove(item)
            heapq.heapify(self._heap)
        self._destroy(item.runner)
        return True

    def collect_all(self) -> int:
        n = 0
        while True:
            runner = self._pop()
            if runner is None:
                return n
            self._destroy(runner)
            n += 1

    def make_room_for(self, needed_mb: int, total_live_allocs: int) -> None:
        """Evict terminal allocs until the new alloc fits under the
        gc_max_allocs cap and disk need (gc.go:170 MakeRoomFor)."""
        max_allocs = getattr(self.config, "gc_max_allocs", 50)
        while (total_live_allocs + self.count() >= max_allocs
               and self.count() > 0):
            runner = self._pop()
            if runner is None:
                break
            self._destroy(runner)
        if needed_mb > 0:
            try:
                usage = shutil.disk_usage(self.stats_path)
                free_mb = usage.free >> 20
            except OSError:
                return
            while free_mb < needed_mb and self.count() > 0:
                runner = self._pop()
                if runner is None:
                    return
                self._destroy(runner)
                try:
                    free_mb = shutil.disk_usage(self.stats_path).free >> 20
                except OSError:
                    return

    # -- periodic ----------------------------------------------------------
    def run(self) -> None:
        threading.Thread(target=self._loop, daemon=True, name="client-gc").start()

    def stop(self) -> None:
        self._shutdown.set()

    def _loop(self) -> None:
        interval = getattr(self.config, "gc_interval", 60.0)
        threshold = getattr(self.config, "gc_disk_usage_threshold", 80.0)
        while not self._shutdown.wait(interval):
            try:
                usage = shutil.disk_usage(self.stats_path)
                pct = 100.0 * (usage.total - usage.free) / max(1, usage.total)
            except OSError:
                continue
            if pct >= threshold:
                runner = self._pop()
                if runner is not None:
                    self.logger.info("gc: disk %.0f%% — collecting %s",
                                     pct, runner.alloc.id[:8])
                    self._destroy(runner)
