"""Task template rendering (reference: client/consul_template.go:52-534
TaskTemplateManager): renders task templates from Consul KV / services /
env, blocks task start until every template has rendered once, and applies
the template's change_mode (noop | signal | restart) when watched data
changes.

The template language is the consul-template function subset the tree's
jobs actually use, over ``{{ ... }}`` actions:

  {{key "some/key"}}        — catalog KV lookup (blocks until present)
  {{env "NAME"}}            — task environment
  {{service "name"}}        — "addr:port" list, comma-separated
  {{range service "name"}}...{{.Address}}:{{.Port}}...{{end}} — iteration
"""

from __future__ import annotations

import logging
import os
import re
import signal as signal_mod
import threading
from typing import Callable, Dict, List, Optional, Tuple

from ..structs import structs as s

RENDER_POLL = 0.2

_ACTION = re.compile(
    r"\{\{\s*(key|env|service)\s+\"([^\"]+)\"\s*\}\}")
_RANGE = re.compile(
    r"\{\{\s*range\s+service\s+\"([^\"]+)\"\s*\}\}(.*?)\{\{\s*end\s*\}\}",
    re.S)


def parse_signal(name: str) -> int:
    """'SIGHUP' → signal number (task_runner signal plumbing)."""
    if not name:
        return signal_mod.SIGHUP
    name = name.upper()
    if not name.startswith("SIG"):
        name = "SIG" + name
    return int(getattr(signal_mod, name, signal_mod.SIGHUP))


class TemplateError(Exception):
    pass


class MissingDependency(Exception):
    """A referenced KV key is absent — the render blocks until it exists
    (consul-template blocks on missing dependencies)."""


class TaskTemplateManager:
    """Renders a task's templates and drives change modes."""

    def __init__(
        self,
        templates: List[s.Template],
        task_dir: str,
        env: Dict[str, str],
        catalog=None,
        on_signal: Optional[Callable[[int], None]] = None,
        on_restart: Optional[Callable[[], None]] = None,
        logger: Optional[logging.Logger] = None,
    ):
        self.templates = templates
        self.task_dir = task_dir
        self.env = env
        self.catalog = catalog
        self.on_signal = on_signal
        self.on_restart = on_restart
        self.logger = logger or logging.getLogger("nomad_tpu.template")
        self._rendered: Dict[int, str] = {}    # template idx -> content
        # Generation observed BEFORE the first render: a mutation landing
        # between the initial render and the watcher's first poll must
        # still trigger a re-render.
        self._gen0 = catalog.generation() if catalog is not None else 0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None

    # -- rendering -----------------------------------------------------

    def _source(self, tmpl: s.Template) -> str:
        if tmpl.embedded_tmpl:
            return tmpl.embedded_tmpl
        if tmpl.source_path:
            path = tmpl.source_path
            if not os.path.isabs(path):
                path = os.path.join(self.task_dir, path)
            with open(path, "r", encoding="utf-8") as fh:
                return fh.read()
        raise TemplateError("template has neither source nor embedded data")

    def render_one(self, tmpl: s.Template) -> str:
        src = self._source(tmpl)

        def expand_range(m: "re.Match") -> str:
            name, body = m.group(1), m.group(2)
            out = []
            for e in (self.catalog.service(name) if self.catalog else []):
                out.append(body.replace("{{.Address}}", e.address)
                               .replace("{{.Port}}", str(e.port))
                               .replace("{{.Name}}", e.name))
            return "".join(out)

        src = _RANGE.sub(expand_range, src)

        def expand(m: "re.Match") -> str:
            fn, arg = m.group(1), m.group(2)
            if fn == "env":
                return self.env.get(arg, "")
            if fn == "key":
                if self.catalog is None:
                    raise MissingDependency(arg)
                val = self.catalog.kv_get(arg)
                if val is None:
                    raise MissingDependency(arg)
                return val
            if fn == "service":
                entries = (self.catalog.service(name=arg)
                           if self.catalog else [])
                return ",".join(f"{e.address}:{e.port}" for e in entries)
            return m.group(0)

        return _ACTION.sub(expand, src)

    def _dest(self, tmpl: s.Template) -> str:
        dest = tmpl.dest_path
        if not os.path.isabs(dest):
            dest = os.path.join(self.task_dir, dest)
        return dest

    def _write(self, tmpl: s.Template, content: str) -> None:
        dest = self._dest(tmpl)
        os.makedirs(os.path.dirname(dest), exist_ok=True)
        with open(dest, "w", encoding="utf-8") as fh:
            fh.write(content)
        try:
            os.chmod(dest, int(tmpl.perms or "0644", 8))
        except (ValueError, OSError):
            pass

    # -- lifecycle -----------------------------------------------------

    def render_all_blocking(self, should_abort: Callable[[], bool],
                            poll: float = RENDER_POLL) -> bool:
        """Initial render of every template; blocks while dependencies are
        missing (consul_template.go: tasks do not start until templates
        render).  Returns False if aborted."""
        pending = list(enumerate(self.templates))
        while pending:
            still: List[Tuple[int, s.Template]] = []
            for idx, tmpl in pending:
                try:
                    content = self.render_one(tmpl)
                except MissingDependency as e:
                    self.logger.debug("template blocked on missing key %s", e)
                    still.append((idx, tmpl))
                    continue
                self._write(tmpl, content)
                self._rendered[idx] = content
            pending = still
            if pending:
                if should_abort():
                    return False
                self._stop.wait(poll)
                if self._stop.is_set():
                    return False
        return True

    def start_watching(self) -> None:
        """Re-render on KV/service changes, applying change modes
        (consul_template.go change-mode dispatch)."""
        self._thread = threading.Thread(target=self._watch_loop,
                                        name="template-watch", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()

    def _watch_loop(self, poll: float = RENDER_POLL) -> None:
        last_gen = self._gen0
        while not self._stop.wait(poll):
            if self.catalog is not None:
                gen = self.catalog.generation()
                if gen == last_gen:
                    continue  # neither KV nor the service set changed
                last_gen = gen
            restart_needed = False
            signals: List[int] = []
            for i, tmpl in enumerate(self.templates):
                try:
                    content = self.render_one(tmpl)
                except MissingDependency:
                    continue  # key deleted: keep the last rendered output
                except Exception as e:
                    # A broken source/render must not kill the watcher —
                    # later changes still need re-render + change modes.
                    self.logger.warning("template render failed: %s", e)
                    continue
                if content == self._rendered.get(i):
                    continue
                if tmpl.splay:
                    # Jittered splay prevents thundering restarts
                    # (consul_template.go splay); bounded for tests.
                    self._stop.wait(min(tmpl.splay, 0.25))
                try:
                    self._write(tmpl, content)
                except OSError as e:
                    self.logger.warning("template write failed: %s", e)
                    continue
                self._rendered[i] = content
                if tmpl.change_mode == s.TEMPLATE_CHANGE_MODE_RESTART:
                    restart_needed = True
                elif tmpl.change_mode == s.TEMPLATE_CHANGE_MODE_SIGNAL:
                    signals.append(parse_signal(tmpl.change_signal))
            if restart_needed and self.on_restart is not None:
                self.on_restart()
            elif signals and self.on_signal is not None:
                for sig in sorted(set(signals)):
                    self.on_signal(sig)
