"""Client state persistence: alloc/task-runner state checkpointed to
disk so a restarted agent re-attaches to its work
(reference: client/client.go:357 bolt state.db,
alloc_runner.go:322 saveAllocRunnerState).

The reference uses boltdb; here each alloc's state is one msgpack file
(whitelisted struct trees via server/log_codec — never pickle, so a
corrupt or attacker-written state file can only inject data, not code)
under ``<state_dir>/allocs/<alloc_id>`` written atomically (tmp+rename),
giving the same crash-safety contract (a partially written state file is
never observed).
"""
from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional

from ..server.log_codec import decode_payload, encode_payload


class StateDB:
    def __init__(self, state_dir: str):
        self.dir = os.path.join(state_dir, "allocs")
        os.makedirs(self.dir, exist_ok=True)
        self._lock = threading.Lock()

    def _path(self, alloc_id: str) -> str:
        return os.path.join(self.dir, alloc_id)

    def put_alloc_runner(self, alloc_id: str, state: Dict) -> None:
        path = self._path(alloc_id)
        tmp = path + ".tmp"
        with self._lock:
            with open(tmp, "wb") as f:
                f.write(encode_payload(state))
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)

    def get_alloc_runner(self, alloc_id: str) -> Optional[Dict]:
        try:
            with open(self._path(alloc_id), "rb") as f:
                return decode_payload(f.read())
        except Exception:
            # Unreadable/corrupt state file == no state (the agent
            # restarts the alloc from the server's view).
            return None

    def list_alloc_runners(self) -> List[str]:
        try:
            return [f for f in os.listdir(self.dir) if not f.endswith(".tmp")]
        except OSError:
            return []

    def delete_alloc_runner(self, alloc_id: str) -> None:
        try:
            os.unlink(self._path(alloc_id))
        except OSError:
            pass
