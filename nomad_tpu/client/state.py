"""Client state persistence: alloc/task-runner state checkpointed to
disk so a restarted agent re-attaches to its work
(reference: client/client.go:357 bolt state.db,
alloc_runner.go:322 saveAllocRunnerState).

The reference uses boltdb; here each alloc's state is one msgpack file
(whitelisted struct trees via server/log_codec — never pickle, so a
corrupt or attacker-written state file can only inject data, not code)
under ``<state_dir>/allocs/<alloc_id>`` written atomically (tmp+rename),
giving the same crash-safety contract (a partially written state file is
never observed).
"""
from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional

from ..server.log_codec import decode_payload, encode_payload


class StateDB:
    def __init__(self, state_dir: str):
        self.dir = os.path.join(state_dir, "allocs")
        os.makedirs(self.dir, exist_ok=True)
        self._lock = threading.Lock()
        # Tmp names carry a writer-thread suffix (".tmp<ident>"), so a
        # crash between write and rename strands one; reap here, before
        # this process has any writer.  Age-gated: a sibling process
        # mid-handover may still be between fsync and rename on its own
        # tmp — deleting that would crash its os.replace — and a live
        # write is milliseconds old, never minutes.
        now = time.time()
        try:
            for f in os.listdir(self.dir):
                if ".tmp" not in f:
                    continue
                p = os.path.join(self.dir, f)
                try:
                    if now - os.path.getmtime(p) > 60.0:
                        os.unlink(p)
                except OSError:
                    pass
        except OSError:
            pass

    def _path(self, alloc_id: str) -> str:
        return os.path.join(self.dir, alloc_id)

    def put_alloc_runner(self, alloc_id: str, state: Dict) -> None:
        # fsync OUTSIDE the lock (the ISSUE 15 lint's lock-blocking
        # rule — the PR 9 fsync-under-lock class): each writer builds a
        # private tmp file and only the atomic rename serializes.
        path = self._path(alloc_id)
        tmp = f"{path}.tmp{threading.get_ident()}"
        with open(tmp, "wb") as f:
            f.write(encode_payload(state))
            f.flush()
            os.fsync(f.fileno())
        with self._lock:
            os.replace(tmp, path)

    def get_alloc_runner(self, alloc_id: str) -> Optional[Dict]:
        try:
            with open(self._path(alloc_id), "rb") as f:
                return decode_payload(f.read())
        except Exception:
            # Unreadable/corrupt state file == no state (the agent
            # restarts the alloc from the server's view).
            return None

    def list_alloc_runners(self) -> List[str]:
        try:
            return [f for f in os.listdir(self.dir) if ".tmp" not in f]
        except OSError:
            return []

    def delete_alloc_runner(self, alloc_id: str) -> None:
        try:
            os.unlink(self._path(alloc_id))
        except OSError:
            pass
