"""Task environment builder: NOMAD_* variables and ``${...}``
interpolation for commands/args/configs
(reference: client/driver/env/env.go:101-630).
"""
from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ...structs import structs as s

# Env var names (env.go:16-100)
ALLOC_DIR = "NOMAD_ALLOC_DIR"
TASK_LOCAL_DIR = "NOMAD_TASK_DIR"
SECRETS_DIR = "NOMAD_SECRETS_DIR"
MEMORY_LIMIT = "NOMAD_MEMORY_LIMIT"
CPU_LIMIT = "NOMAD_CPU_LIMIT"
ALLOC_ID = "NOMAD_ALLOC_ID"
ALLOC_NAME = "NOMAD_ALLOC_NAME"
ALLOC_INDEX = "NOMAD_ALLOC_INDEX"
TASK_NAME = "NOMAD_TASK_NAME"
GROUP_NAME = "NOMAD_GROUP_NAME"
JOB_NAME = "NOMAD_JOB_NAME"
DATACENTER = "NOMAD_DC"
REGION = "NOMAD_REGION"
META_PREFIX = "NOMAD_META_"
ADDR_PREFIX = "NOMAD_ADDR_"
IP_PREFIX = "NOMAD_IP_"
HOST_PORT_PREFIX = "NOMAD_HOST_PORT_"
PORT_PREFIX = "NOMAD_PORT_"
VAULT_TOKEN = "VAULT_TOKEN"

_INTERP = re.compile(r"\$\{([^}]+)\}")


@dataclass
class TaskEnv:
    """Immutable rendered environment (env.go:101 TaskEnv)."""

    env_map: Dict[str, str] = field(default_factory=dict)
    node_attrs: Dict[str, str] = field(default_factory=dict)

    def env(self) -> Dict[str, str]:
        return dict(self.env_map)

    def all(self) -> Dict[str, str]:
        m = dict(self.node_attrs)
        m.update(self.env_map)
        return m

    def replace_env(self, text: Optional[str]) -> Optional[str]:
        """``${var}`` interpolation against env + node attrs
        (env.go:178 ReplaceEnv / helper/args)."""
        if text is None:
            return None
        table = self.all()

        def sub(m: re.Match) -> str:
            return table.get(m.group(1).strip(), "")

        return _INTERP.sub(sub, text)

    def parse_and_replace(self, args: Optional[List[str]]) -> List[str]:
        return [self.replace_env(a) for a in (args or [])]


class Builder:
    """Accumulates job/alloc/task/node facts, then ``build()`` renders the
    TaskEnv (env.go:247 Builder)."""

    def __init__(self):
        self._env: Dict[str, str] = {}
        self._meta: Dict[str, str] = {}
        self._node_attrs: Dict[str, str] = {}
        self._networks: List[s.NetworkResource] = []
        self.task_name = ""
        self.group_name = ""
        self.job_name = ""
        self.alloc_id = ""
        self.alloc_name = ""
        self.alloc_index = -1
        self.datacenter = ""
        self.region = ""
        self.mem_limit = 0
        self.cpu_limit = 0
        self.alloc_dir = ""
        self.local_dir = ""
        self.secrets_dir = ""
        self.vault_token = ""

    # -- fact setters ------------------------------------------------------
    def set_task(self, task: s.Task) -> "Builder":
        self.task_name = task.name
        if task.resources:
            self.mem_limit = task.resources.memory_mb
            self.cpu_limit = task.resources.cpu
            self._networks = [n.copy() for n in (task.resources.networks or [])]
        self._env.update({k: str(v) for k, v in (task.env or {}).items()})
        self._meta.update(task.meta or {})
        return self

    def set_alloc(self, alloc: s.Allocation) -> "Builder":
        self.alloc_id = alloc.id
        self.alloc_name = alloc.name
        self.job_name = alloc.job.name if alloc.job else alloc.job_id
        self.group_name = alloc.task_group
        # alloc index = trailing [N] of "job.group[N]" (structs.go Allocation.Index)
        m = re.search(r"\[(\d+)\]$", alloc.name or "")
        self.alloc_index = int(m.group(1)) if m else -1
        if alloc.job:
            self._meta = {**(alloc.job.meta or {}), **self._meta}
            tg = alloc.job.lookup_task_group(alloc.task_group)
            if tg is not None:
                self._meta.update(tg.meta or {})
        res = (alloc.task_resources or {}).get(self.task_name)
        if res is not None and res.networks:
            self._networks = [n.copy() for n in res.networks]
        return self

    def set_node(self, node: s.Node) -> "Builder":
        self.datacenter = node.datacenter
        attrs = {}
        attrs["node.unique.id"] = node.id
        attrs["node.datacenter"] = node.datacenter
        attrs["node.unique.name"] = node.name
        attrs["node.class"] = node.node_class
        for k, v in (node.attributes or {}).items():
            attrs[f"attr.{k}"] = v
        for k, v in (node.meta or {}).items():
            attrs[f"meta.{k}"] = v
        self._node_attrs.update(attrs)
        return self

    def set_region(self, region: str) -> "Builder":
        self.region = region
        return self

    def set_dirs(self, alloc_dir: str, local_dir: str, secrets_dir: str) -> "Builder":
        self.alloc_dir = alloc_dir
        self.local_dir = local_dir
        self.secrets_dir = secrets_dir
        return self

    def set_vault_token(self, token: str) -> "Builder":
        self.vault_token = token
        return self

    def set_env(self, key: str, value: str) -> "Builder":
        self._env[key] = value
        return self

    # -- rendering ---------------------------------------------------------
    @staticmethod
    def _clean(name: str) -> str:
        return re.sub(r"[^a-zA-Z0-9_]", "_", name)

    def build(self) -> TaskEnv:
        env: Dict[str, str] = {}
        if self.alloc_dir:
            env[ALLOC_DIR] = self.alloc_dir
        if self.local_dir:
            env[TASK_LOCAL_DIR] = self.local_dir
        if self.secrets_dir:
            env[SECRETS_DIR] = self.secrets_dir
        if self.mem_limit:
            env[MEMORY_LIMIT] = str(self.mem_limit)
        if self.cpu_limit:
            env[CPU_LIMIT] = str(self.cpu_limit)
        if self.alloc_id:
            env[ALLOC_ID] = self.alloc_id
        if self.alloc_name:
            env[ALLOC_NAME] = self.alloc_name
        if self.alloc_index >= 0:
            env[ALLOC_INDEX] = str(self.alloc_index)
        if self.task_name:
            env[TASK_NAME] = self.task_name
        if self.group_name:
            env[GROUP_NAME] = self.group_name
        if self.job_name:
            env[JOB_NAME] = self.job_name
        if self.datacenter:
            env[DATACENTER] = self.datacenter
        if self.region:
            env[REGION] = self.region
        if self.vault_token:
            env[VAULT_TOKEN] = self.vault_token

        # Network/port env (env.go:447 buildNetworkEnv)
        for net in self._networks:
            for label, port in net.port_labels().items():
                clean = self._clean(label)
                env[f"{IP_PREFIX}{clean}"] = net.ip
                env[f"{PORT_PREFIX}{clean}"] = str(port)
                env[f"{HOST_PORT_PREFIX}{clean}"] = str(port)
                env[f"{ADDR_PREFIX}{clean}"] = f"{net.ip}:{port}"

        for k, v in self._meta.items():
            env[f"{META_PREFIX}{self._clean(k.upper())}"] = str(v)
            env[f"{META_PREFIX}{self._clean(k)}"] = str(v)

        # Task env block last, interpolated against node attrs + built env
        table = dict(self._node_attrs)
        table.update({f"env.{k}": v for k, v in env.items()})
        table.update(env)
        for k, v in self._env.items():
            env[k] = _INTERP.sub(lambda m: table.get(m.group(1).strip(), ""), v)

        return TaskEnv(env_map=env, node_attrs=dict(self._node_attrs))
