"""Driver framework + builtin drivers (reference: client/driver/)."""

from .driver import (
    BUILTIN_DRIVERS,
    Driver,
    DriverAbilities,
    DriverContext,
    DriverError,
    DriverHandle,
    ExecContext,
    RecoverableError,
    StartResponse,
    WaitResult,
    new_driver,
    register_driver,
    validate_driver_config,
)

__all__ = [
    "BUILTIN_DRIVERS",
    "Driver",
    "DriverAbilities",
    "DriverContext",
    "DriverError",
    "DriverHandle",
    "ExecContext",
    "RecoverableError",
    "StartResponse",
    "WaitResult",
    "new_driver",
    "register_driver",
    "validate_driver_config",
]
