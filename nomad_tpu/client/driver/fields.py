"""Driver-config field schemas (reference: helper/fields FieldSchema +
FieldData, used by every driver's Validate to type-check its task config
map before start)."""

from __future__ import annotations

from typing import Any, Dict, List, Optional


class FieldSchema:
    """One config field: expected type, requiredness."""

    __slots__ = ("type", "required")

    def __init__(self, type: str = "string", required: bool = False):
        self.type = type          # string | int | bool | list | map
        self.required = required


_CHECKS = {
    "string": lambda v: isinstance(v, str),
    "int": lambda v: isinstance(v, int) and not isinstance(v, bool),
    # coercible variants for drivers that cast at start time
    # (helper/fields is similarly WeaklyTyped for HCL-decoded maps):
    "intlike": lambda v: (isinstance(v, int) and not isinstance(v, bool))
    or (isinstance(v, str) and (v.lstrip("-").isdigit() if v else False)),
    "duration": lambda v: isinstance(v, (str, int, float))
    and not isinstance(v, bool),
    "bool": lambda v: isinstance(v, bool) or v in ("true", "false"),
    "boollike": lambda v: isinstance(v, bool) or str(v).lower() in (
        "true", "false", "1", "0", "yes", "no"),
    "list": lambda v: isinstance(v, (list, tuple)),
    "map": lambda v: isinstance(v, dict),
}


def validate_fields(config: Optional[Dict[str, Any]],
                    schema: Dict[str, FieldSchema],
                    strict: bool = False) -> List[str]:
    """Validate a driver config map against its schema
    (helper/fields FieldData.Validate): type mismatches, missing required
    fields, and — when strict — unknown keys.  Returns problems."""
    problems: List[str] = []
    config = config or {}
    if not isinstance(config, dict):
        return ["driver config must be a map"]
    for key, fs in schema.items():
        if key not in config:
            if fs.required:
                problems.append(f"missing required field {key!r}")
            continue
        check = _CHECKS.get(fs.type)
        if check is not None and not check(config[key]):
            problems.append(
                f"field {key!r} must be of type {fs.type}, "
                f"got {type(config[key]).__name__}")
            continue
        if fs.required and fs.type == "string" and config[key] == "":
            problems.append(f"field {key!r} must not be empty")
    if strict:
        for key in config:
            if key not in schema:
                problems.append(f"unknown driver config field {key!r}")
    return problems
