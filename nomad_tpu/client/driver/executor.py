"""Universal executor: runs task processes with stdout/stderr log
rotation, pid tracking, resource stats, and graceful shutdown
(reference: client/driver/executor/executor.go:50-726,
client/driver/logging/rotator.go).

The reference runs this as a go-plugin *subprocess* so tasks survive agent
restarts; here tasks are direct children detached into their own session
(``start_new_session``), and re-attach after agent restart is done by pid
(`attach`), which covers the same restart-survival contract without a
plugin RPC layer.
"""
from __future__ import annotations

import os
import resource
import signal
import subprocess
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .driver import WaitResult


class LogRotator:
    """Size-based rotating file writer
    (reference: client/driver/logging/rotator.go).

    Files are named ``<task>.<stream>.<n>`` under the log dir; at most
    ``max_files`` are kept.
    """

    def __init__(self, log_dir: str, base_name: str,
                 max_files: int = 10, file_size_mb: int = 10):
        self.log_dir = log_dir
        self.base_name = base_name
        self.max_files = max(1, max_files)
        self.max_bytes = file_size_mb * 1024 * 1024
        self._idx = self._initial_index()
        self._fh = None
        self._written = 0
        self._lock = threading.Lock()

    def _path(self, idx: int) -> str:
        return os.path.join(self.log_dir, f"{self.base_name}.{idx}")

    def _initial_index(self) -> int:
        try:
            existing = [
                int(f.rsplit(".", 1)[1])
                for f in os.listdir(self.log_dir)
                if f.startswith(self.base_name + ".") and f.rsplit(".", 1)[1].isdigit()
            ]
        except OSError:
            existing = []
        return max(existing, default=0)

    def _open(self) -> None:
        path = self._path(self._idx)
        self._fh = open(path, "ab")
        self._written = self._fh.tell()

    def write(self, data: bytes) -> None:
        with self._lock:
            if self._fh is None:
                self._open()
            if self._written + len(data) > self.max_bytes:
                self._fh.close()
                self._idx += 1
                self._open()
                self._purge()
            self._fh.write(data)
            self._fh.flush()
            self._written += len(data)

    def _purge(self) -> None:
        lo = self._idx - self.max_files + 1
        for f in os.listdir(self.log_dir):
            if f.startswith(self.base_name + "."):
                tail = f.rsplit(".", 1)[1]
                if tail.isdigit() and int(tail) < lo:
                    try:
                        os.unlink(os.path.join(self.log_dir, f))
                    except OSError:
                        pass

    def close(self) -> None:
        with self._lock:
            if self._fh:
                self._fh.close()
                self._fh = None


@dataclass
class ExecCommand:
    """(executor.go ExecCommand)."""

    cmd: str
    args: List[str] = field(default_factory=list)
    env: Dict[str, str] = field(default_factory=dict)
    cwd: str = ""
    task_name: str = "task"
    log_dir: str = ""
    max_log_files: int = 10
    max_log_file_size_mb: int = 10
    cpu_limit: int = 0        # MHz ask — cpu.shares/weight when cgroups apply
    memory_limit_mb: int = 0  # cgroup memory limit; RLIMIT_AS fallback
    user: str = ""
    use_cgroups: bool = False  # exec-family isolation (executor_linux.go)
    cgroup_name: str = ""


class Executor:
    """Runs one task process (reference: executor.go:50 UniversalExecutor)."""

    def __init__(self, command: ExecCommand):
        self.command = command
        self.proc: Optional[subprocess.Popen] = None
        self.pid = 0
        self.start_time = 0.0
        self.exited = threading.Event()
        self.result: Optional[WaitResult] = None
        self._out_rot: Optional[LogRotator] = None
        self._err_rot: Optional[LogRotator] = None
        self._pumps: List[threading.Thread] = []
        self.cgroup = None

    # -- lifecycle ---------------------------------------------------------
    def launch(self) -> int:
        c = self.command
        stdout = stderr = subprocess.DEVNULL
        if c.log_dir:
            os.makedirs(c.log_dir, exist_ok=True)
            self._out_rot = LogRotator(c.log_dir, f"{c.task_name}.stdout",
                                       c.max_log_files, c.max_log_file_size_mb)
            self._err_rot = LogRotator(c.log_dir, f"{c.task_name}.stderr",
                                       c.max_log_files, c.max_log_file_size_mb)
            stdout = stderr = subprocess.PIPE

        # Isolation: cgroup limits when requested and the host allows
        # (executor_linux.go configureCgroups); RLIMIT_AS fallback keeps
        # a memory bound on hosts without cgroups.
        use_rlimit = c.memory_limit_mb > 0
        if c.use_cgroups:
            from . import cgroups

            if cgroups.available():
                self.cgroup = cgroups.TaskCgroup(
                    c.cgroup_name or f"{c.task_name}-{os.getpid()}",
                    cpu_mhz=c.cpu_limit, memory_mb=c.memory_limit_mb)
                if self.cgroup.create():
                    use_rlimit = False
                else:
                    self.cgroup = None

        cg_paths = list(self.cgroup.paths) if self.cgroup is not None else []

        def preexec():
            # Join the cgroup BEFORE exec so nothing the task forks can
            # escape it (executor_linux.go joins pre-exec); if the join
            # fails, fall back to RLIMIT_AS in-child.
            joined = False
            for path in cg_paths:
                try:
                    with open(os.path.join(path, "cgroup.procs"), "w") as fh:
                        fh.write(str(os.getpid()))
                    joined = True
                except OSError:
                    pass
            if (use_rlimit or (cg_paths and not joined)) \
                    and c.memory_limit_mb > 0:
                lim = c.memory_limit_mb * 1024 * 1024
                try:
                    resource.setrlimit(resource.RLIMIT_AS, (lim, lim))
                except (ValueError, OSError):
                    pass

        self.proc = subprocess.Popen(
            [c.cmd] + list(c.args),
            env=c.env or None,
            cwd=c.cwd or None,
            stdout=stdout,
            stderr=stderr,
            start_new_session=True,
            preexec_fn=preexec,
        )
        self.pid = self.proc.pid
        self.start_time = time.time()
        if self._out_rot:
            self._pumps = [
                threading.Thread(target=self._pump, args=(self.proc.stdout, self._out_rot),
                                 daemon=True),
                threading.Thread(target=self._pump, args=(self.proc.stderr, self._err_rot),
                                 daemon=True),
            ]
            for t in self._pumps:
                t.start()
        threading.Thread(target=self._wait, daemon=True).start()
        return self.pid

    @staticmethod
    def _pump(stream, rot: LogRotator) -> None:
        try:
            for chunk in iter(lambda: stream.read(8192), b""):
                rot.write(chunk)
        except (OSError, ValueError):
            pass
        finally:
            rot.close()

    def _wait(self) -> None:
        rc = self.proc.wait()
        for t in self._pumps:
            t.join(timeout=2.0)
        if rc < 0:
            self.result = WaitResult(exit_code=0, signal=-rc)
        else:
            self.result = WaitResult(exit_code=rc)
        if self.cgroup is not None:
            # Reap stragglers the task forked, then remove the group
            # (executor_linux.go destroyCgroup).
            self.cgroup.destroy()
            self.cgroup = None
        self.exited.set()

    # -- control -----------------------------------------------------------
    def shutdown(self, grace: float = 5.0) -> None:
        """SIGINT → grace → SIGKILL the whole process group
        (executor.go Exit/ShutDown)."""
        if self.proc is None or self.result is not None:
            return
        try:
            os.killpg(self.pid, signal.SIGINT)
        except (ProcessLookupError, PermissionError, OSError):
            return
        if not self.exited.wait(grace):
            try:
                os.killpg(self.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError, OSError):
                pass

    def send_signal(self, sig: int) -> None:
        if self.proc is not None and self.result is None:
            os.kill(self.pid, sig)

    def stats(self) -> Dict:
        """Resource usage snapshot (executor.go:643 collectPids/stats)."""
        try:
            with open(f"/proc/{self.pid}/stat", "rb") as f:
                parts = f.read().split()
            utime, stime = int(parts[13]), int(parts[14])
            rss_pages = int(parts[23])
            hz = os.sysconf("SC_CLK_TCK")
            page = os.sysconf("SC_PAGE_SIZE")
            return {
                "pid": self.pid,
                "cpu_seconds": (utime + stime) / hz,
                "rss_bytes": rss_pages * page,
                "uptime": time.time() - self.start_time,
            }
        except (OSError, IndexError, ValueError):
            return {"pid": self.pid}


def attach(pid: int) -> Optional["AttachedExecutor"]:
    """Re-attach to a still-running task process after agent restart
    (reference: executor plugin re-connect, task_runner.go:279)."""
    try:
        os.kill(pid, 0)
    except (ProcessLookupError, PermissionError):
        return None
    return AttachedExecutor(pid)


class AttachedExecutor(Executor):
    """Executor recovered by pid: can signal/kill/poll but not re-collect
    the exit code (the reaper lost it across the restart) — reports exit 0
    when the pid disappears, like the reference's best-effort re-attach."""

    def __init__(self, pid: int):
        super().__init__(ExecCommand(cmd=""))
        self.pid = pid
        self.start_time = time.time()
        threading.Thread(target=self._poll, daemon=True).start()

    def _poll(self) -> None:
        while True:
            try:
                os.kill(self.pid, 0)
            except (ProcessLookupError, PermissionError):
                self.result = WaitResult(exit_code=0)
                self.exited.set()
                return
            time.sleep(1.0)

    def shutdown(self, grace: float = 5.0) -> None:
        if self.result is not None:
            return
        try:
            os.killpg(self.pid, signal.SIGINT)
        except (ProcessLookupError, PermissionError, OSError):
            try:
                os.kill(self.pid, signal.SIGINT)
            except (ProcessLookupError, PermissionError, OSError):
                return
        if not self.exited.wait(grace):
            try:
                os.killpg(self.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError, OSError):
                pass
