"""Universal executor: runs task processes with stdout/stderr log
rotation, pid tracking, resource stats, and graceful shutdown
(reference: client/driver/executor/executor.go:50-726,
client/driver/logging/rotator.go).

The reference runs this as a go-plugin *subprocess* so tasks survive agent
restarts; here tasks are direct children detached into their own session
(``start_new_session``), and re-attach after agent restart is done by pid
(`attach`), which covers the same restart-survival contract without a
plugin RPC layer.
"""
from __future__ import annotations

import os
import resource
import signal
import subprocess
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from .driver import WaitResult


class LogRotator:
    """Size-based rotating file writer
    (reference: client/driver/logging/rotator.go).

    Files are named ``<task>.<stream>.<n>`` under the log dir; at most
    ``max_files`` are kept.
    """

    def __init__(self, log_dir: str, base_name: str,
                 max_files: int = 10, file_size_mb: int = 10):
        self.log_dir = log_dir
        self.base_name = base_name
        self.max_files = max(1, max_files)
        self.max_bytes = file_size_mb * 1024 * 1024
        self._idx = self._initial_index()
        self._fh = None
        self._written = 0
        self._lock = threading.Lock()

    def _path(self, idx: int) -> str:
        return os.path.join(self.log_dir, f"{self.base_name}.{idx}")

    def _initial_index(self) -> int:
        try:
            existing = [
                int(f.rsplit(".", 1)[1])
                for f in os.listdir(self.log_dir)
                if f.startswith(self.base_name + ".") and f.rsplit(".", 1)[1].isdigit()
            ]
        except OSError:
            existing = []
        return max(existing, default=0)

    def _open(self) -> None:
        path = self._path(self._idx)
        self._fh = open(path, "ab")
        self._written = self._fh.tell()

    def write(self, data: bytes) -> None:
        with self._lock:
            if self._fh is None:
                self._open()
            if self._written + len(data) > self.max_bytes:
                self._fh.close()
                self._idx += 1
                self._open()
                self._purge()
            self._fh.write(data)
            self._fh.flush()
            self._written += len(data)

    def _purge(self) -> None:
        lo = self._idx - self.max_files + 1
        for f in os.listdir(self.log_dir):
            if f.startswith(self.base_name + "."):
                tail = f.rsplit(".", 1)[1]
                if tail.isdigit() and int(tail) < lo:
                    try:
                        os.unlink(os.path.join(self.log_dir, f))
                    except OSError:
                        pass

    def close(self) -> None:
        with self._lock:
            if self._fh:
                self._fh.close()
                self._fh = None


@dataclass
class ExecCommand:
    """(executor.go ExecCommand)."""

    cmd: str
    args: List[str] = field(default_factory=list)
    env: Dict[str, str] = field(default_factory=dict)
    cwd: str = ""
    task_name: str = "task"
    log_dir: str = ""
    max_log_files: int = 10
    max_log_file_size_mb: int = 10
    cpu_limit: int = 0        # MHz ask — cpu.shares/weight when cgroups apply
    memory_limit_mb: int = 0  # cgroup memory limit; RLIMIT_AS fallback
    user: str = ""
    use_cgroups: bool = False  # exec-family isolation (executor_linux.go)
    cgroup_name: str = ""


class Executor:
    """Runs one task process (reference: executor.go:50 UniversalExecutor)."""

    def __init__(self, command: ExecCommand):
        self.command = command
        self.proc: Optional[subprocess.Popen] = None
        self.pid = 0
        self.start_time = 0.0
        self.exited = threading.Event()
        self.result: Optional[WaitResult] = None
        self._out_rot: Optional[LogRotator] = None
        self._err_rot: Optional[LogRotator] = None
        self._pumps: List[threading.Thread] = []
        self.cgroup = None

    # -- lifecycle ---------------------------------------------------------
    def launch(self) -> int:
        c = self.command
        stdout = stderr = subprocess.DEVNULL
        if c.log_dir:
            os.makedirs(c.log_dir, exist_ok=True)
            self._out_rot = LogRotator(c.log_dir, f"{c.task_name}.stdout",
                                       c.max_log_files, c.max_log_file_size_mb)
            self._err_rot = LogRotator(c.log_dir, f"{c.task_name}.stderr",
                                       c.max_log_files, c.max_log_file_size_mb)
            stdout = stderr = subprocess.PIPE

        # Isolation: cgroup limits when requested and the host allows
        # (executor_linux.go configureCgroups); RLIMIT_AS fallback keeps
        # a memory bound on hosts without cgroups.
        use_rlimit = c.memory_limit_mb > 0
        if c.use_cgroups:
            from . import cgroups

            if cgroups.available():
                self.cgroup = cgroups.TaskCgroup(
                    c.cgroup_name or f"{c.task_name}-{os.getpid()}",
                    cpu_mhz=c.cpu_limit, memory_mb=c.memory_limit_mb)
                if self.cgroup.create():
                    use_rlimit = False
                else:
                    self.cgroup = None

        cg_paths = list(self.cgroup.paths) if self.cgroup is not None else []

        def preexec():
            # Join the cgroup BEFORE exec so nothing the task forks can
            # escape it (executor_linux.go joins pre-exec); if the join
            # fails, fall back to RLIMIT_AS in-child.
            joined = False
            for path in cg_paths:
                try:
                    with open(os.path.join(path, "cgroup.procs"), "w") as fh:
                        fh.write(str(os.getpid()))
                    joined = True
                except OSError:
                    pass
            if (use_rlimit or (cg_paths and not joined)) \
                    and c.memory_limit_mb > 0:
                lim = c.memory_limit_mb * 1024 * 1024
                try:
                    resource.setrlimit(resource.RLIMIT_AS, (lim, lim))
                except (ValueError, OSError):
                    pass

        self.proc = subprocess.Popen(
            [c.cmd] + list(c.args),
            env=c.env or None,
            cwd=c.cwd or None,
            stdout=stdout,
            stderr=stderr,
            start_new_session=True,
            preexec_fn=preexec,
        )
        self.pid = self.proc.pid
        self.start_time = time.time()
        if self._out_rot:
            self._pumps = [
                threading.Thread(target=self._pump, args=(self.proc.stdout, self._out_rot),
                                 daemon=True),
                threading.Thread(target=self._pump, args=(self.proc.stderr, self._err_rot),
                                 daemon=True),
            ]
            for t in self._pumps:
                t.start()
        threading.Thread(target=self._wait, daemon=True).start()
        return self.pid

    @staticmethod
    def _pump(stream, rot: LogRotator) -> None:
        try:
            for chunk in iter(lambda: stream.read(8192), b""):
                rot.write(chunk)
        except (OSError, ValueError):
            pass
        finally:
            rot.close()

    def _wait(self) -> None:
        rc = self.proc.wait()
        for t in self._pumps:
            t.join(timeout=2.0)
        if rc < 0:
            self.result = WaitResult(exit_code=0, signal=-rc)
        else:
            self.result = WaitResult(exit_code=rc)
        if self.cgroup is not None:
            # Reap stragglers the task forked, then remove the group
            # (executor_linux.go destroyCgroup).
            self.cgroup.destroy()
            self.cgroup = None
        self.exited.set()

    # -- control -----------------------------------------------------------
    def shutdown(self, grace: float = 5.0) -> None:
        """SIGINT → grace → SIGKILL the whole process group
        (executor.go Exit/ShutDown)."""
        if self.proc is None or self.result is not None:
            return
        try:
            os.killpg(self.pid, signal.SIGINT)
        except (ProcessLookupError, PermissionError, OSError):
            return
        if not self.exited.wait(grace):
            try:
                os.killpg(self.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError, OSError):
                pass

    def send_signal(self, sig: int) -> None:
        if self.proc is not None and self.result is None:
            os.kill(self.pid, sig)

    def stats(self) -> Dict:
        """Resource usage snapshot (executor.go:643 collectPids/stats)."""
        try:
            with open(f"/proc/{self.pid}/stat", "rb") as f:
                parts = f.read().split()
            utime, stime = int(parts[13]), int(parts[14])
            rss_pages = int(parts[23])
            hz = os.sysconf("SC_CLK_TCK")
            page = os.sysconf("SC_PAGE_SIZE")
            return {
                "pid": self.pid,
                "cpu_seconds": (utime + stime) / hz,
                "rss_bytes": rss_pages * page,
                "uptime": time.time() - self.start_time,
            }
        except (OSError, IndexError, ValueError):
            return {"pid": self.pid}


class SupervisedExecutor(Executor):
    """Runs the task under a DETACHED supervisor subprocess
    (driver/supervisor.py ≙ the reference's go-plugin executor,
    client/driver/executor_plugin.go): the agent can die and restart and
    the supervisor keeps running the task, serving control on a unix
    socket and persisting the exit status to disk — so re-attach
    re-collects the real exit code, not a best-effort guess."""

    def __init__(self, command: ExecCommand, ctl_dir: str):
        super().__init__(command)
        self.ctl_dir = ctl_dir
        self.supervisor_pid = 0
        self._sup_proc = None  # Popen when we spawned it (enables reaping)

    def launch(self) -> int:
        import json
        import sys

        from . import supervisor as sup

        os.makedirs(self.ctl_dir, exist_ok=True)
        with open(os.path.join(self.ctl_dir, "command.json"), "w") as fh:
            json.dump(self.command.__dict__, fh)
        # The supervisor needs the package importable regardless of the
        # agent's own cwd.
        pkg_root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__)))))
        env = dict(os.environ)
        env["PYTHONPATH"] = pkg_root + (
            os.pathsep + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
        proc = subprocess.Popen(
            [sys.executable, "-m", "nomad_tpu.client.driver.supervisor",
             self.ctl_dir],
            env=env, stdin=subprocess.DEVNULL, stdout=subprocess.DEVNULL,
            stderr=subprocess.DEVNULL, start_new_session=True)
        self.supervisor_pid = proc.pid
        self._sup_proc = proc
        # Wait for the task pid (or an immediate launch failure).  The
        # supervisor is a fresh interpreter: its startup alone costs
        # 2-4s on this image (jax pre-import), and full-suite load can
        # multiply that — a 15s bound flaked roughly once per suite run.
        pid_path = os.path.join(self.ctl_dir, "task.pid")
        deadline = time.time() + 45.0
        while time.time() < deadline:
            if os.path.exists(pid_path):
                with open(pid_path) as fh:
                    self.pid = json.load(fh)["pid"]
                break
            if os.path.exists(sup.exit_path(self.ctl_dir)):
                break  # launch failed; watcher delivers the error result
            if proc.poll() is not None and not os.path.exists(pid_path):
                raise OSError(
                    f"supervisor exited rc={proc.returncode} before launch")
            time.sleep(0.02)
        else:
            raise OSError("timed out waiting for supervised task launch")
        self.start_time = time.time()
        threading.Thread(target=self._watch, daemon=True).start()
        return self.pid

    # -- result collection -------------------------------------------------

    def _watch(self) -> None:
        """Block on the supervisor's wait op; fall back to polling
        exit.json if the socket goes away (supervisor reaped after
        persisting the status).

        The degraded guess (exit 0, no record) is a LAST resort: the task
        pid looking dead does not mean the status is lost — the task is
        the supervisor's child, so the pid only becomes signalable-dead
        after the supervisor reaps it, at which point the supervisor is
        about to persist exit.json (pump joins + fsync in between).
        Degrading while the supervisor is still alive fabricates an exit 0
        before logs are flushed (VERDICT r3 weak-3), so only give up once
        the supervisor itself is gone AND a grace period for a straggling
        exit.json write has passed."""
        import json

        from . import supervisor as sup

        sup_gone_since = None
        while True:
            try:
                resp = sup.request(self.ctl_dir, {"op": "wait"}, timeout=None)
                res = resp["result"]
                self.result = WaitResult(exit_code=res["exit_code"],
                                         signal=res["signal"])
                self.exited.set()
                return
            except (OSError, KeyError, ValueError):
                pass
            ep = sup.exit_path(self.ctl_dir)
            if os.path.exists(ep):
                with open(ep) as fh:
                    res = json.load(fh)
                self.result = WaitResult(exit_code=res.get("exit_code", 0),
                                         signal=res.get("signal", 0))
                self.exited.set()
                return
            if self._supervisor_alive():
                sup_gone_since = None
            elif sup_gone_since is None:
                sup_gone_since = time.monotonic()
            elif time.monotonic() - sup_gone_since > 2.0:
                # Supervisor dead >2s and still no exit record: the status
                # really is lost — degrade like a pid re-attach.
                self.result = WaitResult(exit_code=0)
                self.exited.set()
                return
            time.sleep(0.25)

    def _supervisor_alive(self) -> bool:
        import json

        if self._sup_proc is not None:
            # We spawned it: poll() both reaps a zombie (which os.kill
            # would misreport as alive forever) and answers liveness.
            return self._sup_proc.poll() is None
        pid = self.supervisor_pid
        if not pid:
            try:
                with open(os.path.join(self.ctl_dir,
                                       "supervisor.pid")) as fh:
                    pid = json.load(fh)["pid"]
            except (OSError, ValueError, KeyError):
                return False
        try:
            os.kill(pid, 0)
            return True
        except PermissionError:
            return True
        except OSError:
            return False

    # -- control (socket first, direct-signal fallback) --------------------

    def shutdown(self, grace: float = 5.0) -> None:
        from . import supervisor as sup

        if self.result is not None:
            return
        try:
            sup.request(self.ctl_dir, {"op": "shutdown", "grace": grace})
            self.exited.wait(grace + 5.0)
            return
        except (OSError, ValueError):
            pass
        if self.pid:
            try:
                os.killpg(self.pid, signal.SIGINT)
            except (ProcessLookupError, PermissionError, OSError):
                return
            if not self.exited.wait(grace):
                try:
                    os.killpg(self.pid, signal.SIGKILL)
                except (ProcessLookupError, PermissionError, OSError):
                    pass

    def send_signal(self, sig: int) -> None:
        from . import supervisor as sup

        try:
            sup.request(self.ctl_dir, {"op": "signal", "sig": sig})
        except (OSError, ValueError):
            if self.pid and self.result is None:
                os.kill(self.pid, sig)

    def stats(self) -> Dict:
        from . import supervisor as sup

        try:
            return sup.request(self.ctl_dir, {"op": "stats"})["stats"]
        except (OSError, KeyError, ValueError):
            return super().stats()


def attach_supervised(ctl_dir: str) -> Optional["SupervisedExecutor"]:
    """Re-attach to a supervised task after agent restart: the exit
    status persisted by the supervisor (exit.json) makes collection
    exact even when the task finished while the agent was down."""
    import json

    from . import supervisor as sup

    if not os.path.isdir(ctl_dir):
        return None
    ex = SupervisedExecutor(ExecCommand(cmd=""), ctl_dir)
    pid_path = os.path.join(ctl_dir, "task.pid")
    if os.path.exists(pid_path):
        try:
            with open(pid_path) as fh:
                ex.pid = json.load(fh)["pid"]
        except (OSError, ValueError, KeyError):
            pass
    ep = sup.exit_path(ctl_dir)
    live = False
    if not os.path.exists(ep):
        try:
            resp = sup.request(ctl_dir, {"op": "ping"}, timeout=2.0)
            live = bool(resp.get("ok"))
        except (OSError, ValueError):
            live = False
        if not live and ex.pid:
            try:
                os.kill(ex.pid, 0)
            except (ProcessLookupError, PermissionError):
                return None  # no record, no task: nothing to re-attach
    ex.start_time = time.time()
    threading.Thread(target=ex._watch, daemon=True).start()
    return ex


def attach(pid: int) -> Optional["AttachedExecutor"]:
    """Re-attach to a still-running task process after agent restart
    (reference: executor plugin re-connect, task_runner.go:279)."""
    try:
        os.kill(pid, 0)
    except (ProcessLookupError, PermissionError):
        return None
    return AttachedExecutor(pid)


class AttachedExecutor(Executor):
    """Executor recovered by pid: can signal/kill/poll but not re-collect
    the exit code (the reaper lost it across the restart) — reports exit 0
    when the pid disappears, like the reference's best-effort re-attach."""

    def __init__(self, pid: int):
        super().__init__(ExecCommand(cmd=""))
        self.pid = pid
        self.start_time = time.time()
        threading.Thread(target=self._poll, daemon=True).start()

    def _poll(self) -> None:
        while True:
            try:
                os.kill(self.pid, 0)
            except (ProcessLookupError, PermissionError):
                self.result = WaitResult(exit_code=0)
                self.exited.set()
                return
            time.sleep(1.0)

    def shutdown(self, grace: float = 5.0) -> None:
        if self.result is not None:
            return
        try:
            os.killpg(self.pid, signal.SIGINT)
        except (ProcessLookupError, PermissionError, OSError):
            try:
                os.kill(self.pid, signal.SIGINT)
            except (ProcessLookupError, PermissionError, OSError):
                return
        if not self.exited.wait(grace):
            try:
                os.killpg(self.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError, OSError):
                pass
