"""Process-executing drivers: raw_exec, exec, java, qemu
(reference: client/driver/raw_exec.go, exec.go + exec_linux.go,
java.go, qemu.go).

All four share the Executor; they differ in how the command line is
assembled and how availability is fingerprinted.  The reference's `exec`
driver isolates via cgroups+chroot; here `exec` runs inside the task dir
with an RLIMIT_AS memory cap — the strongest isolation available without
root — while `raw_exec` runs with no isolation, exactly as the reference
distinguishes them.
"""
from __future__ import annotations

import os
import subprocess
import threading
from typing import Dict, List, Optional

from ...structs import structs as s
from .fields import FieldSchema
from .driver import (
    Driver,
    DriverAbilities,
    DriverError,
    DriverHandle,
    ExecContext,
    FS_ISOLATION_CHROOT,
    FS_ISOLATION_NONE,
    StartResponse,
    WaitResult,
    find_executable,
    opt,
    register_driver,
)
from .executor import (
    AttachedExecutor,
    ExecCommand,
    Executor,
    SupervisedExecutor,
    attach,
    attach_supervised,
)


class ExecutorHandle(DriverHandle):
    """Wraps a live Executor (reference: raw_exec.go rawExecHandle)."""

    def __init__(self, executor: Executor, task_name: str, kill_timeout: float):
        self.executor = executor
        self.task_name = task_name
        self.kill_timeout = kill_timeout or 5.0

    def id(self) -> str:
        ctl = getattr(self.executor, "ctl_dir", None)
        if ctl:
            return f"sup:{ctl}"
        return f"pid:{self.executor.pid}"

    def wait_ch(self) -> threading.Event:
        return self.executor.exited

    def wait_result(self) -> WaitResult:
        self.executor.exited.wait()
        return self.executor.result

    def update(self, task: s.Task) -> None:
        self.kill_timeout = task.kill_timeout or self.kill_timeout

    def kill(self) -> None:
        self.executor.shutdown(grace=self.kill_timeout)

    def signal(self, sig: int) -> None:
        self.executor.send_signal(sig)

    def exec_cmd(self, cmd: str, args: List[str]):
        try:
            out = subprocess.run([cmd] + args, capture_output=True, timeout=30)
            return (out.stdout + out.stderr, out.returncode)
        except (OSError, subprocess.SubprocessError) as e:
            return (str(e).encode(), 1)

    def stats(self) -> Dict:
        return self.executor.stats()


class _ExecFamilyDriver(Driver):
    """Shared start path for raw_exec/exec/java/qemu."""

    # cgroup isolation for the family (executor_linux.go); raw_exec opts
    # out to run unisolated like the reference.
    use_cgroups = True

    name = ""
    isolation = FS_ISOLATION_NONE
    enforce_memory = False

    def abilities(self) -> DriverAbilities:
        return DriverAbilities(send_signals=True, exec=True)

    def fs_isolation(self) -> str:
        return self.isolation

    def command_line(self, exec_ctx: ExecContext, task: s.Task) -> tuple[str, List[str]]:
        cfg = task.config or {}
        command = opt(cfg, "command", "")
        if not command:
            raise DriverError(f"missing 'command' in {self.name} driver config")
        args = [str(a) for a in opt(cfg, "args", []) or []]
        env = exec_ctx.task_env
        return env.replace_env(command), env.parse_and_replace(args)

    CONFIG_FIELDS = {
        "command": FieldSchema("string", required=True),
        "args": FieldSchema("list"),
    }

    def ctl_dir(self, exec_ctx: ExecContext, task_name: str) -> str:
        """The supervisor control dir for a task (one place owns the
        naming convention; LxcDriver reads it pre-launch)."""
        return os.path.join(exec_ctx.task_dir.dir, f".{task_name}.executor")

    def start(self, exec_ctx: ExecContext, task: s.Task) -> StartResponse:
        cmd, args = self.command_line(exec_ctx, task)
        td = exec_ctx.task_dir
        resolved = find_executable(cmd) or os.path.join(td.dir, cmd)
        exec_cmd = ExecCommand(
            cmd=resolved,
            args=args,
            env=exec_ctx.task_env.env(),
            cwd=td.dir,
            task_name=task.name,
            log_dir=td.log_dir,
            max_log_files=task.log_config.max_files if task.log_config else 10,
            max_log_file_size_mb=(
                task.log_config.max_file_size_mb if task.log_config else 10),
            memory_limit_mb=(
                task.resources.memory_mb
                if (self.enforce_memory and task.resources) else 0),
            cpu_limit=(task.resources.cpu if task.resources else 0),
            # exec-family isolation (exec_linux.go): cgroups when the
            # host allows; raw_exec opts out by design (raw_exec.go).
            use_cgroups=self.use_cgroups,
            cgroup_name=f"{self.ctx.alloc_id[:8]}-{task.name}",
        )
        # Every exec-family task runs under a detached supervisor
        # subprocess (driver/supervisor.py ≙ executor_plugin.go): the
        # agent can restart and re-attach with the real exit status.
        executor = SupervisedExecutor(exec_cmd,
                                      self.ctl_dir(exec_ctx, task.name))
        try:
            executor.launch()
        except OSError as e:
            raise DriverError(f"failed to launch {resolved}: {e}") from e
        return StartResponse(
            handle=ExecutorHandle(executor, task.name, task.kill_timeout))

    def open(self, exec_ctx: ExecContext, handle_id: str) -> DriverHandle:
        if handle_id.startswith("sup:"):
            ex = attach_supervised(handle_id.split(":", 1)[1])
            if ex is None:
                raise DriverError(f"supervised task gone: {handle_id!r}")
            return ExecutorHandle(ex, "reattached", 5.0)
        if not handle_id.startswith("pid:"):
            raise DriverError(f"bad handle id {handle_id!r}")
        pid = int(handle_id.split(":", 1)[1])
        ex = attach(pid)
        if ex is None:
            raise DriverError(f"process {pid} not running")
        return ExecutorHandle(ex, "reattached", 5.0)


class RawExecDriver(_ExecFamilyDriver):
    """(raw_exec.go) — no isolation; must be enabled explicitly via client
    option ``driver.raw_exec.enable``."""

    use_cgroups = False

    name = "raw_exec"
    isolation = FS_ISOLATION_NONE

    def fingerprint(self, node: s.Node) -> bool:
        options = getattr(self.ctx.config, "options", {}) or {}
        if str(options.get("driver.raw_exec.enable", "")).lower() in ("1", "true"):
            node.attributes["driver.raw_exec"] = "1"
            return True
        node.attributes.pop("driver.raw_exec", None)
        return False


class ExecDriver(_ExecFamilyDriver):
    """(exec.go / exec_linux.go) — isolated exec; linux only."""

    name = "exec"
    isolation = FS_ISOLATION_CHROOT
    enforce_memory = True

    def fingerprint(self, node: s.Node) -> bool:
        if os.name != "posix" or not os.path.isdir("/proc"):
            return False
        node.attributes["driver.exec"] = "1"
        return True


class JavaDriver(_ExecFamilyDriver):
    """(java.go) — runs jars via the JVM."""

    name = "java"
    enforce_memory = True

    CONFIG_FIELDS = {
        "jar_path": FieldSchema("string"),
        "class": FieldSchema("string"),
        "class_path": FieldSchema("string"),
        "jvm_options": FieldSchema("list"),
        "args": FieldSchema("list"),
    }

    def validate(self, config) -> None:
        super().validate(config)
        if not (config or {}).get("jar_path") and not (config or {}).get("class"):
            raise ValueError("missing 'jar_path' or 'class'")

    def command_line(self, exec_ctx: ExecContext, task: s.Task):
        cfg = task.config or {}
        env = exec_ctx.task_env
        args: List[str] = [str(a) for a in opt(cfg, "jvm_options", []) or []]
        jar = opt(cfg, "jar_path", "")
        if jar:
            args += ["-jar", env.replace_env(jar)]
        else:
            cls = opt(cfg, "class", "")
            if not cls:
                raise DriverError("missing 'jar_path' or 'class' in java config")
            cp = opt(cfg, "class_path", "")
            if cp:
                args += ["-cp", env.replace_env(cp)]
            args.append(cls)
        args += env.parse_and_replace([str(a) for a in opt(cfg, "args", []) or []])
        return "java", args

    def fingerprint(self, node: s.Node) -> bool:
        path = find_executable("java")
        if not path:
            node.attributes.pop("driver.java", None)
            return False
        node.attributes["driver.java"] = "1"
        try:
            out = subprocess.run(["java", "-version"], capture_output=True,
                                 timeout=10).stderr.decode()
            first = out.splitlines()[0] if out else ""
            if '"' in first:
                node.attributes["driver.java.version"] = first.split('"')[1]
        except (OSError, subprocess.SubprocessError):
            pass
        return True


class QemuDriver(_ExecFamilyDriver):
    """(qemu.go) — boots VM images via qemu-system-x86_64."""

    name = "qemu"
    isolation = "image"

    CONFIG_FIELDS = {
        "image_path": FieldSchema("string", required=True),
        "accelerator": FieldSchema("string"),
        "args": FieldSchema("list"),
        "port_map": FieldSchema("map"),
    }

    def command_line(self, exec_ctx: ExecContext, task: s.Task):
        cfg = task.config or {}
        env = exec_ctx.task_env
        image = env.replace_env(opt(cfg, "image_path", ""))
        mem = task.resources.memory_mb if task.resources else 128
        args = ["-machine", "type=pc,accel=" + opt(cfg, "accelerator", "tcg"),
                "-name", task.name, "-m", f"{mem}M",
                "-drive", f"file={image}", "-nographic"]
        for extra in opt(cfg, "args", []) or []:
            args.append(env.replace_env(str(extra)))
        return "qemu-system-x86_64", args

    def fingerprint(self, node: s.Node) -> bool:
        path = find_executable("qemu-system-x86_64")
        if not path:
            node.attributes.pop("driver.qemu", None)
            return False
        node.attributes["driver.qemu"] = "1"
        return True


class DockerDriver(_ExecFamilyDriver):
    """(docker.go) — container tasks via the docker CLI when present.

    The reference speaks the docker API; driving the CLI keeps the same
    user-visible contract (image pull, port map, run, stop) without a
    vendored API client.
    """

    name = "docker"
    isolation = "image"

    CONFIG_FIELDS = {
        "image": FieldSchema("string", required=True),
        "command": FieldSchema("string"),
        "args": FieldSchema("list"),
        "port_map": FieldSchema("map"),
        "network_mode": FieldSchema("string"),
        "labels": FieldSchema("map"),
    }

    def command_line(self, exec_ctx: ExecContext, task: s.Task):
        cfg = task.config or {}
        env = exec_ctx.task_env
        image = env.replace_env(opt(cfg, "image", ""))
        name = f"nomad-{task.name}-{os.path.basename(exec_ctx.task_dir.dir)}"
        args = ["run", "--rm", "--name", name]
        for k, v in exec_ctx.task_env.env().items():
            args += ["-e", f"{k}={v}"]
        if task.resources and task.resources.memory_mb:
            args += ["--memory", f"{task.resources.memory_mb}m"]
        cmd_override = opt(cfg, "command", "")
        args.append(image)
        if cmd_override:
            args.append(env.replace_env(cmd_override))
            args += env.parse_and_replace(
                [str(a) for a in opt(cfg, "args", []) or []])
        return "docker", args

    def fingerprint(self, node: s.Node) -> bool:
        path = find_executable("docker")
        if not path:
            node.attributes.pop("driver.docker", None)
            return False
        try:
            out = subprocess.run(["docker", "version", "--format",
                                  "{{.Server.Version}}"],
                                 capture_output=True, timeout=5)
            if out.returncode != 0:
                return False
            node.attributes["driver.docker"] = "1"
            node.attributes["driver.docker.version"] = out.stdout.decode().strip()
            return True
        except (OSError, subprocess.SubprocessError):
            return False

    def periodic(self):
        return (True, 30.0)


def _docker_factory(ctx):
    """Prefer the Engine API over the daemon socket (docker.go's actual
    transport); fall back to the CLI shell-out when no socket answers."""
    from .docker_api import DockerAPI, DockerAPIDriver

    api = DockerAPI()
    if api.available():
        return DockerAPIDriver(ctx, api)
    return DockerDriver(ctx)


register_driver("raw_exec", RawExecDriver)
register_driver("exec", ExecDriver)
register_driver("java", JavaDriver)
register_driver("qemu", QemuDriver)
register_driver("docker", _docker_factory)
