"""cgroup resource isolation for executed tasks (reference:
client/driver/executor/executor_linux.go — configureCgroups applies
memory.limit_in_bytes, cpu.shares, and cleanup kills the group).

Supports both hierarchies:
  v2 (unified): /sys/fs/cgroup/cgroup.controllers present —
      memory.max + cpu.weight, membership via cgroup.procs
  v1 (split):   per-controller trees memory/ and cpu/

Availability is probed once; on hosts without writable cgroups (or
non-root) isolation degrades to the executor's RLIMIT fallback, like the
reference's non-Linux executors."""

from __future__ import annotations

import functools
import logging
import os
import signal
import time
from typing import List, Optional

CGROUP_ROOT = "/sys/fs/cgroup"
PARENT = "nomad-tpu"

logger = logging.getLogger("nomad_tpu.cgroups")


def _is_v2() -> bool:
    return os.path.exists(os.path.join(CGROUP_ROOT, "cgroup.controllers"))


@functools.lru_cache(maxsize=1)
def available() -> bool:
    """Writable cgroup tree + root: isolation can be applied.  Probed
    once per process (a host property that does not change)."""
    if os.geteuid() != 0:
        return False
    try:
        if _is_v2():
            probe = os.path.join(CGROUP_ROOT, f"{PARENT}-probe")
        else:
            probe = os.path.join(CGROUP_ROOT, "memory", f"{PARENT}-probe")
        os.makedirs(probe, exist_ok=True)
        os.rmdir(probe)
        return True
    except OSError:
        return False


class TaskCgroup:
    """One task's cgroup(s): created with limits, pid added, destroyed
    with the task (executor_linux.go configureCgroups + destroyCgroup)."""

    def __init__(self, name: str, cpu_mhz: int = 0, memory_mb: int = 0):
        self.name = name
        self.cpu_mhz = cpu_mhz
        self.memory_mb = memory_mb
        self.paths: List[str] = []

    def _write(self, path: str, fname: str, value: str) -> bool:
        try:
            with open(os.path.join(path, fname), "w") as fh:
                fh.write(value)
            return True
        except OSError as e:
            logger.warning("cgroup write %s/%s failed: %s", path, fname, e)
            return False

    def create(self) -> bool:
        """True only when the MEMORY limit verifiably applied — a caller
        that drops its RLIMIT fallback on our word must not be lied to."""
        try:
            mem_ok = True
            if _is_v2():
                parent = os.path.join(CGROUP_ROOT, PARENT)
                os.makedirs(parent, exist_ok=True)
                # v2 children only get controller files once the parent
                # delegates them (cgroup.subtree_control).
                self._write(CGROUP_ROOT, "cgroup.subtree_control",
                            "+memory +cpu")
                self._write(parent, "cgroup.subtree_control",
                            "+memory +cpu")
                path = os.path.join(parent, self.name)
                os.makedirs(path, exist_ok=True)
                if self.memory_mb > 0:
                    mem_ok = self._write(path, "memory.max",
                                         str(self.memory_mb * 1024 * 1024))
                if self.cpu_mhz > 0:
                    # cpu.weight 1-10000; the reference maps MHz shares —
                    # same monotone mapping, clamped.
                    self._write(path, "cpu.weight",
                                str(max(1, min(10000, self.cpu_mhz))))
                self.paths = [path]
            else:
                mem = os.path.join(CGROUP_ROOT, "memory", PARENT, self.name)
                cpu = os.path.join(CGROUP_ROOT, "cpu", PARENT, self.name)
                os.makedirs(mem, exist_ok=True)
                os.makedirs(cpu, exist_ok=True)
                if self.memory_mb > 0:
                    mem_ok = self._write(mem, "memory.limit_in_bytes",
                                         str(self.memory_mb * 1024 * 1024))
                if self.cpu_mhz > 0:
                    # cpu.shares: MHz, floor 2 (executor_linux.go)
                    self._write(cpu, "cpu.shares",
                                str(max(2, self.cpu_mhz)))
                self.paths = [mem, cpu]
            if not mem_ok:
                self.destroy(kill=False)
                return False
            return True
        except OSError as e:
            logger.warning("cgroup create failed for %s: %s", self.name, e)
            self.paths = []
            return False

    def add_pid(self, pid: int) -> None:
        for path in self.paths:
            self._write(path, "cgroup.procs", str(pid))

    def pids(self) -> List[int]:
        """Union over every hierarchy — a process may have joined only
        one of the v1 controllers."""
        out: set = set()
        for path in self.paths:
            try:
                with open(os.path.join(path, "cgroup.procs")) as fh:
                    out.update(int(line) for line in fh if line.strip())
            except OSError:
                pass
        return sorted(out)

    def destroy(self, kill: bool = True, timeout: float = 5.0) -> None:
        """Kill every process still in the group, then remove it
        (executor_linux.go destroyCgroup)."""
        if kill:
            deadline = time.time() + timeout
            sig = signal.SIGKILL
            while time.time() < deadline:
                pids = self.pids()
                if not pids:
                    break
                for pid in pids:
                    try:
                        os.kill(pid, sig)
                    except (ProcessLookupError, PermissionError):
                        pass
                time.sleep(0.05)
        for path in self.paths:
            try:
                os.rmdir(path)
            except OSError:
                pass
        self.paths = []
