"""Docker driver over the Engine API (reference: client/driver/docker.go,
which speaks the API via go-dockerclient — the CLI shell-out in
exec_drivers.py remains as the fallback when no daemon socket is
reachable).

The API client is a minimal HTTP-over-unix-socket implementation
(http.client with a connect() override) covering the container lifecycle
the driver needs: ping/version, image pull, create/start/wait/kill/
remove, multiplexed log streaming, and one-shot stats.  No SDK.
"""
from __future__ import annotations

import http.client
import json
import os
import socket
import struct
import threading
from typing import Dict, List, Optional, Tuple

from ...structs import structs as s
from .driver import (
    Driver,
    DriverAbilities,
    DriverError,
    DriverHandle,
    ExecContext,
    StartResponse,
    WaitResult,
    opt,
)

DEFAULT_SOCKET = "/var/run/docker.sock"
API_VERSION = "v1.24"  # old enough for every live daemon

# socket path → (available, probed_at); see DockerAPI.available().
_AVAILABLE_CACHE: Dict[str, tuple] = {}


class _UnixHTTPConnection(http.client.HTTPConnection):
    def __init__(self, socket_path: str, timeout: Optional[float]):
        super().__init__("localhost", timeout=timeout)
        self._socket_path = socket_path

    def connect(self) -> None:
        sock = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        if self.timeout is not None:
            sock.settimeout(self.timeout)
        sock.connect(self._socket_path)
        self.sock = sock


class DockerAPIError(DriverError):
    pass


class DockerAPI:
    """Minimal Docker Engine API client."""

    def __init__(self, socket_path: str = DEFAULT_SOCKET):
        host = os.environ.get("DOCKER_HOST", "")
        if host.startswith("unix://"):
            socket_path = host[len("unix://"):]
        self.socket_path = socket_path

    def available(self, cache_ttl: float = 30.0) -> bool:
        """Daemon reachability, cached per socket path: the probe runs on
        every driver instantiation (incl. static job validation), and a
        present-but-hung daemon must not stall each of those by 2s."""
        import time as _time

        ent = _AVAILABLE_CACHE.get(self.socket_path)
        now = _time.monotonic()
        if ent is not None and now - ent[1] < cache_ttl:
            return ent[0]
        ok = False
        if os.path.exists(self.socket_path):
            try:
                status, _ = self._request("GET", "/_ping", timeout=2)
                ok = status == 200
            except (OSError, http.client.HTTPException):
                ok = False
        _AVAILABLE_CACHE[self.socket_path] = (ok, now)
        return ok

    # -- plumbing ----------------------------------------------------------

    def _request(self, method: str, path: str, body: Optional[dict] = None,
                 timeout: Optional[float] = 60.0,
                 raw: bool = False) -> Tuple[int, object]:
        conn = _UnixHTTPConnection(self.socket_path, timeout)
        try:
            payload = None
            headers = {}
            if body is not None:
                payload = json.dumps(body).encode()
                headers["Content-Type"] = "application/json"
            conn.request(method, f"/{API_VERSION}{path}"
                         if not path.startswith("/_") else path,
                         body=payload, headers=headers)
            resp = conn.getresponse()
            data = resp.read()
            if raw:
                return resp.status, data
            if data and resp.headers.get_content_type() == "application/json":
                try:
                    return resp.status, json.loads(data)
                except json.JSONDecodeError:
                    return resp.status, data
            return resp.status, data
        finally:
            conn.close()

    def _check(self, status: int, data, what: str):
        if status >= 300:
            msg = data.get("message") if isinstance(data, dict) else data
            raise DockerAPIError(f"{what}: HTTP {status}: {msg}")
        return data

    # -- API surface -------------------------------------------------------

    def version(self) -> dict:
        return self._check(*self._request("GET", "/version", timeout=5),
                           "version")

    def pull(self, image: str) -> None:
        """POST /images/create — consume the progress stream fully (the
        pull isn't done until the stream closes)."""
        if ":" not in image.rsplit("/", 1)[-1]:
            image = image + ":latest"
        status, data = self._request(
            "POST", f"/images/create?fromImage={image}", timeout=600,
            raw=True)
        if status >= 300:
            raise DockerAPIError(f"pull {image}: HTTP {status}: "
                                 f"{data[:200]!r}")
        # Progress stream is NDJSON; an inline error object means failure.
        for line in data.splitlines():
            try:
                msg = json.loads(line)
            except json.JSONDecodeError:
                continue
            if isinstance(msg, dict) and msg.get("error"):
                raise DockerAPIError(f"pull {image}: {msg['error']}")

    def image_exists(self, image: str) -> bool:
        status, _ = self._request("GET", f"/images/{image}/json", timeout=10)
        return status == 200

    def create_container(self, name: str, config: dict) -> str:
        data = self._check(*self._request(
            "POST", f"/containers/create?name={name}", body=config),
            f"create {name}")
        return data["Id"]

    def start(self, cid: str) -> None:
        status, data = self._request("POST", f"/containers/{cid}/start")
        if status not in (204, 304):
            self._check(status, data, f"start {cid}")

    def wait(self, cid: str) -> int:
        """Blocks until the container exits; returns its exit code."""
        data = self._check(*self._request(
            "POST", f"/containers/{cid}/wait", timeout=None), f"wait {cid}")
        return int(data.get("StatusCode", -1))

    def kill(self, cid: str, signal_name: str = "SIGKILL") -> None:
        status, data = self._request(
            "POST", f"/containers/{cid}/kill?signal={signal_name}")
        if status not in (204, 304, 404, 409):
            self._check(status, data, f"kill {cid}")

    def stop(self, cid: str, timeout_s: int = 5) -> None:
        status, data = self._request(
            "POST", f"/containers/{cid}/stop?t={timeout_s}",
            timeout=timeout_s + 30)
        if status not in (204, 304, 404):
            self._check(status, data, f"stop {cid}")

    def remove(self, cid: str, force: bool = True) -> None:
        status, data = self._request(
            "DELETE", f"/containers/{cid}?force={'true' if force else 'false'}")
        if status not in (204, 404):
            self._check(status, data, f"remove {cid}")

    def inspect(self, cid: str) -> dict:
        return self._check(*self._request("GET", f"/containers/{cid}/json"),
                           f"inspect {cid}")

    def logs(self, cid: str) -> Tuple[bytes, bytes]:
        """Full stdout/stderr so far, demultiplexed from the 8-byte-header
        stream framing (Engine API 'attach' framing)."""
        status, data = self._request(
            "GET", f"/containers/{cid}/logs?stdout=1&stderr=1", raw=True)
        if status >= 300:
            raise DockerAPIError(f"logs {cid}: HTTP {status}")
        return _demux(data)

    def stats(self, cid: str) -> dict:
        status, data = self._request(
            "GET", f"/containers/{cid}/stats?stream=false", timeout=10)
        if status >= 300:
            return {}
        return data if isinstance(data, dict) else {}


def _demux(raw: bytes) -> Tuple[bytes, bytes]:
    """Split a multiplexed attach/logs stream into (stdout, stderr).
    Frames: [stream u8][0 u8 x3][len u32 BE][payload]."""
    out, err = bytearray(), bytearray()
    i = 0
    n = len(raw)
    while i + 8 <= n:
        stream = raw[i]
        # A valid frame header is [0|1|2][\x00 x3][len u32]; anything else
        # means the stream is unframed (TTY container) — hand it back raw.
        if stream not in (0, 1, 2) or raw[i + 1:i + 4] != b"\x00\x00\x00":
            if i == 0:
                return bytes(raw), b""
            out.extend(raw[i:])
            break
        (length,) = struct.unpack(">I", raw[i + 4:i + 8])
        payload = raw[i + 8:i + 8 + length]
        if stream == 2:
            err.extend(payload)
        else:
            out.extend(payload)
        i += 8 + length
    if i == 0 and n:  # shorter than one header: raw
        return bytes(raw), b""
    return bytes(out), bytes(err)


class DockerAPIHandle(DriverHandle):
    """Handle for an API-managed container: waits via /wait, kills via
    /kill, reattaches by container id after agent restart."""

    def __init__(self, api: DockerAPI, cid: str, task_name: str,
                 log_dir: Optional[str] = None):
        self.api = api
        self.cid = cid
        self.task_name = task_name
        self.log_dir = log_dir
        self._done = threading.Event()
        self._result = WaitResult()
        self._waiter = threading.Thread(target=self._wait_loop,
                                        name=f"docker-wait-{cid[:12]}",
                                        daemon=True)
        self._waiter.start()

    def _wait_loop(self) -> None:
        try:
            code = self.api.wait(self.cid)
            self._result = WaitResult(exit_code=code)
        except Exception as exc:
            self._result = WaitResult(exit_code=-1, err=str(exc))
        try:
            self._flush_logs()
            self.api.remove(self.cid, force=True)
        except Exception:
            pass
        self._done.set()

    def _flush_logs(self) -> None:
        """Write collected container output into the task log tree the fs
        endpoint serves (executor log-file naming)."""
        if not self.log_dir:
            return
        try:
            out, err = self.api.logs(self.cid)
        except Exception:
            return
        os.makedirs(self.log_dir, exist_ok=True)
        for suffix, data in (("stdout", out), ("stderr", err)):
            with open(os.path.join(
                    self.log_dir, f"{self.task_name}.{suffix}.0"), "ab") as fh:
                fh.write(data)

    # -- DriverHandle ------------------------------------------------------

    def id(self) -> str:
        return f"docker-api:{self.cid}"

    def wait_ch(self) -> threading.Event:
        return self._done

    def wait_result(self) -> WaitResult:
        return self._result

    def update(self, task: s.Task) -> None:
        pass

    def kill(self) -> None:
        # Transport hiccups are absorbed like the executor handle's
        # shutdown path — the destroy flow must not mark the task dead
        # while leaving the container running silently; the wait loop
        # still owns cleanup when the container eventually exits.
        try:
            self.api.kill(self.cid)
        except (OSError, http.client.HTTPException, DriverError) as exc:
            import logging

            logging.getLogger("nomad_tpu.client.driver.docker").warning(
                "docker kill %s failed: %s", self.cid[:12], exc)

    def signal(self, sig: int) -> None:
        import signal as _signal

        try:
            name = _signal.Signals(sig).name
        except ValueError:
            name = str(sig)
        try:
            self.api.kill(self.cid, name)
        except (OSError, http.client.HTTPException, DriverError) as exc:
            import logging

            logging.getLogger("nomad_tpu.client.driver.docker").warning(
                "docker signal %s %s failed: %s", name, self.cid[:12], exc)

    def stats(self) -> Dict:
        """Executor-schema stats ({rss_bytes, cpu_seconds, ...}) so the
        client stats endpoint reports one shape regardless of which
        transport ran the docker task."""
        raw = self.api.stats(self.cid)
        mem = (raw.get("memory_stats") or {}).get("usage", 0)
        cpu_ns = ((raw.get("cpu_stats") or {}).get("cpu_usage") or {}).get(
            "total_usage", 0)
        return {"rss_bytes": mem, "cpu_seconds": cpu_ns / 1e9,
                "container_id": self.cid}


class DockerAPIDriver(Driver):
    """Container tasks via the Engine API (docker.go semantics: pull if
    absent, create with env/memory/labels/network, start, wait)."""

    name = "docker"

    # Single source of truth for the task-config schema: whichever
    # transport the factory picks, a docker job validates identically.
    from .exec_drivers import DockerDriver as _CLI

    CONFIG_FIELDS = _CLI.CONFIG_FIELDS

    def __init__(self, ctx, api: Optional[DockerAPI] = None):
        super().__init__(ctx)
        self.api = api or DockerAPI()

    def abilities(self) -> DriverAbilities:
        return DriverAbilities(send_signals=True, exec=False)

    def fs_isolation(self) -> str:
        from .driver import FS_ISOLATION_IMAGE

        return FS_ISOLATION_IMAGE

    def prestart(self, exec_ctx: ExecContext, task: s.Task):
        cfg = task.config or {}
        image = exec_ctx.task_env.replace_env(opt(cfg, "image", ""))
        if not image:
            raise DriverError("docker: image required")
        if not self.api.image_exists(image):
            self.api.pull(image)
        return None

    def start(self, exec_ctx: ExecContext, task: s.Task) -> StartResponse:
        cfg = task.config or {}
        env = exec_ctx.task_env
        image = env.replace_env(opt(cfg, "image", ""))
        # Unique per allocation (docker.go names containers
        # <task>-<alloc-id>); two allocs of the same task on one node
        # must not collide.
        name = f"nomad-{task.name}-{self.ctx.alloc_id or os.getpid()}"

        container: dict = {
            "Image": image,
            "Env": [f"{k}={v}" for k, v in env.env().items()],
            "Labels": dict(opt(cfg, "labels", {}) or {}),
            "HostConfig": {},
        }
        cmd_override = opt(cfg, "command", "")
        if cmd_override:
            container["Cmd"] = [env.replace_env(cmd_override)] + \
                env.parse_and_replace(
                    [str(a) for a in opt(cfg, "args", []) or []])
        hc = container["HostConfig"]
        if task.resources is not None:
            if task.resources.memory_mb:
                hc["Memory"] = task.resources.memory_mb * 1024 * 1024
            if task.resources.cpu:
                hc["CpuShares"] = task.resources.cpu
        mode = opt(cfg, "network_mode", "")
        if mode:
            hc["NetworkMode"] = mode
        # Mount the task dir at the NOMAD_TASK_DIR the env advertises.
        task_dir = getattr(exec_ctx.task_dir, "dir", None)
        if task_dir:
            hc["Binds"] = [f"{task_dir}:/nomad/task"]
        # Port bindings from the task's network offer + port_map labels.
        port_map = dict(opt(cfg, "port_map", {}) or {})
        bindings: Dict[str, list] = {}
        nets = task.resources.networks if task.resources else []
        for net in nets or []:
            for port in list(net.reserved_ports) + list(net.dynamic_ports):
                inside = int(port_map.get(port.label, port.value))
                bindings[f"{inside}/tcp"] = [
                    {"HostIp": net.ip or "", "HostPort": str(port.value)}]
        if bindings:
            hc["PortBindings"] = bindings
            container["ExposedPorts"] = {k: {} for k in bindings}

        # Purge a stale same-name container (crash before the wait loop's
        # remove) — docker.go does the same before create.
        self.api.remove(name, force=True)
        cid = self.api.create_container(name, container)
        try:
            self.api.start(cid)
        except DriverError:
            # Don't leak the created-but-unstarted container.
            self.api.remove(cid, force=True)
            raise
        log_dir = getattr(exec_ctx.task_dir, "log_dir", None)
        handle = DockerAPIHandle(self.api, cid, task.name, log_dir)
        return StartResponse(handle=handle)

    def open(self, exec_ctx: ExecContext, handle_id: str) -> DriverHandle:
        if not handle_id.startswith("docker-api:"):
            raise DriverError(f"not a docker api handle: {handle_id}")
        cid = handle_id.split(":", 1)[1]
        self.api.inspect(cid)  # raises if gone
        log_dir = getattr(exec_ctx.task_dir, "log_dir", None)
        task_name = getattr(exec_ctx.task_dir, "task_name", None) or \
            os.path.basename(exec_ctx.task_dir.dir)
        return DockerAPIHandle(self.api, cid, task_name, log_dir)

    def fingerprint(self, node: s.Node) -> bool:
        if not self.api.available():
            # The daemon went away: withdraw the capability so the
            # scheduler stops placing docker tasks here (the sibling
            # drivers pop their attribute the same way).
            node.attributes.pop("driver.docker", None)
            node.attributes.pop("driver.docker.version", None)
            return False
        try:
            ver = self.api.version()
        except DriverError:
            node.attributes.pop("driver.docker", None)
            node.attributes.pop("driver.docker.version", None)
            return False
        node.attributes["driver.docker"] = "1"
        node.attributes["driver.docker.version"] = str(
            ver.get("Version", ""))
        return True

    def periodic(self):
        return (True, 30.0)
