"""Mock driver: simulates task lifecycles without running processes —
the workhorse of client/integration tests
(reference: client/driver/mock_driver.go, build tag ``nomad_test``).

Config keys (task.config):
  start_error / start_error_recoverable — fail Start()
  run_for          — simulated run duration ("10s" or seconds)
  exit_code        — exit code reported at the end of run_for
  exit_signal      — signal reported
  exit_err_msg     — error string attached to the wait result
  signal_error     — error returned from Signal()
  kill_after       — extra delay after kill before reporting exit
"""
from __future__ import annotations

import threading
import time

from ...structs import structs as s
from .fields import FieldSchema
from .driver import (
    Driver,
    DriverAbilities,
    DriverError,
    DriverHandle,
    ExecContext,
    FS_ISOLATION_NONE,
    RecoverableError,
    StartResponse,
    WaitResult,
    opt,
    parse_duration,
    register_driver,
)


class MockDriverHandle(DriverHandle):
    def __init__(self, task_name: str, run_for: float, exit_code: int,
                 exit_signal: int, exit_err: str, signal_err: str,
                 kill_after: float):
        self.task_name = task_name
        self.run_for = run_for
        self.exit_code = exit_code
        self.exit_signal = exit_signal
        self.exit_err = exit_err or None
        self.signal_err = signal_err
        self.kill_after = kill_after
        self._done = threading.Event()
        self._kill = threading.Event()
        self._result = WaitResult()
        self._start = time.time()
        self._thread = threading.Thread(target=self._run, daemon=True)
        self._thread.start()

    def _run(self) -> None:
        killed = self._kill.wait(timeout=self.run_for)
        if killed:
            if self.kill_after > 0:
                time.sleep(self.kill_after)
            self._result = WaitResult(exit_code=1, signal=9)
        else:
            self._result = WaitResult(
                exit_code=self.exit_code, signal=self.exit_signal,
                err=self.exit_err)
        self._done.set()

    def id(self) -> str:
        return f"mock:{self.task_name}:{self._start}"

    def wait_ch(self) -> threading.Event:
        return self._done

    def wait_result(self) -> WaitResult:
        self._done.wait()
        return self._result

    def update(self, task: s.Task) -> None:
        return None

    def kill(self) -> None:
        self._kill.set()

    def signal(self, sig: int) -> None:
        if self.signal_err:
            raise DriverError(self.signal_err)

    def exec_cmd(self, cmd, args):
        return (b"", 0)

    def stats(self):
        return {"pid": 0, "uptime": time.time() - self._start}


class MockDriver(Driver):
    def abilities(self) -> DriverAbilities:
        return DriverAbilities(send_signals=True, exec=True)

    def fs_isolation(self) -> str:
        return FS_ISOLATION_NONE

    def start(self, exec_ctx: ExecContext, task: s.Task) -> StartResponse:
        cfg = task.config or {}
        start_err = opt(cfg, "start_error", "")
        if start_err:
            if opt(cfg, "start_error_recoverable", False, bool):
                raise RecoverableError(start_err)
            raise DriverError(start_err)
        handle = MockDriverHandle(
            task_name=task.name,
            run_for=parse_duration(opt(cfg, "run_for", 0)),
            exit_code=opt(cfg, "exit_code", 0, int),
            exit_signal=opt(cfg, "exit_signal", 0, int),
            exit_err=opt(cfg, "exit_err_msg", ""),
            signal_err=opt(cfg, "signal_error", ""),
            kill_after=parse_duration(opt(cfg, "kill_after", 0)),
        )
        return StartResponse(handle=handle)

    def open(self, exec_ctx: ExecContext, handle_id: str) -> DriverHandle:
        # A restarted agent cannot re-attach to a purely simulated task;
        # return a handle that reports immediate success.
        h = MockDriverHandle(task_name="reattached", run_for=0, exit_code=0,
                             exit_signal=0, exit_err="", signal_err="",
                             kill_after=0)
        return h

    # Weakly typed like the driver's own start-time casts (parse_duration
    # passes numbers through; exit codes cast digit strings).
    CONFIG_FIELDS = {
        "run_for": FieldSchema("duration"),
        "start_error": FieldSchema("string"),
        "start_error_recoverable": FieldSchema("boollike"),
        "exit_code": FieldSchema("intlike"),
        "exit_signal": FieldSchema("intlike"),
        "exit_err_msg": FieldSchema("string"),
        "signal_error": FieldSchema("string"),
        "stdout_string": FieldSchema("string"),
        "kill_after": FieldSchema("duration"),
    }

    def fingerprint(self, node: s.Node) -> bool:
        node.attributes["driver.mock_driver"] = "1"
        return True


register_driver("mock_driver", MockDriver)
