"""Container-image drivers: rkt and lxc
(reference: client/driver/rkt.go:1-647, client/driver/lxc.go:1-519).

Both drive their engine's CLI in the foreground under the shared
SupervisedExecutor, so handle attach/kill/stats and agent-restart
re-attach come from the same machinery as the exec family.  The
reference links go-lxc and shells out to the rkt binary; a foreground
CLI run keeps the identical user-visible contract (image fetch,
mount layout, net/dns config, stop-on-kill) without vendoring either
runtime.  Command assembly is pure (``command_line``), so tests
exercise the full argument surface without the binaries installed.
"""
from __future__ import annotations

import os
import subprocess
from typing import List

from ...structs import structs as s
from .driver import (
    DriverError,
    ExecContext,
    StartResponse,
    find_executable,
    opt,
    register_driver,
)
from .exec_drivers import _ExecFamilyDriver
from .fields import FieldSchema

# In-container mount targets (reference: client/allocdir/alloc_dir.go
# SharedAllocContainerPath / TaskLocalContainerPath / TaskSecretsContainerPath).
ALLOC_CONTAINER_PATH = "/alloc"
LOCAL_CONTAINER_PATH = "/local"
SECRETS_CONTAINER_PATH = "/secrets"

# Client option gating user-supplied host volumes (rkt.go:52
# rktVolumesConfigOption, default enabled).
RKT_VOLUMES_OPTION = "rkt.volumes.enabled"
# Client option gating the lxc driver itself (lxc.go lxcConfigOption).
LXC_ENABLE_OPTION = "driver.lxc.enable"
LXC_VOLUMES_OPTION = "lxc.volumes.enabled"


class RktDriver(_ExecFamilyDriver):
    """(rkt.go) — CoreOS rkt pods via ``rkt run`` in the foreground.

    The reference execs rkt under its executor plugin with
    --uuid-file-save for re-attach; here the foreground rkt process
    itself runs under the supervisor, so the uuid file is kept for
    status/debugging parity and the supervisor owns the lifecycle.
    """

    name = "rkt"
    isolation = "image"
    use_cgroups = False          # rkt manages its own pod cgroups

    CONFIG_FIELDS = {
        "image": FieldSchema("string", required=True),
        "command": FieldSchema("string"),
        "args": FieldSchema("list"),
        "trust_prefix": FieldSchema("string"),
        "dns_servers": FieldSchema("list"),
        "dns_search_domains": FieldSchema("list"),
        "net": FieldSchema("list"),
        "port_map": FieldSchema("map"),
        "volumes": FieldSchema("list"),
        "insecure_options": FieldSchema("list"),
        "no_overlay": FieldSchema("bool"),
        "debug": FieldSchema("bool"),
    }

    def _volumes_enabled(self) -> bool:
        options = getattr(self.ctx.config, "options", {}) or {}
        return str(options.get(RKT_VOLUMES_OPTION, "1")).lower() in (
            "1", "true", "")

    def command_line(self, exec_ctx: ExecContext, task: s.Task):
        """rkt.go:251-370 cmdArgs assembly, minus the trust pre-step
        (which runs in start())."""
        cfg = task.config or {}
        env = exec_ctx.task_env
        td = exec_ctx.task_dir
        debug = bool(opt(cfg, "debug", False, cast=bool))

        args: List[str] = []
        insecure = [str(i) for i in opt(cfg, "insecure_options", []) or []]
        if opt(cfg, "trust_prefix", ""):
            if insecure:
                args.append("--insecure-options=" + ",".join(insecure))
        else:
            # No trust prefix ⇒ signature verification is off, like the
            # reference (rkt.go:270-279).
            args.append("--insecure-options=" +
                        (",".join(insecure) if insecure else "all"))
        args.append(f"--debug={str(debug).lower()}")
        args.append("run")
        if opt(cfg, "no_overlay", False, cast=bool):
            args.append("--no-overlay=true")
        uuid_path = os.path.join(td.local_dir, "rkt.uuid")
        args.append(f"--uuid-file-save={uuid_path}")

        # The standard task-dir mounts (rkt.go:298-313).
        mounts = [
            ("alloc", td.shared_alloc_dir, ALLOC_CONTAINER_PATH),
            ("local", td.local_dir, LOCAL_CONTAINER_PATH),
            ("secrets", td.secrets_dir, SECRETS_CONTAINER_PATH),
        ]
        for name, source, target in mounts:
            args.append(f"--volume={name},kind=host,source={source}")
            args.append(f"--mount=volume={name},target={target}")
        user_volumes = [str(v) for v in opt(cfg, "volumes", []) or []]
        if user_volumes and not self._volumes_enabled():
            raise DriverError(
                f"volumes are disabled on this client ({RKT_VOLUMES_OPTION})")
        for i, vol in enumerate(user_volumes):
            parts = env.replace_env(vol).split(":")
            if len(parts) != 2:
                raise DriverError(f"invalid rkt volume {vol!r} "
                                  "(want /host/path:/container/path)")
            args.append(f"--volume=task-{i},kind=host,source={parts[0]}")
            args.append(f"--mount=volume=task-{i},target={parts[1]}")

        for net in opt(cfg, "net", []) or []:
            args.append(f"--net={env.replace_env(str(net))}")
        for dns in opt(cfg, "dns_servers", []) or []:
            args.append(f"--dns={env.replace_env(str(dns))}")
        for domain in opt(cfg, "dns_search_domains", []) or []:
            args.append(f"--dns-search={env.replace_env(str(domain))}")
        for name, host_port in (opt(cfg, "port_map", {}) or {}).items():
            args.append(f"--port={name}:{host_port}")

        # Resource isolators (rkt.go:340-352).
        if task.resources:
            if task.resources.memory_mb:
                args.append(f"--memory={task.resources.memory_mb}M")
            if task.resources.cpu:
                args.append(f"--cpu={task.resources.cpu}m")

        args.append(env.replace_env(opt(cfg, "image", "")))
        command = opt(cfg, "command", "")
        if command:
            args.append(f"--exec={env.replace_env(command)}")
        task_args = env.parse_and_replace(
            [str(a) for a in opt(cfg, "args", []) or []])
        if task_args:
            args.append("--")
            args.extend(task_args)
        return "rkt", args

    def start(self, exec_ctx: ExecContext, task: s.Task) -> StartResponse:
        cfg = task.config or {}
        trust_prefix = opt(cfg, "trust_prefix", "")
        if trust_prefix:
            # Synchronous trust before run (rkt.go:257-268).
            debug = str(bool(opt(cfg, "debug", False, cast=bool))).lower()
            out = self._run_rkt_trust(trust_prefix, debug)
            if out.returncode != 0:
                raise DriverError(
                    f"rkt trust failed for prefix {trust_prefix!r}: "
                    f"{out.stderr.decode(errors='replace')}")
        return super().start(exec_ctx, task)

    def _run_rkt_trust(self, prefix: str, debug: str):
        return subprocess.run(
            ["rkt", "trust", "--skip-fingerprint-review=true",
             f"--prefix={prefix}", f"--debug={debug}"],
            capture_output=True, timeout=120)

    def fingerprint(self, node: s.Node) -> bool:
        """rkt.go:171-215: present + versions recorded."""
        if not find_executable("rkt"):
            node.attributes.pop("driver.rkt", None)
            return False
        try:
            out = subprocess.run(["rkt", "version"], capture_output=True,
                                 timeout=10).stdout.decode(errors="replace")
        except (OSError, subprocess.SubprocessError):
            return False
        versions = {}
        for line in out.splitlines():
            if ":" in line:
                k, _, v = line.partition(":")
                versions[k.strip().lower()] = v.strip()
        node.attributes["driver.rkt"] = "1"
        if "rkt version" in versions:
            node.attributes["driver.rkt.version"] = versions["rkt version"]
        if "appc version" in versions:
            node.attributes["driver.rkt.appc.version"] = versions["appc version"]
        return True

    def periodic(self):
        return (True, 30.0)


class LxcDriver(_ExecFamilyDriver):
    """(lxc.go) — LXC system containers.

    The reference drives liblxc via go-lxc (Create from a template,
    Start, then poll state); the CLI equivalents are ``lxc-create`` as
    a synchronous pre-step and a foreground ``lxc-start -F`` owned by
    the supervisor, with the task-dir mounts injected as
    lxc.mount.entry config items (lxc.go:244-258).
    """

    name = "lxc"
    isolation = "image"
    use_cgroups = False          # lxc manages the container cgroups

    CONFIG_FIELDS = {
        "template": FieldSchema("string", required=True),
        "distro": FieldSchema("string"),
        "release": FieldSchema("string"),
        "arch": FieldSchema("string"),
        "image_variant": FieldSchema("string"),
        "image_server": FieldSchema("string"),
        "gpg_key_id": FieldSchema("string"),
        "gpg_key_server": FieldSchema("string"),
        "disable_gpg": FieldSchema("bool"),
        "flush_cache": FieldSchema("bool"),
        "force_cache": FieldSchema("bool"),
        "template_args": FieldSchema("list"),
        "log_level": FieldSchema("string"),
        "verbosity": FieldSchema("string"),
        "volumes": FieldSchema("list"),
    }

    def container_name(self, exec_ctx: ExecContext, task: s.Task) -> str:
        """(lxc.go:200) <task>-<alloc_id>."""
        return f"{task.name}-{self.ctx.alloc_id}"

    def create_args(self, exec_ctx: ExecContext, task: s.Task) -> List[str]:
        """lxc-create argument list from the template options
        (lxc.go:228-242 TemplateOptions)."""
        cfg = task.config or {}
        env = exec_ctx.task_env
        name = self.container_name(exec_ctx, task)
        args = ["-n", name, "-t", env.replace_env(opt(cfg, "template", ""))]
        targs: List[str] = []
        for key, flag in (("distro", "--dist"), ("release", "--release"),
                          ("arch", "--arch"), ("image_variant", "--variant"),
                          ("image_server", "--server"),
                          ("gpg_key_id", "--keyid"),
                          ("gpg_key_server", "--keyserver")):
            val = opt(cfg, key, "")
            if val:
                targs += [flag, env.replace_env(str(val))]
        if opt(cfg, "disable_gpg", False, cast=bool):
            targs.append("--no-validate")
        if opt(cfg, "flush_cache", False, cast=bool):
            targs.append("--flush-cache")
        if opt(cfg, "force_cache", False, cast=bool):
            targs.append("--force-cache")
        targs += env.parse_and_replace(
            [str(a) for a in opt(cfg, "template_args", []) or []])
        if targs:
            args.append("--")
            args.extend(targs)
        return args

    def command_line(self, exec_ctx: ExecContext, task: s.Task):
        """The foreground run: lxc-start -F with the task-dir bind
        mounts (lxc.go:244-258 sets these as lxc.mount.entry items)."""
        cfg = task.config or {}
        td = exec_ctx.task_dir
        name = self.container_name(exec_ctx, task)
        args = ["-F", "-n", name]
        log_level = opt(cfg, "log_level", "")
        if log_level:
            args += ["-l", str(log_level)]
        mounts = [
            (td.shared_alloc_dir, ALLOC_CONTAINER_PATH.lstrip("/")),
            (td.local_dir, LOCAL_CONTAINER_PATH.lstrip("/")),
            (td.secrets_dir, SECRETS_CONTAINER_PATH.lstrip("/")),
        ]
        options = getattr(self.ctx.config, "options", {}) or {}
        volumes_ok = str(options.get(LXC_VOLUMES_OPTION, "1")).lower() in (
            "1", "true", "")
        for vol in opt(cfg, "volumes", []) or []:
            if not volumes_ok:
                raise DriverError(
                    f"volumes are disabled on this client "
                    f"({LXC_VOLUMES_OPTION})")
            parts = str(vol).split(":")
            if len(parts) != 2 or parts[1].startswith("/"):
                raise DriverError(
                    f"invalid lxc volume {vol!r} (want "
                    "/host/path:relative/container/path)")
            mounts.append((parts[0], parts[1]))
        for source, target in mounts:
            args += ["-s",
                     f"lxc.mount.entry={source} {target} "
                     "none rw,bind,create=dir 0 0"]
        return "lxc-start", args

    def start(self, exec_ctx: ExecContext, task: s.Task) -> StartResponse:
        create = self.create_args(exec_ctx, task)
        out = self._run_lxc_create(create)
        if out.returncode != 0:
            raise DriverError(
                f"lxc-create failed: {out.stderr.decode(errors='replace')}")
        return super().start(exec_ctx, task)

    def _run_lxc_create(self, args: List[str]):
        return subprocess.run(["lxc-create"] + args, capture_output=True,
                              timeout=600)

    def fingerprint(self, node: s.Node) -> bool:
        """lxc.go:139-160: gated by driver.lxc.enable + liblxc present."""
        options = getattr(self.ctx.config, "options", {}) or {}
        enabled = str(options.get(LXC_ENABLE_OPTION, "")).lower() in (
            "1", "true")
        if not enabled or not find_executable("lxc-start"):
            node.attributes.pop("driver.lxc", None)
            return False
        try:
            out = subprocess.run(["lxc-start", "--version"],
                                 capture_output=True,
                                 timeout=10).stdout.decode(errors="replace")
        except (OSError, subprocess.SubprocessError):
            return False
        node.attributes["driver.lxc"] = "1"
        node.attributes["driver.lxc.version"] = out.strip()
        return True


register_driver("rkt", RktDriver)
register_driver("lxc", LxcDriver)
