"""Container-image drivers: rkt and lxc
(reference: client/driver/rkt.go:1-647, client/driver/lxc.go:1-519).

Both drive their engine's CLI in the foreground under the shared
SupervisedExecutor, so handle attach/kill/stats and agent-restart
re-attach come from the same machinery as the exec family.  The
reference links go-lxc and shells out to the rkt binary; a foreground
CLI run keeps the identical user-visible contract (image fetch,
mount layout, net/dns config, stop-on-kill) without vendoring either
runtime.  Command assembly is pure (``command_line``), so tests
exercise the full argument surface without the binaries installed.
"""
from __future__ import annotations

import os
import subprocess
from typing import List

from ...structs import structs as s
from .driver import (
    DriverError,
    DriverHandle,
    ExecContext,
    StartResponse,
    find_executable,
    opt,
    register_driver,
)
from .exec_drivers import ExecutorHandle, _ExecFamilyDriver
from .fields import FieldSchema

# In-container mount targets (reference: client/allocdir/alloc_dir.go
# SharedAllocContainerPath / TaskLocalContainerPath / TaskSecretsContainerPath).
ALLOC_CONTAINER_PATH = "/alloc"
LOCAL_CONTAINER_PATH = "/local"
SECRETS_CONTAINER_PATH = "/secrets"

# Client option gating user-supplied host volumes (rkt.go:52
# rktVolumesConfigOption, default enabled).
RKT_VOLUMES_OPTION = "rkt.volumes.enabled"
# Client option gating the lxc driver itself (lxc.go lxcConfigOption).
LXC_ENABLE_OPTION = "driver.lxc.enable"
LXC_VOLUMES_OPTION = "lxc.volumes.enabled"


class RktDriver(_ExecFamilyDriver):
    """(rkt.go) — CoreOS rkt pods via ``rkt run`` in the foreground.

    The reference execs rkt under its executor plugin with
    --uuid-file-save for re-attach; here the foreground rkt process
    itself runs under the supervisor, so the uuid file is kept for
    status/debugging parity and the supervisor owns the lifecycle.
    """

    name = "rkt"
    isolation = "image"
    use_cgroups = False          # rkt manages its own pod cgroups

    CONFIG_FIELDS = {
        "image": FieldSchema("string", required=True),
        "command": FieldSchema("string"),
        "args": FieldSchema("list"),
        "trust_prefix": FieldSchema("string"),
        "dns_servers": FieldSchema("list"),
        "dns_search_domains": FieldSchema("list"),
        "net": FieldSchema("list"),
        "port_map": FieldSchema("map"),
        "volumes": FieldSchema("list"),
        "insecure_options": FieldSchema("list"),
        "no_overlay": FieldSchema("bool"),
        "debug": FieldSchema("bool"),
    }

    def _volumes_enabled(self) -> bool:
        options = getattr(self.ctx.config, "options", {}) or {}
        return str(options.get(RKT_VOLUMES_OPTION, "1")).lower() in (
            "1", "true", "")

    def command_line(self, exec_ctx: ExecContext, task: s.Task):
        """rkt.go:251-370 cmdArgs assembly, minus the trust pre-step
        (which runs in start())."""
        cfg = task.config or {}
        env = exec_ctx.task_env
        td = exec_ctx.task_dir
        debug = bool(opt(cfg, "debug", False, cast=bool))

        args: List[str] = []
        insecure = [str(i) for i in opt(cfg, "insecure_options", []) or []]
        if opt(cfg, "trust_prefix", ""):
            if insecure:
                args.append("--insecure-options=" + ",".join(insecure))
        else:
            # No trust prefix ⇒ signature verification is off, like the
            # reference (rkt.go:270-279).
            args.append("--insecure-options=" +
                        (",".join(insecure) if insecure else "all"))
        args.append(f"--debug={str(debug).lower()}")
        args.append("run")
        if opt(cfg, "no_overlay", False, cast=bool):
            args.append("--no-overlay=true")
        uuid_path = os.path.join(td.local_dir, "rkt.uuid")
        args.append(f"--uuid-file-save={uuid_path}")

        # The standard task-dir mounts (rkt.go:298-313).
        mounts = [
            ("alloc", td.shared_alloc_dir, ALLOC_CONTAINER_PATH),
            ("local", td.local_dir, LOCAL_CONTAINER_PATH),
            ("secrets", td.secrets_dir, SECRETS_CONTAINER_PATH),
        ]
        for name, source, target in mounts:
            args.append(f"--volume={name},kind=host,source={source}")
            args.append(f"--mount=volume={name},target={target}")
        user_volumes = [str(v) for v in opt(cfg, "volumes", []) or []]
        if user_volumes and not self._volumes_enabled():
            raise DriverError(
                f"volumes are disabled on this client ({RKT_VOLUMES_OPTION})")
        for i, vol in enumerate(user_volumes):
            parts = env.replace_env(vol).split(":")
            if len(parts) != 2:
                raise DriverError(f"invalid rkt volume {vol!r} "
                                  "(want /host/path:/container/path)")
            args.append(f"--volume=task-{i},kind=host,source={parts[0]}")
            args.append(f"--mount=volume=task-{i},target={parts[1]}")

        for net in opt(cfg, "net", []) or []:
            args.append(f"--net={env.replace_env(str(net))}")
        for dns in opt(cfg, "dns_servers", []) or []:
            args.append(f"--dns={env.replace_env(str(dns))}")
        for domain in opt(cfg, "dns_search_domains", []) or []:
            args.append(f"--dns-search={env.replace_env(str(domain))}")
        for name, host_port in (opt(cfg, "port_map", {}) or {}).items():
            args.append(f"--port={name}:{host_port}")

        # Resource isolators (rkt.go:340-352).
        if task.resources:
            if task.resources.memory_mb:
                args.append(f"--memory={task.resources.memory_mb}M")
            if task.resources.cpu:
                args.append(f"--cpu={task.resources.cpu}m")

        args.append(env.replace_env(opt(cfg, "image", "")))
        command = opt(cfg, "command", "")
        if command:
            args.append(f"--exec={env.replace_env(command)}")
        task_args = env.parse_and_replace(
            [str(a) for a in opt(cfg, "args", []) or []])
        if task_args:
            args.append("--")
            args.extend(task_args)
        return "rkt", args

    def start(self, exec_ctx: ExecContext, task: s.Task) -> StartResponse:
        cfg = task.config or {}
        trust_prefix = opt(cfg, "trust_prefix", "")
        if trust_prefix:
            # Synchronous trust before run (rkt.go:257-268).
            debug = str(bool(opt(cfg, "debug", False, cast=bool))).lower()
            out = self._run_rkt_trust(trust_prefix, debug)
            if out.returncode != 0:
                raise DriverError(
                    f"rkt trust failed for prefix {trust_prefix!r}: "
                    f"{out.stderr.decode(errors='replace')}")
        return super().start(exec_ctx, task)

    def _run_rkt_trust(self, prefix: str, debug: str):
        return subprocess.run(
            ["rkt", "trust", "--skip-fingerprint-review=true",
             f"--prefix={prefix}", f"--debug={debug}"],
            capture_output=True, timeout=120)

    def fingerprint(self, node: s.Node) -> bool:
        """rkt.go:171-215: present + versions recorded.

        Attributes are dropped up front and re-set only on a fully
        working binary: absent, raising, and nonzero-exit rkt all stop
        advertising the driver identically."""
        for attr in ("driver.rkt", "driver.rkt.version",
                     "driver.rkt.appc.version"):
            node.attributes.pop(attr, None)
        if not find_executable("rkt"):
            return False
        try:
            out = subprocess.run(["rkt", "version"], capture_output=True,
                                 timeout=10)
        except (OSError, subprocess.SubprocessError):
            return False
        if out.returncode != 0:
            return False
        versions = {}
        for line in out.stdout.decode(errors="replace").splitlines():
            if ":" in line:
                k, _, v = line.partition(":")
                versions[k.strip().lower()] = v.strip()
        node.attributes["driver.rkt"] = "1"
        if "rkt version" in versions:
            node.attributes["driver.rkt.version"] = versions["rkt version"]
        if "appc version" in versions:
            node.attributes["driver.rkt.appc.version"] = versions["appc version"]
        return True

    def periodic(self):
        return (True, 30.0)


def _lxc_teardown(container_name: str) -> None:
    """Authoritative container stop + rootfs removal (lxc.go:388
    h.container.Stop(); the CLI twin is lxc-stop -k).  Signaling the
    foreground lxc-start monitor is not enough: if the supervisor
    escalates to SIGKILL, the monitor dies but the container init is
    reparented and keeps running — so always force-stop the container
    itself, then destroy the lxc-create'd rootfs."""
    for cmd, timeout in ((["lxc-stop", "-n", container_name, "-k"], 30),
                         (["lxc-destroy", "-n", container_name, "-f"], 60)):
        try:
            subprocess.run(cmd, capture_output=True, timeout=timeout)
        except (OSError, subprocess.SubprocessError):
            pass


class LxcHandle(ExecutorHandle):
    """ExecutorHandle that also owns the container lifecycle: after the
    monitor is signaled (and possibly SIGKILLed past the grace period),
    force-stop the container and remove its rootfs."""

    def __init__(self, executor, task_name: str, kill_timeout: float,
                 container_name: str):
        super().__init__(executor, task_name, kill_timeout)
        self.container_name = container_name

    def kill(self) -> None:
        super().kill()
        # Synchronous on purpose: a restart re-enters start() with the
        # SAME container name the moment kill() returns, and agent
        # shutdown exits the process right after — a background teardown
        # would either destroy the restarted container or never run.
        self.executor.exited.wait(self.kill_timeout + 10.0)
        _lxc_teardown(self.container_name)


class LxcDriver(_ExecFamilyDriver):
    """(lxc.go) — LXC system containers.

    The reference drives liblxc via go-lxc (Create from a template,
    Start, then poll state); the CLI equivalents are ``lxc-create`` as
    a synchronous pre-step and a foreground ``lxc-start -F`` owned by
    the supervisor, with the task-dir mounts injected as
    lxc.mount.entry config items (lxc.go:244-258).
    """

    name = "lxc"
    isolation = "image"
    use_cgroups = False          # lxc manages the container cgroups

    CONFIG_FIELDS = {
        "template": FieldSchema("string", required=True),
        "distro": FieldSchema("string"),
        "release": FieldSchema("string"),
        "arch": FieldSchema("string"),
        "image_variant": FieldSchema("string"),
        "image_server": FieldSchema("string"),
        "gpg_key_id": FieldSchema("string"),
        "gpg_key_server": FieldSchema("string"),
        "disable_gpg": FieldSchema("bool"),
        "flush_cache": FieldSchema("bool"),
        "force_cache": FieldSchema("bool"),
        "template_args": FieldSchema("list"),
        "log_level": FieldSchema("string"),
        "verbosity": FieldSchema("string"),
        "volumes": FieldSchema("list"),
    }

    def container_name(self, exec_ctx: ExecContext, task: s.Task) -> str:
        """(lxc.go:200) <task>-<alloc_id>, plus a per-launch nonce.

        The nonce makes each start attempt's container unique: the task
        runner is released by the executor's exit event, not by kill()
        returning, so a restart can lxc-create while the previous
        handle's stop/destroy is still in flight — under a reused name
        that teardown would hit the NEW container.  The previous
        launch's name is persisted in the ctl dir and cleaned up before
        the next create."""
        if self._launch_name is None:
            self._launch_name = (
                f"{task.name}-{self.ctx.alloc_id}-{os.urandom(4).hex()}")
        return self._launch_name

    _launch_name: str | None = None

    def create_args(self, exec_ctx: ExecContext, task: s.Task) -> List[str]:
        """lxc-create argument list from the template options
        (lxc.go:228-242 TemplateOptions)."""
        cfg = task.config or {}
        env = exec_ctx.task_env
        name = self.container_name(exec_ctx, task)
        args = ["-n", name, "-t", env.replace_env(opt(cfg, "template", ""))]
        targs: List[str] = []
        for key, flag in (("distro", "--dist"), ("release", "--release"),
                          ("arch", "--arch"), ("image_variant", "--variant"),
                          ("image_server", "--server"),
                          ("gpg_key_id", "--keyid"),
                          ("gpg_key_server", "--keyserver")):
            val = opt(cfg, key, "")
            if val:
                targs += [flag, env.replace_env(str(val))]
        if opt(cfg, "disable_gpg", False, cast=bool):
            targs.append("--no-validate")
        if opt(cfg, "flush_cache", False, cast=bool):
            targs.append("--flush-cache")
        if opt(cfg, "force_cache", False, cast=bool):
            targs.append("--force-cache")
        targs += env.parse_and_replace(
            [str(a) for a in opt(cfg, "template_args", []) or []])
        if targs:
            args.append("--")
            args.extend(targs)
        return args

    def command_line(self, exec_ctx: ExecContext, task: s.Task):
        """The foreground run: lxc-start -F with the task-dir bind
        mounts (lxc.go:244-258 sets these as lxc.mount.entry items)."""
        cfg = task.config or {}
        td = exec_ctx.task_dir
        name = self.container_name(exec_ctx, task)
        args = ["-F", "-n", name]
        log_level = opt(cfg, "log_level", "")
        if log_level:
            args += ["-l", str(log_level)]
        mounts = [
            (td.shared_alloc_dir, ALLOC_CONTAINER_PATH.lstrip("/")),
            (td.local_dir, LOCAL_CONTAINER_PATH.lstrip("/")),
            (td.secrets_dir, SECRETS_CONTAINER_PATH.lstrip("/")),
        ]
        options = getattr(self.ctx.config, "options", {}) or {}
        volumes_ok = str(options.get(LXC_VOLUMES_OPTION, "1")).lower() in (
            "1", "true", "")
        for vol in opt(cfg, "volumes", []) or []:
            if not volumes_ok:
                raise DriverError(
                    f"volumes are disabled on this client "
                    f"({LXC_VOLUMES_OPTION})")
            parts = str(vol).split(":")
            if len(parts) != 2 or parts[1].startswith("/"):
                raise DriverError(
                    f"invalid lxc volume {vol!r} (want "
                    "/host/path:relative/container/path)")
            mounts.append((parts[0], parts[1]))
        for source, target in mounts:
            args += ["-s",
                     f"lxc.mount.entry={source} {target} "
                     "none rw,bind,create=dir 0 0"]
        return "lxc-start", args

    def start(self, exec_ctx: ExecContext, task: s.Task) -> StartResponse:
        ctl_dir = self.ctl_dir(exec_ctx, task.name)
        # A task that exited on its own (no kill()) leaves its rootfs
        # behind; clean up the PREVIOUS launch's container before
        # creating this one.
        try:
            with open(os.path.join(ctl_dir, "container.name")) as fh:
                prev = fh.read().strip()
        except OSError:
            prev = ""
        if prev:
            _lxc_teardown(prev)
        self._launch_name = None        # fresh nonce for this attempt
        name = self.container_name(exec_ctx, task)
        create = self.create_args(exec_ctx, task)
        out = self._run_lxc_create(create)
        if out.returncode != 0:
            raise DriverError(
                f"lxc-create failed: {out.stderr.decode(errors='replace')}")
        # Persist the name BEFORE launching: the moment a container can
        # be running, a re-attaching agent (and the next start attempt)
        # must be able to find and tear it down.
        os.makedirs(ctl_dir, exist_ok=True)
        with open(os.path.join(ctl_dir, "container.name"), "w") as fh:
            fh.write(name)
        try:
            resp = super().start(exec_ctx, task)
        except DriverError:
            # Supervisor launch failed after the rootfs was built: tear
            # it down now — a rescheduled alloc may never retry here.
            _lxc_teardown(name)
            try:
                os.unlink(os.path.join(ctl_dir, "container.name"))
            except OSError:
                pass
            raise
        base = resp.handle
        return StartResponse(
            handle=LxcHandle(base.executor, task.name, task.kill_timeout,
                             name),
            network=resp.network)

    def open(self, exec_ctx: ExecContext, handle_id: str) -> DriverHandle:
        name = ""
        if handle_id.startswith("sup:"):
            ctl_dir = handle_id.split(":", 1)[1]
            try:
                with open(os.path.join(ctl_dir, "container.name")) as fh:
                    name = fh.read().strip()
            except OSError:
                pass
        try:
            base = super().open(exec_ctx, handle_id)
        except DriverError:
            # Supervisor gone (e.g. OOM-killed): the reparented container
            # init may still be running — tear it down before reporting
            # the task lost, or it leaks forever.
            if name:
                _lxc_teardown(name)
            raise
        if name:
            return LxcHandle(base.executor, base.task_name,
                             base.kill_timeout, name)
        return base

    def _run_lxc_create(self, args: List[str]):
        return subprocess.run(["lxc-create"] + args, capture_output=True,
                              timeout=600)

    def fingerprint(self, node: s.Node) -> bool:
        """lxc.go:139-160: gated by driver.lxc.enable + liblxc present.
        Disabled, absent, raising, and nonzero-exit lxc-start all stop
        advertising the driver identically."""
        node.attributes.pop("driver.lxc", None)
        node.attributes.pop("driver.lxc.version", None)
        options = getattr(self.ctx.config, "options", {}) or {}
        enabled = str(options.get(LXC_ENABLE_OPTION, "")).lower() in (
            "1", "true")
        if not enabled or not find_executable("lxc-start"):
            return False
        try:
            out = subprocess.run(["lxc-start", "--version"],
                                 capture_output=True, timeout=10)
        except (OSError, subprocess.SubprocessError):
            return False
        if out.returncode != 0:
            return False
        node.attributes["driver.lxc"] = "1"
        node.attributes["driver.lxc.version"] = out.stdout.decode(
            errors="replace").strip()
        return True


register_driver("rkt", RktDriver)
register_driver("lxc", LxcDriver)
