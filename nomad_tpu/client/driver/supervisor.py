"""Task supervisor: a detached per-task subprocess that owns the task's
lifecycle, so the agent can restart and re-attach with FULL control —
including collecting the exit code of a task that finished while the
agent was down.

Reference: the go-plugin executor subprocess
(client/driver/executor_plugin.go:1-60, plugins.go, executor.go:50,211):
every exec-family task runs under a plugin process the agent talks to
over RPC; agent restarts reconnect to the still-running plugin.  Here the
supervisor is ``python -m nomad_tpu.client.driver.supervisor <ctl_dir>``,
detached into its own session, embedding the in-process Executor and
serving a line-JSON protocol on a unix socket:

    {"op": "ping"}                     → {"ok": true, "pid": <task pid>}
    {"op": "stats"}                    → {"ok": true, "stats": {...}}
    {"op": "signal", "sig": N}         → {"ok": true}
    {"op": "shutdown", "grace": secs}  → {"ok": true}
    {"op": "wait"}                     → blocks; {"ok": true, "result": ...}

Durability: when the task exits, the supervisor atomically writes
``exit.json`` into the control dir before anything else — so even if the
supervisor itself dies (or is reaped long before the agent returns), the
exit status is collectable from disk.  The control dir is the contract:

    <ctl_dir>/command.json    — the ExecCommand (written by the agent)
    <ctl_dir>/supervisor.pid  — the supervisor's pid
    <ctl_dir>/task.pid        — the task's pid (written post-launch)
    <ctl_dir>/sock            — control socket
    <ctl_dir>/exit.json       — terminal WaitResult (written at task exit)
"""
from __future__ import annotations

import json
import os
import socket
import sys
import threading
import time

# How long the supervisor keeps serving the socket after the task exits
# (exit.json already persisted): enough for a live agent to collect the
# wait() result without a disk poll round.
LINGER_AFTER_EXIT = 60.0


def _write_atomic(path: str, data: dict) -> None:
    tmp = path + ".tmp"
    with open(tmp, "w") as fh:
        json.dump(data, fh)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)


def sock_path(ctl_dir: str) -> str:
    """Control-socket path for a ctl dir.

    NOT inside the ctl dir: AF_UNIX paths are capped at ~108 bytes and
    ctl dirs live inside alloc dirs whose paths can be arbitrarily deep
    (a too-long bind/connect path was the silent supervisor crash behind
    VERDICT r3 weak-3's missing logs). The supervisor binds a short
    socket inside a private mode-0700 tempdir (unpredictable, so no
    shared-/tmp squatting or hijack) and advertises the real path via
    ``sock.path`` in the permission-protected ctl dir."""
    try:
        with open(os.path.join(ctl_dir, "sock.path")) as fh:
            return fh.read().strip()
    except OSError:
        # No advertisement (supervisor not up yet, or pre-bind): a
        # connect() to this per-ctl-dir placeholder fails cleanly.
        return os.path.join(ctl_dir, "sock")


def _make_private_sock_path() -> str:
    """A short socket path in a fresh private (0700) directory."""
    import tempfile

    d = tempfile.mkdtemp(prefix="ntpu-sup-")
    return os.path.join(d, "s")


def exit_path(ctl_dir: str) -> str:
    return os.path.join(ctl_dir, "exit.json")


def request(ctl_dir: str, req: dict, timeout: float = 5.0) -> dict:
    """One request/response round on the supervisor socket."""
    with socket.socket(socket.AF_UNIX, socket.SOCK_STREAM) as sk:
        sk.settimeout(timeout)
        sk.connect(sock_path(ctl_dir))
        sk.sendall((json.dumps(req) + "\n").encode())
        buf = b""
        while not buf.endswith(b"\n"):
            chunk = sk.recv(65536)
            if not chunk:
                break
            buf += chunk
    return json.loads(buf.decode())


def main(ctl_dir: str) -> int:
    from .executor import ExecCommand, Executor

    with open(os.path.join(ctl_dir, "command.json")) as fh:
        spec = json.load(fh)
    command = ExecCommand(**spec)

    _write_atomic(os.path.join(ctl_dir, "supervisor.pid"),
                  {"pid": os.getpid()})

    # Bind the control socket BEFORE launching the task: the agent's
    # launch() returns once task.pid exists, so binding first guarantees
    # its result watcher can always take the socket wait path instead of
    # racing exit.json on disk (the race behind VERDICT r3 weak-3).
    server = None
    spath = ""
    try:
        spath = _make_private_sock_path()
        server = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
        server.bind(spath)
        server.listen(8)
        with open(os.path.join(ctl_dir, "sock.path.tmp"), "w") as fh:
            fh.write(spath)
        os.replace(os.path.join(ctl_dir, "sock.path.tmp"),
                   os.path.join(ctl_dir, "sock.path"))
    except OSError:
        # Degraded but alive: no control socket, but the task still runs,
        # logs still pump, and exit.json still lands on disk. Never let
        # socket setup kill the supervisor.
        if server is not None:
            server.close()
        server = None
        _cleanup_sock(ctl_dir, spath)
        spath = ""

    executor = Executor(command)
    try:
        pid = executor.launch()
    except OSError as exc:
        _write_atomic(exit_path(ctl_dir),
                      {"exit_code": 127, "signal": 0,
                       "err": str(exc), "finished_at": time.time()})
        if server is not None:
            server.close()
        _cleanup_sock(ctl_dir, spath)
        return 1
    _write_atomic(os.path.join(ctl_dir, "task.pid"), {"pid": pid})

    done = threading.Event()

    def reaper():
        executor.exited.wait()
        res = executor.result
        _write_atomic(exit_path(ctl_dir), {
            "exit_code": res.exit_code,
            "signal": res.signal,
            "err": getattr(res, "err", "") or "",
            "finished_at": time.time(),
        })
        time.sleep(LINGER_AFTER_EXIT)
        done.set()
        # Wake the accept loop.
        try:
            poke = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
            poke.connect(spath)
            poke.close()
        except OSError:
            pass

    threading.Thread(target=reaper, daemon=True).start()

    def serve(conn: socket.socket) -> None:
        try:
            fh = conn.makefile("rwb")
            line = fh.readline()
            if not line:
                return
            req = json.loads(line.decode())
            op = req.get("op")
            if op == "ping":
                resp = {"ok": True, "pid": executor.pid,
                        "exited": executor.result is not None}
            elif op == "stats":
                resp = {"ok": True, "stats": executor.stats()}
            elif op == "signal":
                executor.send_signal(int(req.get("sig", 15)))
                resp = {"ok": True}
            elif op == "shutdown":
                # Run the grace period out of line so the reply is
                # immediate; exit status arrives via wait/exit.json.
                threading.Thread(
                    target=executor.shutdown,
                    kwargs={"grace": float(req.get("grace", 5.0))},
                    daemon=True).start()
                resp = {"ok": True}
            elif op == "wait":
                executor.exited.wait()
                res = executor.result
                resp = {"ok": True, "result": {
                    "exit_code": res.exit_code, "signal": res.signal,
                    "err": getattr(res, "err", "") or ""}}
            else:
                resp = {"ok": False, "err": f"unknown op {op!r}"}
            fh.write((json.dumps(resp) + "\n").encode())
            fh.flush()
        except (OSError, ValueError):
            pass
        finally:
            try:
                conn.close()
            except OSError:
                pass

    if server is None:
        # No control socket (cleaned up in the bind-failure handler):
        # just outlive the task long enough for a collector to land on
        # exit.json.
        executor.exited.wait()
        time.sleep(LINGER_AFTER_EXIT)
        return 0
    while not done.is_set():
        try:
            conn, _ = server.accept()
        except OSError:
            break
        threading.Thread(target=serve, args=(conn,), daemon=True).start()
    server.close()
    _cleanup_sock(ctl_dir, spath)
    return 0


def _cleanup_sock(ctl_dir: str, spath: str) -> None:
    for p in (os.path.join(ctl_dir, "sock.path"), spath):
        try:
            os.unlink(p)
        except OSError:
            pass
    if spath:
        try:
            os.rmdir(os.path.dirname(spath))
        except OSError:
            pass


if __name__ == "__main__":
    sys.exit(main(sys.argv[1]))
