"""Driver framework: the pluggable task-execution interface of the data
plane (reference: client/driver/driver.go:25-318).

A Driver knows how to validate a task's config, fingerprint its own
availability onto the node, and start a task — returning a DriverHandle
the TaskRunner uses to wait on / signal / kill the running task.  The
registry maps driver names (``task.driver``) to factories, mirroring
``BuiltinDrivers`` (driver.go:25-32).
"""
from __future__ import annotations

import logging
import os
import threading
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

from ...structs import structs as s
from .env import TaskEnv


# FS isolation modes advertised by drivers
# (reference: client/structs/structs.go FSIsolation).
FS_ISOLATION_NONE = "none"
FS_ISOLATION_CHROOT = "chroot"
FS_ISOLATION_IMAGE = "image"


class DriverError(Exception):
    """Base error for driver failures."""


class RecoverableError(DriverError):
    """An error the restart tracker may retry
    (reference: nomad/structs/errors.go IsRecoverable)."""


def is_recoverable(err: BaseException) -> bool:
    return isinstance(err, RecoverableError)


@dataclass
class WaitResult:
    """Outcome of a finished task process
    (reference: client/driver/structs/structs.go WaitResult)."""

    exit_code: int = 0
    signal: int = 0
    err: Optional[str] = None

    def successful(self) -> bool:
        return self.exit_code == 0 and self.signal == 0 and self.err is None


@dataclass
class DriverAbilities:
    """(driver.go:246-256)."""

    send_signals: bool = False
    exec: bool = False


@dataclass
class DriverContext:
    """Everything a driver factory gets handed
    (reference: driver.go:107-135 DriverContext)."""

    driver_name: str
    alloc_id: str
    config: "object"           # client config (duck-typed; needs .options dict)
    node: Optional[s.Node] = None
    task_env: Optional[TaskEnv] = None
    logger: logging.Logger = field(
        default_factory=lambda: logging.getLogger("nomad_tpu.client.driver"))


@dataclass
class ExecContext:
    """Paths a task executes within (reference: driver.go:339-352)."""

    task_dir: "object"         # allocdir.TaskDir
    task_env: TaskEnv


@dataclass
class PrestartResponse:
    """(driver.go:258-270) — created resources + network, pre-start."""

    created_resources: Dict[str, List[str]] = field(default_factory=dict)


@dataclass
class StartResponse:
    handle: "DriverHandle" = None
    network: Optional[s.NetworkResource] = None


class DriverHandle:
    """Live interface to a started task (reference: driver.go:295-318).

    ``wait_ch()`` returns a threading.Event set when the task exits;
    ``wait_result()`` then yields the WaitResult.  This replaces Go's
    ``WaitCh() chan *WaitResult``.
    """

    def id(self) -> str:
        raise NotImplementedError

    def wait_ch(self) -> threading.Event:
        raise NotImplementedError

    def wait_result(self) -> WaitResult:
        raise NotImplementedError

    def update(self, task: s.Task) -> None:
        raise NotImplementedError

    def kill(self) -> None:
        raise NotImplementedError

    def signal(self, sig: int) -> None:
        raise NotImplementedError

    def exec_cmd(self, cmd: str, args: List[str]) -> tuple[bytes, int]:
        raise NotImplementedError

    def stats(self) -> Dict:
        return {}


class Driver:
    """Task-execution backend (reference: driver.go:207-243)."""

    def __init__(self, ctx: DriverContext):
        self.ctx = ctx
        self.logger = ctx.logger

    # -- lifecycle ---------------------------------------------------------
    def prestart(self, exec_ctx: ExecContext, task: s.Task) -> Optional[PrestartResponse]:
        return None

    def start(self, exec_ctx: ExecContext, task: s.Task) -> StartResponse:
        raise NotImplementedError

    def open(self, exec_ctx: ExecContext, handle_id: str) -> DriverHandle:
        """Re-attach to a running task after agent restart (driver.go:224)."""
        raise NotImplementedError

    def cleanup(self, exec_ctx: ExecContext, resources: Dict[str, List[str]]) -> None:
        return None

    # -- metadata ----------------------------------------------------------
    # Field schema for this driver's task config (helper/fields role);
    # subclasses declare {field: FieldSchema} and inherit validation.
    CONFIG_FIELDS: Dict = {}

    def validate(self, config: Dict) -> None:
        """Raise ValueError on bad task driver config (driver.go:230 via
        helper/fields FieldData.Validate)."""
        from .fields import validate_fields

        problems = validate_fields(config, self.CONFIG_FIELDS)
        if problems:
            raise ValueError("; ".join(problems))

    def abilities(self) -> DriverAbilities:
        return DriverAbilities()

    def fs_isolation(self) -> str:
        return FS_ISOLATION_NONE

    # -- fingerprinting ----------------------------------------------------
    def fingerprint(self, node: s.Node) -> bool:
        """Detect availability; set ``driver.<name>`` node attribute and
        return applicability (reference: each driver's Fingerprint)."""
        return False

    def periodic(self) -> tuple[bool, float]:
        """(enabled, period_seconds) — most drivers are static."""
        return (False, 0.0)


# ---------------------------------------------------------------------------
# Registry (reference: driver.go:25-41 BuiltinDrivers / NewDriver)

DriverFactory = Callable[[DriverContext], Driver]

BUILTIN_DRIVERS: Dict[str, DriverFactory] = {}


def register_driver(name: str, factory: DriverFactory) -> None:
    BUILTIN_DRIVERS[name] = factory


def new_driver(name: str, ctx: DriverContext) -> Driver:
    factory = BUILTIN_DRIVERS.get(name)
    if factory is None:
        raise DriverError(f"unknown driver '{name}'")
    ctx.driver_name = name
    return factory(ctx)


def validate_driver_config(name: str, config: Dict, node: Optional[s.Node] = None) -> None:
    """Static validation used by job endpoints / jobspec checks."""
    ctx = DriverContext(driver_name=name, alloc_id="", config=None, node=node)
    new_driver(name, ctx).validate(config)


# ---------------------------------------------------------------------------
# Shared option parsing helper (mapstructure-equivalent, weakly typed)

_DURATION_SUFFIX = {"ns": 1e-9, "us": 1e-6, "ms": 1e-3, "s": 1.0, "m": 60.0, "h": 3600.0}


def parse_duration(v) -> float:
    """'10s'/'1m'/'250ms' → seconds; numbers pass through."""
    if isinstance(v, (int, float)):
        return float(v)
    txt = str(v).strip()
    for suf in ("ms", "us", "ns", "s", "m", "h"):
        if txt.endswith(suf):
            return float(txt[: -len(suf)]) * _DURATION_SUFFIX[suf]
    return float(txt)


def opt(config: Dict, key: str, default=None, cast=None):
    if key not in config or config[key] is None:
        return default
    v = config[key]
    if cast is bool and isinstance(v, str):
        return v.lower() in ("1", "true", "yes")
    if cast is not None:
        return cast(v)
    return v


def find_executable(name: str) -> Optional[str]:
    """PATH lookup used by driver fingerprints."""
    if os.path.sep in name:
        return name if os.access(name, os.X_OK) else None
    for p in os.environ.get("PATH", "").split(os.pathsep):
        cand = os.path.join(p, name)
        if os.path.isfile(cand) and os.access(cand, os.X_OK):
            return cand
    return None
