"""Host stats collection (reference: client/stats/host.go:78-213).

gopsutil-equivalents read straight from /proc; fields mirror
HostStats so the `/v1/client/stats` payload shape matches.
"""
from __future__ import annotations

import os
import shutil
import time
from typing import Dict, List, Optional


class HostStatsCollector:
    def __init__(self, alloc_dir: str = "/"):
        self.alloc_dir = alloc_dir if os.path.exists(alloc_dir) else "/"
        self._last_cpu: Optional[List[int]] = None
        self._last_ts = 0.0

    def collect(self) -> Dict:
        now = time.time()
        stats = {
            "Timestamp": int(now * 1e9),
            "Uptime": self._uptime(),
            "Memory": self._memory(),
            "CPU": self._cpu(now),
            "DiskStats": [self._disk(self.alloc_dir)],
            "AllocDirStats": self._disk(self.alloc_dir),
        }
        return stats

    @staticmethod
    def _uptime() -> int:
        try:
            with open("/proc/uptime") as f:
                return int(float(f.read().split()[0]))
        except (OSError, ValueError, IndexError):
            return 0

    @staticmethod
    def _memory() -> Dict:
        info = {}
        try:
            with open("/proc/meminfo") as f:
                for line in f:
                    k, v = line.split(":", 1)
                    info[k] = int(v.strip().split()[0]) * 1024
        except (OSError, ValueError, IndexError):
            return {}
        total = info.get("MemTotal", 0)
        free = info.get("MemFree", 0)
        avail = info.get("MemAvailable", free)
        return {"Total": total, "Available": avail, "Free": free,
                "Used": total - avail}

    def _cpu(self, now: float) -> List[Dict]:
        try:
            with open("/proc/stat") as f:
                first = f.readline().split()
            ticks = [int(x) for x in first[1:8]]
        except (OSError, ValueError, IndexError):
            return []
        out = []
        if self._last_cpu is not None:
            dt = [b - a for a, b in zip(self._last_cpu, ticks)]
            total = sum(dt) or 1
            idle = dt[3]
            out = [{
                "CPU": "cpu-total",
                "User": 100.0 * dt[0] / total,
                "System": 100.0 * dt[2] / total,
                "Idle": 100.0 * idle / total,
                "Total": 100.0 * (total - idle) / total,
            }]
        self._last_cpu = ticks
        self._last_ts = now
        return out

    @staticmethod
    def _disk(path: str) -> Dict:
        try:
            u = shutil.disk_usage(path)
        except OSError:
            return {"Device": path}
        return {
            "Device": path,
            "Mountpoint": path,
            "Size": u.total,
            "Used": u.used,
            "Available": u.free,
            "UsedPercent": 100.0 * u.used / max(1, u.total),
        }


class ServerList:
    """Prioritized, shuffled server endpoint list
    (reference: client/serverlist.go)."""

    def __init__(self, servers: Optional[List[str]] = None):
        import random
        self._rand = random.Random()
        self._servers: List[str] = list(servers or [])
        self._rand.shuffle(self._servers)

    def all(self) -> List[str]:
        return list(self._servers)

    def set(self, servers: List[str]) -> None:
        self._servers = list(servers)
        self._rand.shuffle(self._servers)

    def failed(self, server: str) -> None:
        """Demote a failed server to the back of the list."""
        if server in self._servers:
            self._servers.remove(server)
            self._servers.append(server)

    def first(self) -> Optional[str]:
        return self._servers[0] if self._servers else None
