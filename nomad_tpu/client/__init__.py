"""L5 data plane: node agent, alloc/task runners, drivers, fingerprints
(reference: client/)."""

from .alloc_runner import AllocRunner, get_client_status
from .client import Client
from .config import ClientConfig
from .restarts import RestartTracker
from .task_runner import TaskRunner

__all__ = [
    "AllocRunner",
    "Client",
    "ClientConfig",
    "RestartTracker",
    "TaskRunner",
    "get_client_status",
]
