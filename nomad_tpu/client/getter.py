"""Artifact fetcher (reference: client/getter/getter.go:36-127, which
wraps go-getter).

Supports ``file://`` paths, plain local paths, ``http(s)://`` URLs,
``git::`` clones (ref via the ``ref`` getter option), and ``s3://``
objects (anonymous for public objects; SigV4-signed via stdlib
hmac/hashlib when AWS_ACCESS_KEY_ID/AWS_SECRET_ACCESS_KEY are present —
no SDK dependency), with optional sha256/md5 checksum verification via
the same ``checksum=<type>:<hex>`` option go-getter uses.  Source
strings are env-interpolated before fetch (getter.go GetArtifact).
"""
from __future__ import annotations

import hashlib
import os
import shutil
import urllib.parse
import urllib.request
from typing import Optional

from ..structs import structs as s
from .driver.env import TaskEnv


class ArtifactError(Exception):
    pass


def get_artifact(task_env: TaskEnv, artifact: s.TaskArtifact, task_dir: str) -> str:
    source = task_env.replace_env(artifact.getter_source or "")
    if not source:
        raise ArtifactError("artifact source empty")
    rel_dest = task_env.replace_env(artifact.relative_dest or "local/")
    dest_dir = os.path.join(task_dir, rel_dest.lstrip("/"))
    os.makedirs(dest_dir, exist_ok=True)

    # git::<url> (go-getter forced-protocol syntax) clones the repository
    # into the destination directory.
    if source.startswith("git::") or source.endswith(".git"):
        return _get_git(source, artifact, dest_dir)
    if source.startswith("s3::") or source.startswith("s3://"):
        dest = _get_s3(source, artifact, task_env, dest_dir)
        _verify_checksum(artifact, task_env, dest)
        return dest

    parsed = urllib.parse.urlparse(source)
    name = os.path.basename(parsed.path) or "artifact"
    dest = os.path.join(dest_dir, name)

    if parsed.scheme in ("", "file"):
        src_path = parsed.path if parsed.scheme == "file" else source
        if not os.path.exists(src_path):
            raise ArtifactError(f"artifact not found: {src_path}")
        if os.path.isdir(src_path):
            shutil.copytree(src_path, dest, dirs_exist_ok=True)
        else:
            shutil.copy2(src_path, dest)
    elif parsed.scheme in ("http", "https"):
        try:
            with urllib.request.urlopen(source, timeout=30) as resp, \
                    open(dest, "wb") as out:
                shutil.copyfileobj(resp, out)
        except OSError as e:
            raise ArtifactError(f"failed to fetch {source}: {e}") from e
    else:
        raise ArtifactError(f"unsupported artifact scheme {parsed.scheme!r}")

    _verify_checksum(artifact, task_env, dest)
    return dest


def _verify_checksum(artifact: s.TaskArtifact, task_env: TaskEnv, path: str) -> None:
    opts = artifact.getter_options or {}
    spec = task_env.replace_env(opts.get("checksum", "") or "")
    if not spec or os.path.isdir(path):
        return
    try:
        algo, want = spec.split(":", 1)
    except ValueError:
        raise ArtifactError(f"bad checksum spec {spec!r}")
    try:
        h = hashlib.new(algo)
    except ValueError:
        raise ArtifactError(f"unsupported checksum algo {algo!r}")
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 16), b""):
            h.update(chunk)
    if h.hexdigest() != want.lower():
        raise ArtifactError(
            f"checksum mismatch for {path}: got {h.hexdigest()}, want {want}")


def _get_s3(source: str, artifact: s.TaskArtifact, task_env: TaskEnv,
            dest_dir: str) -> str:
    """Fetch an S3 object (go-getter's s3 getter, client/getter).

    Source forms:
      s3://bucket/key            — region via the ``region`` getter
                                   option or AWS_REGION (default us-east-1)
      s3::https://host/bucket/key — explicit endpoint (go-getter forced-
                                   protocol form; also how tests point at
                                   a local fake)
    Anonymous unless AWS_ACCESS_KEY_ID/AWS_SECRET_ACCESS_KEY are set, in
    which case the request is SigV4-signed with stdlib hmac/hashlib."""
    opts = artifact.getter_options or {}
    region = task_env.replace_env(opts.get("region", "") or "") or \
        os.environ.get("AWS_REGION") or "us-east-1"

    if source.startswith("s3::"):
        # Forced-protocol form: an explicit, already-encoded URL.
        url = source[len("s3::"):]
        parsed = urllib.parse.urlparse(url)
        key_path = parsed.path.lstrip("/")
    else:
        # s3://bucket/key — the key is RAW (may contain spaces/#/?), so
        # parse it manually (urlparse would strip a '#key-fragment') and
        # percent-encode it into the URL we actually send.
        rest = source[len("s3://"):]
        bucket, _, key_path = rest.partition("/")
        host = f"{bucket}.s3.{region}.amazonaws.com"
        url = (f"https://{host}/"
               f"{urllib.parse.quote(key_path, safe='/-_.~')}")
        parsed = urllib.parse.urlparse(url)

    name = os.path.basename(key_path) or "artifact"
    dest = os.path.join(dest_dir, name)

    headers = {}
    access = os.environ.get("AWS_ACCESS_KEY_ID")
    secret = os.environ.get("AWS_SECRET_ACCESS_KEY")
    if access and secret:
        headers = _sigv4_headers(
            "GET", parsed, region, access, secret,
            os.environ.get("AWS_SESSION_TOKEN"))

    req = urllib.request.Request(url, headers=headers)
    try:
        with urllib.request.urlopen(req, timeout=60) as resp, \
                open(dest, "wb") as out:
            shutil.copyfileobj(resp, out)
    except OSError as e:
        raise ArtifactError(f"failed to fetch {source}: {e}") from e
    return dest


def _sigv4_headers(method: str, parsed, region: str, access: str,
                   secret: str, session_token: Optional[str]) -> dict:
    """AWS Signature Version 4 for a bodyless request — the standard
    canonical-request / string-to-sign / signing-key derivation, done
    with hashlib+hmac so no SDK is needed."""
    import datetime
    import hmac

    t = datetime.datetime.now(datetime.timezone.utc)
    amz_date = t.strftime("%Y%m%dT%H%M%SZ")
    datestamp = t.strftime("%Y%m%d")
    service = "s3"
    payload_hash = hashlib.sha256(b"").hexdigest()
    host = parsed.netloc

    signed = {"host": host, "x-amz-content-sha256": payload_hash,
              "x-amz-date": amz_date}
    if session_token:
        signed["x-amz-security-token"] = session_token
    signed_names = ";".join(sorted(signed))
    canonical_headers = "".join(
        f"{k}:{signed[k]}\n" for k in sorted(signed))
    # Canonical URI: each path segment URI-encoded exactly once (an
    # already-encoded path must not be double-encoded — unquote first),
    # and the query string as sorted, individually-encoded k=v pairs.
    segments = (parsed.path or "/").split("/")
    canonical_uri = "/".join(
        urllib.parse.quote(urllib.parse.unquote(seg), safe="-_.~")
        for seg in segments) or "/"
    q_pairs = urllib.parse.parse_qsl(parsed.query, keep_blank_values=True)
    canonical_query = "&".join(
        f"{urllib.parse.quote(k, safe='-_.~')}="
        f"{urllib.parse.quote(v, safe='-_.~')}"
        for k, v in sorted(q_pairs))
    canonical = (f"{method}\n{canonical_uri}\n{canonical_query}\n"
                 f"{canonical_headers}\n{signed_names}\n{payload_hash}")
    scope = f"{datestamp}/{region}/{service}/aws4_request"
    to_sign = ("AWS4-HMAC-SHA256\n" + amz_date + "\n" + scope + "\n"
               + hashlib.sha256(canonical.encode()).hexdigest())

    def _hmac(key: bytes, msg: str) -> bytes:
        return hmac.new(key, msg.encode(), hashlib.sha256).digest()

    k = _hmac(("AWS4" + secret).encode(), datestamp)
    k = _hmac(k, region)
    k = _hmac(k, service)
    k = _hmac(k, "aws4_request")
    signature = hmac.new(k, to_sign.encode(), hashlib.sha256).hexdigest()

    headers = {
        "x-amz-date": amz_date,
        "x-amz-content-sha256": payload_hash,
        "Authorization": (
            f"AWS4-HMAC-SHA256 Credential={access}/{scope}, "
            f"SignedHeaders={signed_names}, Signature={signature}"),
    }
    if session_token:
        headers["x-amz-security-token"] = session_token
    return headers


def _get_git(source: str, artifact: s.TaskArtifact, dest_dir: str) -> str:
    """Clone a git artifact (go-getter's git detector): ``git::<url>``,
    optional ``ref`` getter option selects a branch/tag/commit."""
    import subprocess

    opts = artifact.getter_options or {}
    if opts.get("checksum"):
        # go-getter rejects checksums on directory gets; silently skipping
        # a user-specified integrity check would be worse.
        raise ArtifactError(
            "checksum verification is not supported for git artifacts")
    url = source[len("git::"):] if source.startswith("git::") else source
    name = os.path.basename(urllib.parse.urlparse(url).path)
    if name.endswith(".git"):
        name = name[:-4]
    dest = os.path.join(dest_dir, name or "repo")
    # Restart loops re-run artifact fetch; a stale clone must not fail it.
    if os.path.isdir(dest):
        shutil.rmtree(dest, ignore_errors=True)
    ref = opts.get("ref", "")
    try:
        subprocess.run(["git", "clone", "--quiet", url, dest],
                       check=True, capture_output=True, timeout=300)
        if ref:
            subprocess.run(["git", "-C", dest, "checkout", "--quiet", ref],
                           check=True, capture_output=True, timeout=60)
    except FileNotFoundError as e:
        raise ArtifactError(f"git not available: {e}") from e
    except subprocess.TimeoutExpired as e:
        raise ArtifactError(f"git clone timed out: {e}") from e
    except subprocess.CalledProcessError as e:
        raise ArtifactError(
            f"git clone failed: {e.stderr.decode(errors='replace')}") from e
    return dest
