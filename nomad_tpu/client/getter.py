"""Artifact fetcher (reference: client/getter/getter.go:36-127, which
wraps go-getter).

Supports ``file://`` paths, plain local paths, ``http(s)://`` URLs, and
``git::`` clones (ref via the ``ref`` getter option), with optional
sha256/md5 checksum verification via the same ``checksum=<type>:<hex>``
option go-getter uses.  Source strings are env-interpolated before fetch
(getter.go GetArtifact).
"""
from __future__ import annotations

import hashlib
import os
import shutil
import urllib.parse
import urllib.request
from typing import Optional

from ..structs import structs as s
from .driver.env import TaskEnv


class ArtifactError(Exception):
    pass


def get_artifact(task_env: TaskEnv, artifact: s.TaskArtifact, task_dir: str) -> str:
    source = task_env.replace_env(artifact.getter_source or "")
    if not source:
        raise ArtifactError("artifact source empty")
    rel_dest = task_env.replace_env(artifact.relative_dest or "local/")
    dest_dir = os.path.join(task_dir, rel_dest.lstrip("/"))
    os.makedirs(dest_dir, exist_ok=True)

    # git::<url> (go-getter forced-protocol syntax) clones the repository
    # into the destination directory.
    if source.startswith("git::") or source.endswith(".git"):
        return _get_git(source, artifact, dest_dir)

    parsed = urllib.parse.urlparse(source)
    name = os.path.basename(parsed.path) or "artifact"
    dest = os.path.join(dest_dir, name)

    if parsed.scheme in ("", "file"):
        src_path = parsed.path if parsed.scheme == "file" else source
        if not os.path.exists(src_path):
            raise ArtifactError(f"artifact not found: {src_path}")
        if os.path.isdir(src_path):
            shutil.copytree(src_path, dest, dirs_exist_ok=True)
        else:
            shutil.copy2(src_path, dest)
    elif parsed.scheme in ("http", "https"):
        try:
            with urllib.request.urlopen(source, timeout=30) as resp, \
                    open(dest, "wb") as out:
                shutil.copyfileobj(resp, out)
        except OSError as e:
            raise ArtifactError(f"failed to fetch {source}: {e}") from e
    else:
        raise ArtifactError(f"unsupported artifact scheme {parsed.scheme!r}")

    _verify_checksum(artifact, task_env, dest)
    return dest


def _verify_checksum(artifact: s.TaskArtifact, task_env: TaskEnv, path: str) -> None:
    opts = artifact.getter_options or {}
    spec = task_env.replace_env(opts.get("checksum", "") or "")
    if not spec or os.path.isdir(path):
        return
    try:
        algo, want = spec.split(":", 1)
    except ValueError:
        raise ArtifactError(f"bad checksum spec {spec!r}")
    try:
        h = hashlib.new(algo)
    except ValueError:
        raise ArtifactError(f"unsupported checksum algo {algo!r}")
    with open(path, "rb") as f:
        for chunk in iter(lambda: f.read(1 << 16), b""):
            h.update(chunk)
    if h.hexdigest() != want.lower():
        raise ArtifactError(
            f"checksum mismatch for {path}: got {h.hexdigest()}, want {want}")


def _get_git(source: str, artifact: s.TaskArtifact, dest_dir: str) -> str:
    """Clone a git artifact (go-getter's git detector): ``git::<url>``,
    optional ``ref`` getter option selects a branch/tag/commit."""
    import subprocess

    opts = artifact.getter_options or {}
    if opts.get("checksum"):
        # go-getter rejects checksums on directory gets; silently skipping
        # a user-specified integrity check would be worse.
        raise ArtifactError(
            "checksum verification is not supported for git artifacts")
    url = source[len("git::"):] if source.startswith("git::") else source
    name = os.path.basename(urllib.parse.urlparse(url).path)
    if name.endswith(".git"):
        name = name[:-4]
    dest = os.path.join(dest_dir, name or "repo")
    # Restart loops re-run artifact fetch; a stale clone must not fail it.
    if os.path.isdir(dest):
        shutil.rmtree(dest, ignore_errors=True)
    ref = opts.get("ref", "")
    try:
        subprocess.run(["git", "clone", "--quiet", url, dest],
                       check=True, capture_output=True, timeout=300)
        if ref:
            subprocess.run(["git", "-C", dest, "checkout", "--quiet", ref],
                           check=True, capture_output=True, timeout=60)
    except FileNotFoundError as e:
        raise ArtifactError(f"git not available: {e}") from e
    except subprocess.TimeoutExpired as e:
        raise ArtifactError(f"git clone timed out: {e}") from e
    except subprocess.CalledProcessError as e:
        raise ArtifactError(
            f"git clone failed: {e.stderr.decode(errors='replace')}") from e
    return dest
