"""Client: the node agent — fingerprints the host, registers the node,
heartbeats, long-polls its allocation set, diffs it against running
AllocRunners, and batches alloc status updates back to the servers
(reference: client/client.go:99-2461).

The server connection is abstracted behind the duck-typed RPC surface
(node_register / node_update_status / node_update_allocs /
node_get_client_allocs) so the same Client runs against an in-process
Server (dev/test, like the reference's dev agent) or a remote RPC proxy
(agent networking layer).
"""
from __future__ import annotations

import logging
import os
import tempfile
import threading
import time
from typing import Callable, Dict, List, Optional

from .. import fault
from ..structs import structs as s
from ..utils.backoff import Backoff
from .alloc_runner import AllocRunner
from .config import ClientConfig
from .fingerprint import fingerprint_node
from .gc import AllocGarbageCollector
from .state import StateDB
from .stats import HostStatsCollector, ServerList

# Import for driver-registry side effects (BuiltinDrivers registration).
from .driver import mock_driver as _mock_driver  # noqa: F401
from .driver import exec_drivers as _exec_drivers  # noqa: F401
from .driver import container_drivers as _container_drivers  # noqa: F401
from .driver.driver import BUILTIN_DRIVERS, DriverContext, new_driver

# Status-sync batching interval (client.go:76-78 allocSyncIntv = 200ms).
ALLOC_SYNC_INTERVAL = 0.2
REGISTER_RETRY_INTERVAL = 15.0
INITIAL_HEARTBEAT_STAGGER = 10.0


class Client:
    def __init__(self, config: Optional[ClientConfig] = None,
                 rpc=None,
                 logger: Optional[logging.Logger] = None,
                 vault_api=None,
                 consul=None):
        self.config = config or ClientConfig()
        self.rpc = rpc
        self.logger = logger or logging.getLogger("nomad_tpu.client")
        # Consul-shaped service client (command/agent/consul/client.go:87);
        # owned by the agent, shared with task runners for service
        # registration with the task lifecycle.
        self.consul = consul

        # Vault token manager (client/vaultclient): derives through the
        # server RPC, renews directly against Vault.  Transport resolution:
        # injected vault_api (tests/agent) > configured vault_addr (real
        # HTTP) > the in-process server's own transport (dev agent).
        from .vaultclient import ClientVaultClient

        if vault_api is None and getattr(self.config, "vault_addr", ""):
            from ..server.vault import HTTPVault

            vault_api = HTTPVault(self.config.vault_addr,
                                  getattr(self.config, "vault_token", ""))
        if vault_api is None:
            server_vault = getattr(rpc, "vault", None)
            if server_vault is not None and server_vault.enabled:
                vault_api = server_vault.api
        self.vault_client = ClientVaultClient(
            derive_fn=self._derive_vault_tokens,
            renew_fn=(vault_api.renew_token if vault_api is not None
                      else None),
            unwrap_fn=(vault_api.unwrap if vault_api is not None
                       else None),
            logger=self.logger.getChild("vault"))

        if not self.config.alloc_dir:
            self.config.alloc_dir = tempfile.mkdtemp(prefix="nomad-tpu-alloc-")
        self.state_db: Optional[StateDB] = None
        if self.config.state_dir:
            self.state_db = StateDB(self.config.state_dir)

        self.node = self._setup_node()
        self._fingerprint()
        self._setup_drivers()

        self.alloc_runners: Dict[str, AllocRunner] = {}
        self._alloc_lock = threading.Lock()
        self._alloc_updates: Dict[str, s.Allocation] = {}
        self._alloc_updates_lock = threading.Lock()
        self.garbage_collector = AllocGarbageCollector(
            self.config, stats_path=self.config.alloc_dir, logger=self.logger)
        self.host_stats = HostStatsCollector(self.config.alloc_dir)
        self.servers = ServerList(self.config.servers)

        self.heartbeat_ttl = 10.0
        self._registered = threading.Event()
        self._shutdown = threading.Event()
        self._threads: List[threading.Thread] = []
        self._latest_alloc_index = 0

        # Restore persisted alloc runners before any server traffic, like
        # NewClient → restoreState (client.go:335).
        if self.state_db is not None:
            self._restore_state()

    # -- node construction -------------------------------------------------
    def _setup_node(self) -> s.Node:
        """(client.go:253 setupNode)."""
        node = s.Node(
            id=s.generate_uuid(),
            datacenter=self.config.datacenter,
            name=self.config.node_name or os.uname().nodename,
            node_class=self.config.node_class,
            attributes={},
            meta=dict(self.config.meta),
            resources=s.Resources(),
            reserved=self.config.reserved or s.Resources(),
            status=s.NODE_STATUS_INIT,
        )
        return node

    def _fingerprint(self) -> None:
        applied = fingerprint_node(self.config, self.node)
        if self.config.cpu_total_compute:
            self.node.resources.cpu = self.config.cpu_total_compute
            self.node.attributes["cpu.totalcompute"] = str(
                self.config.cpu_total_compute)
        self.logger.info("client: fingerprints applied: %s", ",".join(applied))

    def _setup_drivers(self) -> None:
        """Driver availability scan (client.go:969 setupDrivers)."""
        avail = []
        for name in BUILTIN_DRIVERS:
            ctx = DriverContext(driver_name=name, alloc_id="",
                                config=self.config, node=self.node)
            try:
                d = new_driver(name, ctx)
                if d.fingerprint(self.node):
                    avail.append(name)
            except Exception:
                continue
        self.node.compute_class()
        self.logger.info("client: available drivers: %s", ",".join(avail))

    # -- lifecycle ---------------------------------------------------------
    def _derive_vault_tokens(self, alloc_id: str, task_names):
        """Node.DeriveVaultToken through whichever server RPC surface this
        client was built with (in-proc Server or RemoteServerRPC)."""
        return self.rpc.derive_vault_token(alloc_id, task_names)

    def start(self) -> None:
        for target in (self._register_and_heartbeat, self._watch_allocations,
                       self._alloc_sync_loop):
            t = threading.Thread(target=target, daemon=True,
                                 name=f"client-{target.__name__}")
            t.start()
            self._threads.append(t)
        self.vault_client.start()
        self.garbage_collector.run()

    def shutdown(self) -> None:
        self._shutdown.set()
        self.vault_client.stop()
        self.garbage_collector.stop()
        with self._alloc_lock:
            runners = list(self.alloc_runners.values())
        for r in runners:
            r.save_state()

    # -- registration + heartbeat (client.go:1031) -------------------------
    @staticmethod
    def _client_rpc_fault(method: str) -> None:
        """Client-side ``rpc.send`` fault point: the agent's logical
        server calls pass through here even when the transport is an
        in-process Server (dev/test), so scenarios can drop or delay a
        client's registration/heartbeat/watch traffic deterministically
        regardless of wiring.  drop/error/truncate all surface as the
        exception the surrounding retry loop already handles."""
        act = fault.faultpoint("rpc.send", method=method, side="client")
        if act is None:
            return
        if act.kind == "delay":
            time.sleep(act.delay)
            return
        if act.kind in ("drop", "truncate", "error", "crash"):
            act.raise_injected()

    def _try_register(self) -> bool:
        try:
            self._client_rpc_fault("Node.Register")
            _index, ttl = self.rpc.node_register(self.node.copy())
            self.heartbeat_ttl = ttl or self.heartbeat_ttl
            self.node.status = s.NODE_STATUS_READY
            self._registered.set()
            return True
        except Exception as e:
            self.logger.warning("client: registration failed: %s", e)
            return False

    def _consul_discover_servers(self) -> bool:
        """Find servers through a Consul-shaped catalog when none answer
        (client.go:2139 consulDiscovery): query the configured catalog's
        'nomad' service for RPC endpoints."""
        addr = getattr(self.config, "consul_address", "")
        if not addr:
            return False
        import json
        import urllib.request
        try:
            url = addr.rstrip("/") + "/v1/catalog/service/nomad"
            with urllib.request.urlopen(url, timeout=5.0) as resp:
                entries = json.loads(resp.read() or b"[]")
        except Exception as e:
            self.logger.warning("client: consul discovery failed: %s", e)
            return False
        servers = [f"{e['Address']}:{e['Port']}" for e in entries
                   if e.get("Address") and e.get("Port")]
        if not servers:
            return False
        self.logger.info("client: discovered servers via consul: %s",
                         ",".join(servers))
        self.servers.set(servers)
        if hasattr(self.rpc, "servers"):
            self.rpc.servers = list(servers)
        return True

    def _register_and_heartbeat(self) -> None:
        # Jittered exponential backoff between registration attempts: a
        # fleet re-registering after a server restart must spread out
        # rather than re-dial on one fixed 15s boundary.
        register_backoff = Backoff(base=0.5,
                                   max_delay=REGISTER_RETRY_INTERVAL)
        while not self._shutdown.is_set():
            if self._try_register():
                break
            if self._consul_discover_servers():
                register_backoff.reset()
                continue  # fresh servers — retry immediately
            if self._shutdown.wait(register_backoff.next_delay()):
                return
        # Heartbeat at TTL/2-ish like the reference's jittered resend
        while not self._shutdown.is_set():
            wait = max(0.5, self.heartbeat_ttl / 2.0)
            if self._shutdown.wait(wait):
                return
            try:
                self._client_rpc_fault("Node.UpdateStatus")
                _index, ttl = self.rpc.node_update_status(
                    self.node.id, s.NODE_STATUS_READY)
                if ttl:
                    self.heartbeat_ttl = ttl
            except Exception as e:
                # The server may have forgotten us (restart with lost state,
                # node GC) — fall back to re-registration like
                # client.go:1127 (retryRegisterNode on heartbeat failure).
                self.logger.warning(
                    "client: heartbeat failed, re-registering: %s", e)
                self._try_register()

    # -- allocation watching (client.go:1364 watchAllocations) -------------
    def _watch_allocations(self) -> None:
        self._registered.wait()
        watch_backoff = Backoff(base=0.25, max_delay=5.0)
        while not self._shutdown.is_set():
            try:
                self._client_rpc_fault("Node.GetClientAllocs")
                allocs, index = self.rpc.node_get_client_allocs(
                    self.node.id, min_index=self._latest_alloc_index,
                    max_wait=5.0)
            except Exception as e:
                self.logger.warning("client: alloc watch failed: %s", e)
                if self._shutdown.wait(watch_backoff.next_delay()):
                    return
                continue
            watch_backoff.reset()
            if index <= self._latest_alloc_index:
                continue
            self._latest_alloc_index = index
            self._run_allocs(allocs)

    def _run_allocs(self, server_allocs: List[s.Allocation]) -> None:
        """Diff desired vs running (client.go:1559 runAllocs)."""
        by_id = {a.id: a for a in server_allocs}
        with self._alloc_lock:
            existing = dict(self.alloc_runners)

        # removals: the server no longer knows the alloc
        for alloc_id, runner in existing.items():
            if alloc_id not in by_id:
                self._remove_alloc(alloc_id, runner)

        for alloc_id, alloc in by_id.items():
            runner = existing.get(alloc_id)
            if runner is None:
                if not alloc.terminal_status():
                    self._add_alloc(alloc)
            elif alloc.alloc_modify_index > runner.alloc.alloc_modify_index:
                runner.update(alloc)

    def _add_alloc(self, alloc: s.Allocation) -> None:
        """(client.go:1812 addAlloc) + sticky-disk chaining."""
        tg = alloc.job.lookup_task_group(alloc.task_group) if alloc.job else None
        prev_dir = None
        remote_migrate = False
        if (alloc.previous_allocation and tg is not None
                and tg.ephemeral_disk is not None and tg.ephemeral_disk.sticky):
            with self._alloc_lock:
                prev = self.alloc_runners.get(alloc.previous_allocation)
            if prev is not None:
                prev_dir = prev.alloc_dir
            elif tg.ephemeral_disk.migrate:
                # Previous alloc lives on another node: pull its sticky
                # data over that node's HTTP fs surface once it is
                # terminal (client.go:1743 migrateRemoteAllocDir).
                remote_migrate = True

        self.garbage_collector.make_room_for(
            tg.ephemeral_disk.size_mb if tg and tg.ephemeral_disk else 0,
            total_live_allocs=len(self.alloc_runners))

        runner = AllocRunner(
            config=self.config,
            alloc=alloc,
            updater=self._alloc_status_update,
            node=self.node,
            state_db=self.state_db,
            prev_alloc_dir=prev_dir,
            vault_client=self.vault_client,
            consul=self.consul,
            logger=self.logger,
        )
        # Block start on the previous alloc reaching a terminal state
        # (sticky disk / in-place upgrade ordering, client.go:1654).
        if alloc.previous_allocation:
            with self._alloc_lock:
                prev = self.alloc_runners.get(alloc.previous_allocation)
            if prev is not None and not prev.done.is_set():
                runner.waiting_on_previous.clear()
                threading.Thread(
                    target=lambda: (prev.done.wait(),
                                    runner.waiting_on_previous.set()),
                    daemon=True).start()
            elif remote_migrate:
                runner.waiting_on_previous.clear()
                threading.Thread(
                    target=self._migrate_remote_alloc_dir,
                    args=(alloc.previous_allocation, runner),
                    daemon=True).start()
        with self._alloc_lock:
            self.alloc_runners[alloc.id] = runner
        runner.run()

    def _migrate_remote_alloc_dir(self, prev_alloc_id: str,
                                  runner: AllocRunner) -> None:
        """Pull the previous allocation's sticky data from its node's HTTP
        fs surface once that alloc is terminal
        (client.go:1743 migrateRemoteAllocDir).  Always releases the
        runner's start gate — a failed migration starts fresh, it does
        not wedge the replacement."""
        import base64
        import json as _json
        import tempfile
        import urllib.request

        try:
            deadline = time.time() + 300.0
            prev = None
            terminal = False
            while time.time() < deadline and not self._shutdown.is_set():
                prev = self.rpc.alloc_get(prev_alloc_id)
                if prev is None or prev.terminal_status() \
                        or prev.client_terminal_status():
                    terminal = True
                    break
                time.sleep(0.5)
            if prev is None:
                return
            if not terminal:
                # The old alloc is still live: snapshotting a dir being
                # written would migrate torn data.  Start fresh instead.
                self.logger.warning(
                    "migration: previous alloc %s still running after "
                    "wait; starting without sticky data",
                    prev_alloc_id[:8])
                return
            node = self.rpc.node_get(prev.node_id)
            if node is None or not node.http_addr:
                self.logger.warning(
                    "migration: node %s has no HTTP address", prev.node_id)
                return
            url = (f"http://{node.http_addr}/v1/client/fs/snapshot/"
                   f"{prev_alloc_id}")
            # Stream the tar frames to a temp file: sticky disks can be
            # GBs; neither side holds the whole archive in memory.
            fd, tmp = tempfile.mkstemp(suffix=".tar")
            size = 0
            try:
                with os.fdopen(fd, "wb") as out, urllib.request.urlopen(
                        url, timeout=300.0) as resp:
                    for line in resp:
                        line = line.strip()
                        if not line:
                            continue
                        frame = _json.loads(line)
                        if frame.get("Data"):
                            chunk = base64.b64decode(frame["Data"])
                            out.write(chunk)
                            size += len(chunk)
            except Exception:
                try:
                    os.unlink(tmp)  # never leak a partial multi-GB tar
                except OSError:
                    pass
                raise
            if size:
                runner.remote_snapshot_path = tmp
                self.logger.info(
                    "migration: pulled %d bytes of sticky data for %s",
                    size, runner.alloc.id[:8])
            else:
                os.unlink(tmp)
        except Exception as e:
            self.logger.warning("migration from %s failed: %s",
                                prev_alloc_id[:8], e)
        finally:
            runner.waiting_on_previous.set()

    def _remove_alloc(self, alloc_id: str, runner: AllocRunner) -> None:
        with self._alloc_lock:
            self.alloc_runners.pop(alloc_id, None)
        runner.destroy()
        self.garbage_collector.mark_for_collection(runner)

    # -- status sync (client.go:1305 allocSync) ----------------------------
    def _alloc_status_update(self, alloc: s.Allocation) -> None:
        with self._alloc_updates_lock:
            self._alloc_updates[alloc.id] = alloc
        if alloc.terminal_status():
            with self._alloc_lock:
                runner = self.alloc_runners.get(alloc.id)
            if runner is not None:
                self.garbage_collector.mark_for_collection(runner)

    def _alloc_sync_loop(self) -> None:
        while not self._shutdown.wait(ALLOC_SYNC_INTERVAL):
            with self._alloc_updates_lock:
                if not self._alloc_updates:
                    continue
                batch = list(self._alloc_updates.values())
                self._alloc_updates = {}
            try:
                self.rpc.node_update_allocs(batch)
            except Exception as e:
                self.logger.warning("client: alloc sync failed: %s", e)
                with self._alloc_updates_lock:
                    for a in batch:
                        self._alloc_updates.setdefault(a.id, a)

    # -- restore (client.go:335 restoreState) ------------------------------
    def _restore_state(self) -> None:
        for alloc_id in self.state_db.list_alloc_runners():
            state = self.state_db.get_alloc_runner(alloc_id)
            if not state:
                continue
            alloc = state.get("alloc")
            if alloc is None:
                continue
            runner = AllocRunner(
                config=self.config, alloc=alloc,
                updater=self._alloc_status_update, node=self.node,
                state_db=self.state_db, vault_client=self.vault_client,
                consul=self.consul, logger=self.logger)
            runner.task_states = dict(state.get("task_states", {}))
            with self._alloc_lock:
                self.alloc_runners[alloc_id] = runner
            if not alloc.terminal_status():
                runner.run()
            else:
                runner.done.set()
                self.garbage_collector.mark_for_collection(runner)

    # -- introspection (client HTTP endpoints) -----------------------------
    def get_alloc_runner(self, alloc_id: str) -> Optional[AllocRunner]:
        with self._alloc_lock:
            return self.alloc_runners.get(alloc_id)

    def get_client_alloc(self, alloc_id: str) -> Optional[s.Allocation]:
        runner = self.get_alloc_runner(alloc_id)
        return runner.current_alloc() if runner else None

    def stats(self) -> Dict:
        with self._alloc_lock:
            n = len(self.alloc_runners)
        return {
            "node_id": self.node.id,
            "known_servers": self.servers.all(),
            "num_allocations": n,
            "last_heartbeat_ttl": self.heartbeat_ttl,
            "host_stats": self.host_stats.collect(),
        }

    def num_allocs(self) -> int:
        with self._alloc_lock:
            return len(self.alloc_runners)

    def stream_task_logs(self, alloc_id: str, task: str,
                         log_type: str = "stdout", offset: int = 0,
                         origin: str = "start", follow: bool = False):
        """Framed log streaming with follow across rotations
        (fs_endpoint.go logs handler); yields StreamFrame dicts."""
        from .fs_stream import stream_log_frames

        runner = self.get_alloc_runner(alloc_id)
        if runner is None:
            raise KeyError(f"unknown allocation ID {alloc_id!r}")
        log_dir = os.path.join(runner.alloc_dir.alloc_dir, "alloc", "logs")

        def alive() -> bool:
            r = self.get_alloc_runner(alloc_id)
            return r is not None and not r.alloc.terminal_status()

        return stream_log_frames(log_dir, task, log_type, offset=offset,
                                 origin=origin, follow=follow, alive=alive)

    def stream_file(self, alloc_id: str, path: str, offset: int = 0,
                    origin: str = "start", follow: bool = False):
        """Framed single-file streaming (fs_endpoint.go stream handler)."""
        from .fs_stream import stream_file_frames

        runner = self.get_alloc_runner(alloc_id)
        if runner is None:
            raise KeyError(f"unknown allocation ID {alloc_id!r}")
        abs_path = runner.alloc_dir._safe_path(path)

        def alive() -> bool:
            r = self.get_alloc_runner(alloc_id)
            return r is not None and not r.alloc.terminal_status()

        return stream_file_frames(abs_path, path, offset=offset,
                                  origin=origin, follow=follow, alive=alive)

    def task_logs(self, alloc_id: str, task: str, log_type: str = "stdout",
                  max_bytes: int = 1 << 20) -> str:
        """Concatenate the tail of the rotated log files for a task (fs logs
        endpoint; reference: client log streaming via AllocDir ReadAt).
        Reads newest-first and stops once max_bytes is gathered so large
        rotations aren't buffered whole."""
        runner = self.get_alloc_runner(alloc_id)
        if runner is None:
            raise KeyError(f"unknown allocation ID {alloc_id!r}")
        log_dir = os.path.join(runner.alloc_dir.alloc_dir, "alloc", "logs")
        if not os.path.isdir(log_dir):
            return ""
        prefix = f"{task}.{log_type}."
        files = sorted(
            (f for f in os.listdir(log_dir) if f.startswith(prefix)),
            key=lambda f: int(f.rsplit(".", 1)[-1])
            if f.rsplit(".", 1)[-1].isdigit() else 0)
        chunks: List[bytes] = []
        remaining = max_bytes
        for fname in reversed(files):
            if remaining <= 0:
                break
            path = os.path.join(log_dir, fname)
            size = os.path.getsize(path)
            with open(path, "rb") as fh:
                if size > remaining:
                    fh.seek(size - remaining)
                data = fh.read(remaining)
            chunks.append(data)
            remaining -= len(data)
        return b"".join(reversed(chunks)).decode("utf-8", "replace")
