"""Client configuration (reference: client/config/config.go)."""
from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..structs import structs as s


@dataclass
class ClientConfig:
    state_dir: str = ""                 # "" → no persistence (dev mode)
    alloc_dir: str = ""                 # "" → tmp dir
    region: str = "global"
    datacenter: str = "dc1"
    node_name: str = ""
    node_class: str = ""
    network_interface: str = ""
    network_speed: int = 0
    cpu_total_compute: int = 0
    max_kill_timeout: float = 30.0
    meta: Dict[str, str] = field(default_factory=dict)
    options: Dict[str, str] = field(default_factory=dict)
    reserved: Optional[s.Resources] = None
    servers: List[str] = field(default_factory=list)
    # GC knobs (client/config/config.go:180-204)
    gc_interval: float = 60.0
    gc_disk_usage_threshold: float = 80.0
    gc_inode_usage_threshold: float = 70.0
    gc_max_allocs: int = 50
    gc_parallel_destroys: int = 2
    # Consul-shaped catalog HTTP address for server discovery
    # (client/config consul block; client.go:2139 consulDiscovery)
    consul_address: str = ""
    # Vault transport for client-side token renewal
    # (client/vaultclient against the real Vault HTTP API)
    vault_addr: str = ""
    vault_token: str = ""
    # Dev-mode shortcuts
    dev_mode: bool = False

    def read_option(self, key: str, default: str = "") -> str:
        return self.options.get(key, default)

    def read_bool_option(self, key: str, default: bool = False) -> bool:
        v = self.options.get(key)
        if v is None:
            return default
        return str(v).lower() in ("1", "true", "yes")
