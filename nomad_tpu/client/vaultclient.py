"""Client-side Vault token manager (reference:
client/vaultclient/vaultclient.go): derives tokens through the server RPC
(Node.DeriveVaultToken) and keeps them alive with a renewal min-heap."""

from __future__ import annotations

import heapq
import logging
import threading
import time
from typing import Callable, Dict, List, Optional


class ClientVaultClient:
    """Renewal heap + derive pass-through.

    ``derive_fn(alloc_id, task_names) -> {task: {token, accessor, ttl}}``
    is the server RPC; ``renew_fn(token, increment) -> new_ttl`` talks to
    Vault directly (the reference client renews against Vault itself)."""

    def __init__(self, derive_fn: Callable, renew_fn: Optional[Callable],
                 logger: Optional[logging.Logger] = None,
                 unwrap_fn: Optional[Callable] = None):
        self.derive_fn = derive_fn
        self.renew_fn = renew_fn
        self.unwrap_fn = unwrap_fn
        self.logger = logger or logging.getLogger("nomad_tpu.vaultclient")
        self._l = threading.Lock()
        self._heap: List = []          # (due_time, seq, token, ttl)
        self._tracked: Dict[str, float] = {}   # token -> ttl
        self._seq = 0
        self._stop = threading.Event()
        self._wake = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def start(self) -> None:
        self._thread = threading.Thread(target=self._renewal_loop,
                                        name="vault-renewal", daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self._wake.set()

    # -- derive --------------------------------------------------------

    def derive_token(self, alloc_id: str, task_names: List[str]
                     ) -> Dict[str, Dict]:
        out = self.derive_fn(alloc_id, task_names)
        # Servers response-wrap derived tokens (vault.go getWrappingFn):
        # unwrap the single-use cubbyhole here so task runners see the
        # plain {token, accessor, ttl} shape.
        unwrapped: Dict[str, Dict] = {}
        for task, info in out.items():
            if "wrapped_token" in info:
                if self.unwrap_fn is None:
                    raise RuntimeError(
                        "received a wrapped Vault token but no unwrap "
                        "transport is configured (vault_addr)")
                plain = dict(self.unwrap_fn(info["wrapped_token"]))
                if float(plain.get("ttl") or 0.0) <= 0.0:
                    # The unwrap response omitted lease_duration: fall
                    # back to the envelope's requested task-token TTL so
                    # the renewal heap gets a real deadline instead of a
                    # ttl=0 immediate-renewal churn loop.
                    plain["ttl"] = float(info.get("ttl") or 0.0)
                unwrapped[task] = plain
            else:
                unwrapped[task] = info
        return unwrapped

    # -- renewal heap (vaultclient.go renewal loop) ----------------------

    def renew_token(self, token: str, ttl: float) -> None:
        """Track ``token`` for periodic renewal at ttl/2 cadence.
        ``ttl <= 0`` is refused outright: a zero deadline would schedule
        the token for immediate, never-ending renewal churn."""
        if ttl <= 0:
            self.logger.warning(
                "vault: refusing to track token with non-positive ttl "
                "%.1fs (missing lease_duration?); it will not be renewed",
                ttl)
            return
        if self.renew_fn is None:
            # Without a Vault transport the heap cannot actually renew —
            # say so instead of silently letting the token expire at TTL.
            self.logger.warning(
                "vault: no renewal transport configured (vault_addr); "
                "token will expire at its original TTL")
        with self._l:
            if token in self._tracked:
                return
            self._tracked[token] = ttl
            self._seq += 1
            heapq.heappush(self._heap,
                           (time.monotonic() + ttl / 2, self._seq, token))
        self._wake.set()

    def stop_renew_token(self, token: str) -> None:
        with self._l:
            self._tracked.pop(token, None)

    def num_tracked(self) -> int:
        with self._l:
            return len(self._tracked)

    def _renewal_loop(self) -> None:
        while not self._stop.is_set():
            with self._l:
                due = self._heap[0][0] if self._heap else None
            now = time.monotonic()
            if due is None or due > now:
                self._wake.wait(timeout=0.5 if due is None
                                else min(due - now, 5.0))
                self._wake.clear()
                continue
            with self._l:
                _, _, token = heapq.heappop(self._heap)
                ttl = self._tracked.get(token)
            if ttl is None:
                continue  # stopped tracking — drop silently
            try:
                new_ttl = (self.renew_fn(token, ttl)
                           if self.renew_fn is not None else ttl)
            except Exception as e:
                self.logger.warning("vault: token renewal failed: %s", e)
                # Retry sooner, like the reference's backoff on failure.
                new_ttl = min(ttl, 60.0)
            with self._l:
                if token in self._tracked:
                    self._tracked[token] = new_ttl
                    self._seq += 1
                    heapq.heappush(
                        self._heap,
                        (time.monotonic() + max(new_ttl / 2, 1.0),
                         self._seq, token))
