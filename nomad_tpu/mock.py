"""Object-mother test fixtures (reference: nomad/mock/mock.go).

Used by unit tests, the scheduler harness, differential tests, and the
benchmark cluster generators.
"""
from __future__ import annotations

import random
from typing import Optional

from .structs import structs as s


def node(seed: Optional[random.Random] = None) -> s.Node:
    """A ready linux node with exec driver (mock.go:9 Node)."""
    n = s.Node(
        id=s.generate_uuid(),
        datacenter="dc1",
        name="foobar",
        attributes={
            "kernel.name": "linux",
            "arch": "x86",
            "nomad.version": "0.5.0",
            "driver.exec": "1",
        },
        resources=s.Resources(
            cpu=4000,
            memory_mb=8192,
            disk_mb=100 * 1024,
            iops=150,
            networks=[
                s.NetworkResource(device="eth0", cidr="192.168.0.100/32", mbits=1000)
            ],
        ),
        reserved=s.Resources(
            cpu=100,
            memory_mb=256,
            disk_mb=4 * 1024,
            networks=[
                s.NetworkResource(
                    device="eth0",
                    ip="192.168.0.100",
                    reserved_ports=[s.Port("main", 22)],
                    mbits=1,
                )
            ],
        ),
        links={"consul": "foobar.dc1"},
        meta={"pci-dss": "true", "database": "mysql", "version": "5.6"},
        node_class="linux-medium-pci",
        status=s.NODE_STATUS_READY,
    )
    n.compute_class()
    return n


def job() -> s.Job:
    """A 10-count service job with one web task (mock.go:62 Job)."""
    j = s.Job(
        region="global",
        id=s.generate_uuid(),
        name="my-job",
        type=s.JOB_TYPE_SERVICE,
        priority=50,
        all_at_once=False,
        datacenters=["dc1"],
        constraints=[s.Constraint("${attr.kernel.name}", "linux", "=")],
        task_groups=[
            s.TaskGroup(
                name="web",
                count=10,
                ephemeral_disk=s.EphemeralDisk(size_mb=150),
                restart_policy=s.RestartPolicy(
                    attempts=3, interval=600.0, delay=60.0,
                    mode=s.RESTART_POLICY_MODE_DELAY,
                ),
                tasks=[
                    s.Task(
                        name="web",
                        driver="exec",
                        config={"command": "/bin/date"},
                        env={"FOO": "bar"},
                        services=[
                            s.Service(
                                name="${TASK}-frontend",
                                port_label="http",
                                tags=["pci:${meta.pci-dss}", "datacenter:${node.datacenter}"],
                                checks=[
                                    s.ServiceCheck(
                                        name="check-table",
                                        type="script",
                                        command="/usr/local/check-table-${meta.database}",
                                        args=["${meta.version}"],
                                        interval=30.0,
                                        timeout=5.0,
                                    )
                                ],
                            ),
                            s.Service(name="${TASK}-admin", port_label="admin"),
                        ],
                        resources=s.Resources(
                            cpu=500,
                            memory_mb=256,
                            networks=[
                                s.NetworkResource(
                                    mbits=50,
                                    dynamic_ports=[s.Port("http"), s.Port("admin")],
                                )
                            ],
                        ),
                        meta={"foo": "bar"},
                    )
                ],
                meta={"elb_check_type": "http"},
            )
        ],
        meta={"owner": "armon"},
        status=s.JOB_STATUS_PENDING,
        version=0,
        create_index=42,
        modify_index=99,
        job_modify_index=99,
    )
    j.canonicalize()
    return j


def system_job() -> s.Job:
    """A system job: one alloc per feasible node (mock.go:158 SystemJob)."""
    j = s.Job(
        region="global",
        id=s.generate_uuid(),
        name="my-job",
        type=s.JOB_TYPE_SYSTEM,
        priority=100,
        datacenters=["dc1"],
        constraints=[s.Constraint("${attr.kernel.name}", "linux", "=")],
        task_groups=[
            s.TaskGroup(
                name="web",
                count=1,
                restart_policy=s.RestartPolicy(
                    attempts=3, interval=600.0, delay=60.0,
                    mode=s.RESTART_POLICY_MODE_DELAY,
                ),
                tasks=[
                    s.Task(
                        name="web",
                        driver="exec",
                        config={"command": "/bin/date"},
                        resources=s.Resources(
                            cpu=500,
                            memory_mb=256,
                            networks=[
                                s.NetworkResource(mbits=50, dynamic_ports=[s.Port("http")])
                            ],
                        ),
                    )
                ],
            )
        ],
        meta={"owner": "armon"},
        status=s.JOB_STATUS_PENDING,
        create_index=42,
        modify_index=99,
    )
    j.canonicalize()
    return j


def batch_job() -> s.Job:
    j = job()
    j.type = s.JOB_TYPE_BATCH
    return j


def periodic_job() -> s.Job:
    """A batch job on a 30-minute cron (mock.go:219 PeriodicJob)."""
    j = job()
    j.type = s.JOB_TYPE_BATCH
    j.periodic = s.PeriodicConfig(
        enabled=True, spec_type=s.PERIODIC_SPEC_CRON, spec="*/30 * * * *"
    )
    j.status = s.JOB_STATUS_RUNNING
    return j


def eval() -> s.Evaluation:  # noqa: A001 — matches reference fixture name
    return s.Evaluation(
        id=s.generate_uuid(),
        priority=50,
        type=s.JOB_TYPE_SERVICE,
        job_id=s.generate_uuid(),
        status=s.EVAL_STATUS_PENDING,
    )


def job_summary(job_id: str) -> s.JobSummary:
    return s.JobSummary(
        job_id=job_id,
        summary={"web": s.TaskGroupSummary(queued=0, starting=0)},
    )


def alloc() -> s.Allocation:
    """A placed web alloc with port reservations (mock.go:255 Alloc)."""
    j = job()
    a = s.Allocation(
        id=s.generate_uuid(),
        eval_id=s.generate_uuid(),
        node_id="12345678-abcd-efab-cdef-123456789abc",
        task_group="web",
        resources=s.Resources(
            cpu=500,
            memory_mb=256,
            disk_mb=150,
            networks=[
                s.NetworkResource(
                    device="eth0",
                    ip="192.168.0.100",
                    reserved_ports=[s.Port("main", 5000)],
                    mbits=50,
                    dynamic_ports=[s.Port("http")],
                )
            ],
        ),
        task_resources={
            "web": s.Resources(
                cpu=500,
                memory_mb=256,
                networks=[
                    s.NetworkResource(
                        device="eth0",
                        ip="192.168.0.100",
                        reserved_ports=[s.Port("main", 5000)],
                        mbits=50,
                        dynamic_ports=[s.Port("http")],
                    )
                ],
            )
        },
        shared_resources=s.Resources(disk_mb=150),
        job=j,
        desired_status=s.ALLOC_DESIRED_STATUS_RUN,
        client_status=s.ALLOC_CLIENT_STATUS_PENDING,
    )
    a.job_id = j.id
    a.name = f"{j.name}.web[0]"
    return a


def plan() -> s.Plan:
    return s.Plan(priority=50)


def plan_result() -> s.PlanResult:
    return s.PlanResult()
