"""PlanQueue: leader-only priority queue of submitted plans with futures
(reference: nomad/plan_queue.go:29-180)."""
from __future__ import annotations

import heapq
import itertools
import threading
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

from ..structs import structs as s

# Bound on the per-job last-apply fence table: evictions fold into the
# global floor, so the map cannot grow with job cardinality (dispatch
# workloads mint a unique child job id per dispatch).
JOB_APPLY_CAP = 16384


class PlanFuture:
    """Future for a submitted plan's result.

    claim()/cancel() close the abandoned-plan race: a submitter whose
    wait timed out cancels the future, and the applier claims it before
    evaluating — so a plan is either cancelled (never applied; the
    submitter may safely replan without double-committing placements) or
    claimed (the applier owns it; the submitter must keep waiting)."""

    def __init__(self):
        self._event = threading.Event()
        self._result: Optional[s.PlanResult] = None
        self._error: Optional[Exception] = None
        self._state_l = threading.Lock()
        self._claimed = False
        self._cancelled = False

    def claim(self) -> bool:
        """Applier-side: take ownership; False if already cancelled."""
        with self._state_l:
            if self._cancelled:
                return False
            self._claimed = True
            return True

    def cancel(self) -> bool:
        """Submitter-side: abandon; False if the applier already owns it
        (the plan may still commit — keep waiting)."""
        with self._state_l:
            if self._claimed:
                return False
            self._cancelled = True
            return True

    def respond(self, result: Optional[s.PlanResult], error: Optional[Exception]):
        self._result = result
        self._error = error
        self._event.set()
        return self

    def wait(self, timeout: Optional[float] = None) -> s.PlanResult:
        if not self._event.wait(timeout):
            raise TimeoutError("plan future timed out")
        if self._error is not None:
            raise self._error
        return self._result


@dataclass(order=True)
class _PendingPlan:
    sort_key: Tuple[int, int, int]
    plan: s.Plan = field(compare=False)
    future: PlanFuture = field(compare=False)


class PlanQueue:
    def __init__(self):
        self._l = threading.Lock()
        self._cond = threading.Condition(self._l)
        self._enabled = False
        self._heap: List[_PendingPlan] = []
        self._seq = itertools.count()
        # Per-job last plan-apply index (stale-snapshot fence): a worker
        # may reuse a cached snapshot for job J only if it covers J's
        # newest committed plan — the broker serializes evals per job,
        # but an eval CREATED before J's previous plan applied can be
        # DEQUEUED after it, and scheduling J from a snapshot that
        # misses J's own placements would double-place them (capacity
        # re-checks can't catch same-job duplication).  Plans with no
        # attributable job bump the global floor instead; so do LRU
        # evictions past JOB_APPLY_CAP (conservative: unknown jobs then
        # require a snapshot past the evicted apply, never an older
        # one).
        self._job_apply: "OrderedDict[str, int]" = OrderedDict()
        self._apply_floor = 0

    def note_applied(self, job_id: str, index: int) -> None:
        with self._l:
            if job_id:
                if index > self._job_apply.get(job_id, 0):
                    self._job_apply[job_id] = index
                self._job_apply.move_to_end(job_id)
                while len(self._job_apply) > JOB_APPLY_CAP:
                    _, evicted = self._job_apply.popitem(last=False)
                    if evicted > self._apply_floor:
                        self._apply_floor = evicted
            elif index > self._apply_floor:
                self._apply_floor = index

    def applied_index_for(self, job_id: str) -> int:
        with self._l:
            return max(self._job_apply.get(job_id, 0), self._apply_floor)

    def enabled(self) -> bool:
        with self._l:
            return self._enabled

    def set_enabled(self, enabled: bool) -> None:
        with self._l:
            self._enabled = enabled
            if not enabled:
                # Pending submitters must hear about the discard — a
                # silent drop would hang their future.wait() forever
                # (plan_queue.go Flush responds with an error).
                for item in self._heap:
                    item.future.respond(None, RuntimeError(
                        "plan queue is disabled (leadership lost)"))
                self._heap = []
            self._cond.notify_all()

    def enqueue(self, plan: s.Plan) -> PlanFuture:
        """(plan_queue.go:95)."""
        future = PlanFuture()
        with self._l:
            if not self._enabled:
                raise RuntimeError("plan queue is disabled")
            heapq.heappush(
                self._heap,
                _PendingPlan((-plan.priority, 0, next(self._seq)), plan, future))
            self._cond.notify_all()
        return future

    def dequeue(self, timeout: Optional[float] = None) -> Optional[Tuple[s.Plan, PlanFuture]]:
        import time as _time

        deadline = None if timeout is None else _time.monotonic() + timeout
        with self._l:
            while True:
                if not self._enabled:
                    return None
                if self._heap:
                    pending = heapq.heappop(self._heap)
                    return pending.plan, pending.future
                remaining = None if deadline is None else deadline - _time.monotonic()
                if remaining is not None and remaining <= 0:
                    return None
                self._cond.wait(remaining if remaining is not None else 1.0)

    def depth(self) -> int:
        with self._l:
            return len(self._heap)
