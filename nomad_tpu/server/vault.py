"""Server-side Vault subsystem (reference: nomad/vault.go:234-1218
vaultClient): derives per-task tokens against a Vault endpoint, renews the
server's own token, and revokes accessors when allocations terminate.

The transport is pluggable: ``HTTPVault`` speaks the real Vault token API
(/v1/auth/token/*); ``FakeVault`` is the in-memory double used by tests
and dev mode (the role of nomad/vault_testing.go + testutil/vault.go).
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..structs import structs as s


class VaultError(Exception):
    pass


@dataclass
class VaultConfig:
    """(reference: nomad/structs/config/vault.go VaultConfig)."""

    enabled: bool = False
    addr: str = "https://vault.service.consul:8200"
    token: str = ""
    task_token_ttl: float = 72 * 3600.0
    allow_unauthenticated: bool = True
    # Response-wrap derived task tokens (vault.go getWrappingFn).  ON by
    # default: clients receive a single-use wrapping token, never the
    # raw secret on the wire.  Non-embedded clients WITHOUT a
    # ``vault_addr`` cannot unwrap — set this off for them (or configure
    # vault_addr); see README "Vault" upgrade note (ADVICE r5
    # server.py:1277).
    wrap_derived_tokens: bool = True


# Wrapping TTL for derived task tokens (vault.go:28 vaultTokenCreateTTL):
# the server hands the client a single-use wrapping token whose cubbyhole
# holds the real secret; an uncommitted leak dies with the wrapper.
WRAP_TTL_S = 120.0


class VaultAPI:
    """The subset of Vault's token API the control plane uses."""

    def create_token(self, policies: List[str], ttl: float,
                     metadata: Dict[str, str],
                     wrap_ttl: float = 0.0) -> Dict:
        """→ {"token", "accessor", "ttl"} (auth/token/create), or with
        ``wrap_ttl`` > 0 a response-wrapped secret
        {"wrapped_token", "wrap_ttl"} (sys/wrapping semantics)."""
        raise NotImplementedError

    def unwrap(self, wrapping_token: str) -> Dict:
        """Single-use cubbyhole unwrap (sys/wrapping/unwrap) →
        {"token", "accessor", "ttl"}."""
        raise NotImplementedError

    def renew_token(self, token: str, increment: float) -> float:
        """→ new ttl seconds (auth/token/renew)."""
        raise NotImplementedError

    def revoke_accessor(self, accessor: str) -> None:
        """(auth/token/revoke-accessor)."""
        raise NotImplementedError

    def lookup_token(self, token: str) -> Dict:
        """(auth/token/lookup)."""
        raise NotImplementedError


class FakeVault(VaultAPI):
    """In-memory Vault double: real token/accessor lifecycle, inspectable
    revocations (nomad/vault_testing.go)."""

    def __init__(self, clock=time.time) -> None:
        self._l = threading.Lock()
        self.clock = clock
        self.tokens: Dict[str, Dict] = {}          # token -> record
        self.by_accessor: Dict[str, str] = {}      # accessor -> token
        self.wrapped: Dict[str, Dict] = {}         # wrap token -> cubbyhole
        self.revoked_accessors: List[str] = []
        self.renew_calls = 0
        self.unwrap_calls = 0
        # Test fault injection: revoke_accessor raises while > 0.
        self.fail_revokes = 0

    def create_token(self, policies, ttl, metadata, wrap_ttl=0.0):
        token = "s." + s.generate_uuid()
        accessor = "a." + s.generate_uuid()
        with self._l:
            rec = {"token": token, "accessor": accessor,
                   "policies": list(policies), "ttl": ttl,
                   "expires": self.clock() + ttl,
                   "metadata": dict(metadata), "revoked": False}
            self.tokens[token] = rec
            self.by_accessor[accessor] = token
            if wrap_ttl > 0:
                # Response wrapping: the real secret lives in a cubbyhole
                # behind a single-use wrapping token with its own short
                # TTL (vault.go getWrappingFn; sys/wrapping semantics).
                wrap = "w." + s.generate_uuid()
                self.wrapped[wrap] = {
                    "secret": {"token": token, "accessor": accessor,
                               "ttl": ttl},
                    "expires": self.clock() + wrap_ttl,
                    "used": False}
                return {"wrapped_token": wrap, "wrap_ttl": wrap_ttl,
                        "accessor": accessor, "ttl": ttl}
        return {"token": token, "accessor": accessor, "ttl": ttl}

    def unwrap(self, wrapping_token):
        with self._l:
            rec = self.wrapped.get(wrapping_token)
            self.unwrap_calls += 1
            if rec is None:
                raise VaultError("unknown wrapping token")
            if rec["used"]:
                raise VaultError("wrapping token already used")
            if self.clock() > rec["expires"]:
                raise VaultError("wrapping token expired")
            rec["used"] = True
            return dict(rec["secret"])

    def renew_token(self, token, increment):
        with self._l:
            rec = self.tokens.get(token)
            if rec is None or rec["revoked"]:
                raise VaultError("token not found or revoked")
            rec["expires"] = time.time() + increment
            rec["ttl"] = increment
            self.renew_calls += 1
            return increment

    def revoke_accessor(self, accessor):
        with self._l:
            if self.fail_revokes > 0:
                self.fail_revokes -= 1
                raise VaultError("injected revoke failure")
            token = self.by_accessor.get(accessor)
            if token is not None:
                self.tokens[token]["revoked"] = True
            self.revoked_accessors.append(accessor)

    def lookup_token(self, token):
        with self._l:
            rec = self.tokens.get(token)
            if rec is None or rec["revoked"]:
                raise VaultError("token not found or revoked")
            return dict(rec)

    # test helpers
    def is_revoked(self, accessor: str) -> bool:
        with self._l:
            return accessor in self.revoked_accessors


class HTTPVault(VaultAPI):
    """Real-Vault transport over its HTTP token API (vault.go uses the
    official client; the wire calls are the same four)."""

    def __init__(self, addr: str, token: str, timeout: float = 10.0):
        self.addr = addr.rstrip("/")
        self.token = token
        self.timeout = timeout

    def _call(self, method: str, path: str, body: Optional[dict] = None,
              headers: Optional[dict] = None,
              token_override: Optional[str] = None):
        import json
        import urllib.request

        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(self.addr + path, data=data,
                                     method=method)
        req.add_header("X-Vault-Token", token_override or self.token)
        for k, v in (headers or {}).items():
            req.add_header(k, v)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                raw = resp.read()
                return json.loads(raw) if raw else {}
        except Exception as e:  # connection + HTTP errors alike
            raise VaultError(f"vault request {path} failed: {e}") from e

    def create_token(self, policies, ttl, metadata, wrap_ttl=0.0):
        headers = ({"X-Vault-Wrap-TTL": f"{int(wrap_ttl)}s"}
                   if wrap_ttl > 0 else None)
        out = self._call("POST", "/v1/auth/token/create", {
            "policies": policies, "ttl": f"{int(ttl)}s",
            "meta": metadata, "renewable": True}, headers=headers)
        if wrap_ttl > 0:
            wi = out.get("wrap_info") or {}
            return {"wrapped_token": wi.get("token", ""),
                    "wrap_ttl": float(wi.get("ttl", wrap_ttl)),
                    "accessor": wi.get("wrapped_accessor", ""),
                    "ttl": ttl}
        auth = out.get("auth") or {}
        return {"token": auth.get("client_token", ""),
                "accessor": auth.get("accessor", ""),
                "ttl": float(auth.get("lease_duration", ttl))}

    def unwrap(self, wrapping_token):
        out = self._call("POST", "/v1/sys/wrapping/unwrap", {},
                         token_override=wrapping_token)
        auth = out.get("auth") or {}
        # lease_duration may be absent (or 0) in the unwrap response;
        # emit 0.0 and let the consumer (ClientVaultClient.derive_token)
        # fall back to the wrapped envelope's requested TTL — a 0 TTL
        # must never reach the renewal heap (it would schedule immediate
        # never-ending renewal churn).
        return {"token": auth.get("client_token", ""),
                "accessor": auth.get("accessor", ""),
                "ttl": float(auth.get("lease_duration") or 0.0)}

    def renew_token(self, token, increment):
        out = self._call("POST", "/v1/auth/token/renew", {
            "token": token, "increment": f"{int(increment)}s"})
        return float((out.get("auth") or {}).get("lease_duration", increment))

    def revoke_accessor(self, accessor):
        self._call("POST", "/v1/auth/token/revoke-accessor",
                   {"accessor": accessor})

    def lookup_token(self, token):
        return self._call("POST", "/v1/auth/token/lookup", {"token": token})


class ServerVaultClient:
    """Token derivation + revocation driver on the server
    (vault.go:234 vaultClient; DeriveToken at vault.go:~900,
    RevokeTokens at vault.go:~1050)."""

    def __init__(self, config: VaultConfig, api: Optional[VaultAPI] = None,
                 logger: Optional[logging.Logger] = None,
                 clock=time.time, rand=None):
        import random

        self.config = config
        self.logger = logger or logging.getLogger("nomad_tpu.vault")
        self.api = api if api is not None else (
            HTTPVault(config.addr, config.token) if config.enabled else None)
        self._stop = threading.Event()
        self.clock = clock
        self.rand = rand if rand is not None else random.random
        # Self-token renewal state (vault.go:467 renewalLoop).
        self.creation_ttl = 0.0
        self.last_renewed = 0.0
        self._backoff = 0.0
        self.connection_lost: Optional[str] = None
        self._renew_thread: Optional[threading.Thread] = None
        self._renew_wake = threading.Event()
        # Revocation retry queue (vault.go:1027 storeForRevocation +
        # :1104 revokeDaemon): accessor → give-up deadline (token TTL).
        self._rev_l = threading.Lock()
        self._revoking: Dict[str, float] = {}
        self._active = True

    @property
    def enabled(self) -> bool:
        return self.config.enabled and self.api is not None

    def stop(self) -> None:
        self._stop.set()
        self._renew_wake.set()

    # -- activation (vault.go:290 SetActive) ---------------------------

    def set_active(self, active: bool) -> None:
        """Leadership hook: while inactive, queued revocations are
        cleared — another server is assumed to be taking over them."""
        self._active = active
        if not active:
            with self._rev_l:
                self._revoking.clear()

    # -- self-token renewal (vault.go:467-567) -------------------------

    def start_renewal(self, creation_ttl: Optional[float] = None) -> None:
        """Begin renewing the server's own Vault token.  The creation
        TTL comes from a lookup-self (parseSelfToken, vault.go:590)
        unless given explicitly."""
        if not self.enabled:
            return
        if creation_ttl is None:
            try:
                info = self.api.lookup_token(self.config.token)
                creation_ttl = float(info.get("ttl", 0) or
                                     info.get("creation_ttl", 0) or 3600.0)
            except VaultError as e:
                self.logger.warning("vault: self-token lookup failed: %s", e)
                creation_ttl = 3600.0
        self.creation_ttl = creation_ttl
        self.last_renewed = self.clock()
        self._renew_thread = threading.Thread(
            target=self._renewal_loop, name="vault-self-renewal",
            daemon=True)
        self._renew_thread.start()

    def renewal_tick(self) -> Optional[float]:
        """One renewal attempt; returns seconds until the next attempt,
        or None when renewal must stop (token expired — vault.go:528
        'failed to renew before lease expiration').

        Success schedules the next renew at HALF the time to expiry;
        failure backs off 5s → ×1.25 → 30s cap, ×(1 + rand) jitter,
        never more than half the remaining lease."""
        now = self.clock()
        expiration = self.last_renewed + self.creation_ttl
        try:
            self.api.renew_token(self.config.token, self.creation_ttl)
            self.last_renewed = self.clock()
            self._backoff = 0.0
            return (self.last_renewed + self.creation_ttl
                    - self.clock()) / 2.0
        except VaultError as e:
            self.logger.warning("vault: self-token renewal failed: %s", e)
            if self._backoff < 5:
                self._backoff = 5.0
            elif self._backoff >= 24:
                self._backoff = 30.0
            else:
                self._backoff *= 1.25
            backoff = self._backoff * (1.0 + self.rand())
            max_backoff = (expiration - now) / 2.0
            if max_backoff < 0:
                self.connection_lost = str(e)
                self.logger.error(
                    "vault: failed to renew token before lease "
                    "expiration; stopping renewal")
                return None
            return min(backoff, max_backoff)

    def _renewal_loop(self) -> None:
        delay = 0.0
        while not self._stop.is_set():
            self._renew_wake.wait(timeout=max(0.01, delay))
            self._renew_wake.clear()
            if self._stop.is_set():
                return
            delay = self.renewal_tick()
            if delay is None:
                return

    # -- revocation retry (vault.go:1027, :1104) -----------------------

    def store_for_revocation(self, accessors: List[str],
                             ttl: Optional[float] = None) -> None:
        """Queue failed revocations for retry until the token's TTL —
        past that the token is dead anyway (vault.go:965)."""
        deadline = self.clock() + (ttl if ttl is not None
                                   else self.config.task_token_ttl)
        with self._rev_l:
            for acc in accessors:
                self._revoking.setdefault(acc, deadline)

    def tick_revocations(self) -> List[str]:
        """One retry pass over the queue; returns accessors revoked this
        pass.  Entries past their deadline are dropped (token TTL'd)."""
        if not self.enabled or not self._active:
            return []
        now = self.clock()
        with self._rev_l:
            pending = list(self._revoking.items())
        done: List[str] = []
        for acc, deadline in pending:
            if now > deadline:
                with self._rev_l:
                    self._revoking.pop(acc, None)
                continue
            try:
                self.api.revoke_accessor(acc)
                done.append(acc)
                with self._rev_l:
                    self._revoking.pop(acc, None)
            except VaultError as e:
                self.logger.warning("vault: retry revoke %s failed: %s",
                                    acc, e)
        return done

    def num_revoking(self) -> int:
        with self._rev_l:
            return len(self._revoking)

    def derive_token(self, alloc: s.Allocation, task_names: List[str],
                     wrapped: bool = False) -> Dict[str, Dict]:
        """Create one token per task → {task: {token, accessor, ttl}}.
        Tasks must carry a vault block (vault.go DeriveToken
        validation).  With ``wrapped``, each entry is response-wrapped
        ({task: {wrapped_token, wrap_ttl, accessor, ttl}}) — the client
        unwraps the single-use cubbyhole (vault.go getWrappingFn), so a
        secret leaked before distribution dies with the wrapper."""
        if not self.enabled:
            raise VaultError("Vault is not enabled")
        job = alloc.job
        if job is None:
            raise VaultError("allocation has no job")
        tg = next((g for g in job.task_groups
                   if g.name == alloc.task_group), None)
        if tg is None:
            raise VaultError(f"unknown task group {alloc.task_group!r}")
        out: Dict[str, Dict] = {}
        for name in task_names:
            task = next((t for t in tg.tasks if t.name == name), None)
            if task is None or task.vault is None:
                raise VaultError(
                    f"task {name!r} does not request a Vault token")
            out[name] = self.api.create_token(
                task.vault.policies, self.config.task_token_ttl,
                {"AllocationID": alloc.id, "Task": name,
                 "NodeID": alloc.node_id},
                wrap_ttl=WRAP_TTL_S if wrapped else 0.0)
        return out

    def revoke_accessors(self, accessors: List[str]) -> List[str]:
        """Best-effort revoke; returns accessors revoked successfully."""
        if not self.enabled:
            return list(accessors)  # nothing to revoke against
        done = []
        for acc in accessors:
            try:
                self.api.revoke_accessor(acc)
                done.append(acc)
            except VaultError as e:
                self.logger.warning("vault: revoke %s failed: %s", acc, e)
        return done
