"""Server-side Vault subsystem (reference: nomad/vault.go:234-1218
vaultClient): derives per-task tokens against a Vault endpoint, renews the
server's own token, and revokes accessors when allocations terminate.

The transport is pluggable: ``HTTPVault`` speaks the real Vault token API
(/v1/auth/token/*); ``FakeVault`` is the in-memory double used by tests
and dev mode (the role of nomad/vault_testing.go + testutil/vault.go).
"""

from __future__ import annotations

import logging
import threading
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..structs import structs as s


class VaultError(Exception):
    pass


@dataclass
class VaultConfig:
    """(reference: nomad/structs/config/vault.go VaultConfig)."""

    enabled: bool = False
    addr: str = "https://vault.service.consul:8200"
    token: str = ""
    task_token_ttl: float = 72 * 3600.0
    allow_unauthenticated: bool = True


class VaultAPI:
    """The subset of Vault's token API the control plane uses."""

    def create_token(self, policies: List[str], ttl: float,
                     metadata: Dict[str, str]) -> Dict:
        """→ {"token", "accessor", "ttl"} (auth/token/create)."""
        raise NotImplementedError

    def renew_token(self, token: str, increment: float) -> float:
        """→ new ttl seconds (auth/token/renew)."""
        raise NotImplementedError

    def revoke_accessor(self, accessor: str) -> None:
        """(auth/token/revoke-accessor)."""
        raise NotImplementedError

    def lookup_token(self, token: str) -> Dict:
        """(auth/token/lookup)."""
        raise NotImplementedError


class FakeVault(VaultAPI):
    """In-memory Vault double: real token/accessor lifecycle, inspectable
    revocations (nomad/vault_testing.go)."""

    def __init__(self) -> None:
        self._l = threading.Lock()
        self.tokens: Dict[str, Dict] = {}          # token -> record
        self.by_accessor: Dict[str, str] = {}      # accessor -> token
        self.revoked_accessors: List[str] = []
        self.renew_calls = 0

    def create_token(self, policies, ttl, metadata):
        token = "s." + s.generate_uuid()
        accessor = "a." + s.generate_uuid()
        with self._l:
            rec = {"token": token, "accessor": accessor,
                   "policies": list(policies), "ttl": ttl,
                   "expires": time.time() + ttl,
                   "metadata": dict(metadata), "revoked": False}
            self.tokens[token] = rec
            self.by_accessor[accessor] = token
        return {"token": token, "accessor": accessor, "ttl": ttl}

    def renew_token(self, token, increment):
        with self._l:
            rec = self.tokens.get(token)
            if rec is None or rec["revoked"]:
                raise VaultError("token not found or revoked")
            rec["expires"] = time.time() + increment
            rec["ttl"] = increment
            self.renew_calls += 1
            return increment

    def revoke_accessor(self, accessor):
        with self._l:
            token = self.by_accessor.get(accessor)
            if token is not None:
                self.tokens[token]["revoked"] = True
            self.revoked_accessors.append(accessor)

    def lookup_token(self, token):
        with self._l:
            rec = self.tokens.get(token)
            if rec is None or rec["revoked"]:
                raise VaultError("token not found or revoked")
            return dict(rec)

    # test helpers
    def is_revoked(self, accessor: str) -> bool:
        with self._l:
            return accessor in self.revoked_accessors


class HTTPVault(VaultAPI):
    """Real-Vault transport over its HTTP token API (vault.go uses the
    official client; the wire calls are the same four)."""

    def __init__(self, addr: str, token: str, timeout: float = 10.0):
        self.addr = addr.rstrip("/")
        self.token = token
        self.timeout = timeout

    def _call(self, method: str, path: str, body: Optional[dict] = None):
        import json
        import urllib.request

        data = json.dumps(body).encode() if body is not None else None
        req = urllib.request.Request(self.addr + path, data=data,
                                     method=method)
        req.add_header("X-Vault-Token", self.token)
        try:
            with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                raw = resp.read()
                return json.loads(raw) if raw else {}
        except Exception as e:  # connection + HTTP errors alike
            raise VaultError(f"vault request {path} failed: {e}") from e

    def create_token(self, policies, ttl, metadata):
        out = self._call("POST", "/v1/auth/token/create", {
            "policies": policies, "ttl": f"{int(ttl)}s",
            "meta": metadata, "renewable": True})
        auth = out.get("auth") or {}
        return {"token": auth.get("client_token", ""),
                "accessor": auth.get("accessor", ""),
                "ttl": float(auth.get("lease_duration", ttl))}

    def renew_token(self, token, increment):
        out = self._call("POST", "/v1/auth/token/renew", {
            "token": token, "increment": f"{int(increment)}s"})
        return float((out.get("auth") or {}).get("lease_duration", increment))

    def revoke_accessor(self, accessor):
        self._call("POST", "/v1/auth/token/revoke-accessor",
                   {"accessor": accessor})

    def lookup_token(self, token):
        return self._call("POST", "/v1/auth/token/lookup", {"token": token})


class ServerVaultClient:
    """Token derivation + revocation driver on the server
    (vault.go:234 vaultClient; DeriveToken at vault.go:~900,
    RevokeTokens at vault.go:~1050)."""

    def __init__(self, config: VaultConfig, api: Optional[VaultAPI] = None,
                 logger: Optional[logging.Logger] = None):
        self.config = config
        self.logger = logger or logging.getLogger("nomad_tpu.vault")
        self.api = api if api is not None else (
            HTTPVault(config.addr, config.token) if config.enabled else None)
        self._stop = threading.Event()

    @property
    def enabled(self) -> bool:
        return self.config.enabled and self.api is not None

    def stop(self) -> None:
        self._stop.set()

    def derive_token(self, alloc: s.Allocation, task_names: List[str]
                     ) -> Dict[str, Dict]:
        """Create one token per task → {task: {token, accessor, ttl}}.
        Tasks must carry a vault block (vault.go DeriveToken
        validation)."""
        if not self.enabled:
            raise VaultError("Vault is not enabled")
        job = alloc.job
        if job is None:
            raise VaultError("allocation has no job")
        tg = next((g for g in job.task_groups
                   if g.name == alloc.task_group), None)
        if tg is None:
            raise VaultError(f"unknown task group {alloc.task_group!r}")
        out: Dict[str, Dict] = {}
        for name in task_names:
            task = next((t for t in tg.tasks if t.name == name), None)
            if task is None or task.vault is None:
                raise VaultError(
                    f"task {name!r} does not request a Vault token")
            out[name] = self.api.create_token(
                task.vault.policies, self.config.task_token_ttl,
                {"AllocationID": alloc.id, "Task": name,
                 "NodeID": alloc.node_id})
        return out

    def revoke_accessors(self, accessors: List[str]) -> List[str]:
        """Best-effort revoke; returns accessors revoked successfully."""
        if not self.enabled:
            return list(accessors)  # nothing to revoke against
        done = []
        for acc in accessors:
            try:
                self.api.revoke_accessor(acc)
                done.append(acc)
            except VaultError as e:
                self.logger.warning("vault: revoke %s failed: %s", acc, e)
        return done
