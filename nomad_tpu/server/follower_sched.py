"""Follower-read scheduling: eval workers on FOLLOWER servers
(ISSUE 10 / ROADMAP item 2 — the horizontal control-plane scale axis).

The reference's optimistic-concurrency design (PAPER.md L3) lets a
scheduler work off ANY state snapshot as long as the plan applier
serializes the commit: capacity staleness is caught by the applier's
per-node re-check, and same-job duplication is fenced by ordering.  PR 7
exploited that within one server (the stale-snapshot worker pool); this
module exploits it across servers:

- a :class:`FollowerWorker` runs on every server of a multi-raft
  cluster.  While its server is a follower it PULLS ready evals from
  the leader's broker over RPC (``Eval.DequeueBatch``), schedules them
  against its **locally replicated FSM** (MultiRaft applies the same
  log), and forwards the resulting plan to the leader's serialized
  plan-apply (``Plan.Submit``).  While its server is the leader it
  idles — the local worker pool owns the broker there.

Consistency argument (why a follower snapshot can never stale
double-place):

1. every eval's dequeue reply carries a **plan fence** — the leader's
   ``PlanQueue.applied_index_for(job_id)``, the raft index of the job's
   newest committed plan — and the follower schedules only once its own
   applied index covers ``max(eval.trigger_index(), fence)`` (it WAITS
   for replication, or hands the eval back via nack when its log cannot
   catch up inside the sync limit);
2. the broker serializes evals per job (one outstanding delivery), so
   no two schedulers ever hold the same job concurrently;
3. the plan still commits through the **leader's** single plan-apply
   thread, whose live-store fit re-check rejects any capacity the
   follower's snapshot over-promised (partial commit + replan, exactly
   the PR 7 conflict path).

(1)+(2) make the follower's snapshot cover the job's own placements,
(3) covers everyone else's — the same two-part argument as the
single-server stale-snapshot pool, with replication lag folded into the
fence wait.

Failure semantics: ``Plan.Submit``/``Eval.*`` replies of
``NoLeaderError`` (the request was refused before touching the plan
queue) retry against the embedded leader hint; transport errors AFTER a
plan submit may have applied remotely, so they are never retried — the
worker nacks and the redelivered eval replans off fresh state, where a
committed plan shows up as a no-op diff.
"""
from __future__ import annotations

import logging
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..structs import structs as s
from ..utils.telemetry import NULL_TELEMETRY
from .eval_broker import EvalBrokerError
from .raft import RaftLog
from .rpc import RPC_NOMAD, DialError, NoLeaderError, RPCError
from .worker import RAFT_SYNC_LIMIT, Worker


class FollowerLagError(Exception):
    """The follower's replicated log could not catch up to the eval's
    fence inside the sync limit — the eval is handed back (nack) for
    redelivery to a caught-up worker."""


class LeaderChannel:
    """RPC channel from a follower to the cluster leader.

    Resolves the leader address per call (the follower's raft layer
    tracks it from AppendEntries), follows a bounded number of
    ``NoLeaderError`` hints, and keeps the forwarded-plan telemetry the
    loadgen report and ``/v1/broker/stats`` surface:

    - ``nomad.plan.forward``        — per-plan forward RTT histogram
    - ``nomad.plan.forward.inflight`` gauge via :meth:`inflight`
    - forwarded/error counters via :meth:`stats`
    """

    MAX_HINT_HOPS = 2

    def __init__(self, pool, leader_addr_fn, my_addr: str = "",
                 metrics=None):
        self.pool = pool
        self.leader_addr_fn = leader_addr_fn
        self.my_addr = my_addr
        self.metrics = metrics if metrics is not None else NULL_TELEMETRY
        self._l = threading.Lock()
        self._inflight_plans = 0
        self.forwarded_plans = 0
        self.forward_errors = 0

    @staticmethod
    def _looks_like_addr(hint: str) -> bool:
        host, sep, port = hint.rpartition(":")
        return bool(sep) and bool(host) and port.isdigit()

    def call(self, method: str, body, timeout: float = 10.0):
        """One leader RPC.  A ``NoLeaderError`` reply means the remote
        refused BEFORE acting (leader-only gate), so following the hint
        and retrying is safe for every method on this channel; a
        post-send transport error is NOT retried (the request may have
        applied) and propagates to the caller."""
        addr = self.leader_addr_fn() or ""
        last: Optional[Exception] = None
        for _hop in range(self.MAX_HINT_HOPS + 1):
            if not addr or addr == self.my_addr:
                # No known leader (election in flight), or WE are the
                # leader (the local worker pool owns the broker).
                raise NoLeaderError(addr or "")
            try:
                return self.pool.call(addr, method, body,
                                      channel=RPC_NOMAD, timeout=timeout)
            except NoLeaderError as e:
                last = e
                hint = str(e).strip()
                if self._looks_like_addr(hint) and hint != addr:
                    addr = hint
                    continue
                raise
            except DialError:
                # Never sent: re-resolve once (leadership may have just
                # moved and our raft layer already knows the new addr).
                fresh = self.leader_addr_fn() or ""
                if fresh and fresh != addr:
                    addr = fresh
                    continue
                raise
        raise last if last is not None else NoLeaderError(addr)

    # -- plan forwarding ---------------------------------------------------

    # Below this many homogeneous placements the per-alloc wire form is
    # kept (slab overhead isn't worth it).
    COMPACT_MIN = 4

    @classmethod
    def _strip_plan_for_wire(cls, plan: s.Plan) -> s.Plan:
        """Wire-size surgery on a COPY (the caller's objects are
        untouched), two layers:

        1. every placement alloc embeds the full Job tree and a plan's
           placements all belong to ``plan.job`` — ship the job ONCE on
           the plan and the allocs with ``job=None`` (the receiving
           endpoint re-denormalizes before evaluation);
        2. a task group's placements are near-identical (the TG spec
           fixes resources/tasks; only id/name/node/prev vary) — ride
           the PR 9 columnar machinery and ship them as an
           :class:`AllocSlab` (proto once + per-alloc columns).  The
           leader's applier, FSM (O(columns) insert, ONE
           AllocPlacedBulk event), and every follower's replicated
           apply all get the columnar cost too.  Per-alloc scoring
           forensics (Allocation.metrics) don't ride a slab — the same
           trade the TPU batch path makes at scale; allocs with port
           reservations stay in per-alloc form (ports differ per
           alloc).

        Together ~20-40x off the per-plan codec cost at gang scale."""
        if plan.job is None or not plan.node_allocation:
            return plan
        slim = s.Plan(
            eval_id=plan.eval_id, eval_token=plan.eval_token,
            snapshot_index=plan.snapshot_index, priority=plan.priority,
            all_at_once=plan.all_at_once, job=plan.job,
            node_update=plan.node_update,
            node_preemptions=plan.node_preemptions,
            alloc_slabs=list(plan.alloc_slabs),
            annotations=plan.annotations)
        slim.node_allocation = {}
        by_tg: Dict[str, List[Tuple[str, s.Allocation]]] = {}
        for node_id, allocs in plan.node_allocation.items():
            for alloc in allocs:
                res = alloc.resources
                compactable = (
                    alloc.job is not None
                    and alloc.job_id == plan.job.id
                    and not alloc.terminal_status()
                    and not (res is not None and res.networks)
                    and not any(tr.networks
                                for tr in alloc.task_resources.values()))
                if compactable:
                    by_tg.setdefault(alloc.task_group, []).append(
                        (node_id, alloc))
                    continue
                if alloc.job is not None and alloc.job_id == plan.job.id:
                    alloc = alloc.copy()
                    alloc.job = None
                slim.node_allocation.setdefault(node_id, []).append(alloc)
        for tg, items in by_tg.items():
            if len(items) < cls.COMPACT_MIN:
                for node_id, alloc in items:
                    alloc = alloc.copy()
                    alloc.job = None
                    slim.node_allocation.setdefault(node_id,
                                                    []).append(alloc)
                continue
            proto = items[0][1].copy()
            proto.job = None
            proto.id = ""
            proto.name = ""
            proto.node_id = ""
            proto.previous_allocation = ""
            proto.metrics = None
            slim.alloc_slabs.append(s.AllocSlab(
                proto=proto,
                ids=[a.id for _, a in items],
                names=[a.name for _, a in items],
                node_ids=[nid for nid, _ in items],
                prev_ids=[a.previous_allocation or "" for _, a in items]))
        return slim

    def submit_plan(self, plan: s.Plan) -> Optional[s.PlanResult]:
        """Forward one plan to the leader's serialized plan-apply and
        block for the result (the remote twin of PlanQueue.enqueue +
        future.wait).  Full commits come back as a compact
        ``{"Full": true}`` marker (the result would only echo the
        plan's own allocations); the PlanResult is rebuilt locally from
        the original plan."""
        from ..api.codec import ensure

        t0 = time.perf_counter()
        with self._l:
            self._inflight_plans += 1
        try:
            # RAW dataclass on the wire: struct-codec connections encode
            # it with the generated flat layout (server/rpc.py); legacy
            # msgpack connections get the CamelCase tree at the frame.
            reply = self.call(
                "Plan.Submit",
                {"Plan": self._strip_plan_for_wire(plan)},
                timeout=120.0)
        except Exception:
            with self._l:
                self.forward_errors += 1
            raise
        finally:
            with self._l:
                self._inflight_plans -= 1
            self.metrics.measure_since("plan.forward", t0)
        with self._l:
            self.forwarded_plans += 1
        data = reply.get("Result") if isinstance(reply, dict) else None
        if data is None:
            return None
        if isinstance(data, dict) and data.get("Full"):
            return s.PlanResult(
                node_update=plan.node_update,
                node_allocation=plan.node_allocation,
                alloc_slabs=list(plan.alloc_slabs),
                node_preemptions=plan.node_preemptions,
                refresh_index=0,
                alloc_index=int(data.get("AllocIndex", 0) or 0))
        return ensure(s.PlanResult, data)

    def inflight(self) -> int:
        with self._l:
            return self._inflight_plans

    def stats(self) -> Dict[str, int]:
        with self._l:
            return {"ForwardedPlans": self.forwarded_plans,
                    "ForwardErrors": self.forward_errors,
                    "ForwardedPlansInFlight": self._inflight_plans}


def _as_broker_error(exc: Exception) -> EvalBrokerError:
    """Wire errors from broker methods come back as RPCError strings
    ('EvalBrokerError: …'); surface them to the worker loop as the
    EvalBrokerError it already handles (skip/backoff semantics)."""
    if isinstance(exc, EvalBrokerError):
        return exc
    return EvalBrokerError(str(exc))


class RemoteBroker:
    """The EvalBroker subset workers consume, carried over the wire to
    the leader.  Dequeue replies feed three local caches:

    - per-eval delivery attempts (tracing/forensics),
    - per-job plan fences (the stale double-place guard — shared with
      :class:`RemotePlanQueue` via ``fences``),
    - the leader's applied index (the follower snapshot-lag sample).
    """

    def __init__(self, channel: LeaderChannel, fences: Dict[str, int],
                 metrics=None):
        self.channel = channel
        self.metrics = metrics if metrics is not None else NULL_TELEMETRY
        self._fences = fences
        self._attempts: Dict[str, int] = {}
        self.last_leader_applied = 0

    def dequeue_batch(self, schedulers: List[str], max_batch: int,
                      timeout: Optional[float] = None,
                      ) -> List[Tuple[s.Evaluation, str]]:
        from ..api.codec import ensure

        wait = float(timeout or 0.0)
        try:
            reply = self.channel.call(
                "Eval.DequeueBatch",
                {"Schedulers": list(schedulers), "Max": int(max_batch),
                 "Timeout": wait},
                timeout=max(10.0, wait + 5.0))
        except (NoLeaderError, RPCError, OSError) as e:
            raise _as_broker_error(e)
        out: List[Tuple[s.Evaluation, str]] = []
        self.last_leader_applied = int(reply.get("AppliedIndex", 0) or 0)
        for item in reply.get("Evals") or []:
            ev = ensure(s.Evaluation, item["Eval"])
            fence = int(item.get("PlanFence", 0) or 0)
            if fence > self._fences.get(ev.job_id, 0):
                self._fences[ev.job_id] = fence
            self._attempts[ev.id] = int(item.get("Attempts", 0) or 0)
            out.append((ev, item["Token"]))
        return out

    def dequeue(self, schedulers: List[str],
                timeout: Optional[float] = None):
        batch = self.dequeue_batch(schedulers, 1, timeout)
        return batch[0] if batch else (None, "")

    def _simple(self, method: str, eval_id: str, token: str) -> None:
        try:
            self.channel.call(method, {"EvalID": eval_id, "Token": token})
        except (NoLeaderError, RPCError, OSError) as e:
            raise _as_broker_error(e)

    def ack(self, eval_id: str, token: str) -> None:
        self._simple("Eval.Ack", eval_id, token)
        self._attempts.pop(eval_id, None)

    def nack(self, eval_id: str, token: str) -> None:
        self._simple("Eval.Nack", eval_id, token)
        self._attempts.pop(eval_id, None)

    # Nack-deadline pause/resume: LOCAL no-ops by default.  The worker
    # loop pauses around in-worker queueing measured in milliseconds,
    # while remote deliveries run against the full (default 60s) nack
    # deadline — four extra leader round trips per eval bought nothing
    # but leader CPU.  At-least-once semantics are unchanged: a follower
    # that dies mid-eval lets the deadline fire and the eval redelivers;
    # the token fence already rejects the dead delivery's late writes.
    # The wire methods (Eval.PauseNack/ResumeNack) exist for deployments
    # running short deadlines: NOMAD_TPU_REMOTE_NACK_PAUSE=1 re-enables.
    def _remote_pause(self) -> bool:
        from ..utils import knobs

        return knobs.get_bool("NOMAD_TPU_REMOTE_NACK_PAUSE")

    def pause_nack_timeout(self, eval_id: str, token: str) -> None:
        if self._remote_pause():
            self._simple("Eval.PauseNack", eval_id, token)

    def resume_nack_timeout(self, eval_id: str, token: str) -> None:
        if self._remote_pause():
            self._simple("Eval.ResumeNack", eval_id, token)

    def delivery_attempts(self, eval_id: str) -> int:
        return self._attempts.get(eval_id, 0)


class _RemotePlanFuture:
    """Duck-types PlanFuture for WorkerPlanner.submit_plan: the RPC runs
    at wait() so the submit/wait split matches the local queue's."""

    def __init__(self, channel: LeaderChannel, plan: s.Plan):
        self.channel = channel
        self.plan = plan

    def wait(self, timeout: Optional[float] = None):
        return self.channel.submit_plan(self.plan)


class RemotePlanQueue:
    """The PlanQueue subset workers consume: plan submission forwards
    to the leader; the per-job apply fence reads the cache the dequeue
    replies maintain (the leader stamps each eval with its job's newest
    committed plan index)."""

    def __init__(self, channel: LeaderChannel, fences: Dict[str, int]):
        self.channel = channel
        self._fences = fences

    def enqueue(self, plan: s.Plan) -> _RemotePlanFuture:
        return _RemotePlanFuture(self.channel, plan)

    def applied_index_for(self, job_id: str) -> int:
        return self._fences.get(job_id, 0)

    def note_applied(self, job_id: str, index: int) -> None:
        if index > self._fences.get(job_id, 0):
            self._fences[job_id] = index


class FollowerWorker(Worker):
    """A scheduling worker bound to a server's LOCAL raft/FSM but to the
    LEADER's broker and plan queue over RPC.  Active only while the
    owning server is a follower with a known leader; on the leader it
    parks (the in-process pool owns the broker there).

    Core evals are excluded: GC sweeps mutate state through many apply
    types and must see current state — they stay leader-local.
    """

    FOLLOWER_SCHEDULERS = [s.JOB_TYPE_SERVICE, s.JOB_TYPE_BATCH,
                           s.JOB_TYPE_SYSTEM]

    def __init__(self, raft: RaftLog, channel: LeaderChannel,
                 is_leader_fn, schedulers: Optional[List[str]] = None,
                 logger: Optional[logging.Logger] = None, metrics=None):
        fences: Dict[str, int] = {}
        broker = RemoteBroker(channel, fences, metrics=metrics)
        plan_queue = RemotePlanQueue(channel, fences)
        super().__init__(
            broker, plan_queue, raft,
            schedulers=schedulers or list(self.FOLLOWER_SCHEDULERS),
            blocked_evals=None,
            logger=(logger or logging.getLogger("nomad_tpu.worker")
                    ).getChild("follower"),
            metrics=metrics)
        self.channel = channel
        self._is_leader_fn = is_leader_fn

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(target=self.run, daemon=True,
                                        name="follower-worker")
        self._thread.start()

    def _dequeue_batch(self):
        if self._is_leader_fn():
            # The local worker pool owns the broker on the leader; park
            # instead of dequeuing our own broker over loopback RPC.
            self._stop.wait(0.25)
            return []
        batch = super()._dequeue_batch()
        if batch:
            # How far this follower's FSM lags the leader's at dequeue
            # time — the replication debt the fence wait below pays.
            lag = max(0, self.broker.last_leader_applied
                      - self.raft.applied_index_relaxed())
            self.metrics.add_sample("follower.snapshot_lag", lag)
        return batch

    def invoke_scheduler(self, ev: s.Evaluation, token: str) -> None:
        # The follower-read fence: the LOCAL log must cover the eval's
        # trigger indexes AND the job's newest committed plan before a
        # local snapshot may serve this eval.  wait = replication
        # catch-up; a timeout hands the eval back (the nack path).
        required = self._required_index(ev)
        if not self.wait_for_index(required, RAFT_SYNC_LIMIT):
            self.metrics.incr_counter("follower.lag_handback")
            raise FollowerLagError(
                f"follower log at {self.raft.applied_index_relaxed()} "
                f"did not reach fence {required} for eval {ev.id} within "
                f"{RAFT_SYNC_LIMIT}s; handing back")
        self.metrics.incr_counter("follower.evals_scheduled")
        super().invoke_scheduler(ev, token)

    # -- leader-write hooks (the Worker surface that must cross the wire) --

    def apply_eval_updates(self, evals: List[s.Evaluation]) -> None:
        self.channel.call("Eval.Update", {"Evals": list(evals)})

    def reblock_eval_update(self, ev: s.Evaluation, token: str) -> None:
        self.channel.call("Eval.Reblock", {"Eval": ev, "Token": token})
