"""Replicated-log state machine (reference: nomad/fsm.go:115-600).

Decodes log entries and dispatches them to the StateStore; emits
blocked-eval unblocks on capacity changes and feeds the eval broker /
periodic dispatcher on the leader — the same side-channel hooks
nomadFSM.Apply performs.
"""
from __future__ import annotations

import logging
import time
from enum import IntEnum
from typing import Callable, Dict, List, Optional

from ..state import PeriodicLaunch, StateStore, VaultAccessor
from ..structs import structs as s


class MessageType(IntEnum):
    """Log message types (reference: structs.go:43-56)."""

    NODE_REGISTER = 0
    NODE_DEREGISTER = 1
    NODE_UPDATE_STATUS = 2
    NODE_UPDATE_DRAIN = 3
    JOB_REGISTER = 4
    JOB_DEREGISTER = 5
    EVAL_UPDATE = 6
    EVAL_DELETE = 7
    ALLOC_UPDATE = 8
    ALLOC_CLIENT_UPDATE = 9
    RECONCILE_JOB_SUMMARIES = 10
    VAULT_ACCESSOR_REGISTER = 11
    VAULT_ACCESSOR_DEREGISTER = 12
    APPLY_PLAN_RESULTS = 13
    PERIODIC_LAUNCH_UPSERT = 14
    PERIODIC_LAUNCH_DELETE = 15
    NAMESPACE_UPSERT = 16
    NAMESPACE_DELETE = 17


class FSM:
    """Applies committed log entries to the state store."""

    def __init__(
        self,
        state: Optional[StateStore] = None,
        logger: Optional[logging.Logger] = None,
        on_eval_update: Optional[Callable[[s.Evaluation], None]] = None,
        on_unblock: Optional[Callable[[str, int], None]] = None,
        on_job_register: Optional[Callable[[s.Job], None]] = None,
        on_job_deregister: Optional[Callable[[str], None]] = None,
        on_alloc_terminal: Optional[Callable[[str], None]] = None,
        on_namespace_update: Optional[
            Callable[[str, Optional[s.Namespace]], None]] = None,
    ):
        self.state = state or StateStore()
        self.logger = logger or logging.getLogger("nomad_tpu.fsm")
        # Leader-side hooks (enabled only on the leader, fsm.go:58-66).
        self.on_eval_update = on_eval_update
        self.on_unblock = on_unblock
        self.on_job_register = on_job_register
        self.on_job_deregister = on_job_deregister
        # Vault revocation trigger (vault.go RevokeTokens via fsm alloc
        # client updates): called with the alloc id on terminal transition.
        self.on_alloc_terminal = on_alloc_terminal
        # Tenancy policy push (leader-side): fires with (name, ns) on
        # upsert and (name, None) on delete, so the broker's fairness
        # weights and the HTTP rate buckets track the committed rows.
        self.on_namespace_update = on_namespace_update
        # Cluster event broker (server/event_broker.py): remembered here
        # so restore() can re-attach it to the replacement state store —
        # a snapshot install must not silently disarm the event stream.
        self.event_broker = None

    # -- apply -------------------------------------------------------------

    def apply(self, index: int, msg_type: MessageType, payload: dict):
        """(fsm.go:115 Apply / :132-158 dispatch)."""
        handler = self._DISPATCH.get(MessageType(msg_type))
        if handler is None:
            raise ValueError(f"failed to apply request: unknown type {msg_type}")
        return handler(self, index, payload)

    # -- node --------------------------------------------------------------

    def _apply_node_register(self, index: int, req: dict):
        node: s.Node = req["node"]
        if not node.computed_class:
            node.compute_class()
        self.state.upsert_node(index, node)
        # Re-registration of a down node restores capacity (fsm.go:182-188).
        if self.on_unblock and node.computed_class:
            self.on_unblock(node.computed_class, index)

    def _apply_node_deregister(self, index: int, req: dict):
        self.state.delete_node(index, req["node_id"])

    def _apply_node_update_status(self, index: int, req: dict):
        self.state.update_node_status(index, req["node_id"], req["status"])
        if req["status"] == s.NODE_STATUS_READY and self.on_unblock:
            node = self.state.node_by_id(None, req["node_id"])
            if node is not None and node.computed_class:
                self.on_unblock(node.computed_class, index)

    def _apply_node_update_drain(self, index: int, req: dict):
        self.state.update_node_drain(index, req["node_id"], req["drain"])

    # -- job ---------------------------------------------------------------

    def _apply_job_register(self, index: int, req: dict):
        job: s.Job = req["job"]
        self.state.upsert_job(index, job)
        if self.on_job_register is not None:
            self.on_job_register(job)

    def _apply_job_deregister(self, index: int, req: dict):
        job_id = req["job_id"]
        purge = req.get("purge", True)
        if purge:
            try:
                self.state.delete_job(index, job_id)
            except KeyError:
                pass
        else:
            job = self.state.job_by_id(None, job_id)
            if job is not None:
                stopped = job.copy()
                stopped.stop = True
                self.state.upsert_job(index, stopped)
        if self.on_job_deregister is not None:
            self.on_job_deregister(job_id)

    # -- evals -------------------------------------------------------------

    def _apply_eval_update(self, index: int, req: dict):
        evals: List[s.Evaluation] = req["evals"]
        self.state.upsert_evals(index, evals)
        if self.on_eval_update is not None:
            for ev in evals:
                # Hand the hook the STORED copy: the store stamps
                # create/modify_index on its own copy, and the broker
                # must enqueue an eval whose modify_index reflects the
                # write — the stale-snapshot fence (worker.py
                # _required_index) keys on it, and an unstamped 0 would
                # let a cached snapshot that predates this eval's job
                # serve its scheduling.
                # A COPY, not the row: the broker mutates its evals
                # (nack re-enqueue delay on ev.wait), and store rows are
                # shared with snapshots.
                stored = self.state.eval_by_id(None, ev.id)
                self.on_eval_update(stored.copy() if stored is not None
                                    else ev)

    def _apply_eval_delete(self, index: int, req: dict):
        self.state.delete_eval(index, req.get("evals", []), req.get("allocs", []))

    # -- allocs ------------------------------------------------------------

    def _apply_alloc_update(self, index: int, req: dict):
        allocs: List[s.Allocation] = req["allocs"]
        job = req.get("job")
        for alloc in allocs:
            if alloc.job is None and not alloc.terminal_status():
                alloc.job = job
            if alloc.resources is None and alloc.task_resources:
                total = s.Resources()
                for tr in alloc.task_resources.values():
                    total.add(tr)
                total.add(alloc.shared_resources)
                alloc.resources = total
        self.state.upsert_allocs(index, allocs)

    def _apply_alloc_client_update(self, index: int, req: dict):
        allocs: List[s.Allocation] = req["allocs"]
        self.state.update_allocs_from_client(index, allocs)
        # Unblock on terminal client updates: capacity freed
        # (fsm.go:465-units).
        for alloc in allocs:
            if not alloc.client_terminal_status():
                continue
            if self.on_alloc_terminal is not None:
                self.on_alloc_terminal(alloc.id)
            if self.on_unblock:
                existing = self.state.alloc_by_id(None, alloc.id)
                if existing is None:
                    continue
                node = self.state.node_by_id(None, existing.node_id)
                if node is not None and node.computed_class:
                    self.on_unblock(node.computed_class, index)

    # -- plan results ------------------------------------------------------

    def _apply_plan_results(self, index: int, req: dict):
        self.state.upsert_plan_results(index, req.get("job"), req["allocs"],
                                       req.get("slabs"),
                                       eval_id=req.get("eval_id", ""))
        # Preemption follow-up evals commit with the evict+place they
        # belong to (plan_apply.py builds them); the applier hands them
        # to BlockedEvals after this apply returns.
        evals = req.get("preemption_evals")
        if evals:
            self.state.upsert_evals(index, evals)

    # -- summaries / vault / periodic --------------------------------------

    def _apply_reconcile_summaries(self, index: int, req: dict):
        self.state.reconcile_job_summaries(index)

    def _apply_vault_register(self, index: int, req: dict):
        accessors: List[VaultAccessor] = req["accessors"]
        self.state.upsert_vault_accessors(index, accessors)

    def _apply_vault_deregister(self, index: int, req: dict):
        self.state.delete_vault_accessors(index, req["accessors"])

    def _apply_periodic_launch_upsert(self, index: int, req: dict):
        self.state.upsert_periodic_launch(
            index, PeriodicLaunch(id=req["job_id"], launch=req["launch"]))

    def _apply_periodic_launch_delete(self, index: int, req: dict):
        self.state.delete_periodic_launch(index, req["job_id"])

    # -- namespaces --------------------------------------------------------

    def _apply_namespace_upsert(self, index: int, req: dict):
        ns: s.Namespace = req["namespace"]
        self.state.upsert_namespace(index, ns)
        if self.on_namespace_update is not None:
            self.on_namespace_update(ns.name, ns)

    def _apply_namespace_delete(self, index: int, req: dict):
        self.state.delete_namespace(index, req["name"])
        if self.on_namespace_update is not None:
            self.on_namespace_update(req["name"], None)

    # -- snapshot / restore ------------------------------------------------

    def snapshot(self) -> bytes:
        """(fsm.go:568).  Columnar-enabled stores emit the v2 binary
        format (struct-of-arrays nodes, columnar slabs, numpy columns —
        state/columnar.py); ``NOMAD_TPU_COLUMNAR=0`` emits the legacy
        per-object msgpack blob."""
        return self.state.persist()

    def restore(self, blob: bytes) -> None:
        """(fsm.go:582) — replaces the state store wholesale.  Both
        snapshot formats restore (the v2 magic is sniffed); v2 slabs
        rehydrate lazily and the restored store encodes through its
        warm numpy columns immediately."""
        self.state = StateStore.restore(blob)
        if self.event_broker is not None:
            self.state.event_broker = self.event_broker
            # The snapshot's writes were never published into the ring:
            # raise the gap horizon so a resume inside that range errors
            # instead of silently replaying nothing.
            self.event_broker.mark_armed(self.state.latest_index())

    _DISPATCH: Dict[MessageType, Callable] = {
        MessageType.NODE_REGISTER: _apply_node_register,
        MessageType.NODE_DEREGISTER: _apply_node_deregister,
        MessageType.NODE_UPDATE_STATUS: _apply_node_update_status,
        MessageType.NODE_UPDATE_DRAIN: _apply_node_update_drain,
        MessageType.JOB_REGISTER: _apply_job_register,
        MessageType.JOB_DEREGISTER: _apply_job_deregister,
        MessageType.EVAL_UPDATE: _apply_eval_update,
        MessageType.EVAL_DELETE: _apply_eval_delete,
        MessageType.ALLOC_UPDATE: _apply_alloc_update,
        MessageType.ALLOC_CLIENT_UPDATE: _apply_alloc_client_update,
        MessageType.RECONCILE_JOB_SUMMARIES: _apply_reconcile_summaries,
        MessageType.VAULT_ACCESSOR_REGISTER: _apply_vault_register,
        MessageType.VAULT_ACCESSOR_DEREGISTER: _apply_vault_deregister,
        MessageType.APPLY_PLAN_RESULTS: _apply_plan_results,
        MessageType.PERIODIC_LAUNCH_UPSERT: _apply_periodic_launch_upsert,
        MessageType.PERIODIC_LAUNCH_DELETE: _apply_periodic_launch_delete,
        MessageType.NAMESPACE_UPSERT: _apply_namespace_upsert,
        MessageType.NAMESPACE_DELETE: _apply_namespace_delete,
    }


class TimeTable:
    """Index ↔ wall-clock mapping used by GC thresholds
    (reference: nomad/timetable.go:14-109)."""

    def __init__(self, granularity: float = 1.0, limit: float = 72 * 3600.0):
        self.granularity = granularity
        self.limit = limit
        self._table: List[tuple] = []  # (index, unix_time), newest first

    def witness(self, index: int, when: Optional[float] = None) -> None:
        when = when if when is not None else time.time()
        if self._table and when - self._table[0][1] < self.granularity:
            return
        self._table.insert(0, (index, when))
        # Trim entries beyond the horizon.
        cutoff = when - self.limit
        while self._table and self._table[-1][1] < cutoff:
            self._table.pop()

    def nearest_index(self, when: float) -> int:
        """Largest index with time <= when."""
        for index, t in self._table:
            if t <= when:
                return index
        return 0

    def nearest_time(self, index: int) -> float:
        for idx, t in self._table:
            if idx <= index:
                return t
        return 0.0
