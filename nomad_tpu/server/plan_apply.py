"""Plan applier: the single serialization point of the optimistic
scheduler (reference: nomad/plan_apply.go:27-371).

One thread dequeues plans, re-checks per-node fit against a state snapshot,
makes the partial/gang-commit decision, applies the committed subset
through the log, and *optimistically* applies it to its local snapshot so
verification of plan N+1 can overlap the apply of plan N.

TPU-native departure: the reference verifies nodes with a worker pool of
NumCPU/2 goroutines (plan_apply.go:49-53); here the per-node AllocsFit
re-check is one call into the vectorized kernel (ops/kernels.py
batch_allocs_fit) when the plan touches many nodes, falling back to the
scalar path for small plans.
"""
from __future__ import annotations

import logging
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from .. import fault
from ..structs import structs as s
from ..utils import tracing
from ..structs.funcs import allocs_fit, remove_allocs
from .fsm import MessageType
from .plan_queue import PlanFuture, PlanQueue
from ..utils.telemetry import NULL_TELEMETRY
from .raft import RaftLog

# Above this many touched nodes the vectorized fit re-check is used.
VECTORIZE_THRESHOLD = 64


class PlanApplier:
    def __init__(self, plan_queue: PlanQueue, raft: RaftLog,
                 logger: Optional[logging.Logger] = None,
                 metrics=None, blocked_evals=None):
        self.plan_queue = plan_queue
        self.raft = raft
        self.metrics = metrics if metrics is not None else NULL_TELEMETRY
        # Preempted jobs' follow-up evals are handed here after a
        # preemption plan commits, so displaced work reschedules.
        self.blocked_evals = blocked_evals
        self.logger = logger or logging.getLogger("nomad_tpu.plan_apply")
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(target=self.run, daemon=True,
                                        name="plan-applier")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def run(self) -> None:
        """The planApply hot loop (plan_apply.go:42-120).

        The reference reuses a snapshot with optimistic local application
        so verification of plan N+1 overlaps the *asynchronous* raft commit
        of plan N.  Our log apply is synchronous (raft.py), so there is no
        commit window to overlap — a fresh snapshot per plan is equivalent
        and avoids masking concurrent non-plan writes.  Revisit when
        multi-voter replication makes commits async."""
        while not self._stop.is_set():
            item = self.plan_queue.dequeue(timeout=0.2)
            if item is None:
                continue
            plan, future = item
            if not future.claim():
                # Submitter gave up (RPC deadline) before we started:
                # skipping here is what makes its replan safe.
                self.logger.warning("plan for eval %s was cancelled before "
                                    "apply; dropping", plan.eval_id)
                continue
            # The fit re-check reads the LIVE store, not a snapshot: a
            # full snapshot per plan is an O(cluster) copy (the single
            # largest applier cost in the load-harness profile), and the
            # applier is the ONLY writer of placements — every alloc an
            # earlier plan added is already applied when the next plan's
            # reads run, which is the one consistency property the
            # optimistic re-check needs (the reference gets it by
            # optimistically applying results to a reused snapshot,
            # plan_apply.go:55-120).  Concurrent non-plan writes (client
            # status, node transitions) make individual reads at-least-
            # as-fresh as any snapshot taken at dequeue time.  Revisit
            # if apply ever becomes async (multi-voter replication).
            snap = self.raft.fsm.state

            # Branch before building span attrs (the disarmed per-plan
            # path pays one load + comparison only).
            tr = tracing.TRACER
            try:
                ev_span = tracing.NOOP if tr is None else tr.span(
                    "plan.evaluate", eval_id=plan.eval_id)
                with self.metrics.measure("plan.evaluate"), ev_span:
                    result = self.evaluate_plan(snap, plan)
            except Exception as exc:  # pragma: no cover — defensive
                self.logger.exception("plan evaluation failed")
                future.respond(None, exc)
                continue

            # Staleness + conflict telemetry for the stale-snapshot
            # worker pool: how far behind the log this plan's snapshot
            # was, and whether the optimistic-concurrency re-check had
            # to reject part of it (the submitter replans the rejected
            # remainder off refreshed state — the requeue path).
            if plan.snapshot_index:
                self.metrics.add_sample(
                    "plan.staleness",
                    max(0, self.raft.applied_index() - plan.snapshot_index))
            if result.refresh_index:
                self.metrics.incr_counter("plan.conflict")
                if tr is not None:
                    tr.event("plan.conflict", eval_id=plan.eval_id,
                             snapshot_index=plan.snapshot_index,
                             refresh_index=result.refresh_index)

            if result.node_update or result.node_allocation or result.alloc_slabs:
                try:
                    ap_span = tracing.NOOP if tr is None else tr.span(
                        "plan.apply", eval_id=plan.eval_id)
                    with self.metrics.measure("plan.apply"), ap_span:
                        index = self.apply_plan(plan, result, snap)
                    result.alloc_index = index
                    if result.refresh_index:
                        # Partial commit: ensure the scheduler sees at least
                        # its own placements (plan_apply.go:187-193).
                        result.refresh_index = max(result.refresh_index, index)
                except Exception as exc:
                    self.logger.exception("failed to apply plan")
                    future.respond(None, exc)
                    continue
            future.respond(result, None)

    # -- evaluation --------------------------------------------------------

    def evaluate_plan(self, snap, plan: s.Plan) -> s.PlanResult:
        """Determine the committable subset (plan_apply.go:202
        evaluatePlan): per-node fit re-check, partial or gang commit.
        Columnar alloc slabs (the TPU batch path) are kept whole on a full
        commit and filtered per node on a partial one."""
        result = s.PlanResult(node_update={}, node_allocation={})
        touched = {*plan.node_update, *plan.node_allocation,
                   *plan.node_preemptions}
        for slab in plan.alloc_slabs:
            touched.update(slab.node_ids)
        node_ids = list(touched)

        slab_adds = self._slab_node_adds(plan)
        fits = self._evaluate_nodes(snap, plan, node_ids, slab_adds)

        partial = False
        gang_failed = False
        ok_nodes = set()
        for node_id, fit in fits.items():
            if not fit:
                partial = True
                if plan.all_at_once:
                    # gang semantics: all or nothing
                    result.node_update = {}
                    result.node_allocation = {}
                    gang_failed = True
                    break
                continue
            ok_nodes.add(node_id)
            if plan.node_update.get(node_id):
                result.node_update[node_id] = plan.node_update[node_id]
            if plan.node_allocation.get(node_id):
                result.node_allocation[node_id] = plan.node_allocation[node_id]
            if plan.node_preemptions.get(node_id):
                result.node_preemptions[node_id] = plan.node_preemptions[node_id]

        if gang_failed:
            result.node_preemptions = {}

        if not gang_failed:
            for slab in plan.alloc_slabs:
                if not partial:
                    result.alloc_slabs.append(slab)
                else:
                    filtered = slab.filter_nodes(ok_nodes)
                    if len(filtered):
                        result.alloc_slabs.append(filtered)

        if partial:
            result.refresh_index = max(
                snap.table_index("nodes"), snap.table_index("allocs"))
        return result

    @staticmethod
    def _slab_node_adds(plan: s.Plan) -> Dict[str, List[Tuple[s.Allocation, int]]]:
        """Per-node (proto, count) additions proposed by the plan's slabs."""
        out: Dict[str, List[Tuple[s.Allocation, int]]] = {}
        for slab in plan.alloc_slabs:
            for nid, cnt in slab.node_counts().items():
                out.setdefault(nid, []).append((slab.proto, cnt))
        return out

    def _evaluate_nodes(self, snap, plan: s.Plan, node_ids: List[str],
                        slab_adds: Optional[Dict] = None) -> Dict[str, bool]:
        slab_adds = slab_adds or {}
        if len(node_ids) >= VECTORIZE_THRESHOLD:
            return self._evaluate_nodes_vectorized(snap, plan, node_ids,
                                                   slab_adds)
        return {nid: self._evaluate_node_plan(snap, plan, nid, slab_adds)
                for nid in node_ids}

    def _preemptions_fresh(self, snap, plan: s.Plan, node_id: str) -> bool:
        """Optimistic-concurrency fence for preemption: every alloc the
        plan evicts must still exist, still be live, and be UNCHANGED
        (modify_index) since the scheduler's snapshot — a concurrent
        client update, stop, or re-plan rejects this node's commit and
        the scheduler replans against fresh state."""
        for preempted in plan.node_preemptions.get(node_id, []):
            existing = snap.alloc_by_id(None, preempted.id)
            if (existing is None or existing.terminal_status()
                    or existing.modify_index != preempted.modify_index):
                return False
        return True

    def _evaluate_node_plan(self, snap, plan: s.Plan, node_id: str,
                            slab_adds: Optional[Dict] = None) -> bool:
        """(plan_apply.go:327 evaluateNodePlan)."""
        if not self._preemptions_fresh(snap, plan, node_id):
            return False
        slab_here = (slab_adds or {}).get(node_id, [])
        if not plan.node_allocation.get(node_id) and not slab_here:
            return True  # evict-only always fits
        node = snap.node_by_id(None, node_id)
        if node is None or node.status != s.NODE_STATUS_READY or node.drain:
            return False
        existing = snap.allocs_by_node_terminal(None, node_id, False)
        remove = list(plan.node_update.get(node_id, []))
        remove.extend(plan.node_preemptions.get(node_id, []))
        remove.extend(plan.node_allocation.get(node_id, []))
        proposed = remove_allocs(existing, remove)
        proposed = proposed + list(plan.node_allocation.get(node_id, []))
        for proto, cnt in slab_here:
            proposed.extend([proto] * cnt)
        try:
            fit, _, _ = allocs_fit(node, proposed)
        except ValueError:
            return False
        return fit

    def _evaluate_nodes_vectorized(
        self, snap, plan: s.Plan, node_ids: List[str],
        slab_adds: Optional[Dict] = None,
    ) -> Dict[str, bool]:
        """Batched re-check: one kernel call replaces the reference's
        NumCPU/2 verification pool (scalar network checks retained
        host-side)."""
        from ..ops.kernels import batch_allocs_fit
        import jax.numpy as jnp

        n = len(node_ids)
        capacity = np.zeros((n, 4), dtype=np.int64)
        used = np.zeros((n, 4), dtype=np.int64)
        ok_static = np.ones(n, dtype=bool)

        def res_vec(r: Optional[s.Resources]) -> np.ndarray:
            if r is None:
                return np.zeros(4, dtype=np.int64)
            return np.array([r.cpu, r.memory_mb, r.disk_mb, r.iops], dtype=np.int64)

        slab_adds = slab_adds or {}
        alloc_only: List[bool] = []
        scalar_fallback: Dict[str, bool] = {}
        for i, node_id in enumerate(node_ids):
            if not self._preemptions_fresh(snap, plan, node_id):
                # Stale preempted alloc: the staleness fence stays
                # host-side (by-id lookups), only the fit math vectorizes.
                alloc_only.append(False)
                ok_static[i] = False
                continue
            slab_here = slab_adds.get(node_id, [])
            if not plan.node_allocation.get(node_id) and not slab_here:
                alloc_only.append(True)
                continue
            alloc_only.append(False)
            node = snap.node_by_id(None, node_id)
            if node is None or node.status != s.NODE_STATUS_READY or node.drain:
                ok_static[i] = False
                continue
            capacity[i] = res_vec(node.resources)
            if node.reserved is not None:
                used[i] += res_vec(node.reserved)
            existing = snap.allocs_by_node_terminal(None, node_id, False)
            remove = list(plan.node_update.get(node_id, []))
            remove.extend(plan.node_preemptions.get(node_id, []))
            remove.extend(plan.node_allocation.get(node_id, []))
            proposed = remove_allocs(existing, remove)
            proposed = proposed + list(plan.node_allocation.get(node_id, []))
            has_networks = False
            for alloc in proposed:
                if alloc.resources is not None:
                    used[i] += res_vec(alloc.resources)
                    has_networks = has_networks or bool(alloc.resources.networks)
                else:
                    used[i] += res_vec(alloc.shared_resources)
                    for tr in alloc.task_resources.values():
                        used[i] += res_vec(tr)
                        has_networks = has_networks or bool(tr.networks)
            for proto, cnt in slab_here:
                used[i] += cnt * res_vec(proto.resources)
                has_networks = has_networks or bool(
                    proto.resources is not None and proto.resources.networks)
            if has_networks:
                # Port/bandwidth accounting stays host-side: full scalar
                # re-check for nodes with network reservations.
                scalar_fallback[node_id] = self._evaluate_node_plan(
                    snap, plan, node_id, slab_adds)

        fit, _ = batch_allocs_fit(
            jnp.asarray(capacity, dtype=jnp.int32),
            jnp.asarray(used, dtype=jnp.int32))
        fit = np.asarray(fit)
        out: Dict[str, bool] = {}
        for i, node_id in enumerate(node_ids):
            if alloc_only[i]:
                out[node_id] = True
            elif node_id in scalar_fallback:
                out[node_id] = scalar_fallback[node_id]
            else:
                out[node_id] = bool(ok_static[i] and fit[i])
        return out

    # -- apply -------------------------------------------------------------

    def apply_plan(self, plan: s.Plan, result: s.PlanResult, snap) -> int:
        """Commit the result through the log (plan_apply.go:123-175
        applyPlan)."""
        import time as _time

        # Fault point BEFORE the raft commit: an injected crash here is a
        # leader dying mid-plan-apply.  Nothing has been accepted yet, so
        # the invariant under test is that the submitting worker nacks,
        # the eval redelivers, and the replan commits everything — no
        # accepted placement is ever lost, no placement double-applies.
        act = fault.faultpoint("plan.apply", eval_id=plan.eval_id)
        if act is not None:
            if act.kind == "delay":
                _time.sleep(act.delay)
            elif act.kind in ("error", "crash", "step_down"):
                act.raise_injected()

        allocs: List[s.Allocation] = []
        for update_list in result.node_update.values():
            allocs.extend(update_list)
        for alloc_list in result.node_allocation.values():
            allocs.extend(alloc_list)
        preempted: List[s.Allocation] = []
        for evicted_list in result.node_preemptions.values():
            allocs.extend(evicted_list)
            preempted.extend(evicted_list)
        now = _time.time()
        for alloc in allocs:
            if alloc.create_time == 0:
                alloc.create_time = now
        for slab in result.alloc_slabs:
            if slab.proto.create_time == 0:
                slab.proto.create_time = now

        # eval_id rides the payload for event-stream correlation: stop/
        # evict/lost updates keep their ORIGINAL placement eval on the
        # alloc row (AppendUpdate), so the driving eval travels separately.
        payload = {"job": plan.job, "allocs": allocs,
                   "eval_id": plan.eval_id}
        if result.alloc_slabs:
            payload["slabs"] = result.alloc_slabs
        preemption_evals: List[s.Evaluation] = []
        if preempted:
            # ONE raft apply carries the evictions, the placements, and
            # the preempted jobs' follow-up evals — evict + place land
            # atomically with the reschedule breadcrumb.
            preemption_evals = s.preemption_follow_up_evals(
                preempted, snap.latest_index(),
                job_lookup=lambda jid: snap.job_by_id(None, jid))
            payload["preemption_evals"] = preemption_evals
        _, index = self.raft.apply(MessageType.APPLY_PLAN_RESULTS, payload)
        # Stale-snapshot fence bookkeeping: workers may not reuse a
        # cached snapshot for this job below this index (worker.py
        # _snapshot_covering).
        self.plan_queue.note_applied(
            plan.job.id if plan.job is not None else "", index)
        # Residency index plumbing (ops/resident.py): record the newest
        # plan-apply index so NodeStateDelta events can line residency
        # churn up against plan traffic.  sys.modules lookup keeps the
        # server import-light — if the ops package (and jax) was never
        # loaded, there is no resident cache to notify.
        import sys as _sys

        res_mod = _sys.modules.get("nomad_tpu.ops.resident")
        if res_mod is not None:
            res_mod.note_plan_applied(index)
        eb = self.raft.fsm.state.event_broker
        if eb is not None:
            # One plan-level summary on top of the per-alloc/slab events
            # the state store published during the apply: the decision
            # record (what this eval's plan did), keyed by eval.  This
            # publish runs after raft.apply returns, outside the
            # raft-serialized apply path, so a concurrent apply may have
            # already published a higher index — clamp keeps the stream
            # monotonic; PlanIndex preserves the true apply index.
            placed = (sum(len(v) for v in result.node_allocation.values())
                      + sum(len(sl.ids) for sl in result.alloc_slabs))
            eb.publish_one(
                s.TOPIC_PLAN, "PlanApplied", plan.eval_id, index,
                {"Placed": placed,
                 "Updated": sum(len(v) for v in result.node_update.values()),
                 "Preempted": len(preempted),
                 "Partial": bool(result.refresh_index),
                 "PlanIndex": index},
                eval_id=plan.eval_id, clamp=True)
        if preemption_evals:
            for ev in preemption_evals:
                ev.snapshot_index = index
            if self.blocked_evals is not None:
                self.blocked_evals.block_preempted(preemption_evals)
        return index
