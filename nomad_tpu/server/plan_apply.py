"""Plan applier: the single serialization point of the optimistic
scheduler (reference: nomad/plan_apply.go:27-371).

One thread dequeues plans, re-checks per-node fit against a state snapshot,
makes the partial/gang-commit decision, applies the committed subset
through the log, and *optimistically* applies it to its local snapshot so
verification of plan N+1 can overlap the apply of plan N.

TPU-native departure: the reference verifies nodes with a worker pool of
NumCPU/2 goroutines (plan_apply.go:49-53); here the per-node AllocsFit
re-check is one call into the vectorized kernel (ops/kernels.py
batch_allocs_fit) when the plan touches many nodes, falling back to the
scalar path for small plans.

Pipelined commit (ISSUE 10): on a multi-voter cluster each raft apply
waits a replication round trip, and a strictly serial applier caps
cluster-wide plan throughput at 1/RTT.  The applier therefore overlaps
the COMMIT of plan N with the EVALUATION of plan N+1 — the reference's
async-commit overlap (plan_apply.go:55-120), realized here as a bounded
pool of commit waiters plus an **optimistic in-flight overlay**: the
placements of not-yet-visible committed plans are added to every fit
re-check, so a node can never be over-committed by two plans racing
through the pipeline.  The overlay is conservative (pending REMOVALS are
ignored), so the re-check can only be stricter than the truth.  Plans
carrying preemptions keep the strict serial path: their staleness fence
reads live alloc rows that an in-flight plan could still change.
"""
from __future__ import annotations

import logging
import os
import queue as _queue
import threading
from typing import Dict, List, Optional, Tuple

import numpy as np

from .. import fault
from ..structs import structs as s
from ..utils import tracing
from ..structs.funcs import allocs_fit, remove_allocs
from .fsm import MessageType
from .plan_queue import PlanFuture, PlanQueue
from ..utils.telemetry import NULL_TELEMETRY
from .raft import RaftLog

# Above this many touched nodes the vectorized fit re-check is used.
VECTORIZE_THRESHOLD = 64


def _pipeline_depth() -> int:
    """Concurrent in-flight plan commits (1 restores the strictly
    serial applier)."""
    from ..utils import knobs

    return max(1, knobs.get_int("NOMAD_TPU_PLAN_PIPELINE"))


class _InflightOverlay:
    """Placements of plans whose raft commit is still in flight, keyed
    by plan: the fit re-check adds them to each touched node's proposed
    set so pipelined plans cannot jointly over-commit a node."""

    def __init__(self):
        self._l = threading.Lock()
        self._plans: Dict[int, Dict[str, List[Tuple[s.Allocation, int]]]] = {}

    def add(self, token: int, result: s.PlanResult) -> None:
        by_node: Dict[str, List[Tuple[s.Allocation, int]]] = {}
        for node_id, allocs in result.node_allocation.items():
            for alloc in allocs:
                by_node.setdefault(node_id, []).append((alloc, 1))
        for slab in result.alloc_slabs:
            for node_id, cnt in slab.node_counts().items():
                by_node.setdefault(node_id, []).append((slab.proto, cnt))
        with self._l:
            self._plans[token] = by_node

    def remove(self, token: int) -> None:
        with self._l:
            self._plans.pop(token, None)

    def pending_for(self, node_id: str) -> List[Tuple[s.Allocation, int]]:
        with self._l:
            out: List[Tuple[s.Allocation, int]] = []
            for by_node in self._plans.values():
                out.extend(by_node.get(node_id, ()))
            return out


class PlanApplier:
    def __init__(self, plan_queue: PlanQueue, raft: RaftLog,
                 logger: Optional[logging.Logger] = None,
                 metrics=None, blocked_evals=None):
        self.plan_queue = plan_queue
        self.raft = raft
        self.metrics = metrics if metrics is not None else NULL_TELEMETRY
        # Preempted jobs' follow-up evals are handed here after a
        # preemption plan commits, so displaced work reschedules.
        self.blocked_evals = blocked_evals
        self.logger = logger or logging.getLogger("nomad_tpu.plan_apply")
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        # Pipelined-commit state (see module docstring): a bounded pool
        # of commit waiters + the in-flight placement overlay + a
        # drain condition the serial (preemption) path waits on.
        self.pipeline_depth = _pipeline_depth()
        self._overlay = _InflightOverlay()
        self._commit_q: "_queue.Queue" = _queue.Queue()
        self._commit_threads: List[threading.Thread] = []
        self._inflight = 0
        self._inflight_cv = threading.Condition()
        self._token_seq = 0
        self._fit_guard_reads = 0

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(target=self.run, daemon=True,
                                        name="plan-applier")
        self._thread.start()
        for i in range(self.pipeline_depth):
            t = threading.Thread(target=self._commit_loop, daemon=True,
                                 name=f"plan-commit-{i}")
            t.start()
            self._commit_threads.append(t)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
        for _ in self._commit_threads:
            self._commit_q.put(None)
        for t in self._commit_threads:
            t.join(timeout=5.0)
        self._commit_threads = []

    def run(self) -> None:
        """The planApply hot loop (plan_apply.go:42-120).

        The fit re-check reads the LIVE store plus the in-flight
        overlay: every alloc an earlier plan added is either already
        applied (visible in the store — overlay entries are removed
        only AFTER their raft apply returns) or still in the overlay,
        which is the one consistency property the optimistic re-check
        needs.  Concurrent non-plan writes (client status, node
        transitions) make individual reads at-least-as-fresh as any
        snapshot taken at dequeue time.  Commit waits run on the waiter
        pool so evaluation of plan N+1 overlaps the (multi-voter,
        round-trip-priced) commit of plan N — the reference's async
        overlap, plan_apply.go:55-120."""
        while not self._stop.is_set():
            item = self.plan_queue.dequeue(timeout=0.2)
            if item is None:
                continue
            plan, future = item
            if not future.claim():
                # Submitter gave up (RPC deadline) before we started:
                # skipping here is what makes its replan safe.
                self.logger.warning("plan for eval %s was cancelled before "
                                    "apply; dropping", plan.eval_id)
                continue
            if plan.node_preemptions:
                # The preemption staleness fence reads live alloc rows
                # (modify_index equality): an in-flight plan could still
                # change them, so preemption plans run strictly serial
                # against a quiesced pipeline.
                self._drain_inflight()
                self._process_plan(plan, future, pipelined=False)
            else:
                self._process_plan(plan, future, pipelined=True)

    def _process_plan(self, plan: s.Plan, future: PlanFuture,
                      pipelined: bool) -> None:
        snap = self.raft.fsm.state
        # Branch before building span attrs (the disarmed per-plan
        # path pays one load + comparison only).
        tr = tracing.TRACER
        try:
            ev_span = tracing.NOOP if tr is None else tr.span(
                "plan.evaluate", eval_id=plan.eval_id)
            with self.metrics.measure("plan.evaluate"), ev_span:
                result = self.evaluate_plan(snap, plan)
        except Exception as exc:  # pragma: no cover — defensive
            self.logger.exception("plan evaluation failed")
            future.respond(None, exc)
            return

        # Staleness + conflict telemetry for the stale-snapshot
        # worker pool: how far behind the log this plan's snapshot
        # was, and whether the optimistic-concurrency re-check had
        # to reject part of it (the submitter replans the rejected
        # remainder off refreshed state — the requeue path).
        if plan.snapshot_index:
            self.metrics.add_sample(
                "plan.staleness",
                max(0, self.raft.applied_index() - plan.snapshot_index))
        if result.refresh_index:
            self.metrics.incr_counter("plan.conflict")
            if tr is not None:
                tr.event("plan.conflict", eval_id=plan.eval_id,
                         snapshot_index=plan.snapshot_index,
                         refresh_index=result.refresh_index)

        if not (result.node_update or result.node_allocation
                or result.alloc_slabs):
            future.respond(result, None)
            return
        if not pipelined or self.pipeline_depth <= 1 \
                or not self._commit_threads:
            self._commit(plan, result, future, snap)
            return
        # Hand the commit wait to the pool: the overlay entry makes the
        # not-yet-visible placements count against every later fit
        # re-check until the raft apply lands.
        with self._inflight_cv:
            while self._inflight >= self.pipeline_depth \
                    and not self._stop.is_set():
                self._inflight_cv.wait(0.2)
            self._inflight += 1
            self._token_seq += 1
            token = self._token_seq
        self._overlay.add(token, result)
        self._commit_q.put((token, plan, result, future, snap))

    def _commit(self, plan, result, future, snap,
                token: Optional[int] = None) -> None:
        tr = tracing.TRACER
        try:
            ap_span = tracing.NOOP if tr is None else tr.span(
                "plan.apply", eval_id=plan.eval_id)
            with self.metrics.measure("plan.apply"), ap_span:
                index = self.apply_plan(plan, result, snap)
            result.alloc_index = index
            if result.refresh_index:
                # Partial commit: ensure the scheduler sees at least
                # its own placements (plan_apply.go:187-193).
                result.refresh_index = max(result.refresh_index, index)
        except Exception as exc:
            self.logger.exception("failed to apply plan")
            future.respond(None, exc)
            return
        finally:
            if token is not None:
                # Remove only now: the FSM apply is visible in the live
                # store (or the plan failed and never will be) — there
                # is no window where a placement is in neither.
                self._overlay.remove(token)
        future.respond(result, None)

    def _commit_loop(self) -> None:
        while True:
            item = self._commit_q.get()
            if item is None:
                return
            token, plan, result, future, snap = item
            try:
                self._commit(plan, result, future, snap, token=token)
            finally:
                with self._inflight_cv:
                    self._inflight -= 1
                    self._inflight_cv.notify_all()

    def _drain_inflight(self) -> None:
        with self._inflight_cv:
            while self._inflight and not self._stop.is_set():
                self._inflight_cv.wait(0.2)

    # -- evaluation --------------------------------------------------------

    def evaluate_plan(self, snap, plan: s.Plan) -> s.PlanResult:
        """Determine the committable subset (plan_apply.go:202
        evaluatePlan): per-node fit re-check, partial or gang commit.
        Columnar alloc slabs (the TPU batch path) are kept whole on a full
        commit and filtered per node on a partial one."""
        result = s.PlanResult(node_update={}, node_allocation={})
        touched = {*plan.node_update, *plan.node_allocation,
                   *plan.node_preemptions}
        for slab in plan.alloc_slabs:
            touched.update(slab.node_ids)
        node_ids = list(touched)

        slab_adds = self._slab_node_adds(plan)
        fits = self._evaluate_nodes(snap, plan, node_ids, slab_adds)

        partial = False
        gang_failed = False
        ok_nodes = set()
        for node_id, fit in fits.items():
            if not fit:
                partial = True
                if plan.all_at_once:
                    # gang semantics: all or nothing
                    result.node_update = {}
                    result.node_allocation = {}
                    gang_failed = True
                    break
                continue
            ok_nodes.add(node_id)
            if plan.node_update.get(node_id):
                result.node_update[node_id] = plan.node_update[node_id]
            if plan.node_allocation.get(node_id):
                result.node_allocation[node_id] = plan.node_allocation[node_id]
            if plan.node_preemptions.get(node_id):
                result.node_preemptions[node_id] = plan.node_preemptions[node_id]

        if gang_failed:
            result.node_preemptions = {}

        if not gang_failed:
            for slab in plan.alloc_slabs:
                if not partial:
                    result.alloc_slabs.append(slab)
                else:
                    filtered = slab.filter_nodes(ok_nodes)
                    if len(filtered):
                        result.alloc_slabs.append(filtered)

        if partial:
            result.refresh_index = max(
                snap.table_index("nodes"), snap.table_index("allocs"))
        return result

    @staticmethod
    def _slab_node_adds(plan: s.Plan) -> Dict[str, List[Tuple[s.Allocation, int]]]:
        """Per-node (proto, count) additions proposed by the plan's slabs."""
        out: Dict[str, List[Tuple[s.Allocation, int]]] = {}
        for slab in plan.alloc_slabs:
            for nid, cnt in slab.node_counts().items():
                out.setdefault(nid, []).append((slab.proto, cnt))
        return out

    def _evaluate_nodes(self, snap, plan: s.Plan, node_ids: List[str],
                        slab_adds: Optional[Dict] = None) -> Dict[str, bool]:
        slab_adds = slab_adds or {}
        # Overlay FIRST, store second: a pipelined sibling whose commit
        # lands between the two reads is then counted TWICE (its
        # placements in the overlay snapshot AND in the store rows) —
        # conservative — instead of in neither view, which would let
        # two plans jointly over-commit a node.
        overlay = {nid: self._overlay.pending_for(nid)
                   for nid in node_ids}
        out = self._evaluate_nodes_columnar(snap, plan, node_ids,
                                            slab_adds, overlay)
        if out is not None:
            return out
        return self._evaluate_nodes_walk(snap, plan, node_ids, slab_adds,
                                         overlay)

    def _evaluate_nodes_walk(self, snap, plan: s.Plan,
                             node_ids: List[str], slab_adds: Dict,
                             overlay: Dict[str, list]) -> Dict[str, bool]:
        if len(node_ids) >= VECTORIZE_THRESHOLD:
            return self._evaluate_nodes_vectorized(snap, plan, node_ids,
                                                   slab_adds, overlay)
        return {nid: self._evaluate_node_plan(snap, plan, nid, slab_adds,
                                              overlay=overlay)
                for nid in node_ids}

    def _evaluate_nodes_columnar(
        self, snap, plan: s.Plan, node_ids: List[str], slab_adds: Dict,
        overlay_map: Dict[str, list], guard: bool = True,
    ) -> Optional[Dict[str, bool]]:
        """Fit re-check off the PR 9 columnar mirror: capacity, reserved,
        eligibility, and LIVE USAGE come straight from the store's numpy
        columns (O(changed) fold) instead of walking every touched
        node's alloc objects — under gang-scale plans the walk was the
        applier's dominant serial cost.  Per-node plan adds/removals and
        the in-flight overlay stay host-side Python (small).  Falls back
        per node for port-reserving allocs (allocs_fit owns port math)
        and rows the mirror dropped; returns None when the mirror is
        unavailable so callers run the walk.  Differential guard: every
        NOMAD_TPU_COLUMNAR_GUARD_EVERY evaluations the walk runs anyway
        and must agree — a mismatch is logged, counted, and the walk's
        verdicts win (tests pin the cadence to 1: every tier-1 plan is
        double-checked)."""
        from ..state import columnar as colmod

        columns_fn = getattr(snap, "columns", None)
        if columns_fn is None or not colmod.enabled():
            return None
        cols = columns_fn()
        if cols is None:
            return None
        usage = snap.column_usage(cols)

        def res_vec(r: Optional[s.Resources]) -> np.ndarray:
            if r is None:
                return np.zeros(4, dtype=np.int64)
            return np.array([r.cpu, r.memory_mb, r.disk_mb, r.iops],
                            dtype=np.int64)

        def combined(alloc: s.Allocation) -> np.ndarray:
            if alloc.resources is not None:
                return res_vec(alloc.resources)
            total = res_vec(alloc.shared_resources)
            for task_res in alloc.task_resources.values():
                total += res_vec(task_res)
            return total

        def has_ports(alloc: s.Allocation) -> bool:
            if alloc.resources is not None and alloc.resources.networks:
                return True
            return any(tr.networks
                       for tr in alloc.task_resources.values())

        out: Dict[str, bool] = {}
        for node_id in node_ids:
            if not self._preemptions_fresh(snap, plan, node_id):
                out[node_id] = False
                continue
            adds = plan.node_allocation.get(node_id, [])
            slab_here = slab_adds.get(node_id, [])
            overlay = overlay_map.get(node_id, ())
            if not adds and not slab_here:
                out[node_id] = True  # evict-only always fits
                continue
            row = cols.row_of.get(node_id)
            if (row is None or row >= cols.n
                    or any(has_ports(a) for a in adds)
                    or any(p.resources is not None and p.resources.networks
                           for p, _ in slab_here)
                    or any(p.resources is not None and p.resources.networks
                           for p, _ in overlay)):
                # Port accounting / dropped mirror rows: scalar walk for
                # this node only.
                out[node_id] = self._evaluate_node_plan(
                    snap, plan, node_id, slab_adds, overlay=overlay_map)
                continue
            if not cols.eligible[row]:
                out[node_id] = False
                continue
            need = cols.res[row] + usage[row]
            for removal in list(plan.node_update.get(node_id, ())) + \
                    list(plan.node_preemptions.get(node_id, ())):
                live = snap.alloc_by_id(None, removal.id)
                if (live is not None and not live.terminal_status()
                        and live.node_id == node_id):
                    need = need - combined(live)
            for alloc in adds:
                need = need + combined(alloc)
            for proto, cnt in slab_here:
                need = need + cnt * res_vec(proto.resources)
            for proto, cnt in overlay:
                need = need + cnt * res_vec(proto.resources)
            out[node_id] = bool(np.all(need <= cols.cap[row]))

        every = colmod.guard_every()
        if guard and every > 0:
            self._fit_guard_reads += 1
            if self._fit_guard_reads % every == 0:
                ref = self._evaluate_nodes_walk(snap, plan, node_ids,
                                                slab_adds, overlay_map)
                if ref != out:
                    # Both passes read LIVE state: a concurrent write
                    # (pipelined sibling commit, client status) between
                    # them yields a benign divergence.  Re-run the
                    # columnar pass — a race will not reproduce against
                    # the walk's (newer) view; a real mirror bug will.
                    out2 = self._evaluate_nodes_columnar(
                        snap, plan, node_ids, slab_adds, overlay_map,
                        guard=False)
                    if out2 == ref:
                        return ref
                    bad = [nid for nid in node_ids
                           if ref.get(nid) != out.get(nid)]
                    colmod.note_guard_mismatch(
                        "plan_fit", f"{len(bad)} node verdicts",
                        Nodes=len(bad))
                    self.logger.error(
                        "columnar plan-fit guard mismatch on %d nodes "
                        "(first: %s); using the walk's verdicts",
                        len(bad), bad[:3])
                    return ref
        return out

    def _preemptions_fresh(self, snap, plan: s.Plan, node_id: str) -> bool:
        """Optimistic-concurrency fence for preemption: every alloc the
        plan evicts must still exist, still be live, and be UNCHANGED
        (modify_index) since the scheduler's snapshot — a concurrent
        client update, stop, or re-plan rejects this node's commit and
        the scheduler replans against fresh state."""
        for preempted in plan.node_preemptions.get(node_id, []):
            existing = snap.alloc_by_id(None, preempted.id)
            if (existing is None or existing.terminal_status()
                    or existing.modify_index != preempted.modify_index):
                return False
        return True

    def _evaluate_node_plan(self, snap, plan: s.Plan, node_id: str,
                            slab_adds: Optional[Dict] = None,
                            overlay: Optional[Dict[str, list]] = None,
                            ) -> bool:
        """(plan_apply.go:327 evaluateNodePlan).  ``overlay`` is the
        pre-captured in-flight placement snapshot (see _evaluate_nodes:
        it must be read BEFORE the store)."""
        if not self._preemptions_fresh(snap, plan, node_id):
            return False
        slab_here = (slab_adds or {}).get(node_id, [])
        if not plan.node_allocation.get(node_id) and not slab_here:
            return True  # evict-only always fits
        node = snap.node_by_id(None, node_id)
        if node is None or node.status != s.NODE_STATUS_READY or node.drain:
            return False
        existing = snap.allocs_by_node_terminal(None, node_id, False)
        remove = list(plan.node_update.get(node_id, []))
        remove.extend(plan.node_preemptions.get(node_id, []))
        remove.extend(plan.node_allocation.get(node_id, []))
        proposed = remove_allocs(existing, remove)
        proposed = proposed + list(plan.node_allocation.get(node_id, []))
        for proto, cnt in slab_here:
            proposed.extend([proto] * cnt)
        # In-flight overlay: placements committed by pipelined siblings
        # but not yet visible in the store count against this node too.
        pending = (overlay.get(node_id, ()) if overlay is not None
                   else self._overlay.pending_for(node_id))
        for proto, cnt in pending:
            proposed.extend([proto] * cnt)
        try:
            fit, _, _ = allocs_fit(node, proposed)
        except ValueError:
            return False
        return fit

    def _evaluate_nodes_vectorized(
        self, snap, plan: s.Plan, node_ids: List[str],
        slab_adds: Optional[Dict] = None,
        overlay: Optional[Dict[str, list]] = None,
    ) -> Dict[str, bool]:
        """Batched re-check: one kernel call replaces the reference's
        NumCPU/2 verification pool (scalar network checks retained
        host-side)."""
        from ..ops.kernels import batch_allocs_fit
        import jax.numpy as jnp

        n = len(node_ids)
        capacity = np.zeros((n, 4), dtype=np.int64)
        used = np.zeros((n, 4), dtype=np.int64)
        ok_static = np.ones(n, dtype=bool)

        def res_vec(r: Optional[s.Resources]) -> np.ndarray:
            if r is None:
                return np.zeros(4, dtype=np.int64)
            return np.array([r.cpu, r.memory_mb, r.disk_mb, r.iops], dtype=np.int64)

        slab_adds = slab_adds or {}
        alloc_only: List[bool] = []
        scalar_fallback: Dict[str, bool] = {}
        for i, node_id in enumerate(node_ids):
            if not self._preemptions_fresh(snap, plan, node_id):
                # Stale preempted alloc: the staleness fence stays
                # host-side (by-id lookups), only the fit math vectorizes.
                alloc_only.append(False)
                ok_static[i] = False
                continue
            slab_here = slab_adds.get(node_id, [])
            if not plan.node_allocation.get(node_id) and not slab_here:
                alloc_only.append(True)
                continue
            alloc_only.append(False)
            node = snap.node_by_id(None, node_id)
            if node is None or node.status != s.NODE_STATUS_READY or node.drain:
                ok_static[i] = False
                continue
            capacity[i] = res_vec(node.resources)
            if node.reserved is not None:
                used[i] += res_vec(node.reserved)
            existing = snap.allocs_by_node_terminal(None, node_id, False)
            remove = list(plan.node_update.get(node_id, []))
            remove.extend(plan.node_preemptions.get(node_id, []))
            remove.extend(plan.node_allocation.get(node_id, []))
            proposed = remove_allocs(existing, remove)
            proposed = proposed + list(plan.node_allocation.get(node_id, []))
            has_networks = False
            for alloc in proposed:
                if alloc.resources is not None:
                    used[i] += res_vec(alloc.resources)
                    has_networks = has_networks or bool(alloc.resources.networks)
                else:
                    used[i] += res_vec(alloc.shared_resources)
                    for tr in alloc.task_resources.values():
                        used[i] += res_vec(tr)
                        has_networks = has_networks or bool(tr.networks)
            for proto, cnt in slab_here:
                used[i] += cnt * res_vec(proto.resources)
                has_networks = has_networks or bool(
                    proto.resources is not None and proto.resources.networks)
            pending = (overlay.get(node_id, ()) if overlay is not None
                       else self._overlay.pending_for(node_id))
            for proto, cnt in pending:
                used[i] += cnt * res_vec(proto.resources)
                # Overlay entries with port reservations route the node
                # to the scalar fallback, where allocs_fit accounts them.
                has_networks = has_networks or bool(
                    proto.resources is not None and proto.resources.networks)
            if has_networks:
                # Port/bandwidth accounting stays host-side: full scalar
                # re-check for nodes with network reservations.
                scalar_fallback[node_id] = self._evaluate_node_plan(
                    snap, plan, node_id, slab_adds, overlay=overlay)

        # Pad the node axis to the next power of two: XLA compiles per
        # shape, and gang-scale plans otherwise mint a fresh compile for
        # every distinct touched-node count — measured as the dominant
        # serial applier cost under the multi-server gang workload.
        # Zero rows trivially fit and are sliced away below.
        padded = 1 << (n - 1).bit_length()
        if padded != n:
            capacity = np.concatenate(
                [capacity, np.zeros((padded - n, 4), dtype=np.int64)])
            used = np.concatenate(
                [used, np.zeros((padded - n, 4), dtype=np.int64)])
        fit, _ = batch_allocs_fit(
            jnp.asarray(capacity, dtype=jnp.int32),
            jnp.asarray(used, dtype=jnp.int32))
        fit = np.asarray(fit)[:n]
        out: Dict[str, bool] = {}
        for i, node_id in enumerate(node_ids):
            if alloc_only[i]:
                out[node_id] = True
            elif node_id in scalar_fallback:
                out[node_id] = scalar_fallback[node_id]
            else:
                out[node_id] = bool(ok_static[i] and fit[i])
        return out

    # -- apply -------------------------------------------------------------

    def apply_plan(self, plan: s.Plan, result: s.PlanResult, snap) -> int:
        """Commit the result through the log (plan_apply.go:123-175
        applyPlan)."""
        import time as _time

        # Fault point BEFORE the raft commit: an injected crash here is a
        # leader dying mid-plan-apply.  Nothing has been accepted yet, so
        # the invariant under test is that the submitting worker nacks,
        # the eval redelivers, and the replan commits everything — no
        # accepted placement is ever lost, no placement double-applies.
        act = fault.faultpoint("plan.apply", eval_id=plan.eval_id)
        if act is not None:
            if act.kind == "delay":
                _time.sleep(act.delay)
            elif act.kind in ("error", "crash", "step_down"):
                act.raise_injected()

        allocs: List[s.Allocation] = []
        for update_list in result.node_update.values():
            allocs.extend(update_list)
        for alloc_list in result.node_allocation.values():
            for alloc in alloc_list:
                # Log-entry slimming: every placement embeds the full
                # Job tree the payload already carries once — strip it
                # on a COPY (the scheduler still holds the originals)
                # and let upsert_plan_results re-denormalize.  Only
                # same-job, non-terminal placements qualify (that is
                # the exact condition the reattach checks).
                if (alloc.job is not None and plan.job is not None
                        and alloc.job_id == plan.job.id
                        and not alloc.terminal_status()):
                    alloc = alloc.copy()
                    alloc.job = None
                allocs.append(alloc)
        preempted: List[s.Allocation] = []
        for evicted_list in result.node_preemptions.values():
            allocs.extend(evicted_list)
            preempted.extend(evicted_list)
        now = _time.time()
        for alloc in allocs:
            if alloc.create_time == 0:
                alloc.create_time = now
        for slab in result.alloc_slabs:
            if slab.proto.create_time == 0:
                slab.proto.create_time = now

        # eval_id rides the payload for event-stream correlation: stop/
        # evict/lost updates keep their ORIGINAL placement eval on the
        # alloc row (AppendUpdate), so the driving eval travels separately.
        payload = {"job": plan.job, "allocs": allocs,
                   "eval_id": plan.eval_id}
        if result.alloc_slabs:
            payload["slabs"] = result.alloc_slabs
        preemption_evals: List[s.Evaluation] = []
        if preempted:
            # ONE raft apply carries the evictions, the placements, and
            # the preempted jobs' follow-up evals — evict + place land
            # atomically with the reschedule breadcrumb.
            preemption_evals = s.preemption_follow_up_evals(
                preempted, snap.latest_index(),
                job_lookup=lambda jid: snap.job_by_id(None, jid))
            payload["preemption_evals"] = preemption_evals
        _, index = self.raft.apply(MessageType.APPLY_PLAN_RESULTS, payload)
        # Stale-snapshot fence bookkeeping: workers may not reuse a
        # cached snapshot for this job below this index (worker.py
        # _snapshot_covering).
        self.plan_queue.note_applied(
            plan.job.id if plan.job is not None else "", index)
        # Residency index plumbing (ops/resident.py): record the newest
        # plan-apply index so NodeStateDelta events can line residency
        # churn up against plan traffic.  sys.modules lookup keeps the
        # server import-light — if the ops package (and jax) was never
        # loaded, there is no resident cache to notify.
        import sys as _sys

        res_mod = _sys.modules.get("nomad_tpu.ops.resident")
        if res_mod is not None:
            res_mod.note_plan_applied(index)
        eb = self.raft.fsm.state.event_broker
        if eb is not None:
            # One plan-level summary on top of the per-alloc/slab events
            # the state store published during the apply: the decision
            # record (what this eval's plan did), keyed by eval.  This
            # publish runs after raft.apply returns, outside the
            # raft-serialized apply path, so a concurrent apply may have
            # already published a higher index — clamp keeps the stream
            # monotonic; PlanIndex preserves the true apply index.
            placed = (sum(len(v) for v in result.node_allocation.values())
                      + sum(len(sl.ids) for sl in result.alloc_slabs))
            eb.publish_one(
                s.TOPIC_PLAN, "PlanApplied", plan.eval_id, index,
                {"Placed": placed,
                 "Updated": sum(len(v) for v in result.node_update.values()),
                 "Preempted": len(preempted),
                 "Partial": bool(result.refresh_index),
                 "PlanIndex": index},
                eval_id=plan.eval_id, clamp=True)
        if preemption_evals:
            for ev in preemption_evals:
                ev.snapshot_index = index
            if self.blocked_evals is not None:
                self.blocked_evals.block_preempted(preemption_evals)
        return index
