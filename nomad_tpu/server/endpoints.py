"""RPC endpoint registry: maps wire method names onto Server methods.

Reference behavior: the endpoint structs registered at nomad/server.go:163-174
(Status, Node, Job, Eval, Plan, Region, Periodic, System, Operator) with
request forwarding to the leader handled inside each endpoint
(nomad/rpc.go:178 forward).  Forwarding ownership here: every Server WRITE
method catches NotLeaderError internally and re-issues the call to the
leader via Server._forward (so the HTTP layer forwards too); this module's
wrapper only marks the one-allowed forwarding hop and translates an
unforwardable NotLeaderError into the wire error.  A new write endpoint
must therefore forward inside its Server method, not here.

Also carries the serf-lite membership channel (Serf.Join / Serf.Members —
reference: nomad/serf.go gossip events) since membership rides the same
RPC port in this build.
"""

from __future__ import annotations

import os
from typing import Any, Dict

from .. import fault
from ..api.codec import ensure, ensure_list
from ..structs import structs as s
from ..utils import contprof, tracing
from . import event_broker as event_stream
from .raft import NotLeaderError
from .rpc import NoLeaderError

# Handlers hand the frame layer RAW dataclasses and accept either raw
# dataclasses (struct-codec connections) or CamelCase wire dicts
# (legacy msgpack connections) via ``ensure`` — server/rpc.py owns the
# per-connection codec choice and the legacy conversion (ISSUE 11).


def register_endpoints(server, rpc) -> None:
    """Attach all wire methods for ``server`` onto RPCServer ``rpc``.

    Forwarding itself lives in the Server write methods (Server._forward);
    see the module docstring for the contract."""

    def register(method, fn):
        def handler(body):
            # Forwarding lives in ONE place: the Server write methods call
            # Server._forward on NotLeaderError.  This wrapper only (a)
            # marks an already-forwarded request in a thread-local so
            # _forward enforces the one-hop rule (the reference's
            # Forwarded flag, nomad/rpc.go), and (b) translates an
            # unforwardable NotLeaderError into the wire error.
            forwarded = isinstance(body, dict) and body.pop("__forwarded__",
                                                            False)
            region_hop = isinstance(body, dict) and body.pop(
                "__region_hop__", False)
            if forwarded:
                server._fwd_ctx.active = True
            if region_hop:
                server._fwd_ctx.region_hop = True
            try:
                return fn(body)
            except NotLeaderError as e:
                # Carry the known leader address so wire clients can
                # redirect (rpc.go structs.ErrNoLeader vs redirect info).
                raise NoLeaderError(str(e) or "no cluster leader")
            finally:
                if forwarded:
                    server._fwd_ctx.active = False
                if region_hop:
                    server._fwd_ctx.region_hop = False
        rpc.register(method, handler)

    # -- Status ------------------------------------------------------------

    def status_ping(body):
        return {"ok": True}

    def status_leader(body):
        return server.leader_address()

    def status_peers(body):
        return server.peer_addresses()

    def status_metrics(body):
        """Telemetry sink dump over the wire (the loadgen harness reads
        follower-server forward-RTT/snapshot-lag samples through this;
        same data /v1/metrics renders on the HTTP side).  Codec
        histograms merge in (process-global, like the HTTP side)."""
        from .. import codec

        sink = server.metrics.sink
        latest = sink.latest() if hasattr(sink, "latest") else {}
        return contprof.merge_metrics(codec.merge_metrics(latest))

    def status_trace_eval(body):
        """Local-tracer span lookup for the trace fan-out (ISSUE 19):
        the leader's /v1/trace/eval/<id> asks peers for spans it does
        not hold (follower-scheduled evals trace on the follower).
        Deliberately NO recursive fan-out — one hop, local spans only."""
        return {"Spans": tracing.trace_for_eval(body.get("EvalID", ""))}

    def status_broker_stats(body):
        return server.broker_stats()

    def status_fingerprint(body):
        """Committed-prefix FSM digest (ISSUE 12): the safety auditor
        polls every server and flags any index that ever maps to two
        different fingerprints — replicated-state divergence, the bug
        class raft is supposed to make impossible."""
        index, fp = server.fsm_fingerprint()
        return {"Index": index, "Fingerprint": fp,
                "AppliedIndex": server.raft.applied_index_relaxed()}

    def event_since(body):
        """Poll-based event-stream tail for RPC-only servers (the
        auditor's per-follower feed; the HTTP agent's /v1/event/stream
        remains the streaming surface).  Returns buffered events with
        index > MinIndex, oldest first, capped at Max."""
        min_index = int(body.get("MinIndex", 0) or 0)
        cap = max(1, min(int(body.get("Max", 256) or 256), 2048))
        broker = server.event_broker
        events = [ev for ev in broker.buffered() if ev.index > min_index]
        out = [{"Topic": ev.topic, "Type": ev.type, "Key": ev.key,
                "Index": ev.index, "Payload": ev.payload or {},
                "EvalID": ev.eval_id} for ev in events[:cap]]
        return {"Events": out, "Latest": broker.latest_index(),
                "Armed": event_stream.armed()}

    rpc.register("Status.Ping", status_ping)
    rpc.register("Status.Leader", status_leader)
    rpc.register("Status.Peers", status_peers)
    rpc.register("Status.Metrics", status_metrics)
    rpc.register("Status.TraceEval", status_trace_eval)
    rpc.register("Status.BrokerStats", status_broker_stats)
    rpc.register("Status.Fingerprint", status_fingerprint)
    rpc.register("Event.Since", event_since)

    # -- Chaos control plane (ISSUE 12, gated) -----------------------------
    # Registered only under NOMAD_TPU_CHAOS=1: the loadgen harness
    # spawns follower subprocesses with it so the chaos scheduler can
    # split/heal the follower's OWN side of a partition over an exempt
    # control pool — never part of a production server's wire surface.

    from ..utils import knobs

    if knobs.get_bool("NOMAD_TPU_CHAOS"):
        def chaos_set_net(body):
            plane = fault.net()
            for p in body.get("Partitions") or []:
                plane.partition(p["Name"], p["Groups"],
                                windows=p.get("Windows"))
            for name in body.get("Heal") or []:
                plane.heal(name)
            if body.get("HealAll"):
                plane.heal()
            return {"Active": plane.active_partitions()}

        def chaos_status(body):
            plane = fault.net()
            return {"Active": plane.active_partitions(),
                    "Trace": [list(t) for t in plane.trace()[-64:]]}

        rpc.register("Chaos.SetNet", chaos_set_net)
        rpc.register("Chaos.Status", chaos_status)

    # -- Serf-lite membership ---------------------------------------------

    def serf_join(body):
        return server.membership_join(body["Member"])

    def serf_members(body):
        return {"Members": server.members()}

    register("Serf.Join", serf_join)
    register("Serf.Members", serf_members)

    # -- Node (client agent surface) --------------------------------------

    def node_register(body):
        node = ensure(s.Node, body["Node"])
        index, ttl = server.node_register(node)
        return {"Index": index, "HeartbeatTTL": ttl}

    def node_update_status(body):
        index, ttl = server.node_update_status(body["NodeID"], body["Status"])
        return {"Index": index, "HeartbeatTTL": ttl}

    def node_get_client_allocs(body):
        allocs, index = server.node_get_client_allocs(
            body["NodeID"], body.get("MinQueryIndex", 0),
            body.get("MaxQueryTime", 30.0))
        return {"Allocs": allocs, "Index": index}

    def node_update_alloc(body):
        allocs = ensure_list(s.Allocation, body["Allocs"])
        index = server.node_update_allocs(allocs)
        return {"Index": index}

    def node_deregister(body):
        index = server.node_deregister(body["NodeID"])
        return {"Index": index}

    def node_update_drain(body):
        index = server.node_update_drain(body["NodeID"], body["Drain"])
        return {"Index": index}

    def node_evaluate(body):
        return {"EvalIDs": server.node_evaluate(body["NodeID"])}

    def node_derive_vault_token(body):
        tokens = server.derive_vault_token(body["AllocID"],
                                           body.get("Tasks") or [])
        return {"Tasks": tokens}

    def node_get(body):
        return {"Node": server.node_get(body["NodeID"])}

    def alloc_get(body):
        return {"Alloc": server.alloc_get(body["AllocID"])}

    register("Node.Get", node_get)
    register("Alloc.Get", alloc_get)
    register("Node.Evaluate", node_evaluate)
    register("Node.DeriveVaultToken", node_derive_vault_token)
    register("Node.Register", node_register)
    register("Node.UpdateStatus", node_update_status)
    register("Node.GetClientAllocs", node_get_client_allocs)
    register("Node.UpdateAlloc", node_update_alloc)
    register("Node.Deregister", node_deregister)
    register("Node.UpdateDrain", node_update_drain)

    # -- Job ---------------------------------------------------------------

    def job_register(body):
        job = ensure(s.Job, body["Job"])
        index, eval_id = server.job_register(job,
                                             region=body.get("Region", ""))
        return {"Index": index, "EvalID": eval_id}

    def job_deregister(body):
        index, eval_id = server.job_deregister(
            body["JobID"], purge=body.get("Purge", True),
            region=body.get("Region", ""))
        return {"Index": index, "EvalID": eval_id}

    def job_evaluate(body):
        index, eval_id = server.job_evaluate(body["JobID"])
        return {"Index": index, "EvalID": eval_id}

    def job_dispatch(body):
        index, child_id, eval_id = server.job_dispatch(
            body["JobID"], body.get("Payload") or b"", body.get("Meta") or {})
        return {"Index": index, "DispatchedJobID": child_id,
                "EvalID": eval_id}

    def job_list(body):
        jobs, index = server.job_list(
            prefix=body.get("Prefix", ""), region=body.get("Region", ""),
            min_index=int(body.get("MinQueryIndex", 0) or 0),
            max_wait=float(body.get("MaxQueryTime", 0) or 0))
        return {"Jobs": jobs, "Index": index}

    def job_get(body):
        job = server.job_get(
            body["JobID"], region=body.get("Region", ""),
            min_index=int(body.get("MinQueryIndex", 0) or 0),
            max_wait=float(body.get("MaxQueryTime", 0) or 0))
        return {"Job": job,
                "Index": server.state.table_index("jobs")}

    register("Job.List", job_list)
    register("Job.Get", job_get)
    register("Job.Register", job_register)
    register("Job.Deregister", job_deregister)
    register("Job.Evaluate", job_evaluate)
    register("Job.Dispatch", job_dispatch)

    # -- Namespace (tenancy plane, ROADMAP item 3) -------------------------

    def namespace_upsert(body):
        ns = ensure(s.Namespace, body["Namespace"])
        return {"Index": server.namespace_upsert(
            ns, region=body.get("Region", ""))}

    def namespace_delete(body):
        return {"Index": server.namespace_delete(
            body["Name"], region=body.get("Region", ""))}

    def namespace_list(body):
        return {"Namespaces": server.namespace_list(
            region=body.get("Region", "")),
            "Index": server.state.table_index("namespaces")}

    def namespace_status(body):
        return server.namespace_status(
            body["Name"], region=body.get("Region", ""))

    register("Namespace.Upsert", namespace_upsert)
    register("Namespace.Delete", namespace_delete)
    register("Namespace.List", namespace_list)
    register("Namespace.Status", namespace_status)

    # -- Eval (worker surface, eval_endpoint.go:64-211) --------------------

    def eval_dequeue(body):
        # Cap the server-side block below the transport read timeout so a
        # client-supplied Timeout cannot park this connection thread
        # (worker long-polls re-issue; eval_broker.go Dequeue).
        timeout = min(float(body.get("Timeout", 0.0) or 0.0), 5.0)
        ev, token = server.eval_dequeue(
            body.get("Schedulers") or [], timeout)
        return {"Eval": ev, "Token": token}

    def eval_ack(body):
        server.eval_ack(body["EvalID"], body["Token"])
        return {}

    def eval_nack(body):
        server.eval_nack(body["EvalID"], body["Token"])
        return {}

    def eval_get(body):
        return {"Eval": server.eval_get(body["EvalID"])}

    def eval_list(body):
        return {"Evals": server.eval_list(),
                "Index": server.state.table_index("evals")}

    def eval_allocations(body):
        return {"Allocs": server.eval_allocations(body["EvalID"]),
                "Index": server.state.table_index("allocs")}

    def eval_dequeue_batch(body):
        # Follower-scheduler pull (server/follower_sched.py).  Same
        # transport-timeout cap as Eval.Dequeue.
        timeout = min(float(body.get("Timeout", 0.0) or 0.0), 5.0)
        reply = server.eval_dequeue_batch(
            body.get("Schedulers") or [], int(body.get("Max", 1) or 1),
            timeout)
        return {"Evals": [{"Eval": item["eval"],
                           "Token": item["token"],
                           "Attempts": item["attempts"],
                           "PlanFence": item["fence"]}
                          for item in reply["items"]],
                "AppliedIndex": reply["applied_index"]}

    def eval_update(body):
        evals = ensure_list(s.Evaluation, body["Evals"])
        return {"Index": server.eval_update(evals)}

    def eval_reblock(body):
        ev = ensure(s.Evaluation, body["Eval"])
        return {"Index": server.eval_reblock(ev, body["Token"])}

    def eval_pause_nack(body):
        server.eval_pause_nack(body["EvalID"], body["Token"])
        return {}

    def eval_resume_nack(body):
        server.eval_resume_nack(body["EvalID"], body["Token"])
        return {}

    register("Eval.Dequeue", eval_dequeue)
    register("Eval.DequeueBatch", eval_dequeue_batch)
    register("Eval.Ack", eval_ack)
    register("Eval.Nack", eval_nack)
    register("Eval.Update", eval_update)
    register("Eval.Reblock", eval_reblock)
    register("Eval.PauseNack", eval_pause_nack)
    register("Eval.ResumeNack", eval_resume_nack)
    register("Eval.GetEval", eval_get)
    register("Eval.List", eval_list)
    register("Eval.Allocations", eval_allocations)

    # -- Plan (plan_endpoint.go) -------------------------------------------

    def plan_submit(body):
        plan = ensure(s.Plan, body["Plan"])
        # Re-denormalize wire-stripped placements (follower_sched
        # _strip_plan_for_wire ships the job once on the plan).
        if plan.job is not None:
            for allocs in plan.node_allocation.values():
                for alloc in allocs:
                    if alloc.job is None:
                        alloc.job = plan.job
        future = server.plan_submit(plan)
        # Bounded: a dropped plan (leadership churn) responds with an
        # error; an unresponsive applier must not pin this thread.  On
        # timeout, cancel-if-unclaimed: either the applier never saw the
        # plan (safe for the worker to replan) or it owns it and will
        # respond — keep waiting a grace period rather than let the same
        # placements commit twice.
        try:
            result = future.wait(timeout=60.0)
        except TimeoutError:
            if future.cancel():
                raise
            try:
                result = future.wait(timeout=540.0)
            except TimeoutError:
                # The applier owns the plan but hasn't responded within
                # the grace period: the outcome is UNKNOWN (the plan may
                # still commit).  Distinct error so the submitter nacks
                # with delay instead of replanning immediately — by
                # redelivery time a committed plan shows up in the
                # scheduler's fresh snapshot as a no-op diff.
                raise TimeoutError(
                    "plan outcome unknown: applier claimed the plan but "
                    "did not respond in 600s; do not replan immediately")
        if result is None:
            return {"Result": None}
        # Full commit: the result would only echo the plan's own
        # allocations back across the wire — reply with a compact
        # marker and let the submitter rebuild it from its plan copy.
        if not result.refresh_index and (
                sum(map(len, result.node_allocation.values()))
                == sum(map(len, plan.node_allocation.values()))
                and sum(map(len, result.node_update.values()))
                == sum(map(len, plan.node_update.values()))
                and sum(len(sl) for sl in result.alloc_slabs)
                == sum(len(sl) for sl in plan.alloc_slabs)):
            return {"Result": {"Full": True,
                               "AllocIndex": result.alloc_index}}
        return {"Result": result}

    register("Plan.Submit", plan_submit)

    # -- Region / Operator -------------------------------------------------

    def region_list(body):
        reply = {"Regions": server.regions()}
        if body.get("Detail"):
            reply["Detail"] = server.region_info()
        return reply

    def operator_raft_config(body):
        return server.raft_configuration()

    def operator_raft_remove_peer(body):
        server.operator_raft_remove_peer(body.get("Address", ""))
        return {}

    rpc.register("Region.List", region_list)
    rpc.register("Operator.RaftGetConfiguration", operator_raft_config)
    rpc.register("Operator.RaftRemovePeerByAddress",
                 operator_raft_remove_peer)

    # -- Alloc -------------------------------------------------------------

    def alloc_list(body):
        return {"Allocs": server.alloc_list(),
                "Index": server.state.table_index("allocs")}

    register("Alloc.List", alloc_list)

    # -- Periodic ----------------------------------------------------------

    def periodic_force(body):
        child = server.periodic_force(body["JobID"])
        return {"ChildJobID": child.id if child else ""}

    register("Periodic.Force", periodic_force)

    # -- System ------------------------------------------------------------

    def system_gc(body):
        server.system_gc()
        return {}

    def system_reconcile(body):
        server.system_reconcile_summaries()
        return {}

    register("System.GarbageCollect", system_gc)
    register("System.ReconcileJobSummaries", system_reconcile)
