"""Codec for replicated-log entry payloads.

The reference transports Raft log entries as msgpack-encoded data-only
structs (nomad/fsm.go:115 decodes each entry with the structs codec;
nomad/structs/structs.go:4637-4665 codec handles).  This module gives the
multi-server log the same property: payloads are msgpack trees in which
dataclass instances are tagged with their type name and re-hydrated through
the reflection wire codec — never pickled, so a peer on the raft channel
can only produce whitelisted data types, not code.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict

import msgpack

from ..api.codec import from_wire, to_wire
from ..state.state_store import PeriodicLaunch, VaultAccessor
from ..structs import structs as _structs

_TAG = "__t"
_DATA = "__d"

# Whitelist of decodable payload types: every dataclass in the structs
# module plus the state-store row types the FSM applies.
_TYPES: Dict[str, type] = {
    name: obj
    for name, obj in vars(_structs).items()
    if isinstance(obj, type) and dataclasses.is_dataclass(obj)
}
_TYPES["PeriodicLaunch"] = PeriodicLaunch
_TYPES["VaultAccessor"] = VaultAccessor


def _enc(v: Any) -> Any:
    if dataclasses.is_dataclass(v) and not isinstance(v, type):
        return {_TAG: type(v).__name__, _DATA: to_wire(v)}
    if isinstance(v, dict):
        return {k: _enc(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_enc(x) for x in v]
    return v


def _dec(v: Any) -> Any:
    if isinstance(v, dict):
        tag = v.get(_TAG)
        if tag is not None and _DATA in v:
            cls = _TYPES.get(tag)
            if cls is None:
                raise ValueError(f"log codec: unknown payload type {tag!r}")
            return from_wire(cls, v[_DATA])
        return {k: _dec(x) for k, x in v.items()}
    if isinstance(v, list):
        return [_dec(x) for x in v]
    return v


def encode_payload(payload: dict) -> bytes:
    return msgpack.packb(_enc(payload), use_bin_type=True)


def decode_payload(blob: bytes) -> dict:
    return _dec(msgpack.unpackb(blob, raw=False))
