"""Codec for replicated-log entry payloads and FSM snapshot sections.

The reference transports Raft log entries as msgpack-encoded data-only
structs (nomad/fsm.go:115 decodes each entry with the structs codec;
nomad/structs/structs.go:4637-4665 codec handles).  Since ISSUE 11 the
default encoding is the generated struct codec (nomad_tpu/codec): flat
per-type layouts, no reflection walk per entry — the leader's entry
encode and every follower's apply decode are the two biggest per-plan
costs LOADGEN_r03 charged to this module's msgpack path.

Compatibility is per frame: codec blobs carry the 0xC1 magic (a byte
msgpack never emits), so ``decode_payload`` sniffs and accepts BOTH
formats forever — WALs, sealed segments, and snapshots written before
the upgrade (or by an ``NOMAD_TPU_CODEC=0`` peer) replay unchanged, and
flipping the kill switch never strands data in either direction.

The msgpack fallback keeps the original tagged-tree form: dataclass
instances are tagged with their type name and re-hydrated through the
reflection wire codec — never pickled, so a peer on the raft channel
can only produce whitelisted data types, not code.  The struct codec
enforces the same whitelist through its type-id registry
(nomad_tpu/codec/schema.py).
"""

from __future__ import annotations

import dataclasses
import time
from typing import Any, Dict

import msgpack

from .. import codec
from ..api.codec import from_wire, to_wire
from ..state.state_store import PeriodicLaunch, VaultAccessor
from ..structs import structs as _structs

_TAG = "__t"
_DATA = "__d"

# Whitelist of decodable payload types: every dataclass in the structs
# module plus the state-store row types the FSM applies.
_TYPES: Dict[str, type] = {
    name: obj
    for name, obj in vars(_structs).items()
    if isinstance(obj, type) and dataclasses.is_dataclass(obj)
}
_TYPES["PeriodicLaunch"] = PeriodicLaunch
_TYPES["VaultAccessor"] = VaultAccessor


def _enc(v: Any) -> Any:
    if dataclasses.is_dataclass(v) and not isinstance(v, type):
        return {_TAG: type(v).__name__, _DATA: to_wire(v)}
    if isinstance(v, dict):
        return {k: _enc(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        return [_enc(x) for x in v]
    return v


def _dec(v: Any) -> Any:
    if isinstance(v, dict):
        tag = v.get(_TAG)
        if tag is not None and _DATA in v:
            cls = _TYPES.get(tag)
            if cls is None:
                raise ValueError(f"log codec: unknown payload type {tag!r}")
            return from_wire(cls, v[_DATA])
        return {k: _dec(x) for k, x in v.items()}
    if isinstance(v, list):
        return [_dec(x) for x in v]
    return v


def encode_payload(payload: dict, subsystem: str = "raft") -> bytes:
    """One log-entry/snapshot-section blob.  Struct codec by default;
    the reflection-msgpack tree under ``NOMAD_TPU_CODEC=0`` or when the
    payload holds something outside the generated schema (counted as a
    codec fallback)."""
    if codec.enabled():
        try:
            return codec.encode(payload, subsystem)
        except codec.CodecError:
            pass  # fall through to the tagged-msgpack tree
    t0 = time.monotonic()
    blob = msgpack.packb(_enc(payload), use_bin_type=True)
    codec.note_msgpack(subsystem, "encode", t0, len(blob))
    return blob


def decode_payload(blob: bytes, subsystem: str = "raft") -> dict:
    """Sniffing decode: 0xC1-tagged struct-codec frames and legacy
    msgpack trees both decode, regardless of the kill switch."""
    if codec.is_frame(blob):
        return codec.decode(blob, subsystem)
    t0 = time.monotonic()
    out = _dec(msgpack.unpackb(blob, raw=False))
    codec.note_msgpack(subsystem, "decode", t0, len(blob))
    return out
