"""Scheduling workers (reference: nomad/worker.go:55-538).

Worker        — per-eval loop: dequeue → wait-for-index → snapshot →
                scheduler.process → ack/nack; implements the scheduler's
                Planner interface by submitting to the plan queue and
                writing evals through the log.
BatchWorker   — the TPU-native replacement: drains the broker into
                fixed-size batches and invokes the 'tpu-batch' scheduler
                once per batch (batching replaces worker concurrency,
                SURVEY.md §2.9).
"""
from __future__ import annotations

import logging
import os
import threading
import time
from typing import List, Optional, Tuple

from ..scheduler import new_scheduler
from ..structs import structs as s
from ..utils import tracing
from ..utils.backoff import Backoff, wait_until
from ..utils.telemetry import NULL_TELEMETRY
from .eval_broker import EvalBroker, EvalBrokerError
from .fsm import MessageType
from .plan_queue import PlanQueue
from .raft import RaftLog

# How long to wait for raft catch-up to an eval's modify index
# (worker.go:229 waitForIndex; default timeout 5s).
DEQUEUE_TIMEOUT = 0.5
RAFT_SYNC_LIMIT = 5.0


def stale_snapshot_enabled() -> bool:
    """Stale-snapshot scheduling (the reference's optimistic-concurrency
    design, PAPER.md L3): workers REUSE a recent state snapshot instead
    of copying the whole store per eval, as long as it covers the eval's
    trigger indexes — any staleness it carries is caught by the plan
    applier's per-node re-check, which partially commits and refreshes
    the scheduler.  Default on; NOMAD_TPU_STALE_SNAPSHOT=0 restores the
    snapshot-per-eval path."""
    from ..utils import knobs

    return knobs.get_bool("NOMAD_TPU_STALE_SNAPSHOT")


def _stale_snapshot_max_lag() -> int:
    """How many raft entries a reused snapshot may lag the applied index
    before the worker refreshes anyway — bounds the conflict rate under
    churn without giving up cross-eval reuse."""
    from ..utils import knobs

    return knobs.get_int("NOMAD_TPU_STALE_SNAPSHOT_LAG")


class WorkerPlanner:
    """The scheduler.Planner implementation workers hand to schedulers
    (worker.go:300-499)."""

    def __init__(self, worker: "Worker", ev: s.Evaluation, token: str,
                 snapshot_index: Optional[int] = None):
        self.worker = worker
        self.eval = ev
        self.token = token
        # The applied index captured WHEN the scheduler's snapshot was
        # taken (worker.go:262 w.snapshotIndex).  Blocked evals must
        # carry this — not the apply index at creation time — or a
        # capacity change landing while the eval is inside the scheduler
        # looks already-seen to BlockedEvals._missed_unblock and the
        # eval sleeps forever.
        self.snapshot_index = snapshot_index

    def submit_plan(self, plan: s.Plan):
        """(worker.go:300 SubmitPlan) — pause the nack timer while in the
        unbounded plan queue, attach the eval token for fencing."""
        w = self.worker
        plan.eval_token = self.token
        if self.snapshot_index is not None:
            plan.snapshot_index = self.snapshot_index
        try:
            w.broker.pause_nack_timeout(self.eval.id, self.token)
        except EvalBrokerError:
            pass
        try:
            tr = tracing.TRACER
            submit_span = tracing.NOOP if tr is None else tr.span(
                "worker.submit_plan", eval_id=self.eval.id)
            with submit_span:
                future = w.plan_queue.enqueue(plan)
                result = future.wait()
        finally:
            try:
                w.broker.resume_nack_timeout(self.eval.id, self.token)
            except EvalBrokerError:
                pass

        state = None
        if result is not None and result.refresh_index:
            # Wait for our state to catch up, then hand a refreshed
            # snapshot to the scheduler (worker.go:335-350).  The
            # refresh also replaces the worker's stale-snapshot cache —
            # a conflict means the cached view lost its bet.
            w.wait_for_index(result.refresh_index, RAFT_SYNC_LIMIT)
            idx = w.raft.applied_index()
            state = w.raft.fsm.state.snapshot()
            if w._stale_ok:
                w._snap_cache = (idx, state)
            self.snapshot_index = idx
        return result, state

    def update_eval(self, ev: s.Evaluation) -> None:
        self.worker.apply_eval_updates([ev])

    def _snapshot_index(self) -> int:
        if self.snapshot_index is not None:
            return self.snapshot_index
        return self.worker.raft.applied_index()

    def create_eval(self, ev: s.Evaluation) -> None:
        ev.snapshot_index = self._snapshot_index()
        self.worker.apply_eval_updates([ev])

    def reblock_eval(self, ev: s.Evaluation) -> None:
        """(worker.go:470 ReblockEval) — update snapshot index and hand it
        to the blocked tracker via the broker requeue path."""
        ev.snapshot_index = self._snapshot_index()
        self.worker.reblock_eval_update(ev, self.token)


class Worker:
    """One scheduling worker (count = num_schedulers, config.go:250)."""

    def __init__(
        self,
        broker: EvalBroker,
        plan_queue: PlanQueue,
        raft: RaftLog,
        schedulers: Optional[List[str]] = None,
        blocked_evals=None,
        logger: Optional[logging.Logger] = None,
        time_table=None,
        metrics=None,
    ):
        self.broker = broker
        self.plan_queue = plan_queue
        self.raft = raft
        self.metrics = metrics if metrics is not None else NULL_TELEMETRY
        self.blocked_evals = blocked_evals
        self.time_table = time_table
        self.schedulers = schedulers or [
            s.JOB_TYPE_SERVICE, s.JOB_TYPE_BATCH, s.JOB_TYPE_SYSTEM, s.JOB_TYPE_CORE]
        self.logger = logger or logging.getLogger("nomad_tpu.worker")
        self._stop = threading.Event()
        self._paused = False
        self._pause_cond = threading.Condition()
        self._thread: Optional[threading.Thread] = None
        # Jittered idle backoff for a disabled broker (follower workers):
        # a fixed 50ms nap synchronized every worker's retry into one
        # thundering dequeue per tick.
        self._idle_backoff = Backoff(base=0.02, max_delay=0.5)
        # Stale-snapshot cache: (applied index at snapshot time, the
        # snapshot).  Reused across evals while it covers the eval's
        # trigger indexes and isn't too far behind the log — the paper's
        # schedule-anywhere-off-a-snapshot discipline; plan-apply's
        # re-check owns correctness.  Per-worker (no lock needed).
        self._stale_ok = stale_snapshot_enabled()
        self._snap_cache: Optional[Tuple[int, object]] = None
        self._snap_max_lag = _stale_snapshot_max_lag()

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        self._stop.clear()
        self._thread = threading.Thread(target=self.run, daemon=True, name="worker")
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        self.set_pause(False)
        if self._thread is not None:
            self._thread.join(timeout=5.0)

    def set_pause(self, paused: bool) -> None:
        """The leader pauses 3/4 of workers (leader.go:114-120)."""
        with self._pause_cond:
            self._paused = paused
            self._pause_cond.notify_all()

    def _check_paused(self) -> None:
        with self._pause_cond:
            while self._paused and not self._stop.is_set():
                self._pause_cond.wait(0.5)

    # -- loop --------------------------------------------------------------

    # How many ready evals one dequeue drains.  Each eval is still
    # scheduled/acked individually (latency and nack semantics are
    # per-eval, unlike BatchWorker's one-kernel-per-batch), but the
    # FIRST eval's fresh snapshot covers its batch-mates' trigger
    # indexes — they were all written before the dequeue — so under
    # backlog the stale-snapshot cache turns one O(cluster) store copy
    # into GREEDY_BATCH evals' worth of scheduling.  Idle brokers
    # return a single eval (or none); the latency-optimal light-load
    # path is unchanged.
    GREEDY_BATCH = 8

    def run(self) -> None:
        while not self._stop.is_set():
            self._check_paused()
            for ev, token in self._dequeue_batch():
                if self._stop.is_set():
                    # Shutting down mid-batch: hand undone evals back
                    # for redelivery instead of scheduling into a
                    # stopping server.
                    try:
                        self.broker.nack(ev.id, token)
                    except EvalBrokerError:
                        pass
                    continue
                # The nack deadline guards PROCESSING, not in-worker
                # queue wait (paused at dequeue below): resume as this
                # eval's turn starts.  A resume failure means the
                # delivery already burned (broker flushed on leadership
                # loss) — skip rather than double-schedule.
                try:
                    self.broker.resume_nack_timeout(ev.id, token)
                except EvalBrokerError:
                    continue
                self.process_eval(ev, token)

    def _dequeue_batch(self) -> List[Tuple[s.Evaluation, str]]:
        try:
            batch = self.broker.dequeue_batch(
                self.schedulers, self.GREEDY_BATCH, DEQUEUE_TIMEOUT)
        except EvalBrokerError:
            time.sleep(self._idle_backoff.next_delay())
            return []
        self._idle_backoff.reset()
        # Pause every batch-mate's nack deadline: the clock must cover
        # one eval's processing (the single-dequeue contract), not its
        # wait behind up to GREEDY_BATCH-1 predecessors — a mid-batch
        # expiry would redeliver an eval this worker is still going to
        # schedule, and same-job double placement is exactly what the
        # capacity re-check cannot catch.
        for ev, token in batch:
            try:
                self.broker.pause_nack_timeout(ev.id, token)
            except EvalBrokerError:
                pass
        return batch

    # The unit of the UNSUFFIXED worker.invoke_scheduler histogram is one
    # scheduler invocation.  For this worker that's one eval; BatchWorker
    # overrides to False because its invocations are whole batches
    # (emitted by TPUBatchScheduler._emit_batch_stats) and mixing its
    # per-eval system/core timings into the same key would conflate two
    # units of work in one percentile window.
    unsuffixed_invoke_sample = True

    def process_eval(self, ev: s.Evaluation, token: str) -> None:
        """Dequeue→schedule→ack cycle (worker.go:106-227)."""
        # Branch on the tracer before building attrs: delivery_attempts
        # takes the broker lock, which the disarmed path must not pay.
        tr = tracing.TRACER
        attempt_span = tracing.NOOP if tr is None else tr.span(
            "worker.attempt", eval_id=ev.id, eval_type=ev.type,
            attempt=self.broker.delivery_attempts(ev.id))
        unsuffixed = (self.metrics if self.unsuffixed_invoke_sample
                      else NULL_TELEMETRY)
        with attempt_span as sp:
            try:
                with self.metrics.measure("worker.wait_for_index"), \
                        tracing.span("worker.wait_for_index"):
                    self.wait_for_index(ev.modify_index, RAFT_SYNC_LIMIT)
                with unsuffixed.measure("worker.invoke_scheduler"), \
                        self.metrics.measure(
                            f"worker.invoke_scheduler.{ev.type}"), \
                        tracing.span("worker.invoke_scheduler"):
                    self.invoke_scheduler(ev, token)
                self.broker.ack(ev.id, token)
            except Exception as exc:
                self.logger.exception("eval %s failed; nacking", ev.id)
                sp.set(nack_reason=f"{type(exc).__name__}: {exc}")
                self.record_eval_failure(ev, exc)
                try:
                    self.broker.nack(ev.id, token)
                except EvalBrokerError:
                    pass

    def record_eval_failure(self, ev: s.Evaluation, exc: Exception) -> None:
        self.record_eval_failures([ev], exc)

    def record_eval_failures(self, evs: List[s.Evaluation],
                             exc: Exception) -> None:
        """Persist WHY these delivery attempts burned onto the evals, so
        ``eval-status`` shows it — without this, the worker-side traceback
        is the only artifact of a nacked attempt.  One raft apply for the
        whole batch (the FSM handler takes a list), and recorded BEFORE
        the nacks: while an eval is outstanding the broker's enqueue
        dedup ignores the status write's enqueue hook, so the update
        can't double-queue it."""
        failed = []
        for ev in evs:
            attempt = self.broker.delivery_attempts(ev.id)
            f = ev.copy()
            f.status_description = (
                f"scheduler error on delivery attempt {attempt}: "
                f"{type(exc).__name__}: {exc}")
            failed.append(f)
        try:
            self.apply_eval_updates(failed)
        except Exception:
            # Recording forensics must never mask the nack itself (e.g.
            # leadership was lost — the next leader redelivers anyway).
            self.logger.debug("could not record failure reason for %d "
                              "evals", len(failed), exc_info=True)

    # -- leader-write hooks ------------------------------------------------
    # The two write surfaces workers/planners touch beyond plan
    # submission.  On a leader-local worker they go straight through the
    # log; FollowerWorker (server/follower_sched.py) overrides both to
    # forward over the wire, which is what lets one WorkerPlanner serve
    # both sides.

    def apply_eval_updates(self, evals: List[s.Evaluation]) -> None:
        self.raft.apply(MessageType.EVAL_UPDATE, {"evals": evals})

    def reblock_eval_update(self, ev: s.Evaluation, token: str) -> None:
        self.apply_eval_updates([ev])
        if self.blocked_evals is not None:
            self.blocked_evals.reblock(ev, token)

    def wait_for_index(self, index: int, timeout: float) -> bool:
        """Wait for log catch-up (worker.go:229).  Backed-off polling:
        sub-millisecond first checks for the common just-behind case,
        ramping to a coarse interval so a genuinely stalled log doesn't
        pin a core.  The relaxed read keeps M polling workers off the
        raft lock (it under-reports by at most an in-flight entry,
        which the next poll observes)."""
        return wait_until(
            lambda: self.raft.applied_index_relaxed() >= index,
            timeout, initial=0.0005, max_interval=0.005)

    def sched_name(self, ev: s.Evaluation) -> str:
        """Scheduler-registry name for an eval (overridable: the batch
        worker swaps in vectorized implementations)."""
        return ev.type

    def _required_index(self, ev: s.Evaluation) -> int:
        """The lowest applied index a snapshot must cover to schedule
        ``ev`` safely — the eval's TRIGGER indexes, not its own write
        index (requiring ev.modify_index would force a fresh snapshot
        for every eval created after the cache, defeating reuse under
        exactly the backlog conditions reuse exists for):

        - ``job_modify_index``  — the job write the eval reconciles
          (job register/update/deregister paths stamp it);
        - ``node_modify_index`` — the node transition (node evals);
        - ``snapshot_index``    — what the last scheduling attempt saw,
          raised to the UNBLOCK index by BlockedEvals on re-admission
          (preemption follow-ups and requeues ride this too);
        - the job's newest committed plan (plan_queue.applied_index_for)
          — broker per-job serialization orders eval N+1's DEQUEUE
          after eval N's plan apply, but not its CREATION, and a
          snapshot missing the job's own placements would double-place
          them (capacity re-checks cannot catch same-job duplication).
        """
        if ev.type == s.JOB_TYPE_CORE:
            # GC sweeps must see current state: a pinned stale cache
            # would hide newly-terminal rows from the core scheduler
            # indefinitely (GC is rare; a fresh snapshot is cheap).
            return self.raft.applied_index()
        return max(ev.trigger_index(),
                   self.plan_queue.applied_index_for(ev.job_id))

    def _snapshot_covering(self, required: int) -> Tuple[int, object]:
        """(index, snapshot) with index >= required.  With stale-snapshot
        scheduling enabled the cached snapshot is reused while it covers
        ``required`` and lags the log by at most the configured bound —
        dropping the O(cluster) store copy from the per-eval path; any
        capacity staleness is the plan applier's re-check problem
        (optimistic concurrency).  Index is read BEFORE the snapshot is
        taken so a blocked eval's snapshot_index never overstates what
        the scheduler saw."""
        if self._stale_ok:
            cached = self._snap_cache
            if cached is not None and cached[0] >= required \
                    and self.raft.applied_index_relaxed() - cached[0] \
                    <= self._snap_max_lag:
                self.metrics.incr_counter("worker.snapshot_reuse")
                return cached
        snapshot_index = self.raft.applied_index()
        snap = self.raft.fsm.state.snapshot()
        if self._stale_ok:
            self._snap_cache = (snapshot_index, snap)
            self.metrics.incr_counter("worker.snapshot_fresh")
        return snapshot_index, snap

    def invoke_scheduler(self, ev: s.Evaluation, token: str) -> None:
        """(worker.go:262): snapshot state, instantiate by eval type."""
        required = self._required_index(ev)
        # The fence is a WAIT, not just a cache-choice input: with
        # multi-voter raft the FSM applier is asynchronous, so even a
        # leader-local fresh snapshot can predate a committed plan
        # still draining (e.g. pre-failover plans under the restored
        # fence floor).  Covered already ⇒ the first poll returns
        # immediately; a wedged applier raises and the eval nacks.
        if not self.wait_for_index(required, RAFT_SYNC_LIMIT):
            raise RuntimeError(
                f"state did not reach fence {required} within "
                f"{RAFT_SYNC_LIMIT}s for eval {ev.id}")
        snapshot_index, snap = self._snapshot_covering(required)
        planner = WorkerPlanner(self, ev, token,
                                snapshot_index=snapshot_index)
        sched_name = self.sched_name(ev)
        if ev.type == s.JOB_TYPE_CORE:
            from .core_sched import CoreScheduler

            CoreScheduler(self.logger, snap, planner, self.raft,
                          time_table=self.time_table).process(ev)
            return
        sched = new_scheduler(sched_name, self.logger, snap, planner)
        sched.process(ev)


class _MuxPlanner:
    """Routes planner calls to the owning eval's WorkerPlanner."""

    def __init__(self, worker: "Worker", batch, snapshot_index: int):
        self.planners = {
            ev.id: WorkerPlanner(worker, ev, token,
                                 snapshot_index=snapshot_index)
            for ev, token in batch}
        self._by_plan_eval = self.planners

    def submit_plan(self, plan):
        return self.planners[plan.eval_id].submit_plan(plan)

    def update_eval(self, ev):
        p = self.planners.get(ev.id) or next(iter(self.planners.values()))
        p.update_eval(ev)

    def create_eval(self, ev):
        p = self.planners.get(ev.previous_eval) or next(iter(self.planners.values()))
        p.create_eval(ev)

    def reblock_eval(self, ev):
        p = self.planners.get(ev.id) or next(iter(self.planners.values()))
        p.reblock_eval(ev)


class _BatchCtx:
    """One in-flight batch of the pipelined drain: broker tokens +
    scheduler + its prepared/dispatched state."""

    __slots__ = ("batch", "sched", "prep", "attempts", "t0")

    def __init__(self, batch, sched, prep, attempts, t0):
        self.batch = batch
        self.sched = sched
        self.prep = prep
        self.attempts = attempts
        # Start of the batch's PROCESSING (before wait_for_index /
        # snapshot / prepare), so the pipelined latency samples cover
        # the same window the serial path's measure() does.
        self.t0 = t0


def pipeline_enabled() -> bool:
    """Opt-in double-buffered batch drain (NOMAD_TPU_PIPELINE=1): while
    batch k's device pass is in flight the worker dequeues + runs the
    host phases of batch k+1, then finalizes k before k+1's usage delta
    is built — see ops/batch_sched.schedule_stream for the ordering
    argument.  Off by default: the serial drain is the long-soaked
    path."""
    from ..utils import knobs

    return knobs.get_bool("NOMAD_TPU_PIPELINE")


class BatchWorker(Worker):
    """Drains evals in batches into the TPU batch scheduler.

    Service and batch evals are batched (their placement logic is the
    generic scheduler's); system evals run through the vectorized
    'tpu-system' pass; core evals stay on the oracle path.
    """

    # Batch invocations own the unsuffixed worker.invoke_scheduler key
    # (see Worker.unsuffixed_invoke_sample).
    unsuffixed_invoke_sample = False

    def __init__(self, *args, max_batch: int = 64, mesh=None, **kwargs):
        super().__init__(*args, **kwargs)
        self.max_batch = max_batch
        # Optional device mesh: placement passes run node-sharded over it
        # (each federated region schedules on its own slice).
        self.mesh = mesh

    def sched_name(self, ev: s.Evaluation) -> str:
        if ev.type == s.JOB_TYPE_SYSTEM:
            from ..ops import system_batch  # noqa: F401 — registers it

            return "tpu-system"
        return super().sched_name(ev)

    def run(self) -> None:
        from ..ops import batch_sched  # noqa: F401 — registers 'tpu-batch'

        pipelined = pipeline_enabled()
        while not self._stop.is_set():
            self._check_paused()
            try:
                batch = self.broker.dequeue_batch(
                    [s.JOB_TYPE_SERVICE, s.JOB_TYPE_BATCH],
                    self.max_batch, DEQUEUE_TIMEOUT)
            except EvalBrokerError:
                time.sleep(self._idle_backoff.next_delay())
                continue
            self._idle_backoff.reset()
            if batch:
                if pipelined:
                    # Per-batch latency samples are taken at each batch's
                    # finish (one drain spans many batches — a single
                    # measure() here would corrupt the histogram).
                    self._process_batches_pipelined(batch)
                else:
                    with self.metrics.measure(
                            "worker.invoke_scheduler.batch"):
                        self.process_batch(batch)
            # Always also poll system/core (zero timeout) so a sustained
            # service/batch stream cannot starve them.
            self._poll_system_core()

    def _poll_system_core(self) -> None:
        try:
            ev, token = self.broker.dequeue(
                [s.JOB_TYPE_SYSTEM, s.JOB_TYPE_CORE], 0)
        except EvalBrokerError:
            return
        if ev is not None:
            self.process_eval(ev, token)

    def process_batch(self, batch: List[Tuple[s.Evaluation, str]]) -> None:
        tr = tracing.TRACER
        if tr is None:
            self._process_batch(batch)
            return
        with tr.span("worker.process_batch",
                     num_evals=len(batch),
                     **tracing.eval_id_attrs(
                         (ev for ev, _ in batch), len(batch))) as sp:
            stats = self._process_batch(batch)
            if stats is not None and stats.device_ran:
                # Fused-path forensics at the worker boundary: which
                # program shape served the batch and what it cost on the
                # link (the single-fetch contract is auditable per batch
                # from the span tree alone).
                sp.set(fused=stats.fused, quantized=stats.quantized,
                       fetch_bytes=stats.fetch_bytes,
                       commit_s=round(stats.commit_seconds, 4))

    def _process_batch(self, batch: List[Tuple[s.Evaluation, str]]):
        """Returns the batch's BatchStats, or None when the batch was
        nacked."""
        max_index = max(ev.modify_index for ev, _ in batch)
        with tracing.span("worker.wait_for_index"):
            self.wait_for_index(max_index, RAFT_SYNC_LIMIT)
        # Always a fresh snapshot on the batch path: the device-resident
        # usage mirror advances by inter-snapshot deltas, and a reused
        # pre-apply snapshot would hide the previous batch's own
        # placements from the next batch's usage encode (conflict churn
        # the per-eval stale-snapshot pool tolerates, the batched kernel
        # path should not).
        snapshot_index = self.raft.applied_index()
        snap = self.raft.fsm.state.snapshot()

        # One scheduler instance per batch; per-eval planners for correct
        # token fencing on ack/nack.
        from ..ops.batch_sched import TPUBatchScheduler

        mux = _MuxPlanner(self, batch, snapshot_index)
        sched = TPUBatchScheduler(self.logger, snap, mux, mesh=self.mesh,
                                  metrics=self.metrics,
                                  snapshot_index=snapshot_index)
        tr = tracing.TRACER
        # Attempt numbers belong to THIS delivery, so capture them before
        # scheduling: a nack-timeout firing mid-batch redelivers the eval
        # and bumps the counter, and reading it afterwards would stamp
        # this delivery's marker with the next delivery's number.
        attempts = {} if tr is None else {
            ev.id: self.broker.delivery_attempts(ev.id)
            for ev, _ in batch}
        try:
            stats = sched.schedule_batch([ev for ev, _ in batch])
        except Exception as exc:
            self.logger.exception("batch scheduling failed; nacking batch")
            self.record_eval_failures([ev for ev, _ in batch], exc)
            for ev, token in batch:
                if tr is not None:
                    # Per-eval attempt marker with the nack reason: the
                    # batch path's twin of the worker.attempt span, so a
                    # redelivered eval's trace explains every burn.
                    tr.event("worker.attempt", eval_id=ev.id,
                             attempt=attempts[ev.id],
                             nack_reason=f"{type(exc).__name__}: {exc}")
                try:
                    self.broker.nack(ev.id, token)
                except EvalBrokerError:
                    pass
            return None
        for ev, token in batch:
            try:
                self.broker.ack(ev.id, token)
            except EvalBrokerError as exc:
                # The delivery burned anyway (typically a nack timeout
                # redelivered the eval mid-batch) — the marker must say
                # so, not read as a clean success.
                if tr is not None:
                    tr.event("worker.attempt", eval_id=ev.id,
                             attempt=attempts[ev.id],
                             nack_reason=f"ack failed: {exc}")
            else:
                if tr is not None:
                    # One worker.attempt marker per delivery, same as the
                    # per-eval Worker's span.
                    tr.event("worker.attempt", eval_id=ev.id,
                             attempt=attempts[ev.id])
        return stats

    # -- pipelined drain (NOMAD_TPU_PIPELINE=1) ----------------------------
    #
    # The double-buffered twin of _process_batch built on the split-phase
    # TPUBatchScheduler API: while batch k's device pass is in flight the
    # broker is polled for batch k+1, whose host phases (wait-for-index,
    # snapshot, reconciliation, spec dedup) run during k's device time.
    # k is then fetched + finalized + acked BEFORE k+1's usage delta is
    # built from a fresh snapshot, so the resident delta feed always
    # reflects k's applied plans.  Per-batch failures nack that batch
    # only, exactly like the serial path.

    def _process_batches_pipelined(
            self, batch: List[Tuple[s.Evaluation, str]]) -> None:
        pending = self._pipeline_start(batch)
        while pending is not None and not self._stop.is_set():
            if self._paused:
                # Honor a pause request mid-stream: settle the in-flight
                # batch and hand control back to run()'s pause wait.
                break
            try:
                nxt = self.broker.dequeue_batch(
                    [s.JOB_TYPE_SERVICE, s.JOB_TYPE_BATCH],
                    self.max_batch, 0)
            except EvalBrokerError:
                nxt = None
            if not nxt:
                break
            ctx = self._pipeline_prepare(nxt)   # overlaps pending's device
            self._pipeline_finish(pending)
            # Anti-starvation between pipelined batches: a sustained
            # service/batch stream must not lock out system/core evals
            # (same guarantee the serial run() loop gives per batch).
            self._poll_system_core()
            pending = (self._pipeline_dispatch(ctx)
                       if ctx is not None else None)
        if pending is not None:  # drain done / stop / pause
            self._pipeline_finish(pending)

    def _pipeline_start(self, batch) -> Optional[_BatchCtx]:
        ctx = self._pipeline_prepare(batch)
        if ctx is None:
            return None
        return self._pipeline_dispatch(ctx)

    def _pipeline_prepare(self, batch) -> Optional[_BatchCtx]:
        from ..ops.batch_sched import TPUBatchScheduler

        t0 = tracing.now()
        tr = tracing.TRACER
        attempts = {} if tr is None else {
            ev.id: self.broker.delivery_attempts(ev.id)
            for ev, _ in batch}
        try:
            max_index = max(ev.modify_index for ev, _ in batch)
            self.wait_for_index(max_index, RAFT_SYNC_LIMIT)
            snapshot_index = self.raft.applied_index()
            snap = self.raft.fsm.state.snapshot()
            mux = _MuxPlanner(self, batch, snapshot_index)
            sched = TPUBatchScheduler(self.logger, snap, mux,
                                      mesh=self.mesh, metrics=self.metrics,
                                      snapshot_index=snapshot_index)
            prep = sched._prepare_batch([ev for ev, _ in batch])
            return _BatchCtx(batch, sched, prep, attempts, t0)
        except Exception as exc:
            self._nack_batch(batch, attempts, exc)
            return None

    def _pipeline_dispatch(self, ctx: _BatchCtx) -> Optional[_BatchCtx]:
        try:
            # Fresh snapshot for the usage delta: the previous batch's
            # plans are applied by now (its _pipeline_finish ran first).
            ctx.sched.state = self.raft.fsm.state.snapshot()
            ctx.sched._dispatch_prepared(ctx.prep)
            return ctx
        except Exception as exc:
            self._nack_batch(ctx.batch, ctx.attempts, exc)
            return None

    def _pipeline_finish(self, ctx: _BatchCtx) -> None:
        tr = tracing.TRACER
        try:
            stats = ctx.sched._complete_prepared(ctx.prep)
        except Exception as exc:
            self._nack_batch(ctx.batch, ctx.attempts, exc)
            return
        ctx.sched._emit_batch_stats(stats)
        # Wall-clock latency of THIS batch (dequeue → acked), which in a
        # pipelined drain includes neighbor batches' host phases
        # interleaved on this thread — an eval-experienced latency, same
        # spirit as the serial measure() but not directly comparable to
        # it under sustained overlap.
        self.metrics.add_sample("worker.invoke_scheduler.batch",
                                (tracing.now() - ctx.t0) * 1000.0)
        if tr is not None:
            # Retroactive span (the pipelined phases interleave batches,
            # so a nested context-managed span would mis-stack).
            tr.record("worker.process_batch", ctx.t0, tracing.now(),
                      num_evals=len(ctx.batch), pipelined=True,
                      fused=stats.fused, fetch_bytes=stats.fetch_bytes,
                      **tracing.eval_id_attrs(
                          (ev for ev, _ in ctx.batch), len(ctx.batch)))
        for ev, token in ctx.batch:
            try:
                self.broker.ack(ev.id, token)
            except EvalBrokerError as exc:
                if tr is not None:
                    tr.event("worker.attempt", eval_id=ev.id,
                             attempt=ctx.attempts[ev.id],
                             nack_reason=f"ack failed: {exc}")
            else:
                if tr is not None:
                    tr.event("worker.attempt", eval_id=ev.id,
                             attempt=ctx.attempts[ev.id])

    def _nack_batch(self, batch, attempts, exc: Exception) -> None:
        tr = tracing.TRACER
        self.logger.exception("batch scheduling failed; nacking batch")
        self.record_eval_failures([ev for ev, _ in batch], exc)
        for ev, token in batch:
            if tr is not None:
                tr.event("worker.attempt", eval_id=ev.id,
                         attempt=attempts.get(ev.id, 0),
                         nack_reason=f"{type(exc).__name__}: {exc}")
            try:
                self.broker.nack(ev.id, token)
            except EvalBrokerError:
                pass
