"""L2+L3 control plane (reference: nomad/)."""

from .blocked_evals import BlockedEvals
from .core_sched import CoreScheduler
from .eval_broker import FAILED_QUEUE, EvalBroker, EvalBrokerError
from .fsm import FSM, MessageType, TimeTable
from .heartbeat import HeartbeatTimers
from .periodic import PeriodicDispatch, derive_job
from .plan_apply import PlanApplier
from .plan_queue import PlanFuture, PlanQueue
from .raft import FileLog, InmemLog, NotLeaderError, RaftLog
from .server import Server, ServerConfig
from .worker import BatchWorker, Worker, WorkerPlanner
