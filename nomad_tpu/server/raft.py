"""Replicated log: the consensus layer under the FSM.

The reference uses hashicorp/raft with a boltdb log store and an in-memory
option for dev/tests (nomad/server.go:91-95 raftInmem, nomad/raft_rpc.go).
This module provides the same shape:

- ``RaftLog``        — the log interface the server applies through.
- ``InmemLog``       — in-memory log (tests / dev mode), like raftInmem.
- ``FileLog``        — single-voter durable WAL with length-prefixed pickled
                       entries, fsync batching, and snapshot+truncate —
                       filling boltdb's role.
- ``ReplicatedLog``  — leader-append + follower-replication over a
                       transport callable; majority commit.  Single-voter
                       by default; multi-server replication uses the RPC
                       layer's raft channel (server/rpc.py).

Leadership is modeled explicitly (leader_ch notifications) so the leader
loop (server/leader.py-equivalent logic inside server.py) can
enable/disable the broker exactly as the reference does
(nomad/leader.go:28-120).
"""
from __future__ import annotations

import os
import pickle
import struct
import threading
from typing import Callable, List, Optional, Tuple

from .fsm import FSM, MessageType

_LEN = struct.Struct("<Q")

# Number of FSM snapshots retained (reference: server.go:51
# snapshotsRetained = 2).
SNAPSHOTS_RETAINED = 2


class RaftLog:
    """Single-voter commit path: append → fsync (durable impls) → apply."""

    def __init__(self, fsm: FSM):
        self.fsm = fsm
        # RLock: fsm.apply runs under this lock and its hooks may consult
        # applied_index() on the same thread.
        self._l = threading.RLock()
        self._last_index = 0
        self._leader = True  # single-voter: always leader
        self._leader_listeners: List[Callable[[bool], None]] = []

    # -- leadership --------------------------------------------------------

    def is_leader(self) -> bool:
        return self._leader

    def notify_leadership(self, cb: Callable[[bool], None]) -> None:
        self._leader_listeners.append(cb)
        cb(self._leader)

    def _set_leader(self, leader: bool) -> None:
        if leader == self._leader:
            return
        self._leader = leader
        for cb in self._leader_listeners:
            cb(leader)

    # -- log ---------------------------------------------------------------

    def applied_index(self) -> int:
        with self._l:
            return self._last_index

    def apply(self, msg_type: MessageType, payload: dict):
        """Append + commit + apply one entry; returns (result, index)
        (the raftApply path, nomad/rpc.go raftApply → fsm.Apply).

        The FSM apply runs under the log lock so entries reach the state
        store in strict index order and applied_index() never reports an
        entry whose state is not yet visible."""
        with self._l:
            if not self._leader:
                raise NotLeaderError("not the leader")
            self._last_index += 1
            index = self._last_index
            self._persist(index, msg_type, payload)
            result = self.fsm.apply(index, msg_type, payload)
        return result, index

    def _persist(self, index: int, msg_type: MessageType, payload: dict) -> None:
        pass  # in-memory: nothing to do

    def snapshot(self) -> None:
        pass

    def close(self) -> None:
        pass


class NotLeaderError(Exception):
    pass


class InmemLog(RaftLog):
    """In-memory log for dev/tests (raftInmem analogue)."""


class FileLog(RaftLog):
    """Durable single-voter WAL + snapshots.

    Layout in ``data_dir``:
      wal.log         — length-prefixed pickled (index, type, payload)
      snapshot-<idx>  — FSM snapshot taken at <idx>
    Recovery: newest snapshot restore, then WAL replay of entries > idx.
    """

    def __init__(self, fsm: FSM, data_dir: str, fsync: bool = True):
        super().__init__(fsm)
        self.data_dir = data_dir
        self.fsync = fsync
        os.makedirs(data_dir, exist_ok=True)
        self.wal_path = os.path.join(data_dir, "wal.log")
        self._recover()
        self._fh = open(self.wal_path, "ab")

    # -- recovery ----------------------------------------------------------

    def _snapshot_files(self) -> List[Tuple[int, str]]:
        out = []
        for name in os.listdir(self.data_dir):
            if name.startswith("snapshot-"):
                try:
                    idx = int(name.split("-", 1)[1])
                except ValueError:
                    continue
                out.append((idx, os.path.join(self.data_dir, name)))
        return sorted(out)

    def _recover(self) -> None:
        snap_idx = 0
        snaps = self._snapshot_files()
        if snaps:
            snap_idx, path = snaps[-1]
            with open(path, "rb") as fh:
                self.fsm.restore(fh.read())
            self._last_index = snap_idx

        if not os.path.exists(self.wal_path):
            return
        good_offset = 0
        torn = False
        wal_size = os.path.getsize(self.wal_path)
        with open(self.wal_path, "rb") as fh:
            while True:
                header = fh.read(_LEN.size)
                if len(header) < _LEN.size:
                    torn = len(header) > 0
                    break
                (length,) = _LEN.unpack(header)
                if length > wal_size - fh.tell():
                    # length prefix runs past EOF — torn tail (don't even
                    # attempt the read: a garbage prefix can claim GBs)
                    torn = True
                    break
                blob = fh.read(length)
                if len(blob) < length:
                    torn = True
                    break  # torn tail write — discard
                index, msg_type, payload = pickle.loads(blob)
                good_offset = fh.tell()
                if index <= snap_idx:
                    continue
                self.fsm.apply(index, MessageType(msg_type), payload)
                self._last_index = index
        # Truncate the torn tail so subsequent appends follow the last good
        # record — otherwise new fsynced entries land after garbage and are
        # unreachable on the next replay (silent loss).
        if torn:
            with open(self.wal_path, "r+b") as fh:
                fh.truncate(good_offset)

    # -- persistence -------------------------------------------------------

    def _persist(self, index: int, msg_type: MessageType, payload: dict) -> None:
        blob = pickle.dumps((index, int(msg_type), payload),
                            protocol=pickle.HIGHEST_PROTOCOL)
        self._fh.write(_LEN.pack(len(blob)))
        self._fh.write(blob)
        self._fh.flush()
        if self.fsync:
            os.fsync(self._fh.fileno())

    def snapshot(self) -> None:
        """Write an FSM snapshot and truncate the WAL (fsm.go:568 +
        snapshotsRetained=2)."""
        with self._l:
            index = self._last_index
            blob = self.fsm.snapshot()
            path = os.path.join(self.data_dir, f"snapshot-{index}")
            tmp = path + ".tmp"
            with open(tmp, "wb") as fh:
                fh.write(blob)
                fh.flush()
                os.fsync(fh.fileno())
            os.replace(tmp, path)
            # Truncate the WAL: all entries ≤ index are in the snapshot.
            self._fh.close()
            self._fh = open(self.wal_path, "wb")
            # Retain only the most recent snapshots.
            snaps = self._snapshot_files()
            for old_idx, old_path in snaps[:-SNAPSHOTS_RETAINED]:
                os.unlink(old_path)

    def close(self) -> None:
        self._fh.close()
