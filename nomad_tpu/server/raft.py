"""Replicated log: the consensus layer under the FSM.

The reference uses hashicorp/raft with a boltdb log store and an in-memory
option for dev/tests (nomad/server.go:91-95 raftInmem, nomad/raft_rpc.go).
This module provides the same shape:

- ``RaftLog``        — the log interface the server applies through.
- ``InmemLog``       — in-memory log (tests / dev mode), like raftInmem.
- ``FileLog``        — single-voter durable WAL with length-prefixed
                       entries (whitelisted msgpack trees via
                       server/log_codec — never pickle, so a corrupt or
                       attacker-written WAL/snapshot can only inject
                       data, not code), fsync batching, and
                       snapshot+truncate — filling boltdb's role.
- ``ReplicatedLog``  — leader-append + follower-replication over a
                       transport callable; majority commit.  Single-voter
                       by default; multi-server replication uses the RPC
                       layer's raft channel (server/rpc.py).

Leadership is modeled explicitly (leader_ch notifications) so the leader
loop (server/leader.py-equivalent logic inside server.py) can
enable/disable the broker exactly as the reference does
(nomad/leader.go:28-120).
"""
from __future__ import annotations

import os
import struct
import threading
import time
from typing import Callable, List, Optional, Tuple

from .. import fault
from ..utils import tracing
from ..utils.telemetry import NULL_TELEMETRY
from .fsm import FSM, MessageType
from .log_codec import decode_payload, encode_payload


def _fire_apply_fault(index: int, msg_type) -> Optional[str]:
    """``raft.apply`` fault point, shared by the single-voter and
    multi-voter apply paths.  Returns "step_down" for the caller to
    translate into its own leadership demotion; crash/error raise here;
    delay sleeps here.  Ctx exposed to rules: the prospective log
    ``index`` and the message type name (e.g. ``"APPLY_PLAN_RESULTS"``)."""
    act = fault.faultpoint(
        "raft.apply", index=index,
        msg_type=getattr(msg_type, "name", str(msg_type)))
    if act is None:
        return None
    if act.kind == "delay":
        import time as _time
        _time.sleep(act.delay)
        return None
    if act.kind == "step_down":
        return "step_down"
    act.raise_injected()
    return None


def _encode_entry(index, msg_type, payload):
    return encode_payload({"i": int(index), "t": int(msg_type),
                           "p": payload})


def _decode_entry(blob):
    """Decode one WAL record; raises on anything that is not a
    well-formed msgpack entry (callers treat that as a corrupt tail)."""
    d = decode_payload(blob)
    return d["i"], d["t"], d["p"]

_LEN = struct.Struct("<Q")

# Number of FSM snapshots retained (reference: server.go:51
# snapshotsRetained = 2).
SNAPSHOTS_RETAINED = 2


def _env_int(name: str, default: int) -> int:
    from ..utils import knobs

    return knobs.get_int(name, default)


def _env_float(name: str, default: float) -> float:
    from ..utils import knobs

    return knobs.get_float(name, default)


class RaftLog:
    """Single-voter commit path: append → fsync (durable impls) → apply."""

    # Telemetry handle, assigned by the owning Server after construction
    # (class default keeps standalone/test construction zero-config).
    metrics = NULL_TELEMETRY

    def __init__(self, fsm: FSM):
        self.fsm = fsm
        # RLock: index assignment/persist run under this lock; FSM-apply
        # hooks may consult applied_index() on the same thread.
        self._l = threading.RLock()
        self._last_index = 0
        self._applied = 0
        self._leader = True  # single-voter: always leader
        self._leader_listeners: List[Callable[[bool], None]] = []
        # Apply sequencer: entries apply to the FSM in strict index
        # order AFTER their durability wait (see apply()).  A sync
        # covers the whole written prefix, so durability of entry N
        # implies durability of everything below it — the wait here is
        # only for apply ORDERING, never for a lower entry's fsync.
        self._apply_cv = threading.Condition()
        self._apply_next = 1
        self._apply_failed = False

    # -- leadership --------------------------------------------------------

    def is_leader(self) -> bool:
        return self._leader

    def notify_leadership(self, cb: Callable[[bool], None]) -> None:
        self._leader_listeners.append(cb)
        cb(self._leader)

    def _set_leader(self, leader: bool) -> None:
        if leader == self._leader:
            return
        self._leader = leader
        for cb in self._leader_listeners:
            cb(leader)

    # -- log ---------------------------------------------------------------

    def applied_index(self) -> int:
        with self._l:
            return self._applied

    def fence_index(self) -> int:
        """Upper bound on every COMMITTED entry's index, safe for the
        follower-read fence floor at leadership establishment.  For the
        single-voter log applied == last; MultiRaft overrides with the
        last LOG index — with async FSM apply the applied index can lag
        committed entries still draining, and a floor below a committed
        plan would let a lagging follower stale double-place."""
        return self.applied_index()

    def applied_index_relaxed(self) -> int:
        """Lock-free lower bound on :meth:`applied_index`.  ``_applied``
        is stamped AFTER each FSM apply (GIL-ordered), so this never
        reports an entry whose state is not yet visible — it may lag an
        in-flight apply by one entry.  For hot read paths (heartbeat
        grants, wait-for-index polling, external event stamping) where
        queueing on the raft lock behind the apply stream is the
        dominant cost; anything that needs the serializes-with-applies
        guarantee (event-broker arming horizon) stays on the locked
        read."""
        applied = getattr(self, "_applied", None)
        return applied if applied is not None else self.applied_index()

    def apply(self, msg_type: MessageType, payload: dict):
        """Append + commit + apply one entry; returns (result, index)
        (the raftApply path, nomad/rpc.go raftApply → fsm.Apply).

        Three phases, preserving durability-before-visibility while
        letting concurrent appliers share one fsync:

        1. Under the log lock: assign the index and WRITE the entry
           (file order == index order, so the durable prefix is always
           gap-free).  No fsync here — holding the lock across the
           fsync made group commit structurally impossible (appends
           were never concurrent) and serialized one fsync per apply.
        2. Outside the lock: wait for durability (_sync_persist);
           concurrent waiters coalesce into one group-commit fsync.
        3. Apply sequencer: FSM applies run in strict index order,
           AFTER durability — nothing external (event stream, blocking
           queries, applied_index readers) can observe state a crash
           would erase.  A sync covers the whole written prefix, so
           waiting for entry N-1's APPLY never waits on another fsync.

        A durability failure poisons the log (fsync failure is fatal —
        the reference panics): the entry was never applied, no retry
        can double-apply, and every queued/later apply fails too."""
        t0 = time.perf_counter()
        with self._l:
            if not self._leader:
                raise NotLeaderError("not the leader")
            if getattr(self, "_wal_failed", False):
                # A durability failure already poisoned this log: the
                # durable prefix is unknown, so NO further applies are
                # accepted — restart to recover from it.
                raise NotLeaderError("write-ahead log failed; restart "
                                     "to recover from the durable prefix")
            # Fault point BEFORE append: an injected crash here models the
            # leader dying before the entry commits — nothing persists,
            # nothing applies, and the caller's retry path must cope.
            if _fire_apply_fault(self._last_index + 1, msg_type) is not None:
                raise NotLeaderError("injected step-down")
            self._last_index += 1
            index = self._last_index
            try:
                token = self._persist(index, msg_type, payload)
            except Exception:
                # Nothing reached the log (writes roll back torn
                # frames): release the index so the apply sequencer
                # never waits on a permanently-missing entry.
                self._last_index -= 1
                raise
        if token is not None:
            try:
                self._sync_persist(token, msg_type)
            except Exception:
                with self._l:
                    self._wal_failed = True
                with self._apply_cv:
                    # The written entry will never apply: every later
                    # (higher-index) applier queued behind it must fail
                    # rather than wait forever.
                    self._apply_failed = True
                    self._apply_cv.notify_all()
                raise
        with self._apply_cv:
            while self._apply_next != index:
                if self._apply_failed:
                    raise NotLeaderError(
                        "write-ahead log failed; restart to recover "
                        "from the durable prefix")
                self._apply_cv.wait()
            try:
                result = self.fsm.apply(index, msg_type, payload)
            finally:
                # ALWAYS advance: an FSM apply that raises (e.g. a
                # deregister of an unknown node) propagates to its one
                # caller exactly as before, but the sequencer must not
                # wedge every later apply behind the dead index.
                self._applied = index  # visible only now: post-durability
                self._apply_next = index + 1
                self._apply_cv.notify_all()
        self.metrics.measure_since("raft.apply", t0)
        # Branch before building attrs: the disarmed commit path pays
        # one load + comparison, no getattr/dict/timestamp.
        tr = tracing.TRACER
        if tr is not None:
            tr.record("raft.apply", t0, time.perf_counter(), index=index,
                      msg_type=getattr(msg_type, "name", str(msg_type)))
        return result, index

    def _persist(self, index: int, msg_type: MessageType, payload: dict):
        return None  # in-memory: nothing to do

    def _sync_persist(self, token, msg_type) -> None:
        pass  # in-memory: nothing to wait for

    def snapshot(self) -> None:
        pass

    def close(self) -> None:
        pass


class NotLeaderError(Exception):
    pass


class InmemLog(RaftLog):
    """In-memory log for dev/tests (raftInmem analogue)."""


class FileLog(RaftLog):
    """Durable single-voter WAL + snapshots.

    Layout in ``data_dir``:
      wal.crc         — CRC-framed records via the native group-commit WAL
                        (nomad_tpu/native/wal.cc) when the toolchain is
                        available: concurrent appends coalesce into one
                        fsync (~10x append throughput under RPC-handler
                        concurrency, the raft-boltdb single-writer role)
      wal.log         — legacy length-prefixed fallback (pure Python),
                        used when native is unavailable; replayed before
                        wal.crc on recovery so upgrades are seamless
      walseg-<idx>.*  — sealed WAL segments rolled at a snapshot: fully
                        fsynced, immutable, deleted once the snapshot
                        blob that covers them is durable (a crash
                        mid-snapshot leaves them for replay — nothing
                        is ever lost to an unfinished snapshot)
      snapshot-<idx>  — FSM snapshot taken at <idx>
    Recovery: newest snapshot restore, then sealed-segment + WAL replay
    of entries > idx.

    Automatic snapshotting (ISSUE 10 / ROADMAP item 2): a live server
    compacts itself — a background thread watches entry/byte thresholds
    and snapshots OFF the apply path (the expensive FSM serialization
    runs on a copy-on-write state snapshot outside the log lock, while
    appends keep flowing into a freshly rolled segment).  Thresholds:
    ``NOMAD_TPU_FILELOG_SNAPSHOT_ENTRIES`` (default 8192, the
    hashicorp/raft SnapshotThreshold), ``_BYTES`` (default 64MB of WAL),
    ``_INTERVAL`` (check cadence, default 1s); 0 entries AND 0 bytes
    disables.  Operator/test-invoked :meth:`snapshot` runs the same
    implementation synchronously.
    """

    def __init__(self, fsm: FSM, data_dir: str, fsync: bool = True,
                 snapshot_entries: Optional[int] = None,
                 snapshot_bytes: Optional[int] = None,
                 snapshot_interval: Optional[float] = None):
        super().__init__(fsm)
        self.data_dir = data_dir
        self.fsync = fsync
        os.makedirs(data_dir, exist_ok=True)
        self.wal_path = os.path.join(data_dir, "wal.log")
        self._nwal = None
        try:
            from ..native import NativeWAL, NativeUnavailable

            try:
                self._nwal = NativeWAL(os.path.join(data_dir, "wal.crc"),
                                       fsync=fsync)
            except NativeUnavailable:
                self._nwal = None
        except ImportError:  # pragma: no cover
            self._nwal = None
        self._recover()
        self._fh = (open(self.wal_path, "ab") if self._nwal is None
                    else None)
        # Pure-Python group-commit state (the fallback twin of
        # native/wal.cc's written/synced seq + single-syncer dance):
        # writes happen in index order under the raft lock; the fsync
        # wait runs outside it so concurrent appliers share one fsync.
        self._py_cv = threading.Condition()
        self._py_written = 0
        self._py_synced = 0
        self._py_sync_in_flight = False
        # Automatic snapshotting state.  _sync_inflight counts appliers
        # holding a durability token (between _persist and the end of
        # _sync_persist): the WAL roll at a snapshot waits it to zero —
        # with the log lock held no new tokens mint, so the old
        # handles/files are quiescent when swapped.
        self._sync_inflight = 0
        self._entries_since_snap = 0
        self._bytes_since_snap = 0
        self._snap_serial = threading.Lock()
        self._snap_stop = threading.Event()
        self._snap_thread: Optional[threading.Thread] = None
        self.snapshot_entries = (snapshot_entries
                                 if snapshot_entries is not None
                                 else _env_int(
                                     "NOMAD_TPU_FILELOG_SNAPSHOT_ENTRIES",
                                     8192))
        self.snapshot_bytes = (snapshot_bytes
                               if snapshot_bytes is not None
                               else _env_int(
                                   "NOMAD_TPU_FILELOG_SNAPSHOT_BYTES",
                                   64 << 20))
        self.snapshot_interval = (snapshot_interval
                                  if snapshot_interval is not None
                                  else _env_float(
                                      "NOMAD_TPU_FILELOG_SNAPSHOT_INTERVAL",
                                      1.0))
        if (self.snapshot_entries > 0 or self.snapshot_bytes > 0) \
                and self.snapshot_interval > 0:
            self._snap_thread = threading.Thread(
                target=self._auto_snapshot_loop, daemon=True,
                name="filelog-snapshot")
            self._snap_thread.start()

    # -- recovery ----------------------------------------------------------

    def _snapshot_files(self) -> List[Tuple[int, str]]:
        out = []
        for name in os.listdir(self.data_dir):
            if name.startswith("snapshot-"):
                try:
                    idx = int(name.split("-", 1)[1])
                except ValueError:
                    continue
                out.append((idx, os.path.join(self.data_dir, name)))
        return sorted(out)

    def _segment_files(self) -> List[str]:
        out = []
        for name in os.listdir(self.data_dir):
            if name.startswith("walseg-"):
                out.append(os.path.join(self.data_dir, name))
        return sorted(out)

    def _recover(self) -> None:
        snap_idx = 0
        snaps = self._snapshot_files()
        if snaps:
            snap_idx, path = snaps[-1]
            with open(path, "rb") as fh:
                self.fsm.restore(fh.read())
            self._last_index = snap_idx
            self._applied = snap_idx

        # Sealed segments first (rolled at snapshots; a crash between the
        # roll and the snapshot blob's fsync leaves their entries ONLY
        # here), then the active logs.  Segments fully covered by the
        # snapshot are deleted — replaying them again would only re-filter.
        entries: List[Tuple[int, int, dict]] = []
        for seg in self._segment_files():
            if seg.endswith(".crc"):
                got = self._read_crc_entries(snap_idx, path=seg)
            else:
                got = self._read_legacy_entries(snap_idx, path=seg)
            if got:
                entries.extend(got)
            else:
                try:
                    os.unlink(seg)
                except OSError:  # pragma: no cover — cleanup best-effort
                    pass

        # Gather entries from BOTH active logs and apply in index order: a
        # node toggled between native and fallback modes may have newer
        # entries in either file.
        entries.extend(self._read_legacy_entries(snap_idx))
        if self._nwal is not None:
            # Native log replay (CRC + torn-tail handling done at open).
            # A CRC-valid record that still fails to decode (garbage or a
            # pre-msgpack-format file) ends replay at the last good entry
            # — and the log is REWRITTEN to that good prefix, so entries
            # appended after this boot land after valid records and stay
            # recoverable (leaving the bad record in place would strand
            # every later append behind it on the next replay).
            good_blobs = []
            bad = False
            for blob in self._nwal.records():
                try:
                    index, msg_type, payload = _decode_entry(blob)
                except Exception:
                    bad = True
                    break
                good_blobs.append(blob)
                if index > snap_idx:
                    entries.append((index, msg_type, payload))
            if bad:
                self._nwal.reset()
                for blob in good_blobs:
                    self._nwal.append(blob)
        else:
            # Native unavailable on THIS boot but a wal.crc exists from a
            # previous one: replay it through the pure-Python CRC reader —
            # silently ignoring it would roll back committed entries and
            # reuse their indexes.
            entries.extend(self._read_crc_entries(snap_idx))
        # Same-index duplicates can only be identical payloads (an index
        # is written to exactly one log at append time); keep the first.
        entries.sort(key=lambda e: e[0])
        prev_index = None
        for index, msg_type, payload in entries:
            if index == prev_index:
                continue
            prev_index = index
            self.fsm.apply(index, MessageType(msg_type), payload)
            self._last_index = index
        self._applied = self._last_index
        self._apply_next = self._last_index + 1

    def _read_crc_entries(self, snap_idx: int, path: Optional[str] = None):
        """Pure-Python reader for the native wal.crc format
        ([u32 len][u32 crc32(payload)][payload]); validates CRCs and
        truncates a torn/corrupt tail exactly like wal.cc recover().
        ``path`` reads a sealed segment instead of the active log."""
        import struct as _struct
        import zlib

        out = []
        path = path or os.path.join(self.data_dir, "wal.crc")
        if not os.path.exists(path):
            return out
        size = os.path.getsize(path)
        good = 0
        with open(path, "rb") as fh:
            while True:
                header = fh.read(8)
                if len(header) < 8:
                    break
                length, crc = _struct.unpack("<II", header)
                if length > size - fh.tell():
                    break
                blob = fh.read(length)
                if len(blob) < length or (zlib.crc32(blob) & 0xFFFFFFFF) != crc:
                    break
                try:
                    index, msg_type, payload = _decode_entry(blob)
                except Exception:
                    break  # undecodable record — treat as corrupt tail
                good = fh.tell()
                if index > snap_idx:
                    out.append((index, msg_type, payload))
        if good < size:
            with open(path, "r+b") as fh:
                fh.truncate(good)
        return out

    def _read_legacy_entries(self, snap_idx: int,
                             path: Optional[str] = None):
        wal_path = path or self.wal_path
        out = []
        if not os.path.exists(wal_path):
            return out
        good_offset = 0
        torn = False
        wal_size = os.path.getsize(wal_path)
        with open(wal_path, "rb") as fh:
            while True:
                header = fh.read(_LEN.size)
                if len(header) < _LEN.size:
                    torn = len(header) > 0
                    break
                (length,) = _LEN.unpack(header)
                if length > wal_size - fh.tell():
                    # length prefix runs past EOF — torn tail (don't even
                    # attempt the read: a garbage prefix can claim GBs)
                    torn = True
                    break
                blob = fh.read(length)
                if len(blob) < length:
                    torn = True
                    break  # torn tail write — discard
                try:
                    index, msg_type, payload = _decode_entry(blob)
                except Exception:
                    # Length-valid but undecodable (garbage flush, or a
                    # pre-msgpack-format record): corrupt tail — truncate
                    # so appends follow the last good record.
                    torn = True
                    break
                good_offset = fh.tell()
                if index <= snap_idx:
                    continue
                out.append((index, msg_type, payload))
        # Truncate the torn tail so subsequent appends follow the last good
        # record — otherwise new fsynced entries land after garbage and are
        # unreachable on the next replay (silent loss).
        if torn:
            with open(wal_path, "r+b") as fh:
                fh.truncate(good_offset)
        return out

    # -- persistence -------------------------------------------------------

    def _persist(self, index: int, msg_type: MessageType, payload: dict):
        """WRITE one entry (buffered, index order — caller holds the
        raft lock) and return the durability token _sync_persist waits
        on outside the lock."""
        blob = _encode_entry(index, msg_type, payload)
        # Fault point ``wal.fsync``: a crash here models the process
        # dying mid-frame — a torn partial record is left on disk (the
        # recovery path must truncate it) and the entry never applies.
        act = fault.faultpoint("wal.fsync", index=index,
                              msg_type=getattr(msg_type, "name",
                                               str(msg_type)))
        if act is not None:
            if act.kind == "delay":
                time.sleep(act.delay)
            else:
                self._write_torn_frame(blob)
                # Crash semantics: this process's log is DEAD.  Without
                # the poison, a caller catching the injected error could
                # keep appending — in the O_APPEND fallback those frames
                # land AFTER the torn one, get acked durable, and are
                # then silently truncated away with the bad tail at the
                # next recovery.
                self._wal_failed = True
                act.raise_injected()
        if self._nwal is not None:
            token = self._nwal.write(blob)
        else:
            pos = self._fh.tell()
            try:
                self._fh.write(_LEN.pack(len(blob)))
                self._fh.write(blob)
                self._fh.flush()
            except OSError:
                # Roll the torn frame back (ENOSPC): left mid-log it would
                # strand later appends behind it — recovery truncates at
                # the first bad frame.
                try:
                    self._fh.seek(pos)
                    self._fh.truncate(pos)
                except OSError:  # pragma: no cover — disk truly gone
                    pass
                raise
            with self._py_cv:
                self._py_written += 1
                token = self._py_written
        # Auto-snapshot accounting (caller holds the raft lock) + the
        # durability-token guard: inflight is raised ONLY once the write
        # succeeded, and _sync_persist's finally lowers it — the WAL
        # roll waits it to zero before swapping handles.
        self._entries_since_snap += 1
        self._bytes_since_snap += len(blob) + _LEN.size
        with self._py_cv:
            self._sync_inflight += 1
        return token

    def _sync_persist(self, seq: int, msg_type) -> None:
        """Wait (outside the raft lock) until the entry written as
        ``seq`` is durable.  Concurrent callers coalesce into one fsync
        — natively via wal.cc's group commit, in the fallback via the
        same written/synced-seq single-syncer dance in Python."""
        t0 = time.perf_counter()
        try:
            self._do_sync_persist(seq)
        finally:
            with self._py_cv:
                self._sync_inflight -= 1
                self._py_cv.notify_all()
        self.metrics.measure_since("raft.fsync", t0)
        if msg_type == MessageType.APPLY_PLAN_RESULTS:
            # The loadgen report's plan_apply_fsync percentiles: the
            # durability wait specifically on the plan-apply path.
            self.metrics.measure_since("raft.fsync.plan", t0)

    def _do_sync_persist(self, seq: int) -> None:
        if self._nwal is not None:
            self._nwal.sync_to(seq)
        elif self.fsync:
            with self._py_cv:
                while True:
                    if getattr(self, "_py_failed", False):
                        # Sticky: a failed fsync may have dropped dirty
                        # pages AND cleared the kernel error state
                        # (fsyncgate) — a retry would return success
                        # and falsely ack never-written entries.
                        raise OSError("wal fsync previously failed")
                    if self._py_synced >= seq:
                        break
                    if not self._py_sync_in_flight:
                        self._py_sync_in_flight = True
                        cover = self._py_written
                        self._py_cv.release()
                        try:
                            os.fsync(self._fh.fileno())
                        except OSError:
                            self._py_cv.acquire()
                            self._py_sync_in_flight = False
                            self._py_failed = True
                            self._py_cv.notify_all()
                            raise
                        self._py_cv.acquire()
                        self._py_sync_in_flight = False
                        self._py_cv.notify_all()
                        if cover > self._py_synced:
                            self._py_synced = cover
                        break
                    self._py_cv.wait()

    def _write_torn_frame(self, blob: bytes) -> None:
        """Simulate a crash mid-append: leave a partial frame (header +
        truncated payload) at the tail of whichever log is active."""
        frame = _LEN.pack(len(blob)) + blob if self._nwal is None else (
            struct.pack("<II", len(blob), 0xDEADBEEF) + blob)
        torn = frame[:max(4, len(frame) // 2)]
        path = (os.path.join(self.data_dir, "wal.crc")
                if self._nwal is not None else self.wal_path)
        try:
            with open(path, "ab") as fh:
                fh.write(torn)
                fh.flush()
        except OSError:  # pragma: no cover — fault plumbing best-effort
            pass

    def _roll_wal(self, index: int) -> List[str]:
        """Seal the active WAL into immutable ``walseg-<index>`` files
        and open fresh logs (caller holds the raft lock).  Everything
        sealed is made durable FIRST — a durability token issued before
        the roll resolves against an already-fsynced prefix, never
        against the fresh (empty) log.  Returns the sealed paths for
        deletion once the snapshot blob that covers them is durable."""
        # Quiesce durability waiters: appends are blocked by the raft
        # lock, so the token set only drains; waiters never need the
        # raft lock, so this cannot deadlock.
        with self._py_cv:
            while self._sync_inflight:
                self._py_cv.wait(0.05)
        segs: List[str] = []
        if self._nwal is not None:
            try:
                self._nwal.sync()
            except OSError:
                self._wal_failed = True
                raise
            self._nwal.close()
            crc_path = os.path.join(self.data_dir, "wal.crc")
            if os.path.exists(crc_path) and os.path.getsize(crc_path):
                seg = os.path.join(self.data_dir,
                                   f"walseg-{index:012d}.crc")
                os.replace(crc_path, seg)
                segs.append(seg)
            from ..native import NativeWAL

            self._nwal = NativeWAL(crc_path, fsync=self.fsync)
            # Legacy records from a pre-native boot are covered too.
            if os.path.exists(self.wal_path) \
                    and os.path.getsize(self.wal_path):
                seg = os.path.join(self.data_dir,
                                   f"walseg-{index:012d}.log")
                os.replace(self.wal_path, seg)
                segs.append(seg)
        else:
            if self.fsync:
                try:
                    os.fsync(self._fh.fileno())
                except OSError:
                    with self._py_cv:
                        self._py_failed = True
                        self._py_cv.notify_all()
                    self._wal_failed = True
                    raise
            self._fh.close()
            if os.path.exists(self.wal_path) \
                    and os.path.getsize(self.wal_path):
                seg = os.path.join(self.data_dir,
                                   f"walseg-{index:012d}.log")
                os.replace(self.wal_path, seg)
                segs.append(seg)
            self._fh = open(self.wal_path, "ab")
            with self._py_cv:
                self._py_synced = self._py_written
                self._py_cv.notify_all()
        self._entries_since_snap = 0
        self._bytes_since_snap = 0
        return segs

    def _persist_snapshot_blob(self, snap_store, index: int) -> None:
        """Serialize + persist the FSM snapshot — the expensive step,
        run OUTSIDE the log lock so appends keep flowing into the fresh
        segment (and the seam the off-apply-path tests hook to prove
        it)."""
        blob = snap_store.persist()
        path = os.path.join(self.data_dir, f"snapshot-{index}")
        tmp = path + ".tmp"
        with open(tmp, "wb") as fh:
            fh.write(blob)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)

    def _snapshot_impl(self) -> bool:
        """One FSM snapshot + WAL compaction (fsm.go:568 +
        snapshotsRetained=2), apply-path-friendly: the log lock is held
        only for the sequencer drain, an O(1) copy-on-write state
        snapshot, and the segment roll; the serialization and the
        fsyncs run outside it."""
        t0 = time.perf_counter()
        with self._snap_serial:
            # Quiesce-at-index loop: the sequencer drain must run
            # WITHOUT the log lock — a live server's FSM-apply hooks
            # read applied_index() (which takes it), so holding it
            # across the drain deadlocks against the very entries being
            # drained.  Instead: read the target index, wait for the
            # sequencer to pass it lock-free, then re-acquire and
            # verify nothing new was assigned; retry on a moving
            # target (a saturated log just postpones compaction to the
            # watcher's next tick).
            for _attempt in range(50):
                with self._l:
                    if getattr(self, "_wal_failed", False):
                        return False
                    index = self._last_index
                with self._apply_cv:
                    while (self._apply_next <= index
                           and not self._apply_failed):
                        self._apply_cv.wait(timeout=1.0)
                with self._l:
                    if getattr(self, "_wal_failed", False):
                        return False
                    if self._last_index != index:
                        continue  # new appends landed; chase the target
                    snap_store = self.fsm.state.snapshot()
                    segs = self._roll_wal(index)
                    break
            else:
                return False  # never quiesced; retry on the next tick
            # Everything below runs while appends flow into the fresh
            # segment.  A crash anywhere here is safe: the sealed
            # segments still hold every entry the unfinished snapshot
            # would have covered.
            self._persist_snapshot_blob(snap_store, index)
            for seg in segs:
                try:
                    os.unlink(seg)
                except OSError:  # pragma: no cover — cleanup best-effort
                    pass
            # Retain only the most recent snapshots.
            for _old_idx, old_path in \
                    self._snapshot_files()[:-SNAPSHOTS_RETAINED]:
                try:
                    os.unlink(old_path)
                except OSError:  # pragma: no cover
                    pass
        self.metrics.incr_counter("raft.snapshot")
        self.metrics.measure_since("raft.snapshot.persist", t0)
        return True

    def _auto_snapshot_loop(self) -> None:
        """Threshold watcher (hashicorp/raft runSnapshots): snapshots
        on the dedicated thread, never on an applier's."""
        import logging as _logging

        while not self._snap_stop.wait(self.snapshot_interval):
            with self._l:
                due = (not getattr(self, "_wal_failed", False) and (
                    (self.snapshot_entries > 0
                     and self._entries_since_snap >= self.snapshot_entries)
                    or (self.snapshot_bytes > 0
                        and self._bytes_since_snap >= self.snapshot_bytes)))
            if not due:
                continue
            try:
                if self._snapshot_impl():
                    self.metrics.incr_counter("raft.snapshot.auto")
            except Exception:
                _logging.getLogger("nomad_tpu.raft").exception(
                    "automatic FSM snapshot failed")

    def snapshot(self) -> None:
        """Write an FSM snapshot and compact the WAL (operator/test
        entry point; the automatic path runs the same implementation)."""
        self._snapshot_impl()

    def close(self) -> None:
        self._snap_stop.set()
        if self._snap_thread is not None:
            self._snap_thread.join(timeout=2.0)
        if self._nwal is not None:
            self._nwal.close()
        if self._fh is not None:
            self._fh.close()


# ---------------------------------------------------------------------------
# Multi-server replication (hashicorp/raft equivalent)
# ---------------------------------------------------------------------------

# Log entries are [index, term, msg_type, payload_blob] lists (msgpack-ready
# for the wire).  msg_type NOOP_TYPE marks the leader's term-establishment
# no-op entry (hashicorp/raft LogNoop): it commits prior-term entries
# without feeding the FSM.  CONFIG_TYPE entries carry the voter set
# (hashicorp/raft LogConfiguration): membership changes replicate through
# the log so every server's quorum derives from a committed configuration,
# never from its private gossip view (which could yield disjoint quorums).
NOOP_TYPE = -1
CONFIG_TYPE = -2


class RaftTimeoutError(Exception):
    """Apply could not reach quorum within the timeout (the reference's
    raft.Apply(…, timeout) ErrEnqueueTimeout/leadership-lost errors)."""


class _ApplyFuture:
    """Resolution of one leader-appended log entry: the FSM result once the
    entry commits, or an error if leadership was lost first.  Fixes the
    round-1 race where concurrent apply() callers could lose their result
    to a sibling thread advancing commit_index."""

    __slots__ = ("_ev", "result", "error")

    def __init__(self):
        self._ev = threading.Event()
        self.result = None
        self.error: Optional[Exception] = None

    def resolve(self, result) -> None:
        self.result = result
        self._ev.set()

    def fail(self, exc: Exception) -> None:
        self.error = exc
        self._ev.set()

    def wait(self, timeout: float):
        if not self._ev.wait(timeout):
            raise RaftTimeoutError("raft apply timed out awaiting quorum")
        if self.error is not None:
            raise self.error
        return self.result


class _RaftStore:
    """Durable raft state: current term + vote, the entry log, and FSM
    snapshots (the raft-boltdb log store + stable store + snapshot store
    roles, nomad/server.go:91-95).  ``data_dir=None`` keeps everything in
    memory (the raftInmem dev path).

    Layout:
      meta            — msgpack {term, voted_for}, rewritten + fsynced
      wal             — length-prefixed msgpack [index, term, type, blob]
      snapshot-<idx>-<term> — FSM snapshot through <idx>
    """

    def __init__(self, data_dir: Optional[str]):
        self.dir = data_dir
        self._fh = None
        if self.dir:
            os.makedirs(self.dir, exist_ok=True)

    # -- load --------------------------------------------------------------

    def load(self):
        """Returns (term, voted_for, peers, base_index, base_term, entries,
        snapshot_blob_or_None)."""
        import msgpack
        term, voted = 0, None
        peers: List[str] = []
        base_index, base_term = 0, 0
        entries: List[list] = []
        snap_blob = None
        if not self.dir:
            return term, voted, peers, base_index, base_term, entries, snap_blob

        meta_path = os.path.join(self.dir, "meta")
        if os.path.exists(meta_path):
            with open(meta_path, "rb") as fh:
                meta = msgpack.unpackb(fh.read(), raw=False)
            term, voted = meta.get("term", 0), meta.get("voted_for")
            peers = meta.get("peers") or []

        snaps = self._snapshot_files()
        if snaps:
            (base_index, base_term), path = snaps[-1]
            with open(path, "rb") as fh:
                snap_blob = fh.read()

        wal_path = os.path.join(self.dir, "wal")
        if os.path.exists(wal_path):
            good = 0
            size = os.path.getsize(wal_path)
            with open(wal_path, "rb") as fh:
                while True:
                    header = fh.read(_LEN.size)
                    if len(header) < _LEN.size:
                        torn = len(header) > 0
                        break
                    (length,) = _LEN.unpack(header)
                    if length > size - fh.tell():
                        torn = True
                        break
                    blob = fh.read(length)
                    if len(blob) < length:
                        torn = True
                        break
                    entry = msgpack.unpackb(blob, raw=False)
                    good = fh.tell()
                    if entry[0] <= base_index:
                        continue  # covered by the snapshot
                    entries.append(entry)
                else:
                    torn = False
            if torn:
                with open(wal_path, "r+b") as fh:
                    fh.truncate(good)
        self._fh = open(os.path.join(self.dir, "wal"), "ab") if self.dir else None
        return term, voted, peers, base_index, base_term, entries, snap_blob

    def _snapshot_files(self):
        out = []
        for name in os.listdir(self.dir):
            if name.startswith("snapshot-"):
                parts = name.split("-")
                try:
                    idx, term = int(parts[1]), int(parts[2])
                except (IndexError, ValueError):
                    continue
                out.append(((idx, term), os.path.join(self.dir, name)))
        return sorted(out)

    # -- persist -----------------------------------------------------------

    def save_meta(self, term: int, voted_for: Optional[str],
                  peers: Optional[List[str]] = None) -> None:
        if not self.dir:
            return
        import msgpack
        path = os.path.join(self.dir, "meta")
        tmp = path + ".tmp"
        with open(tmp, "wb") as fh:
            fh.write(msgpack.packb({"term": term, "voted_for": voted_for,
                                    "peers": peers or []},
                                   use_bin_type=True))
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)

    def append(self, entries: List[list]) -> None:
        if self._fh is None:
            return
        import msgpack
        for e in entries:
            blob = msgpack.packb(e, use_bin_type=True)
            self._fh.write(_LEN.pack(len(blob)))
            self._fh.write(blob)
        self._fh.flush()
        os.fsync(self._fh.fileno())

    def rewrite(self, entries: List[list]) -> None:
        """Conflict truncation / compaction: replace the whole WAL.

        Built atomically (tmp + fsync + rename): truncating the live WAL
        in place would let a crash mid-rewrite wipe already-acked entries
        — a follower counted toward an entry's commit quorum must never
        silently lose it."""
        if not self.dir:
            return
        import msgpack
        path = os.path.join(self.dir, "wal")
        tmp = path + ".tmp"
        with open(tmp, "wb") as fh:
            for e in entries:
                blob = msgpack.packb(e, use_bin_type=True)
                fh.write(_LEN.pack(len(blob)))
                fh.write(blob)
            fh.flush()
            os.fsync(fh.fileno())
        if self._fh is not None:
            self._fh.close()
        os.replace(tmp, path)
        self._fh = open(path, "ab")

    def save_snapshot(self, index: int, term: int, blob: bytes) -> None:
        if not self.dir:
            return
        path = os.path.join(self.dir, f"snapshot-{index}-{term}")
        tmp = path + ".tmp"
        with open(tmp, "wb") as fh:
            fh.write(blob)
            fh.flush()
            os.fsync(fh.fileno())
        os.replace(tmp, path)
        for _, old in self._snapshot_files()[:-SNAPSHOTS_RETAINED]:
            os.unlink(old)

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None


class MultiRaft(RaftLog):
    """Leader election + log replication across servers over the RPC raft
    channel (reference: hashicorp/raft beneath nomad/server.go setupRaft,
    transported via raft_rpc.go:34-90 RaftLayer on the shared RPC port).

    Raft's core, implemented fully: randomized election timeouts, term-voted
    RequestVote with persisted term/vote, AppendEntries with prev-entry
    consistency check and follower conflict truncation, per-peer replicator
    threads driving next/match indexes, majority commit restricted to
    current-term entries, InstallSnapshot for peers behind the compaction
    horizon, ordered FSM apply, and per-index apply futures so every
    ``apply`` caller receives its own FSM result.

    Entry payloads cross the wire as whitelisted msgpack trees
    (server/log_codec.py), never pickle — a raft peer can only inject data,
    not code.

    ``apply`` blocks until the entry is committed by a majority and applied
    locally, then returns (result, index) — identical semantics to the
    single-voter path so the Server code above it does not change.
    """

    # Election timeout must comfortably exceed worst-case scheduling
    # latency for the first post-election heartbeat — too tight and a
    # loaded host deposes every new leader before its heartbeat lands
    # (the reference runs 500ms-1s timeouts against 100ms heartbeats).
    HEARTBEAT_INTERVAL = 0.05
    ELECTION_TIMEOUT = (0.30, 0.60)
    APPLY_TIMEOUT = 10.0
    REPLICATE_BATCH = 512
    # Auto-compact once the in-memory log exceeds this many entries
    # (hashicorp/raft SnapshotThreshold, default 8192).
    SNAPSHOT_THRESHOLD = 8192

    def __init__(self, fsm: FSM, my_addr: str, pool,
                 data_dir: Optional[str] = None, logger=None):
        super().__init__(fsm)
        import logging as _logging
        import random

        self.logger = logger or _logging.getLogger("nomad_tpu.raft")
        self.my_addr = my_addr
        self.pool = pool
        self._rand = random.Random(hash(my_addr) & 0xFFFFFF)
        self._leader = False  # starts as follower, unlike single-voter
        # Timing knobs (instance-level env overrides of the class
        # defaults): a GIL-bound in-process cluster under measurement
        # load can starve the leader's heartbeat threads past the stock
        # 0.3-0.6s window — depositions mid-benchmark measure election
        # churn, not scheduling.  The loadgen harness slows elections
        # down (NOMAD_TPU_RAFT_ELECTION_MIN_S/MAX_S) the way the
        # reference tunes raft_multiplier on loaded hardware.
        self.HEARTBEAT_INTERVAL = _env_float(
            "NOMAD_TPU_RAFT_HEARTBEAT_S", type(self).HEARTBEAT_INTERVAL)
        self.ELECTION_TIMEOUT = (
            _env_float("NOMAD_TPU_RAFT_ELECTION_MIN_S",
                       type(self).ELECTION_TIMEOUT[0]),
            _env_float("NOMAD_TPU_RAFT_ELECTION_MAX_S",
                       type(self).ELECTION_TIMEOUT[1]))

        self.store = _RaftStore(data_dir)
        (self.term, self.voted_for, saved_peers, self.base_index,
         self.base_term, self.log, snap_blob) = self.store.load()
        if snap_blob is not None:
            self.fsm.restore(snap_blob)
        # Only the snapshot prefix is known-committed at boot; WAL entries
        # beyond it may be uncommitted and are re-committed by the leader.
        self.commit_index = self.base_index
        self._last_index = self.base_index  # last *applied*
        self._applied = self.base_index

        self.leader_addr: Optional[str] = None
        self.state = "follower"
        # The voter set comes from the persisted committed configuration;
        # a fresh server has none and cannot campaign until it is either
        # gossip-bootstrapped (initial cluster formation) or added to the
        # cluster through a replicated CONFIG entry.
        self.peers: List[str] = saved_peers or [my_addr]
        self._bootstrapped = bool(saved_peers)
        # Non-voting members (the reference's non_voting_server, ISSUE
        # 10): replicated like voters — they receive AppendEntries /
        # InstallSnapshot and apply the FSM, which is what follower-read
        # scheduling needs — but they are never counted toward quorum
        # and never campaign.  Scheduling capacity scales with learner
        # count while commit latency stays pinned to the voter set.
        self.learners: List[str] = []

        self._futures: dict = {}           # index -> _ApplyFuture
        # Leader-appended entries keep their ORIGINAL payload object so
        # the local FSM apply skips re-decoding its own blob (the
        # single-voter path shares objects the same way; followers
        # decode from the replicated blob as before).  Entries are
        # dropped at apply and at conflict truncation — a truncated
        # index may be refilled by a DIFFERENT leader's entry.
        self._local_payloads: dict = {}    # index -> payload
        self._next: dict = {}              # peer -> next index to send
        self._match: dict = {}             # peer -> highest replicated index
        self._repl_events: dict = {}       # peer -> threading.Event
        self._repl_threads: dict = {}      # peer -> Thread

        self._last_contact = 0.0
        self._stop = threading.Event()
        self._threads: List[threading.Thread] = []
        # Follower-side async apply (ISSUE 10): raft only requires a
        # follower to APPEND before acking — applying committed entries
        # can happen after the reply.  Doing it inline put every FSM
        # apply inside the leader's quorum round trip (a loaded
        # follower's apply time became plan-apply latency cluster-wide);
        # the applier thread drains commit_index in small chunks so
        # incoming AppendEntries interleave on the lock.
        self._apply_kick = threading.Event()
        # Leadership transitions are delivered to callbacks strictly in
        # the order they occurred, by one dispatcher thread.  Spawning a
        # thread per transition could reorder a win-then-step-down into
        # step-down-then-win, leaving the server side believing it leads
        # while raft follows.
        import queue as _queue
        self._leader_q: "_queue.Queue" = _queue.Queue()

    def _leader_dispatch_loop(self) -> None:
        import queue as _queue
        while not self._stop.is_set():
            try:
                val = self._leader_q.get(timeout=0.5)
            except _queue.Empty:
                continue
            try:
                self._set_leader(val)
            except Exception:
                # A raising leadership callback (e.g. an establish-time
                # apply losing leadership mid-flight) must not kill the
                # dispatcher — later transitions still need delivery.
                self.logger.exception("raft: leadership callback failed")

    # -- log shape helpers (caller holds self._l) --------------------------

    def _last_log_index(self) -> int:
        return self.base_index + len(self.log)

    def _term_at(self, index: int) -> int:
        if index == self.base_index:
            return self.base_term
        if index < self.base_index or index > self._last_log_index():
            return -1  # unknown (compacted away / beyond end)
        return self.log[index - self.base_index - 1][1]

    def _entries_from(self, index: int, limit: int) -> List[list]:
        start = index - self.base_index - 1
        return self.log[start:start + limit]

    # -- lifecycle ---------------------------------------------------------

    # Entries applied per lock hold by the async applier: small enough
    # that an incoming AppendEntries (which only needs the lock for the
    # append) never waits behind a long committed backlog.
    APPLY_CHUNK = 16

    def start(self) -> None:
        import time as _time
        self._last_contact = _time.monotonic()
        for target, name in ((self._ticker, "raft-ticker"),
                             (self._leader_dispatch_loop, "raft-leadership"),
                             (self._apply_loop, "raft-applier")):
            t = threading.Thread(target=target, name=name, daemon=True)
            t.start()
            self._threads.append(t)

    def _apply_loop(self) -> None:
        """Follower-side committed-entry applier: drains ``commit_index``
        OUTSIDE the AppendEntries reply path.  Chunked lock holds keep
        appends interleaving; ordering is preserved because _apply_to
        only ever advances _last_index under the lock (the leader's
        inline _advance_commit applies through the same guard, so a
        freshly promoted leader and this thread cannot double-apply)."""
        while not self._stop.is_set():
            if not self._apply_kick.wait(0.05):
                continue
            self._apply_kick.clear()
            while True:
                with self._l:
                    if self._last_index >= self.commit_index:
                        break
                    self._apply_to(min(self.commit_index,
                                       self._last_index + self.APPLY_CHUNK))

    def close(self) -> None:
        self._stop.set()
        with self._l:
            self._fail_futures(NotLeaderError("shutting down"))
        for ev in self._repl_events.values():
            ev.set()
        self.store.close()

    def bootstrap(self, peers: List[str]) -> None:
        """Adopt the *initial* voter set and enable elections (serf.go:91
        maybeBootstrap).  No-op once a configuration exists: later voter
        changes must replicate through the log (propose_config) so every
        server's quorum derives from a committed config — unilateral
        adoption of a private gossip view could produce disjoint quorums
        and split-brain."""
        with self._l:
            if self._bootstrapped:
                return
            self.peers = sorted(set(peers) | {self.my_addr})
            self._bootstrapped = True
            self._persist_meta()

    def propose_config(self, peers: List[str]) -> None:
        """Leader-only voter-set change via a replicated CONFIG log entry
        (hashicorp/raft AddVoter; single-config approximation — the leader
        uses the new config as soon as it is appended, followers on
        apply)."""
        import msgpack
        with self._l:
            if self.state != "leader":
                raise NotLeaderError(self.leader_addr or "")
            peers = sorted(set(peers) | {self.my_addr})
            if peers == self.peers:
                return
            index = self._last_log_index() + 1
            entry = [index, self.term,
                     CONFIG_TYPE, msgpack.packb(peers, use_bin_type=True)]
            self.log.append(entry)
            self.store.append([entry])
            fut = _ApplyFuture()
            self._futures[index] = fut
            self._adopt_peers(peers)
            self._advance_commit()
        self._kick_replicators()
        fut.wait(self.APPLY_TIMEOUT)

    def _adopt_peers(self, peers: List[str]) -> None:
        # caller holds self._l
        added = [p for p in peers if p not in self.peers]
        self.peers = list(peers)
        self._bootstrapped = True
        self._persist_meta()
        if self.state == "leader":
            for p in added:
                if p != self.my_addr:
                    self._start_replicator(p)

    def _quorum(self) -> int:
        return len(self.peers) // 2 + 1

    def is_raft_leader(self) -> bool:
        with self._l:
            return self.state == "leader"

    def fence_index(self) -> int:
        """Last LOG index: election safety puts every committed entry
        at or below it, and unlike the applied index it cannot lag the
        async applier (see RaftLog.fence_index)."""
        with self._l:
            return self._last_log_index()

    # -- persistence helpers (caller holds self._l) ------------------------

    def _persist_meta(self) -> None:
        self.store.save_meta(self.term, self.voted_for,
                             self.peers if self._bootstrapped else [])

    # -- RPC entry (RPCServer.raft_handler) --------------------------------

    def handle_message(self, msg: dict) -> dict:
        if self._stop.is_set():
            raise RuntimeError("raft: node is shut down")
        kind = msg.get("kind")
        if kind == "request_vote":
            return self._on_request_vote(msg)
        if kind == "append_entries":
            return self._on_append_entries(msg)
        if kind == "install_snapshot":
            return self._on_install_snapshot(msg)
        raise ValueError(f"unknown raft message kind {kind!r}")

    # -- election ----------------------------------------------------------

    def _election_timeout(self) -> float:
        lo, hi = self.ELECTION_TIMEOUT
        return lo + self._rand.random() * (hi - lo)

    def add_learner(self, addr: str) -> None:
        """Leader-side: attach a non-voting member to the replication
        fan-out (no CONFIG entry — learners are not part of the
        committed quorum configuration)."""
        with self._l:
            if (addr == self.my_addr or addr in self.peers
                    or addr in self.learners):
                return
            self.learners.append(addr)
            if self.state == "leader":
                self._start_replicator(addr)

    def _ticker(self) -> None:
        import time as _time
        timeout = self._election_timeout()
        while not self._stop.is_set():
            _time.sleep(0.015)
            with self._l:
                # Non-members never campaign: a learner receives the
                # committed voter config (it is not in it), and a voter
                # removed from the config must not start elections its
                # quorum can't win.
                campaigning_ok = (self._bootstrapped
                                  and self.state != "leader"
                                  and self.my_addr in self.peers)
                since = _time.monotonic() - self._last_contact
            if campaigning_ok and since >= timeout:
                self._run_election()
                timeout = self._election_timeout()

    def _run_election(self) -> None:
        import time as _time
        with self._l:
            self.state = "candidate"
            self.term += 1
            term = self.term
            self.voted_for = self.my_addr
            self._persist_meta()
            self.leader_addr = None
            last_index = self._last_log_index()
            last_term = self._term_at(last_index)
            peers = [p for p in self.peers if p != self.my_addr]
            self._last_contact = _time.monotonic()
        votes = 1
        lock = threading.Lock()
        done = threading.Event()

        def ask(peer):
            nonlocal votes
            try:
                from .rpc import RPC_RAFT
                reply = self.pool.call(peer, "raft", {
                    "kind": "request_vote", "term": term,
                    "candidate": self.my_addr,
                    "last_log_index": last_index, "last_log_term": last_term,
                }, channel=RPC_RAFT, timeout=0.5)
            except Exception:
                return
            step_down = False
            with self._l:
                if reply.get("term", 0) > self.term:
                    self._step_down(reply["term"])
                    step_down = True
            if step_down:
                done.set()
                return
            with lock:
                if reply.get("granted"):
                    votes += 1
                    if votes >= self._quorum():
                        done.set()

        threads = [threading.Thread(target=ask, args=(p,), daemon=True)
                   for p in peers]
        for t in threads:
            t.start()
        if not peers:
            done.set()
        done.wait(timeout=0.6)
        with self._l:
            if self.state == "candidate" and self.term == term \
                    and votes >= self._quorum():
                self._become_leader()
                # Callbacks (broker enable, eval restore, …) run on the
                # ordered dispatcher thread, outside the raft lock: they
                # may apply entries themselves.
                self._leader_q.put(True)

    def _become_leader(self) -> None:
        # caller holds self._l
        self.state = "leader"
        self.leader_addr = self.my_addr
        self.logger.info("raft: %s won election for term %d",
                         self.my_addr, self.term)
        last = self._last_log_index()
        for p in self.peers:
            if p == self.my_addr:
                continue
            self._next[p] = last + 1
            self._match[p] = 0
        # Term-establishment entry (Raft §5.4.2 — a leader never counts
        # replicas of old-term entries toward commitment directly).  It
        # carries the current voter configuration so every follower adopts
        # and persists the committed config (hashicorp/raft re-ships its
        # LogConfiguration the same way).
        import msgpack
        cfg = [last + 1, self.term, CONFIG_TYPE,
               msgpack.packb(self.peers, use_bin_type=True)]
        self.log.append(cfg)
        self.store.append([cfg])
        for p in self.peers + self.learners:
            if p != self.my_addr:
                self._start_replicator(p)
        self._advance_commit()

    def _start_replicator(self, peer: str) -> None:
        # caller holds self._l.  Replicator threads are per-(peer, term):
        # a thread from an older term is already exiting (its term check
        # fails), so only an alive *current-term* thread short-circuits.
        old = self._repl_threads.get(peer)
        if old is not None and old[0] == self.term and old[1].is_alive():
            self._repl_events[peer].set()
            return
        self._next.setdefault(peer, self._last_log_index() + 1)
        self._match.setdefault(peer, 0)
        ev = threading.Event()
        ev.set()
        self._repl_events[peer] = ev
        t = threading.Thread(target=self._replicate_peer,
                             args=(peer, self.term, ev),
                             name=f"raft-repl-{peer}", daemon=True)
        self._repl_threads[peer] = (self.term, t)
        t.start()

    def _step_down(self, term: int) -> None:
        # caller holds self._l
        was_leader = self.state == "leader"
        if term > self.term:
            self.term = term
            self.voted_for = None
            self._persist_meta()
        self.state = "follower"
        self._fail_futures(NotLeaderError(self.leader_addr or ""))
        for ev in self._repl_events.values():
            ev.set()  # wake replicators so they observe the term change
        if was_leader:
            self._leader_q.put(False)

    def _fail_futures(self, exc: Exception) -> None:
        # caller holds self._l
        for fut in self._futures.values():
            fut.fail(exc)
        self._futures.clear()

    def _on_request_vote(self, msg: dict) -> dict:
        import time as _time
        with self._l:
            if msg["term"] < self.term:
                return {"granted": False, "term": self.term}
            if msg["term"] > self.term:
                self._step_down(msg["term"])
            my_last = self._last_log_index()
            up_to_date = (
                msg["last_log_term"], msg["last_log_index"]
            ) >= (self._term_at(my_last), my_last)
            if up_to_date and self.voted_for in (None, msg["candidate"]):
                self.voted_for = msg["candidate"]
                self._persist_meta()  # durable before granting (Raft §5.2)
                self._last_contact = _time.monotonic()
                return {"granted": True, "term": self.term}
            return {"granted": False, "term": self.term}

    # -- leader replication ------------------------------------------------

    def _replicate_peer(self, peer: str, term: int, kick: threading.Event,
                        ) -> None:
        """Per-peer replication loop (hashicorp/raft replicate()): ships
        missing entries / heartbeats, falls back to InstallSnapshot when the
        peer is behind the compaction horizon."""
        from .rpc import RPC_RAFT
        while not self._stop.is_set():
            with self._l:
                if self.state != "leader" or self.term != term:
                    return
                ni = self._next.get(peer, self.base_index + 1)
                snapshot_needed = ni <= self.base_index
                if not snapshot_needed:
                    entries = self._entries_from(ni, self.REPLICATE_BATCH)
                    prev_index = ni - 1
                    prev_term = self._term_at(prev_index)
                    commit = self.commit_index
            try:
                if snapshot_needed:
                    self._send_snapshot(peer, term)
                    continue
                reply = self.pool.call(peer, "raft", {
                    "kind": "append_entries", "term": term,
                    "leader": self.my_addr,
                    "prev_log_index": prev_index,
                    "prev_log_term": prev_term,
                    "entries": entries,
                    "leader_commit": commit,
                }, channel=RPC_RAFT, timeout=2.0)
            except Exception:
                kick.clear()
                kick.wait(0.1)
                continue
            with self._l:
                if reply.get("term", 0) > self.term:
                    self._step_down(reply["term"])
                    return
                if self.state != "leader" or self.term != term:
                    return
                if reply.get("success"):
                    sent_through = prev_index + len(entries)
                    self._match[peer] = max(self._match.get(peer, 0),
                                            sent_through)
                    self._next[peer] = sent_through + 1
                    self._advance_commit()
                    more = self._next[peer] <= self._last_log_index()
                else:
                    # Consistency check failed: back up using the
                    # follower's hint (accelerated log backtracking).  A
                    # hint behind our compaction horizon means the entries
                    # it needs are gone — ship a snapshot instead.
                    hint = reply.get("match", prev_index - 1)
                    if hint < self.base_index:
                        self._next[peer] = self.base_index
                    else:
                        self._next[peer] = max(self.base_index + 1,
                                               min(hint + 1, ni - 1))
                    more = True
            if not more:
                kick.clear()
                kick.wait(self.HEARTBEAT_INTERVAL)

    def _snapshot_chunk_size(self) -> int:
        """Bytes per InstallSnapshot chunk (streaming install,
        ISSUE 10): a follower far behind the horizon catches up off the
        PR 9 binary (NTPUSNP2) blob incrementally instead of one giant
        frame — each chunk stays well under the RPC frame cap and
        refreshes the follower's leader-contact clock, so a multi-GB
        install can no longer starve its election timer or blow the
        64MB frame limit."""
        return max(1, _env_int("NOMAD_TPU_SNAPSHOT_CHUNK", 4 << 20))

    def _send_snapshot(self, peer: str, term: int) -> None:
        """InstallSnapshot for a peer behind the log horizon: one frame
        for small blobs (wire-compatible with pre-streaming followers),
        chunked offset/total/done frames past the chunk size."""
        from .rpc import RPC_RAFT
        with self._l:
            if self.state != "leader" or self.term != term:
                return
            blob = self.fsm.snapshot()
            last_index = self._last_index
            last_term = self._term_at(last_index)
            if last_term < 0:
                last_term = self.base_term
            peers = list(self.peers)
        chunk = self._snapshot_chunk_size()
        base = {"kind": "install_snapshot", "term": term,
                "leader": self.my_addr,
                "last_index": last_index, "last_term": last_term,
                "peers": peers}  # config rides the snapshot
        try:
            if len(blob) <= chunk:
                reply = self.pool.call(
                    peer, "raft", dict(base, data=blob),
                    channel=RPC_RAFT, timeout=10.0)
            else:
                total = len(blob)
                reply = None
                for off in range(0, total, chunk):
                    with self._l:
                        if self.state != "leader" or self.term != term:
                            return
                    reply = self.pool.call(peer, "raft", dict(
                        base, data=blob[off:off + chunk], offset=off,
                        total=total, done=off + chunk >= total,
                    ), channel=RPC_RAFT, timeout=10.0)
                    self.metrics.incr_counter("raft.snapshot.chunks_sent")
                    if reply.get("term", 0) > term \
                            or not reply.get("success", False):
                        break  # demoted, or receiver lost the sequence
        except Exception:
            self._repl_events[peer].clear()
            self._repl_events[peer].wait(0.2)
            return
        with self._l:
            if reply is not None and reply.get("term", 0) > self.term:
                self._step_down(reply["term"])
                return
            if reply is None or not reply.get("success", True):
                # Receiver aborted (restart/sequence loss): the
                # replicator loop retries the install from offset 0.
                return
            self._match[peer] = max(self._match.get(peer, 0), last_index)
            self._next[peer] = last_index + 1
            self._advance_commit()

    def _kick_replicators(self) -> None:
        with self._l:
            events = list(self._repl_events.values())
        for ev in events:
            ev.set()

    def _advance_commit(self) -> None:
        """Majority-match commit advancement; only current-term entries
        commit by counting (Raft §5.4.2).  Caller holds self._l."""
        if self.state != "leader":
            return
        matches = sorted(
            [self._last_log_index()]
            + [self._match.get(p, 0) for p in self.peers if p != self.my_addr]
        )
        n = matches[len(matches) - self._quorum()]
        if n > self.commit_index and self._term_at(n) == self.term:
            self.commit_index = n
            if self._threads:
                # FSM application (and future resolution) runs on the
                # dedicated applier thread: replicator reply handling
                # holding the raft lock through every committed entry's
                # FSM apply made lock waits — and therefore the NEXT
                # replication round — scale with apply cost.
                self._apply_kick.set()
            else:  # not start()ed (unit-test harness): inline
                self._apply_to(self.commit_index)

    def _apply_to(self, target: int) -> None:
        """Apply committed entries through ``target`` in index order,
        resolving apply futures.  Caller holds self._l."""
        from .log_codec import decode_payload
        while self._last_index < target:
            idx = self._last_index + 1
            _eidx, _eterm, mt, blob = self.log[idx - self.base_index - 1]
            result = None
            if mt == CONFIG_TYPE:
                import msgpack
                peers = msgpack.unpackb(blob, raw=False)
                if peers != self.peers:
                    self._adopt_peers(peers)
                else:
                    self._bootstrapped = True
                    self._persist_meta()
            elif mt != NOOP_TYPE:
                payload = self._local_payloads.pop(idx, None)
                try:
                    result = self.fsm.apply(
                        idx, MessageType(mt),
                        payload if payload is not None
                        else decode_payload(blob))
                except Exception:
                    self.logger.exception("raft: fsm apply failed at %d", idx)
            self._last_index = idx
            self._applied = idx
            fut = self._futures.pop(idx, None)
            if fut is not None:
                fut.resolve(result)
        if len(self.log) > self.SNAPSHOT_THRESHOLD:
            self._compact()

    # -- follower side -----------------------------------------------------

    def _on_append_entries(self, msg: dict) -> dict:
        import time as _time
        with self._l:
            if msg["term"] < self.term:
                return {"success": False, "term": self.term}
            if msg["term"] > self.term or self.state != "follower":
                self._step_down(msg["term"])
                self.term = msg["term"]
                self._persist_meta()
            self.leader_addr = msg["leader"]
            self._last_contact = _time.monotonic()

            prev_index = msg["prev_log_index"]
            prev_term = msg["prev_log_term"]
            entries = [list(e) for e in msg["entries"]]
            # Anything at or before our snapshot base is already committed
            # here; skip those entries and anchor at the base.
            if prev_index < self.base_index:
                entries = [e for e in entries if e[0] > self.base_index]
                prev_index = self.base_index
                prev_term = self.base_term
            if prev_index > self._last_log_index():
                return {"success": False, "term": self.term,
                        "match": self._last_log_index()}
            if self._term_at(prev_index) != prev_term:
                return {"success": False, "term": self.term,
                        "match": max(self.base_index, prev_index - 1)}
            # Truncate conflicts, then append the new suffix with ONE
            # durable write (one fsync per RPC, not per entry).
            append_from = None
            for k, e in enumerate(entries):
                pos = e[0] - self.base_index - 1
                if pos < len(self.log):
                    if self.log[pos][1] != e[1]:
                        del self.log[pos:]
                        self.store.rewrite(self.log)
                        # A different leader refills these indexes: the
                        # cached local payloads no longer describe them.
                        for cached in [i for i in self._local_payloads
                                       if i >= e[0]]:
                            del self._local_payloads[cached]
                        append_from = k
                        break
                    # identical entry already present — skip
                else:
                    append_from = k
                    break
            if append_from is not None:
                new = entries[append_from:]
                self.log.extend(new)
                self.store.append(new)
            new_commit = min(msg["leader_commit"], self._last_log_index())
            if new_commit > self.commit_index:
                self.commit_index = new_commit
                if self._threads:
                    # Ack now, apply async: the applier thread owns the
                    # FSM catch-up (see _apply_loop) so a busy
                    # follower's apply time never rides the leader's
                    # quorum wait.
                    self._apply_kick.set()
                else:  # not start()ed (unit-test harness): inline
                    self._apply_to(new_commit)
            return {"success": True, "term": self.term,
                    "match": self._last_log_index()}

    def _on_install_snapshot(self, msg: dict) -> dict:
        import time as _time
        with self._l:
            if msg["term"] < self.term:
                return {"term": self.term}
            if msg["term"] > self.term or self.state != "follower":
                self._step_down(msg["term"])
                self.term = msg["term"]
                self._persist_meta()
            self.leader_addr = msg["leader"]
            self._last_contact = _time.monotonic()
            if "offset" in msg:
                # Streaming install: buffer chunks until done.  The key
                # pins one specific snapshot transfer; any sequence
                # break (leader restart, interleaved transfer) replies
                # success=False and the leader restarts from offset 0.
                key = (msg["term"], msg["last_index"], msg["total"])
                rx = getattr(self, "_snap_rx", None)
                if msg["offset"] == 0:
                    rx = self._snap_rx = {"key": key, "chunks": [],
                                          "received": 0}
                if (rx is None or rx["key"] != key
                        or rx["received"] != msg["offset"]):
                    self._snap_rx = None
                    return {"term": self.term, "success": False}
                rx["chunks"].append(msg["data"])
                rx["received"] += len(msg["data"])
                if not msg.get("done"):
                    return {"term": self.term, "success": True}
                self._snap_rx = None
                if rx["received"] != msg["total"]:
                    return {"term": self.term, "success": False}
                msg = dict(msg, data=b"".join(rx["chunks"]))
            self.fsm.restore(msg["data"])
            if msg.get("peers"):
                self._adopt_peers(list(msg["peers"]))
            self.base_index = msg["last_index"]
            self.base_term = msg["last_term"]
            self.log = []
            self._local_payloads.clear()
            self.store.save_snapshot(self.base_index, self.base_term,
                                     msg["data"])
            self.store.rewrite([])
            self.commit_index = self.base_index
            self._last_index = self.base_index
            self._applied = self.base_index
            return {"term": self.term, "success": True}

    # -- compaction --------------------------------------------------------

    def _compact(self) -> None:
        """Snapshot the FSM at the applied index and drop covered entries.
        Caller holds self._l."""
        applied = self._last_index
        if applied <= self.base_index:
            return
        blob = self.fsm.snapshot()
        new_base_term = self._term_at(applied)
        self.log = self.log[applied - self.base_index:]
        self.base_index = applied
        self.base_term = new_base_term
        self.store.save_snapshot(applied, new_base_term, blob)
        self.store.rewrite(self.log)

    def snapshot(self) -> None:
        with self._l:
            self._compact()

    # -- the apply path ----------------------------------------------------

    def apply(self, msg_type: MessageType, payload: dict):
        from .log_codec import encode_payload
        t0 = time.perf_counter()
        # Encode OUTSIDE the raft lock: concurrent appliers pay their
        # own codec time instead of convoying every append behind it
        # (an entry is pure data; index assignment below still orders
        # the log).
        blob = encode_payload(payload)
        with self._l:
            if self.state != "leader":
                raise NotLeaderError(self.leader_addr or "")
            if _fire_apply_fault(self._last_log_index() + 1,
                                 msg_type) is not None:
                # Injected step-down: a real demotion — the cluster
                # re-elects (possibly us) via the normal election timer.
                self._step_down(self.term)
                raise NotLeaderError(self.leader_addr or "")
            index = self._last_log_index() + 1
            entry = [index, self.term, int(msg_type), blob]
            self.log.append(entry)
            self.store.append([entry])
            fut = _ApplyFuture()
            self._futures[index] = fut
            self._local_payloads[index] = payload
            self._advance_commit()  # single-voter clusters commit here
        self._kick_replicators()
        result = fut.wait(self.APPLY_TIMEOUT)
        self.metrics.measure_since("raft.apply", t0)
        tr = tracing.TRACER
        if tr is not None:
            tr.record("raft.apply", t0, time.perf_counter(), index=index,
                      msg_type=getattr(msg_type, "name", str(msg_type)))
        return result, index
